"""Token containers and chained block hashing.

Serves the role of the reference's token library (`lib/tokens/src/lib.rs`,
`lib/llm/src/tokens.rs:49-435`): fixed-size token blocks whose identity is a
*chained* hash — each block's hash commits to the full prefix up to and
including the block — so two sequences share a block hash iff they share the
entire prefix.  These sequence hashes are the keys of the KV-cache world:
the router's radix index, the block-manager reuse pools and the KV events
all speak them.

Hash function: xxh3_64 over (parent_hash_le64 || tokens_le_u32...), with a
fixed salt for the root.  Pure-Python/NumPy; hot batch path vectorizes with
numpy + xxhash over byte views.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np
import xxhash

# Salt used as the "parent hash" of the first block of a sequence, so that
# hash(block0) differs from a raw content hash (defensive versus accidental
# collisions with other hash domains, e.g. local block hashes).
ROOT_PARENT_HASH = 0xD1A0_0000_0000_0001

TokenId = int


def _as_u32(tokens) -> np.ndarray:
    """Coerce tokens to uint32, raising (never wrapping) on out-of-range ids.

    Silent u32 wrap-around would alias cache keys across distinct tokens, so
    both the Python-int path (OverflowError) and the numpy-array path (which
    numpy would happily wrap) must reject out-of-range values.
    """
    arr = np.asarray(tokens)
    if arr.dtype == np.uint32:
        return arr
    if not np.issubdtype(arr.dtype, np.integer):
        # Float/object input: truncation would alias distinct streams, so
        # only exact integer values are accepted.
        try:
            as_int = arr.astype(np.int64)
        except (ValueError, OverflowError, TypeError) as e:
            raise ValueError(f"token ids must be integers: {e}") from e
        if np.issubdtype(arr.dtype, np.floating) and not np.array_equal(as_int, arr):
            raise ValueError("token ids must be integers, got non-integral floats")
        arr = as_int
    if arr.size and (arr.min() < 0 or arr.max() > 0xFFFFFFFF):
        raise ValueError(
            f"token ids must fit in uint32, got range [{arr.min()}, {arr.max()}]"
        )
    return arr.astype(np.uint32)


def hash_block(parent_hash: int, tokens: Sequence[int]) -> int:
    """Chained sequence hash of one block given its parent's sequence hash."""
    h = xxhash.xxh3_64()
    h.update(struct.pack("<Q", parent_hash & 0xFFFFFFFFFFFFFFFF))
    h.update(_as_u32(tokens).tobytes())
    return h.intdigest()


def compute_block_hashes(
    tokens: Sequence[int], block_size: int, parent_hash: int = ROOT_PARENT_HASH
) -> List[int]:
    """Sequence hashes for every *complete* block of `tokens`.

    Analog of the reference's `compute_block_hash_for_seq`
    (`lib/llm/src/kv_router/indexer.rs:123`).  The trailing partial block (if
    any) is not hashed — only full blocks are eligible for reuse/routing.

    The chain runs in the native C++ module when available (csrc/
    block_hash.cpp — byte-identical layout; tests/test_native.py holds
    the parity) and falls back to the per-block Python loop here.
    """
    if block_size <= 0:
        raise ValueError(f"block_size must be positive, got {block_size}")
    arr = _as_u32(tokens)

    from dynamo_tpu import native

    fast = native.chained_block_hashes(arr, block_size, parent_hash)
    if fast is not None:
        return [int(h) for h in fast]

    n_full = len(arr) // block_size
    hashes: List[int] = []
    h = parent_hash
    for i in range(n_full):
        h = hash_block(h, arr[i * block_size : (i + 1) * block_size])
        hashes.append(h)
    return hashes


@dataclass(frozen=True)
class TokenBlock:
    """A complete, immutable block of `block_size` tokens.

    `block_hash` is the chained sequence hash (commits to the whole prefix);
    `parent_hash` is the previous block's sequence hash (ROOT_PARENT_HASH for
    the first block).
    """

    tokens: Tuple[TokenId, ...]
    block_hash: int
    parent_hash: int
    position: int  # block index within its sequence


class TokenBlockSequence:
    """Incrementally maintains the block decomposition + chained hashes of a
    growing token sequence (reference `TokenBlockSequence`,
    `lib/llm/src/tokens.rs:394-435`).

    Append tokens one at a time (decode) or in bulk (prefill); complete
    blocks are frozen with their sequence hash, the partial tail stays
    mutable.
    """

    def __init__(self, tokens: Optional[Iterable[TokenId]] = None, block_size: int = 64):
        if block_size <= 0:
            raise ValueError(f"block_size must be positive, got {block_size}")
        self.block_size = block_size
        self.blocks: List[TokenBlock] = []
        self._partial: List[TokenId] = []
        if tokens is not None:
            self.extend(tokens)

    # -- mutation ---------------------------------------------------------
    def append(self, token: TokenId) -> Optional[TokenBlock]:
        """Append one token; returns the newly completed block, if any."""
        token = int(token)
        if not 0 <= token <= 0xFFFFFFFF:
            # Validate before mutating so a bad token cannot leave _partial
            # oversized and wedge block sealing.
            raise ValueError(f"token id must fit in uint32, got {token}")
        self._partial.append(token)
        if len(self._partial) >= self.block_size:
            return self._seal()
        return None

    def extend(self, tokens: Iterable[TokenId]) -> List[TokenBlock]:
        """Append many tokens in bulk; returns all blocks completed by this
        call.  Bulk path: validates once, hashes each sealed block straight
        from the uint32 array view, and converts to Python ints once via
        tolist() (prefill prompts can be 100k+ tokens).
        """
        arr = _as_u32(list(tokens) if not isinstance(tokens, (list, np.ndarray)) else tokens)
        toks: List[TokenId] = arr.tolist()
        new_blocks: List[TokenBlock] = []
        pos = 0
        n = len(toks)
        while pos < n:
            if not self._partial and n - pos >= self.block_size:
                # Whole block available: hash directly from the array view.
                end = pos + self.block_size
                parent = self.blocks[-1].block_hash if self.blocks else ROOT_PARENT_HASH
                blk = TokenBlock(
                    tokens=tuple(toks[pos:end]),
                    block_hash=hash_block(parent, arr[pos:end]),
                    parent_hash=parent,
                    position=len(self.blocks),
                )
                self.blocks.append(blk)
                new_blocks.append(blk)
                pos = end
            else:
                take = min(self.block_size - len(self._partial), n - pos)
                self._partial.extend(toks[pos : pos + take])
                pos += take
                if len(self._partial) >= self.block_size:
                    new_blocks.append(self._seal())
        return new_blocks

    def truncate(self, length: int) -> None:
        """Truncate the sequence to `length` tokens.

        Chained hashes of a prefix never change, so retained full blocks are
        kept as-is; only the partial tail is rebuilt (rollback — e.g. rejected
        speculative tokens — must be O(dropped), not O(sequence)).
        """
        if length < 0 or length > len(self):
            raise ValueError(f"cannot truncate length {len(self)} to {length}")
        keep_blocks = length // self.block_size
        tail_len = length - keep_blocks * self.block_size
        if tail_len == 0:
            tail: List[TokenId] = []
        elif keep_blocks < len(self.blocks):
            tail = list(self.blocks[keep_blocks].tokens[:tail_len])
        else:
            tail = self._partial[:tail_len]
        self.blocks = self.blocks[:keep_blocks]
        self._partial = tail

    def _seal(self) -> TokenBlock:
        assert len(self._partial) == self.block_size
        parent = self.blocks[-1].block_hash if self.blocks else ROOT_PARENT_HASH
        blk = TokenBlock(
            tokens=tuple(self._partial),
            block_hash=hash_block(parent, self._partial),
            parent_hash=parent,
            position=len(self.blocks),
        )
        self.blocks.append(blk)
        self._partial = []
        return blk

    # -- views ------------------------------------------------------------
    def __len__(self) -> int:
        return len(self.blocks) * self.block_size + len(self._partial)

    @property
    def tokens(self) -> List[TokenId]:
        out: List[TokenId] = []
        for b in self.blocks:
            out.extend(b.tokens)
        out.extend(self._partial)
        return out

    @property
    def partial_tokens(self) -> Tuple[TokenId, ...]:
        return tuple(self._partial)

    @property
    def block_hashes(self) -> List[int]:
        return [b.block_hash for b in self.blocks]

    def last_hash(self) -> int:
        return self.blocks[-1].block_hash if self.blocks else ROOT_PARENT_HASH


@dataclass
class SaltedBlockHasher:
    """Per-model/per-tenant hash domain separation: mixes a salt into the
    root parent hash so identical token streams in different domains do not
    share cache identity (lora adapters, different models behind one router).
    """

    salt: bytes = b""
    _root: int = field(init=False)

    def __post_init__(self) -> None:
        if self.salt:
            h = xxhash.xxh3_64()
            h.update(struct.pack("<Q", ROOT_PARENT_HASH))
            h.update(self.salt)
            self._root = h.intdigest()
        else:
            self._root = ROOT_PARENT_HASH

    @property
    def root(self) -> int:
        return self._root

    def block_hashes(self, tokens: Sequence[int], block_size: int) -> List[int]:
        return compute_block_hashes(tokens, block_size, parent_hash=self._root)
