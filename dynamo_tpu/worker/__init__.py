"""Backend worker entrypoints (reference `dynamo.vllm` / `dynamo.mocker`
worker mains, `components/backends/*/src/dynamo/*/main.py`)."""
