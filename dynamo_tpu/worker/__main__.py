from dynamo_tpu.worker.main import main

main()
