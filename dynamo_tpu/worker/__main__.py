"""`python -m dynamo_tpu.worker` entry.

Multihost flags are pre-scanned BEFORE the heavy imports: the CPU-rig env
(XLA_FLAGS / platform) must be set before jax initialises, and
jax.distributed must join before any engine module touches a device.
"""

import sys


def _flag(name: str):
    argv = sys.argv[1:]
    if name in argv:
        i = argv.index(name)
        if i + 1 < len(argv):
            return argv[i + 1]
    for a in argv:
        if a.startswith(name + "="):
            return a.split("=", 1)[1]
    return None


def _prescan() -> None:
    n_cpu = _flag("--multihost-cpu-devices")
    coord = _flag("--coordinator")
    if not (n_cpu or coord):
        return
    from dynamo_tpu.parallel import multihost

    if n_cpu and int(n_cpu) > 0:
        multihost.setup_cpu_rig(int(n_cpu))
    if coord:
        multihost.initialize(coord,
                             int(_flag("--num-processes") or 1),
                             int(_flag("--process-id") or 0))


_prescan()

from dynamo_tpu.worker.main import main  # noqa: E402

main()
