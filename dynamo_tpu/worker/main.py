"""`python -m dynamo_tpu.worker` — a backend worker process.

Reference analog: `dynamo.vllm`/`dynamo.mocker` mains — connect to the
control plane, serve the engine endpoint, `register_llm`, publish KV
events + load metrics, drain gracefully on SIGTERM (SURVEY.md §3.2).

    python -m dynamo_tpu.worker --control-plane HOST:PORT --mocker
    python -m dynamo_tpu.worker --control-plane HOST:PORT --model tiny-test
"""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal

from dynamo_tpu.llm.discovery import engine_wire_handler, register_llm
from dynamo_tpu.llm.kv_router.protocols import RouterEvent
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.runtime.contracts import never_engine_thread
from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneClient
from dynamo_tpu.runtime.distributed import DistributedRuntime

logger = logging.getLogger("dynamo_tpu.worker")

KV_EVENTS_SUBJECT = "kv_events"        # reference kv_router.rs:56
METRICS_SUBJECT = "load_metrics"       # reference stats endpoint name


def parse_args(argv=None):
    from dynamo_tpu.runtime.config import (
        apply_to_parser_defaults, load_layered_config)

    p = argparse.ArgumentParser(
        "dynamo_tpu.worker",
        description="Layered config: defaults < dynamo.toml [worker] "
                    "section < DYN_* env < these flags "
                    "(runtime/config.py).")
    p.add_argument("--control-plane", default=None,
                   help="control plane HOST:PORT")
    p.add_argument("--namespace", default="dynamo")
    p.add_argument("--component", default="backend")
    p.add_argument("--endpoint", default="generate")
    p.add_argument("--model-name", default="dynamo-tpu")
    p.add_argument("--role", choices=("both", "prefill", "decode", "encode"),
                   default="both",
                   help="disaggregated P/D role: 'prefill' serves the "
                        "prefill queue only (no model registration); "
                        "'decode' registers the model and sends long "
                        "prompts to the prefill queue; 'both' = aggregated; "
                        "'encode' serves the multimodal vision tower "
                        "(encoder/encode endpoint, reference "
                        "multimodal_v1 encode_worker)")
    p.add_argument("--max-local-prefill", type=int, default=None,
                   help="decode role: write the disagg threshold (tokens) "
                        "to the control plane at startup; prompts longer "
                        "than this prefill remotely.  The key is watched, "
                        "so operators can retune it live.")
    p.add_argument("--no-eager-kv", action="store_true",
                   help="decode role: disable eager KV-block streaming "
                        "(pull the whole sealed prefix only after the "
                        "prefill-done announcement, the pre-streaming "
                        "serial protocol)")
    p.add_argument("--no-prefix-share", action="store_true",
                   help="disable fleet-wide prefix reuse: ignore the "
                        "router's remote-prefix hints instead of pulling "
                        "a peer's sealed prefix blocks before prefill "
                        "(this worker still serves kv_blocks as a donor)")
    p.add_argument("--mocker", action="store_true")
    p.add_argument("--model", default=None,
                   help="model preset name (random weights) or HF-layout "
                        "checkpoint directory (real weights + tokenizer)")
    p.add_argument("--num-blocks", type=int, default=512)
    p.add_argument("--block-size", type=int, default=64)
    p.add_argument("--max-prefill-chunk", type=int, default=512,
                   help="chunked-prefill step ceiling (tokens).  Prefill "
                        "workers seal + announce blocks per chunk, so "
                        "smaller chunks mean finer-grained eager KV "
                        "streaming at the cost of more prefill steps")
    # Declarative slice spec (ISSUE 16, fleet/topology.py): ONE string
    # naming the worker's mesh, KV mode, role and plane features —
    # expanded over the loose flags below after parsing, published in
    # the instance record, and consumed by make_sharded_step via the
    # same EngineConfig path.  The loose flags keep working; --slice is
    # the form the planner's role_worker_args and deploy tooling emit.
    p.add_argument("--slice", default=None, metavar="SPEC",
                   help="declarative slice spec, e.g. "
                        "'sp2xtp2,int8,packed,role=prefill' or "
                        "'tp2,int8,role=decode' — mesh descriptor + kv "
                        "mode + role + features (packed/spec/windowN/"
                        "dp_attention); overrides the corresponding "
                        "--tp/--sp/--pp/--kv-quant/--role flags")
    # Parallelism as a serving capability (reference: one-flag TP,
    # `components/backends/sglang/launch/disagg.sh:25`): degrees multiply
    # to the device count; the worker builds the mesh and the engine
    # shards params/cache/step over it.
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel degree (heads/features over ICI)")
    p.add_argument("--dp", type=int, default=1,
                   help="engine-internal data-parallel degree (batch axis)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel degree (MoE models)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel degree: whole-prompt prefills "
                        "past the engine's threshold run ring attention "
                        "over the ICI ring (the long-context prefill "
                        "path); decode stays on the tp/dp plane")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel degree (GPipe stage-rotated "
                        "step).  Decode rides the fused stage programs "
                        "(all-in-one greedy step + schedule-looping "
                        "windows) and --kv-quant composes via stacked "
                        "scale buffers; the remaining impossible combos "
                        "(spec decode, multimodal embeds, "
                        "/v1/embeddings) reject with the capability "
                        "table's pointed errors")
    p.add_argument("--pp-microbatches", type=int, default=2,
                   help="GPipe microbatch count for the pp stage "
                        "schedule (batch rows pad to a multiple of it)")
    p.add_argument("--dp-attention", action="store_true",
                   help="batch-sharded attention with slot-sharded KV "
                        "(tp beyond the kv-head count; reference sglang "
                        "--enable-dp-attention)")
    # Multi-host: one EngineCore spanning N processes (SPMD lockstep,
    # parallel/multihost.py; reference srun_disaggregated.sh / LWS
    # multinode).  All ranks take IDENTICAL flags; rank 0 serves, ranks
    # 1..N-1 follow.  --coordinator/--num-processes/--process-id are
    # consumed by worker/__main__.py BEFORE jax init.
    p.add_argument("--coordinator", default=None,
                   help="jax.distributed coordinator HOST:PORT "
                        "(multihost; all ranks pass the same value)")
    p.add_argument("--num-processes", type=int, default=1)
    p.add_argument("--process-id", type=int, default=0)
    p.add_argument("--lockstep", default=None,
                   help="leader's lockstep channel HOST:PORT (followers "
                        "connect; the leader binds the PORT part)")
    p.add_argument("--multihost-cpu-devices", type=int, default=0,
                   help="CPU test rig: force N virtual CPU devices + "
                        "gloo collectives in this process")
    p.add_argument("--decode-window", type=int, default=8,
                   help="fused decode window length (1 disables)")
    p.add_argument("--kv-quant", choices=("none", "int8"), default="none",
                   help="KV-cache storage mode: 'int8' stores pages as "
                        "int8 with per-token-per-head f32 scales and "
                        "dequantizes inside the decode kernel — ~0.53x "
                        "the HBM bytes per context token at serving "
                        "geometry.  Composes with every mesh (tp/dp/"
                        "dp-attention/sp/pp/multihost — ISSUE 12); "
                        "prefill and decode workers of one disagg pair "
                        "must match (mismatched peers refuse block "
                        "transfer loudly)")
    p.add_argument("--moe-mode",
                   choices=("auto", "dense", "grouped", "dispatch"),
                   default="auto",
                   help="MoE compute mode (dense models ignore it): "
                        "'auto' picks the grouped Pallas kernel on "
                        "meshless TPU engines and ep all-to-all dispatch "
                        "on ep>1 meshes; explicit rungs pin one — "
                        "'grouped' is meshless-only, 'dispatch' needs an "
                        "ep mesh (tp>1 composes: expert MLPs tp-shard "
                        "inside the dispatch body)")
    p.add_argument("--moe-capacity", type=int, default=None, metavar="C",
                   help="bounded per-expert dispatch capacity (tokens "
                        "per expert per source shard).  Default None = "
                        "EXACT routing, nothing dropped.  A bound "
                        "shrinks the all-to-all buffers; overflow "
                        "assignments are DROPPED and counted in "
                        "dynamo_moe_dropped_tokens_total, never silent")
    p.add_argument("--spec-decode", type=int, default=0, metavar="K",
                   help="self-speculative decoding: draft K tokens per "
                        "decode step (prompt-lookup n-gram drafter) and "
                        "verify them in one batched forward.  Greedy "
                        "output is byte-identical to K=0; stochastic "
                        "requests keep their exact sampling distribution "
                        "(rejection-sampling fallback).  0 disables")
    p.add_argument("--spec-ngram", type=int, default=3,
                   help="n-gram length for the prompt-lookup drafter")
    p.add_argument("--packed-prefill", choices=("auto", "on", "off"),
                   default="auto",
                   help="packed ragged prefill plane: chunks pack into "
                        "one flat token axis with per-segment block "
                        "tables and attention streams pages from the "
                        "pool via the Pallas flash-prefill kernel.  "
                        "'auto' = on for TPU meshless engines (MoE "
                        "included) whose geometry passes the Mosaic "
                        "eligibility rule; 'on' forces it (interpret "
                        "mode off-TPU); 'off' keeps the padded gather "
                        "plane")
    p.add_argument("--prewarm-prefill", action="store_true",
                   help="compile the packed prefill shape set at "
                        "startup (through the persistent XLA compile "
                        "cache) so the first request's TTFT doesn't pay "
                        "the cold-prefill compile cliff; no-op when the "
                        "packed plane is off")
    p.add_argument("--speedup-ratio", type=float, default=10.0)
    p.add_argument("--metrics-interval", type=float, default=1.0)
    p.add_argument("--health-port", type=int, default=0,
                   help="per-worker status server port (0 = ephemeral; "
                        "-1 disables; reference system_status_server.rs)")
    p.add_argument("--hbm-poll-interval", type=float, default=10.0,
                   help="seconds between HBM occupancy polls "
                        "(jax device memory_stats; CPU backends fall "
                        "back to process RSS).  0 disables the poller.")
    p.add_argument("--rpc-host", default="127.0.0.1",
                   help="bind + ADVERTISED host for this worker's RPC "
                        "server; cross-host deployments must set a "
                        "routable address (K8s manifests inject the pod "
                        "IP) — the 127.0.0.1 default only works "
                        "single-host")
    p.add_argument("--drain", choices=("on", "off"), default="on",
                   help="SIGTERM drain with live KV migration (ISSUE "
                        "15): leave routing instantly, hand each "
                        "in-flight stream to a peer WITH its sealed KV "
                        "(migrate delta + kv_blocks pull), linger for "
                        "the peers' pulls, then exit.  'off' restores "
                        "the wait-out-every-stream SIGTERM.  The "
                        "control-plane key drain/<pid> (or "
                        "drain/instance/<id>) triggers the same drain "
                        "without a signal")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="bound on each drain phase (stream handoff; "
                        "peer KV pulls): past it the worker exits "
                        "anyway — peers fall back to re-prefill, "
                        "requests still survive")
    p.add_argument("--drain-linger-s", type=float, default=1.0,
                   help="grace after the last stream handoff for peers "
                        "to OPEN their KV pulls before the worker "
                        "starts watching for zero active streams")
    from dynamo_tpu.runtime.device_profiler import add_device_profiler_args
    from dynamo_tpu.runtime.flight_recorder import add_flight_args
    from dynamo_tpu.runtime.ledger import add_ledger_args
    from dynamo_tpu.runtime.slo import add_slo_args
    from dynamo_tpu.runtime.tracing import add_trace_args

    add_trace_args(p)
    add_slo_args(p)
    add_flight_args(p)
    add_ledger_args(p)
    add_device_profiler_args(p)
    apply_to_parser_defaults(p, load_layered_config(
        {"control_plane": None, "namespace": "dynamo",
         "component": "backend", "endpoint": "generate",
         "model_name": "dynamo-tpu", "num_blocks": 512, "block_size": 64,
         "metrics_interval": 1.0},
        section="worker"))
    args = p.parse_args(argv)
    if args.slice:
        try:
            _apply_slice_spec(args)
        except ValueError as e:
            p.error(str(e))
    if not args.control_plane and args.process_id == 0:
        p.error("--control-plane is required (flag, DYN_CONTROL_PLANE, "
                "or dynamo.toml)")
    return args


def _apply_slice_spec(args) -> None:
    """Expand `--slice` over the loose mesh/plane flags — the ONE
    declarative source the engine config, the published instance record
    and the planner's per-role spawn all agree on."""
    from dynamo_tpu.fleet.topology import parse_slice

    spec = parse_slice(args.slice)
    args.dp, args.pp, args.sp, args.ep, args.tp = spec.mesh
    args.role = spec.role
    args.kv_quant = spec.kv_quant if spec.kv_quant != "none" else "none"
    feats = set(spec.features)
    if "packed_prefill" in feats:
        args.packed_prefill = "on"
    if "dp_attention" in feats:
        args.dp_attention = True
    if "spec" in feats and getattr(args, "spec_decode", 0) <= 0:
        args.spec_decode = 3
    for f in feats:
        if f.startswith("window"):
            args.decode_window = int(f[len("window"):])


def derive_slice_spec(args, fabric: str = ""):
    """The SliceSpec this worker PUBLISHES (instance record metadata +
    status registration): mesh degrees, role, kv mode and plane features
    from the resolved flags, per-chip HBM probed from the runtime (0
    when the backend reports none — CPU rigs), and the device-fabric id
    the transfer plane answers on."""
    from dynamo_tpu.fleet.topology import SliceSpec

    feats = []
    if getattr(args, "packed_prefill", "auto") == "on":
        feats.append("packed_prefill")
    if getattr(args, "dp_attention", False):
        feats.append("dp_attention")
    if getattr(args, "spec_decode", 0) > 0:
        feats.append("spec")
    if getattr(args, "decode_window", 1) > 1:
        feats.append(f"window{args.decode_window}")
    hbm = 0
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        hbm = int(stats.get("bytes_limit", 0))
    except Exception:
        hbm = 0  # backend without memory_stats (CPU rig): unknown
    return SliceSpec(
        mesh=(args.dp, getattr(args, "pp", 1), getattr(args, "sp", 1),
              args.ep, args.tp),
        role=args.role,
        kv_quant=getattr(args, "kv_quant", "none"),
        features=tuple(feats),
        hbm_per_chip_bytes=hbm,
        fabric=fabric)


def build_mesh(args):
    """Mesh from the parallelism flags (tp/dp/ep/sp/pp — MeshConfig's
    full axis set; ISSUE 9 satellite: sp-ring prefill and pp pipelines
    were dry-run-proven but unreachable from a real worker because only
    tp/dp/ep were read here).  Under multihost the degrees MUST span
    every process's chips — a prefix-sliced mesh that happens to fit
    one rank's devices would leave follower ranks shadowing computations
    on devices they can't address (and the lockstep channel pure
    overhead)."""
    sp = getattr(args, "sp", 1)
    pp = getattr(args, "pp", 1)
    if args.tp * args.dp * args.ep * sp * pp <= 1:
        if args.num_processes > 1:
            raise SystemExit(
                "--num-processes > 1 needs parallelism degrees that span "
                "the cluster (tp*dp*ep*sp*pp > 1); a meshless engine is "
                "process-local by construction")
        return None
    import jax

    from dynamo_tpu.parallel import MeshConfig, make_mesh

    mesh_cfg = MeshConfig(dp=args.dp, pp=pp, sp=sp, ep=args.ep,
                          tp=args.tp)
    devices = jax.devices()
    if mesh_cfg.size > len(devices):
        raise SystemExit(
            f"mesh {mesh_cfg.describe()} needs {mesh_cfg.size} devices; "
            f"{'the cluster' if args.num_processes > 1 else 'this host'} "
            f"has {len(devices)}")
    if mesh_cfg.size < len(devices):
        logger.warning(
            "mesh %s uses %d of %d devices; the rest idle "
            "(run more workers or raise --dp)",
            mesh_cfg.describe(), mesh_cfg.size, len(devices))
    mesh = make_mesh(mesh_cfg, devices[:mesh_cfg.size])
    if args.num_processes > 1:
        from dynamo_tpu.parallel.multihost import mesh_spans_processes

        if not mesh_spans_processes(mesh):
            raise SystemExit(
                f"mesh {mesh_cfg.describe()} fits rank 0's devices alone; "
                "multihost requires degrees that span all "
                f"{args.num_processes} processes' chips (raise --tp/--dp)")
    return mesh


def run_follower_rank(args) -> None:
    """Ranks 1..N-1 of a multihost worker: build the identical shadow
    EngineCore and replay the leader's lockstep command stream
    (parallel/multihost.py; the srun-rank analog)."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models.loader import resolve_model
    from dynamo_tpu.parallel.multihost import LockstepFollower, run_follower

    if args.mocker:
        raise SystemExit("--mocker has no multihost mode (no device state "
                         "to span processes)")
    if not args.lockstep:
        raise SystemExit("follower ranks need --lockstep HOST:PORT")
    cfg, params, _tok, _tpl = resolve_model(args.model or "llama-3-1b")
    if getattr(args, "moe_capacity", None) is not None:
        cfg = cfg.replace(moe_capacity=args.moe_capacity)
    core = EngineCore(
        EngineConfig(model=cfg,
                     num_blocks=args.num_blocks,
                     mesh=build_mesh(args),
                     dp_attention=args.dp_attention,
                     decode_window=args.decode_window,
                     # The shadow engine must derive the SAME compiled
                     # programs as the leader: cache mode and microbatch
                     # count are part of that identity (ISSUE 12 leg 4 —
                     # a follower without kv_quant would build a bf16
                     # cache and diverge on the first quantized step).
                     # MoE mode and capacity too (ISSUE 17): a follower
                     # resolving a different dispatch ladder rung would
                     # shadow a different compiled step.
                     moe_mode=getattr(args, "moe_mode", "auto"),
                     kv_quant=getattr(args, "kv_quant", "none"),
                     pp_microbatches=getattr(args, "pp_microbatches", 2),
                     scheduler=SchedulerConfig(
                         block_size=args.block_size,
                         max_prefill_chunk=args.max_prefill_chunk)),
        params=params)
    host, port = _split(args.lockstep)
    chan = LockstepFollower(host, port)
    print(f"worker rank {args.process_id}/{args.num_processes} following "
          f"lockstep at {args.lockstep}", flush=True)
    run_follower(core, chan)


async def build_engine(args, kv_event_sink):
    """Returns (engine_client, metrics_fn, shutdown, card_fields,
    transfer_engine) — transfer_engine serves the kv_blocks data plane
    (None for the mocker, which has no real KV bytes)."""
    if args.mocker:
        from dynamo_tpu.llm.mocker import MockEngine, MockEngineArgs

        engine = MockEngine(
            MockEngineArgs(block_size=args.block_size,
                           speedup_ratio=args.speedup_ratio),
            kv_event_sink=kv_event_sink)
        await engine.start()
        return engine, (lambda: engine.metrics), engine.stop, {}, None

    from dynamo_tpu.engine.engine import (
        EngineConfig, EngineCore, InferenceEngine)
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.llm.service import LocalEngineClient
    from dynamo_tpu.models.loader import resolve_model

    cfg, params, tok_spec, template = resolve_model(
        args.model or "llama-3-1b")
    if getattr(args, "moe_capacity", None) is not None:
        # Capacity is a model-level dispatch knob (ModelConfig) so every
        # compiled step sees it; the flag is the deployment's explicit
        # exactness/buffer-size trade (drops are counted, never silent).
        cfg = cfg.replace(moe_capacity=args.moe_capacity)
    mesh = build_mesh(args)
    core = EngineCore(
        EngineConfig(model=cfg,
                     num_blocks=args.num_blocks,
                     mesh=mesh,
                     dp_attention=args.dp_attention,
                     decode_window=args.decode_window,
                     moe_mode=getattr(args, "moe_mode", "auto"),
                     kv_quant=getattr(args, "kv_quant", "none"),
                     pp_microbatches=getattr(args, "pp_microbatches", 2),
                     speculative_tokens=getattr(args, "spec_decode", 0),
                     speculative_ngram=getattr(args, "spec_ngram", 3),
                     packed_prefill={"auto": None, "on": True,
                                     "off": False}[
                         getattr(args, "packed_prefill", "auto")],
                     scheduler=SchedulerConfig(
                         block_size=args.block_size,
                         max_prefill_chunk=args.max_prefill_chunk)),
        params=params,
        kv_event_sink=kv_event_sink)
    if getattr(args, "prewarm_prefill", False):
        # Before the step-loop thread exists the constructing thread
        # owns the core, so the prewarm compiles run here and the first
        # request finds every packed shape in the jit cache.
        n_shapes = core.prewarm_prefill()
        print(f"prewarmed {n_shapes} packed prefill shapes", flush=True)
    engine = InferenceEngine(core)
    await engine.start()
    card_fields = {
        "tokenizer_spec": tok_spec,
        "chat_template": template,
        "max_context": cfg.max_context,
    }
    return LocalEngineClient(engine), (lambda: core.metrics), engine.stop, \
        card_fields, engine


async def run_encode(args, cp, runtime) -> None:
    """The encode-worker role: vision tower behind `encoder/encode` (no
    LLM engine, no model registration; reference
    `examples/multimodal_v1/components/encode_worker.py`)."""
    from dynamo_tpu.llm.multimodal import EncodeWorker, StubVisionEncoder
    from dynamo_tpu.models import config as mcfg

    try:
        hidden = mcfg.get_config(args.model or "llama-3-1b").hidden_size
    except Exception:
        hidden = 2048  # checkpoint-dir models: pass the preset via --model
    worker = EncodeWorker(StubVisionEncoder(hidden))
    endpoint = (runtime.namespace(args.namespace)
                .component("encoder").endpoint("encode"))
    instance = await endpoint.serve(worker.make_handler())
    print(f"encode worker instance {instance.instance_id} at "
          f"{instance.address} (hidden={hidden})", flush=True)
    stop_ev = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop_ev.set)
    await stop_ev.wait()
    await endpoint.leave()
    await runtime.shutdown()
    await cp.close()


async def run(args) -> None:
    from dynamo_tpu import native
    from dynamo_tpu.runtime import flight_recorder
    from dynamo_tpu.runtime.tracing import configure_from_args

    configure_from_args(args, service=f"worker-{args.component}")
    # Flight recorder: the worker's black box (ISSUE 14).  Configured
    # before anything serves so startup compiles/admissions land in the
    # ring; crash triggers (faulthandler, atexit, SIGUSR2) armed here on
    # the main thread.
    recorder = flight_recorder.configure_from_args(
        args, service=f"worker-{args.component}")
    recorder.install_crash_dump()
    # Device-truth plane (ISSUE 20): the XLA cost-analysis harvest must
    # be live BEFORE the engine builds — prewarmed prefill shapes and
    # startup compiles are first-seen exactly once and must land in the
    # program registry.  Captures write next to the flight dumps.
    from dynamo_tpu.runtime import device_profiler

    device_profiler.configure_from_args(
        args, service=f"worker-{args.component}")
    # Request ledger (ISSUE 18): hop ledgers only start when BOTH this
    # switch is on AND the incoming request carries the frontend's
    # ledger annotation.
    from dynamo_tpu.runtime import ledger as ledger_mod

    ledger_mod.configure_from_args(args)
    await native.warmup()  # build the C++ hasher off the event loop
    cp = ControlPlaneClient(*_split(args.control_plane))
    await cp.start()
    runtime = DistributedRuntime(cp, rpc_host=args.rpc_host)
    if args.role == "encode":
        await run_encode(args, cp, runtime)
        return
    # Prefill workers live under their own component so the frontend's
    # per-model clients (which watch the decode endpoint's instance
    # prefix) never route decode traffic to them — the reference's
    # separate prefill component (disagg_serving.md:62-64).
    component = (f"{args.component}-prefill" if args.role == "prefill"
                 else args.component)
    endpoint = (runtime.namespace(args.namespace)
                .component(component).endpoint(args.endpoint))

    loop = asyncio.get_running_loop()
    pending_events: list = []

    def kv_event_sink(event):
        # Engine threads may emit; hop onto the loop for the publish.
        loop.call_soon_threadsafe(pending_events.append, event)

    engine, metrics_fn, shutdown, card_fields, transfer_engine = \
        await build_engine(args, kv_event_sink)
    # Engine-thread stall watchdog (ISSUE 14): the step loop stamps a
    # heartbeat every iteration; no progress for --watchdog-stall-s
    # seconds while prefill/decode work is pending ⇒ stall event +
    # dynamo_engine_stalls_total + automatic flight-recorder dump.
    # Real engines only — the mocker has no step-loop heartbeat.
    watchdog = None
    if args.watchdog_stall_s > 0 and transfer_engine is not None:
        _wd_core = transfer_engine.core

        def _pending_work(core=_wd_core):
            # Off-thread read of live engine state; the watchdog treats
            # any exception here as "no pending work".
            return core.has_work

        watchdog = flight_recorder.StallWatchdog(
            recorder, _pending_work, stall_s=args.watchdog_stall_s)
        watchdog.start()
    lockstep = None
    if args.num_processes > 1:
        from dynamo_tpu.parallel.multihost import LockstepLeader

        if transfer_engine is None:
            raise SystemExit("--num-processes > 1 requires a real engine")
        port = (_split(args.lockstep)[1] if args.lockstep else 0)
        lockstep = LockstepLeader(port=port,
                                  num_followers=args.num_processes - 1)
        logger.info("multihost leader: lockstep on :%d, waiting for %d "
                    "follower(s)", lockstep.port, args.num_processes - 1)
        await asyncio.to_thread(lockstep.wait_for_followers)
        transfer_engine.core._lockstep = lockstep
    transfer_plane = None
    if transfer_engine is not None:
        from dynamo_tpu.llm.block_manager.transfer import (
            KV_BLOCKS_ENDPOINT, make_kv_blocks_handler)
        from dynamo_tpu.llm.discovery import (
            CLEAR_KV_ENDPOINT, EMBED_ENDPOINT, clear_kv_wire_handler,
            embed_wire_handler)

        runtime.rpc.register(KV_BLOCKS_ENDPOINT,
                             make_kv_blocks_handler(transfer_engine))
        runtime.rpc.register(EMBED_ENDPOINT, embed_wire_handler(engine))
        runtime.rpc.register(CLEAR_KV_ENDPOINT,
                             clear_kv_wire_handler(engine))
        if args.num_processes == 1:
            # Device-direct transfer plane (NIXL analog): blocks cross
            # worker↔worker device-to-device via PJRT's transfer service;
            # the host-staged kv_blocks plane stays as fallback.  Sharded
            # caches stage too: extract gathers the canonical block onto
            # device 0, the peer's inject scatters into ITS sharding —
            # so prefill tp=x → decode tp=y reshards in-flight (VERDICT
            # r4 next-5).  Multihost meshes stay host-staged (the plane
            # would need per-rank transfer servers).
            from dynamo_tpu.llm.block_manager.device_transfer import (
                KV_OFFER_ENDPOINT, KV_PULLED_ENDPOINT, KvTransferPlane,
                transfer_available)

            # ALWAYS started (ISSUE 16): start() picks the pjrt
            # transport when this jax build ships the transfer service
            # and falls back to the same-process local fabric otherwise,
            # so drain migration and prefix pulls ride the device plane
            # even on rigs without jax.experimental.transfer —
            # cross-process peers on the local fabric are refused at the
            # offer probe and fall back to the host-staged plane per
            # transfer, not per worker.
            transfer_plane = KvTransferPlane(transfer_engine)
            taddr = transfer_plane.start()
            runtime.rpc.register(KV_OFFER_ENDPOINT,
                                 transfer_plane.make_offer_handler())
            runtime.rpc.register(KV_PULLED_ENDPOINT,
                                 transfer_plane.make_pulled_handler())
            if transfer_available():
                logger.info("device transfer plane on %s (pjrt)", taddr)
            else:
                logger.info(
                    "device transfer plane on %s (local fabric: "
                    "jax.experimental.transfer not in this build; "
                    "same-process peers pull device-direct, "
                    "cross-process pulls ride the host-staged plane)",
                    taddr)

    disagg_client = None
    prefill_task = None
    if args.role != "both" and transfer_engine is None:
        # The mocker has no real KV bytes to serve or pull — disagg roles
        # are meaningless for it.  Refuse loudly rather than serve
        # aggregated while the operator believes disagg is on.
        raise SystemExit(
            f"--role {args.role} requires a real engine (the mocker has "
            "no KV data plane); drop --role or --mocker")
    # Shared worker registry: request-lifecycle histograms (disagg KV
    # transfer, RPC-boundary TTFT/TPOT), the memory-plane KvCacheMetrics
    # family, and SLO burn-rate gauges.
    from dynamo_tpu.runtime.metrics import (
        HbmPoller, KvCacheMetrics, MetricsRegistry, RequestMetrics)
    from dynamo_tpu.runtime.slo import monitor_from_args

    registry = MetricsRegistry()
    request_metrics = RequestMetrics(registry)
    kv_metrics = KvCacheMetrics(registry)
    slo_monitor = monitor_from_args(args, request_metrics,
                                    registry=registry)
    if slo_monitor is not None:
        slo_monitor.start(interval=args.slo_tick)
    # Fleet-wide prefix reuse: consume router remote-prefix hints by
    # pulling the donor's sealed blocks over the kv_blocks plane before
    # engine admission (block_manager/prefix_share.py).  INNERMOST
    # wrapper — directly in front of the local engine — so on a
    # decode-role worker the pull runs AFTER any disagg remote-prefill
    # onboard: blocks the prefill worker already delivered are locally
    # resident by then and the fetcher's residency check skips the wire
    # entirely, while a failed/local-prefill path still benefits from
    # the donor's blocks.  Every real engine also SERVES kv_blocks
    # above, so any worker is a donor.
    prefix_fetcher = None
    serve_base = engine
    if transfer_engine is not None and not args.no_prefix_share:
        from dynamo_tpu.llm.block_manager.prefix_share import (
            PrefixFetcher, PrefixShareClient)

        prefix_fetcher = PrefixFetcher(
            transfer_engine, runtime.client_for, args.block_size,
            plane=transfer_plane)
        serve_base = PrefixShareClient(engine, prefix_fetcher)

    if args.role == "decode":
        from dynamo_tpu.llm.disagg import DisaggDecodeClient, disagg_config_key

        if args.max_local_prefill is not None:
            await cp.put(disagg_config_key(args.namespace),
                         {"max_local_prefill_length": args.max_local_prefill})
        disagg_client = DisaggDecodeClient(
            serve_base, transfer_engine, cp, args.namespace, args.block_size,
            transfer_plane=transfer_plane, request_metrics=request_metrics,
            eager=not args.no_eager_kv)
        await disagg_client.start()
        serve_client = disagg_client
    else:
        serve_client = serve_base

    # SLO-aware tier demotion: while the error budget burns, hot prefix
    # blocks resist device→host→disk demotion (pool.slo_eviction_bias
    # over the monitor's cheap last_max_burn attribute).
    if slo_monitor is not None and transfer_engine is not None:
        manager = getattr(transfer_engine.core.allocator, "manager", None)
        if manager is not None:
            from dynamo_tpu.llm.block_manager.pool import slo_eviction_bias

            manager.set_eviction_bias(slo_eviction_bias(
                lambda: slo_monitor.last_max_burn))
        # QoS preemption lever (ISSUE 15 leg 3): burn >= 1 holds
        # best-effort admissions and sheds running best-effort requests
        # (their KV demotes to the host tier; resume = tier onboard).
        # NOT under multihost lockstep: follower shadow schedulers never
        # see the leader's host-local burn signal, and a pressure-driven
        # preempt only on rank 0 would diverge the SPMD batch shapes.
        if args.num_processes == 1:
            transfer_engine.core.scheduler.qos_pressure_fn = (
                lambda: slo_monitor.last_max_burn)

    # Drain wrapper (ISSUE 15): OUTERMOST serving stage so a drain
    # cancels the whole disagg/prefix-share/engine chain beneath it and
    # ends each wire stream with the KV-carrying migrate delta.
    from dynamo_tpu.llm.drain import (
        DRAIN_PREFIX, DrainableService, drain_key_instance, drain_key_pid)

    drainable = DrainableService(serve_client,
                                 block_size=args.block_size)
    # Published slice topology (ISSUE 16): the instance record carries
    # this worker's SliceSpec so the fleet brain — KvRouter donor picks,
    # QoS selector HBM scaling, planner placement — reasons about mesh
    # shape, role, kv mode and transfer-plane reachability WITHOUT any
    # new scrape path.
    slice_spec = derive_slice_spec(
        args, fabric=transfer_plane.fabric if transfer_plane else "")
    instance = await endpoint.serve(
        engine_wire_handler(drainable, request_metrics=request_metrics),
        metadata={"slice": slice_spec.to_dict()})
    if transfer_engine is not None:
        # Peers pull the handed-off KV from this worker's kv_blocks
        # endpoint — the instance address IS the donor descriptor.
        drainable.kv_address = instance.address
    # (Transfer-plane discovery needs no control-plane record: the peer's
    # RPC address is already the instance record, and the per-transfer
    # descriptor — uuid + transfer address — travels in the kv_offer
    # reply, the NIXL-metadata analog.)
    if args.role == "prefill":
        # Prefill workers serve the queue, not the routed model: no
        # register_llm, so frontends never route decode traffic here
        # (reference prefill workers register under their own component,
        # disagg_serving.md:62-64).
        from dynamo_tpu.llm.disagg import prefill_worker_loop

        prefill_task = asyncio.create_task(prefill_worker_loop(
            cp, args.namespace, engine, instance.address))
    else:
        card = ModelDeploymentCard(name=args.model_name,
                                   kv_block_size=args.block_size,
                                   **card_fields)
        await register_llm(endpoint, instance, card)
    status = None
    hbm_poller = None
    status_reg_task = None
    if args.health_port >= 0:
        from dynamo_tpu.runtime.status import (
            StatusServer, register_status_endpoint_task)

        @never_engine_thread
        def worker_metrics_text() -> str:
            m = metrics_fn()
            ws, ks = m.worker_stats, m.kv_stats
            lines = [
                f"dynamo_worker_request_active_slots {ws.request_active_slots}",
                f"dynamo_worker_requests_waiting {ws.num_requests_waiting}",
                f"dynamo_worker_kv_active_blocks {ks.kv_active_blocks}",
                f"dynamo_worker_kv_usage {ks.gpu_cache_usage_perc}",
                "dynamo_worker_kv_prefix_cache_hit_rate "
                f"{ks.gpu_prefix_cache_hit_rate}",
            ]
            if m.expert_load:
                # MoE telemetry (ISSUE 17): per-expert assignment
                # distribution plus the capacity-honesty drop counter
                # (0 forever at the exact-capacity serving default).
                for e, n in enumerate(m.expert_load):
                    lines.append(
                        f'dynamo_moe_expert_load{{expert="{e}"}} {n}')
                lines.append("dynamo_moe_dropped_tokens_total "
                             f"{m.moe_dropped_tokens}")
            # Serving-loop overhead counters (EngineStepCounters) —
            # host syncs / compiled-shape cache misses per dispatch
            # class; mocker-backed workers have no core and skip this.
            core = getattr(getattr(engine, "_engine", None), "core", None)
            counters = getattr(core, "counters", None)
            if counters is not None:
                for k, v in counters.to_dict().items():
                    lines.append(f"dynamo_worker_engine_{k} {v}")
            # Flight-recorder / stall-watchdog series (ISSUE 14): the
            # step-loop heartbeat age feeds `dynamo top`'s AGE/STL
            # column; the stall counter is the chaos-era "worker wedged
            # under load" alarm.
            age = recorder.last_step_age_s()
            if age is not None:
                lines.append(
                    f"dynamo_engine_last_step_age_seconds {age:.3f}")
            lines.append(f"dynamo_engine_stalls_total {recorder.stalls}")
            lines.append("dynamo_engine_stalled "
                         f"{1 if watchdog is not None and watchdog.stalled else 0}")
            # Elasticity / QoS plane (ISSUE 15): feeds `dynamo top`'s
            # QOS/DRN column and the chaos-test oracles.
            lines.append("dynamo_requests_migrated_total "
                         f"{drainable.migrated_out}")
            lines.append("dynamo_worker_draining "
                         f"{1 if drainable.draining else 0}")
            if core is not None:
                lines.append("dynamo_qos_preemptions_total "
                             f"{core.scheduler.qos_preemptions}")
                lines.append("dynamo_qos_demoted_blocks_total "
                             f"{core.qos_demoted_blocks}")
            if prefix_fetcher is not None:
                lines.append("dynamo_requests_migrated_in_total "
                             f"{prefix_fetcher.migrated_in}")
            # Memory-plane sample at scrape time: pool occupancy /
            # eviction / prefix-hit series land in the shared registry.
            # Runs on the status server's event loop (host ints only),
            # never the engine thread.
            if core is not None:
                kv_metrics.observe_engine(core)
            if prefix_fetcher is not None:
                kv_metrics.observe_prefix_share(prefix_fetcher)
            # Plane-choice tallies (device vs host, with fallback
            # reasons): a fleet silently degraded to host staging shows
            # up here and in `dynamo top`'s PLANE column.
            kv_metrics.observe_transfer_plane()
            # Device-truth plane (ISSUE 20): fold modeled counters
            # against the XLA cost registry at scrape time (host floats
            # only — the engine thread never participates), then export
            # the program registry + drift ratios.
            prof = device_profiler.get_profiler()
            if prof.enabled:
                if core is not None:
                    prof.audit_engine(core)
                lines.extend(prof.metrics_lines())
            return "\n".join(lines) + "\n"

        status = StatusServer(
            registry=registry, extra_text_fn=worker_metrics_text,
            slo_fn=(slo_monitor.payload if slo_monitor is not None
                    else None))
        hport = await status.start(host=args.rpc_host,
                                   port=args.health_port)
        # Advertise for fleet discovery: metrics_aggregator scrapes it,
        # `dynamo top` renders it.  Best-effort with retry — a control
        # plane mid-restart must not crash the worker.
        status_reg_task = register_status_endpoint_task(
            cp, f"worker-{args.role}", hport, host=args.rpc_host,
            extra={"mesh": slice_spec.describe(),
                   "slice": slice_spec.to_dict()})
        if args.hbm_poll_interval > 0:
            hbm_poller = HbmPoller(kv_metrics,
                                   interval=args.hbm_poll_interval)
            hbm_poller.start()
        print(f"worker status server on :{hport}", flush=True)
    print(f"worker instance {instance.instance_id} role={args.role} "
          f"serving {args.model_name!r} at {instance.address}", flush=True)

    async def pump_events():
        while True:
            await asyncio.sleep(0.02)
            while pending_events:
                ev = pending_events.pop(0)
                await cp.publish(KV_EVENTS_SUBJECT, RouterEvent(
                    worker_id=instance.instance_id, event=ev).to_dict())

    async def pump_metrics():
        while True:
            await asyncio.sleep(args.metrics_interval)
            m = metrics_fn()
            await cp.publish(METRICS_SUBJECT, {
                "worker_id": instance.instance_id,
                "metrics": m.to_dict()})

    pumps = [asyncio.create_task(pump_events()),
             asyncio.create_task(pump_metrics())]

    stop_ev = asyncio.Event()
    drain_started = [False]

    async def start_drain(reason: str) -> None:
        """Planned drain (ISSUE 15): leave routing, hand every in-flight
        stream to a peer with its KV, linger for the peers' pulls, then
        let the normal shutdown path run.  Idempotent — a SIGTERM racing
        a control-plane drain command drains once."""
        if drain_started[0]:
            return
        drain_started[0] = True
        try:
            logger.warning("drain (%s): leaving routing, handing off %d "
                           "in-flight stream(s)", reason,
                           drainable.active_requests)
            await endpoint.leave()      # instant removal from routing
            await drainable.drain(args.drain_timeout_s)
            if drainable.migrated_out and transfer_engine is not None:
                # Handed-off KV only moves if the peers' kv_blocks pulls
                # get to run: give them a beat to open their streams,
                # then wait (bounded) until the RPC plane goes quiet.
                await asyncio.sleep(max(0.0, args.drain_linger_s))
                deadline = loop.time() + max(0.0, args.drain_timeout_s)
                while runtime.rpc.active_streams > 0 \
                        and loop.time() < deadline:
                    await asyncio.sleep(0.05)
            logger.info("drain complete: %d stream(s) migrated out",
                        drainable.migrated_out)
        except Exception:
            # A drain that trips over a dead control plane must still
            # END the worker — a latched drain_started with no stop_ev
            # would make every later SIGTERM inert until the connector
            # escalates to SIGKILL (dropping the KV this path exists to
            # save).
            logger.exception("drain (%s) failed; shutting down anyway",
                             reason)
        finally:
            stop_ev.set()

    def on_sigterm():
        if args.drain == "off":
            stop_ev.set()
        else:
            asyncio.ensure_future(start_drain("sigterm"))

    loop.add_signal_handler(signal.SIGINT, stop_ev.set)
    loop.add_signal_handler(signal.SIGTERM, on_sigterm)

    async def watch_drain_commands():
        """The control-plane `drain` command: a put under drain/<pid> or
        drain/instance/<id> drains this worker exactly like SIGTERM —
        the operator/planner surface for boxes where signals don't reach
        (containers, remote hosts)."""
        import os as _os

        mine = {drain_key_pid(_os.getpid()),
                drain_key_instance(instance.instance_id)}
        try:
            watch = await cp.watch_prefix(DRAIN_PREFIX)
            async for ev in watch:
                if ev.kind == "put" and ev.key in mine:
                    logger.warning("control-plane drain command: %s",
                                   ev.key)
                    await start_drain("control_plane")
                    return
        except (ConnectionError, asyncio.CancelledError):
            return  # cp gone / shutdown: the SIGTERM path still drains

    drain_watch = (asyncio.create_task(watch_drain_commands())
                   if args.drain != "off" else None)

    async def watch_profile_commands():
        """The control-plane `profile` command: a put under
        profile/<pid> or profile/instance/<id> runs one bounded device
        capture on this worker (value: capture ms, default 500) — the
        operator surface for boxes where /debug/deviceprofile isn't
        reachable.  Loops: one worker serves many captures."""
        import os as _os

        from dynamo_tpu.runtime.device_profiler import (
            PROFILE_PREFIX, profile_key_instance, profile_key_pid)

        mine = {profile_key_pid(_os.getpid()),
                profile_key_instance(instance.instance_id)}
        prof = device_profiler.get_profiler()
        try:
            watch = await cp.watch_prefix(PROFILE_PREFIX)
            async for ev in watch:
                if ev.kind != "put" or ev.key not in mine:
                    continue
                try:
                    ms = int(ev.value)
                except (TypeError, ValueError):
                    ms = 500
                logger.warning("control-plane profile command: %s "
                               "(%d ms)", ev.key, ms)
                # to_thread: the capture sleeps for its bound; the
                # worker's event loop must keep serving under it.
                res = await asyncio.to_thread(prof.capture, ms)
                logger.warning("device capture result: %s",
                               {k: res.get(k)
                                for k in ("ok", "dir", "error")})
        except (ConnectionError, asyncio.CancelledError):
            return  # cp gone / shutdown: /debug/deviceprofile remains

    profile_watch = (asyncio.create_task(watch_profile_commands())
                     if device_profiler.get_profiler().enabled else None)
    await stop_ev.wait()

    # Graceful drain: leave routing instantly, finish in-flight streams
    # (already done — and bounded — when start_drain ran).
    if drain_watch is not None:
        drain_watch.cancel()
    if profile_watch is not None:
        profile_watch.cancel()
    await endpoint.leave()
    stream_deadline = loop.time() + max(5.0, args.drain_timeout_s)
    while runtime.rpc.active_streams > 0 and loop.time() < stream_deadline:
        await asyncio.sleep(0.05)
    for t in pumps:
        t.cancel()
    if prefill_task:
        prefill_task.cancel()
    if disagg_client is not None:
        await disagg_client.stop()
    if status_reg_task is not None:
        status_reg_task.cancel()
    if watchdog is not None:
        watchdog.stop()
    if hbm_poller is not None:
        hbm_poller.stop()
    if slo_monitor is not None:
        await slo_monitor.stop()
    if status is not None:
        await status.stop()
    await shutdown()
    if lockstep is not None:
        lockstep.close()  # broadcasts "stop"; follower ranks exit
    await runtime.shutdown()
    await cp.close()


def _split(addr: str):
    host, port = addr.rsplit(":", 1)
    return host, int(port)


def main(argv=None) -> None:
    logging.basicConfig(level=logging.INFO)
    args = parse_args(argv)
    if args.num_processes > 1 and args.process_id > 0:
        run_follower_rank(args)   # ranks 1..N-1: shadow engine, no serving
        return
    asyncio.run(run(args))


if __name__ == "__main__":
    main()
