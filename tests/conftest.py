"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's GPU-free test strategy (SURVEY.md §4): all
distributed-sharding tests run on `--xla_force_host_platform_device_count=8`
CPU devices, so CI needs no TPU.  Must run before any `import jax`.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
