"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's GPU-free test strategy (SURVEY.md §4): all
distributed-sharding tests run on `--xla_force_host_platform_device_count=8`
CPU devices, so CI needs no TPU.  Must run before any `import jax`.
"""

import os

# Debug-mode thread-affinity contracts (runtime/contracts.py): the
# decorators on EngineCore step/seal/export internals, the block-manager
# entry points, SloMonitor.tick and KvCacheMetrics sampling assert
# caller-thread identity for the whole suite.  Must be set before any
# dynamo_tpu import — decoration reads the env var at import time (the
# zero-cost-off guarantee).  Respect an explicit =0 so the pinned
# counter tests can be re-run contracts-off for A/B.
os.environ.setdefault("DYNAMO_CONTRACTS", "1")

# The ambient environment may pin JAX to the real TPU (e.g. the "axon"
# plugin, which ignores JAX_PLATFORMS=cpu), but the test suite must stay on
# the virtual CPU mesh — single-chip hardware can't host the 8-way sharding
# tests and TPU compiles would dominate test wall-time.  XLA_FLAGS must be
# set before jax import; jax_platforms must be forced via jax.config (the
# env var alone loses to the TPU plugin).
import re

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "",
    os.environ.get("XLA_FLAGS", ""),
)
os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()

# Persistent XLA compilation cache (same discipline as bench.py): the
# suite builds hundreds of EngineCore instances whose jitted steps lower
# to IDENTICAL HLO, and each new jax.jit instance recompiles it —
# backend-compile dedupe via the disk cache cuts suite wall-time ~35%
# even within one cold run (and more when the driver re-runs tier-1 in
# the same container).  Keys on HLO hash, so test semantics are
# untouched; engine-side counters (EngineStepCounters.xla_cache_misses)
# count traced shapes, not backend compiles, and are unaffected.
try:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                     "/tmp/dynamo_tpu_test_xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
except Exception:
    pass  # older jax without the knobs: run uncached


# -- thread-leak guard -----------------------------------------------------
# Non-daemon threads that outlive their test accumulate silently across
# the suite (an unstopped HbmPoller would be daemon, but kv-offload /
# kv-window-fetch ThreadPoolExecutor workers are NOT) and can wedge
# interpreter exit.  Cheap session-scoped check: compare the non-daemon
# census at session start and end; fail loudly — with names — above an
# allowance that covers executor workers parked until their pool is
# garbage-collected.

import gc  # noqa: E402
import threading  # noqa: E402
import time as _time  # noqa: E402

import pytest  # noqa: E402

# Idle ThreadPoolExecutor workers exit only when their executor is
# collected (weakref wakeup), so the census depends on GC timing; the
# allowance absorbs that churn while still catching a real per-test
# leak (which grows with the test count, not the pool count).
THREAD_LEAK_ALLOWANCE = int(os.environ.get("DYNAMO_THREAD_LEAK_MAX", "24"))


@pytest.fixture(autouse=True, scope="session")
def _thread_leak_guard():
    baseline = {t.ident for t in threading.enumerate() if not t.daemon}
    yield
    gc.collect()  # release executor threads owned by dead engines
    deadline = _time.monotonic() + 2.0
    while True:
        leaked = [t for t in threading.enumerate()
                  if not t.daemon and t.is_alive()
                  and t.ident not in baseline]
        if (len(leaked) <= THREAD_LEAK_ALLOWANCE
                or _time.monotonic() >= deadline):
            break
        _time.sleep(0.1)
    if len(leaked) > THREAD_LEAK_ALLOWANCE:
        names = sorted(t.name for t in leaked)
        pytest.fail(
            f"{len(leaked)} non-daemon thread(s) leaked across the suite "
            f"(allowance {THREAD_LEAK_ALLOWANCE}): {names[:40]}",
            pytrace=False)
