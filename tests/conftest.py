"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's GPU-free test strategy (SURVEY.md §4): all
distributed-sharding tests run on `--xla_force_host_platform_device_count=8`
CPU devices, so CI needs no TPU.  Must run before any `import jax`.
"""

import os

# The ambient environment may pin JAX to the real TPU (e.g. the "axon"
# plugin, which ignores JAX_PLATFORMS=cpu), but the test suite must stay on
# the virtual CPU mesh — single-chip hardware can't host the 8-way sharding
# tests and TPU compiles would dominate test wall-time.  XLA_FLAGS must be
# set before jax import; jax_platforms must be forced via jax.config (the
# env var alone loses to the TPU plugin).
import re

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "",
    os.environ.get("XLA_FLAGS", ""),
)
os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.default_backend() == "cpu", jax.default_backend()
assert len(jax.devices()) == 8, jax.devices()

# Persistent XLA compilation cache (same discipline as bench.py): the
# suite builds hundreds of EngineCore instances whose jitted steps lower
# to IDENTICAL HLO, and each new jax.jit instance recompiles it —
# backend-compile dedupe via the disk cache cuts suite wall-time ~35%
# even within one cold run (and more when the driver re-runs tier-1 in
# the same container).  Keys on HLO hash, so test semantics are
# untouched; engine-side counters (EngineStepCounters.xla_cache_misses)
# count traced shapes, not backend compiles, and are unaffected.
try:
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                     "/tmp/dynamo_tpu_test_xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
except Exception:
    pass  # older jax without the knobs: run uncached
