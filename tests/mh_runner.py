"""Subprocess body for the multihost tests: one leader + one follower
process, each owning 4 virtual CPU devices, running ONE EngineCore over
the 8-device global mesh in SPMD lockstep (parallel/multihost.py).

Reference analog: the per-rank worker body an srun/LWS multinode launch
starts (`components/backends/trtllm/multinode/srun_disaggregated.sh`) —
every rank builds the same engine; rank 0 additionally drives it.

Invoked by tests/test_multihost.py, never by pytest collection:
    python tests/mh_runner.py <leader|follower> <coord_port> <lock_port> \
        <mode>
"""

import json
import sys


def main() -> None:
    role, coord_port, lock_port, mode = sys.argv[1:5]
    devices_per_proc = int(sys.argv[5]) if len(sys.argv) > 5 else 4
    from dynamo_tpu.parallel import multihost

    multihost.setup_cpu_rig(devices_per_proc)
    multihost.initialize(f"127.0.0.1:{coord_port}", 2,
                         0 if role == "leader" else 1)

    import jax

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = mcfg.get_config("tiny-test")
    total = len(jax.devices())
    tp = min(cfg.num_kv_heads, max(1, total // 2))
    mesh = make_mesh(MeshConfig(dp=total // tp, tp=tp), jax.devices())
    dp_attention = mode == "dp_attention"
    # "fused_int8" (ISSUE 12 leg 4 — the lockstep-2proc grid cell):
    # int8 KV + single-step decode, so the leader's command stream
    # replays the FUSED greedy step (replicated [B] token output) with
    # quantized scale buffers riding the sharded cache pytree.
    core = EngineCore(EngineConfig(
        model=cfg, num_blocks=64, mesh=mesh,
        dp_attention=dp_attention,
        enable_prefix_cache=(mode == "prefix"),
        kv_quant="int8" if mode == "fused_int8" else "none",
        decode_window=1 if mode == "fused_int8" else 4,
        scheduler=SchedulerConfig(block_size=16)))

    if role == "follower":
        chan = multihost.LockstepFollower("127.0.0.1", int(lock_port))
        multihost.run_follower(core, chan)
        # Emit the follower's mirrored request log so the test can assert
        # true shadow-state convergence, not just absence of crashes.
        print("FOLLOWER_DONE " + json.dumps(sorted(core._requests.keys())),
              flush=True)
        return

    leader = multihost.LockstepLeader(port=int(lock_port), num_followers=1)
    leader.wait_for_followers()
    core._lockstep = leader

    prompts = {
        "req-a": [1, 2, 3, 4, 5, 6, 7, 8],
        "req-b": [9, 8, 7, 6, 5],
        "req-c": [42, 43],
    }
    # fused_int8 keeps every request greedy so the single-step path
    # actually dispatches the fused program (a stochastic row would
    # route the whole batch through the plain step).
    sampled = ({} if mode == "fused_int8"
               else {"req-c": SamplingParams(temperature=0.8, top_k=20,
                                             seed=1234, max_tokens=12)})
    for rid, toks in prompts.items():
        core.add_request(rid, toks,
                         sampled.get(rid, SamplingParams(max_tokens=12)))
    out: dict = {rid: [] for rid in prompts}
    steps = 0
    while core.has_work and steps < 200:
        for d in core.step():
            out[d.request_id].extend(d.token_ids)
        steps += 1
    leader.close()
    print("LEADER_TOKENS " + json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
