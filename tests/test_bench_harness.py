"""Benchmark-integrity subsystem: calibration guardrails (the fabricated
465-TFLOP/s probe VERDICT r5 printed must be REJECTED), slope
aggregation, the regression gate, and the tier-1 bench_gate smoke."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.bench import gate, harness  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# harness: slope estimation


def test_trimmed_median():
    assert harness.trimmed_median([3.0]) == 3.0
    assert harness.trimmed_median([1.0, 9.0, 2.0]) == 2.0
    # 4+ samples: min and max dropped BEFORE the median — one tenancy
    # pause cannot drag the aggregate.
    assert harness.trimmed_median([1.0, 2.0, 3.0, 100.0]) == 2.5
    assert harness.trimmed_median([0.001, 2.0, 2.1, 2.2, 100.0]) == 2.1
    with pytest.raises(ValueError):
        harness.trimmed_median([])


def test_measure_slope_cancels_fixed_cost():
    # run(m) = fixed 10ms tax + 2ms/call: the slope must recover 2ms.
    est = harness.measure_slope(lambda m: 0.010 + 0.002 * m, 4, 20)
    assert est.per_call_s == pytest.approx(0.002)
    assert len(est.samples) == 3
    assert est.spread == pytest.approx(1.0)
    with pytest.raises(ValueError):
        harness.measure_slope(lambda m: 0.0, 5, 5)


def test_measure_slope_survives_one_poisoned_window():
    # Second repeat hits a "tenancy pause": its short run is inflated,
    # making that slope collapse toward zero (the r5 failure shape).
    calls = {"n": 0}

    def run(m):
        calls["n"] += 1
        if calls["n"] == 3:          # t1 of repeat 2 inflated 50x
            return 0.010 + 0.002 * m + 0.5
        return 0.010 + 0.002 * m

    est = harness.measure_slope(run, 4, 20)
    assert est.per_call_s == pytest.approx(0.002)   # median unharmed
    assert est.spread > 2.0                          # ...but flagged


# ---------------------------------------------------------------------------
# harness: calibration guardrails


def _v5e_flops_probe(measured_tflops, samples=()):
    return harness.Probe(
        name="peak_flops", measured=measured_tflops * 1e12,
        nominal=197e12,
        samples=tuple(s * 1e12 for s in samples), unit=" FLOP/s")


def test_fabricated_465_tflops_probe_rejected():
    """The exact r5 artifact: 465.6 TFLOP/s 'measured' on a 197 TFLOP/s
    v5e must mark the run invalid and suppress vs_baseline."""
    verdict = harness.evaluate_calibration(
        [_v5e_flops_probe(465.6, samples=(455.0, 465.6, 470.2))])
    assert not verdict.calibration_ok
    assert verdict.tenancy_health == "invalid"
    assert "physically impossible" in verdict.reasons[0]

    out = harness.guard_result(
        {"value": 10301.56, "vs_baseline": 0.466, "serving_tok_s": 4803.5},
        verdict)
    assert out["calibration_ok"] is False
    assert out["tenancy_health"] == "invalid"
    assert out["vs_baseline"] is None        # suppressed, not printed
    assert out["run_valid"] is False
    assert out["value"] == 10301.56          # raw numbers stay visible


def test_plausible_probe_passes_and_spread_flags_noise():
    ok = harness.evaluate_calibration(
        [_v5e_flops_probe(184.0, samples=(180.0, 184.0, 190.0))])
    assert ok.calibration_ok and ok.tenancy_health == "ok"

    # Within the datasheet but wildly spread: valid yet NOISY.
    noisy = harness.evaluate_calibration(
        [_v5e_flops_probe(150.0, samples=(50.0, 150.0, 180.0))])
    assert noisy.calibration_ok
    assert noisy.tenancy_health == "noisy"

    out = harness.guard_result({"vs_baseline": 0.9}, noisy)
    assert out["vs_baseline"] == 0.9         # kept: run is usable
    assert out["tenancy_health"] == "noisy"

    # 10% over datasheet is tolerated (clock boost / rounding)...
    assert harness.evaluate_calibration(
        [_v5e_flops_probe(210.0)]).calibration_ok
    # ...11% over is not.
    assert not harness.evaluate_calibration(
        [_v5e_flops_probe(219.0)]).calibration_ok
    # No nominal (CPU fallback): impossibility check skipped.
    free = harness.Probe("peak_flops", 1e15, nominal=None)
    assert harness.evaluate_calibration([free]).calibration_ok


# ---------------------------------------------------------------------------
# regression gate


GOOD = {"value": 10000.0, "serving_tok_s": 8000.0, "prefill_tok_s": 11000.0,
        "itl_ms": 6.5, "calibration_ok": True, "tenancy_health": "ok"}


def test_gate_fails_on_20pct_throughput_drop():
    dropped = dict(GOOD, serving_tok_s=8000.0 * 0.79)   # >20% drop
    res = gate.compare(dropped, GOOD)
    assert not res.ok
    assert res.regressions[0]["metric"] == "serving_tok_s"
    assert res.regressions[0]["change"] == pytest.approx(-0.21)

    barely = dict(GOOD, serving_tok_s=8000.0 * 0.85)    # within threshold
    assert gate.compare(barely, GOOD).ok


def test_gate_latency_direction_and_improvements():
    slow = dict(GOOD, itl_ms=6.5 * 1.3)                 # latency REGRESSES up
    res = gate.compare(slow, GOOD)
    assert not res.ok and res.regressions[0]["metric"] == "itl_ms"

    better = dict(GOOD, serving_tok_s=8000.0 * 1.4, itl_ms=4.0)
    res = gate.compare(better, GOOD)
    assert res.ok
    assert {e["metric"] for e in res.improvements} == \
        {"serving_tok_s", "itl_ms"}


def test_gate_rejects_invalid_new_run_and_skips_invalid_baseline():
    invalid = dict(GOOD, calibration_ok=False, tenancy_health="invalid")
    res = gate.compare(invalid, GOOD)
    assert not res.ok and res.new_invalid

    # Invalid BASELINE: comparison meaningless — skip with warning, the
    # new run is not punished for the old run's broken calibration.
    res = gate.compare(GOOD, invalid)
    assert res.ok and res.baseline_invalid and res.warnings


def test_gate_unwraps_bench_round_files():
    """BENCH_rNN.json driver wrapper ({"parsed": ...}) and the bare
    bench output must both gate."""
    wrapped_old = {"n": 4, "parsed": GOOD}
    new = dict(GOOD, serving_tok_s=8000.0 * 0.5)
    res = gate.compare(new, wrapped_old)
    assert not res.ok
    # Missing metrics are skipped, not crashed on.
    res = gate.compare({"serving_tok_s": 8000.0, "calibration_ok": True},
                       GOOD)
    assert res.ok and "value" in res.skipped

    # Repo artifacts load and unwrap (BENCH_r05 really is in-tree).
    r05 = gate.load_bench_json(os.path.join(REPO, "BENCH_r05.json"))
    assert r05["metric"].startswith("decode_throughput")


@pytest.mark.slow
def test_bench_gate_smoke_cli():
    """CPU-only synthesize → analyze → mocker replay → gate, in a
    subprocess exactly as CI invokes it (slow: spawns a process and
    replays a 40-request trace)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "bench_gate.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=240, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout)
    assert out["smoke"] == "pass"
    assert out["hit_rate_within_5pts"] is True
    assert out["regression_fails"] is True
    assert out["invalid_run_fails"] is True
    assert out["low_mbu_fails"] is True
    assert out["interference_fails"] is True
    assert out["sharded_floor_fails"] is True
    assert out["sharded_decode_section_ok"] is True
    assert out["slow_prefill_plane_fails"] is True
    assert out["prefill_plane_token_parity"] is True
    assert out["slow_device_transfer_fails"] is True
    assert out["transfer_byte_parity"] is True
    assert out["transfer_device_plane_used"] is True


def test_gate_tpu_floors():
    """Absolute floors (MBU, interference) fail a TPU run even when its
    baseline already regressed there — and never apply off-TPU."""
    tpu = dict(GOOD, device="TPU v5 lite0", mbu=0.82,
               mixed_prefill_decode={"interference_ratio": 0.88})
    assert gate.compare(tpu, tpu).ok

    low = dict(tpu, mbu=0.6)
    res = gate.compare(low, low)  # baseline equally low: floors still fail
    assert not res.ok
    assert res.floor_failures and res.floor_failures[0]["metric"] == "mbu"

    interfered = dict(tpu, mixed_prefill_decode={"interference_ratio": 0.7})
    res = gate.compare(interfered, tpu)
    assert not res.ok and res.floor_failures

    # ISSUE 9: a sharded engine whose per-chip throughput collapsed vs
    # meshless fails the floor; a single-chip round (no ratio) skips it.
    slow_sharded = dict(tpu, sharded_decode={"tok_s_per_chip_ratio": 0.5})
    res = gate.compare(slow_sharded, slow_sharded)
    assert not res.ok and any(
        f["metric"] == "sharded_decode.tok_s_per_chip_ratio"
        for f in res.floor_failures)
    ok_sharded = dict(tpu, sharded_decode={"tok_s_per_chip_ratio": 0.91})
    assert gate.compare(ok_sharded, ok_sharded).ok
    single_chip = dict(tpu, sharded_decode={"tp2": {"skipped": "1 chip"}})
    res = gate.compare(single_chip, single_chip)
    assert res.ok
    assert "floor:sharded_decode.tok_s_per_chip_ratio" in res.skipped

    # CPU artifacts carry no roofline: floors are skipped, not failed.
    cpu = dict(GOOD, device="TFRT_CPU_0", mbu=0.01)
    assert gate.compare(cpu, cpu).ok


def test_bench_gate_cli_compares_files(tmp_path):
    new = tmp_path / "new.json"
    base = tmp_path / "base.json"
    base.write_text(json.dumps(GOOD))
    new.write_text(json.dumps(dict(GOOD, serving_tok_s=8000.0 * 0.7)))
    from tools.bench_gate import main

    assert main([str(new), "--baseline", str(base)]) == 1
    new.write_text(json.dumps(GOOD))
    assert main([str(new), "--baseline", str(base)]) == 0
