"""Chaos suite (ISSUE 15): elastic serving under worker death, planned
drain, control-plane partition, and QoS pressure.

The discipline: every scenario asserts on MACHINE-CHECKABLE evidence —
request outcomes (`dynamo_request_outcomes_total`), flight-recorder dump
CONTENTS (tools/trace_merge.load_flight_dump), reaped
`status_endpoints/` registrations (tools/dynamo_top.collect), fetcher
plane counters — never on log text.

In-process engine tests share tiny-test geometry with
tests/test_prefix_share.py (same EngineConfig → same compiled shapes →
compile-cache reuse inside the tier-1 budget); the e2e scenarios run
mocker workers as real OS processes (cheap: no jax engine build).
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from dynamo_tpu.engine.engine import (
    EngineConfig, EngineCore, InferenceEngine, TokenDelta)
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.prefix_share import (
    MIGRATE_ANNOTATION, PrefixFetcher, PrefixShareClient)
from dynamo_tpu.llm.block_manager.transfer import (
    KV_BLOCKS_ENDPOINT, make_kv_blocks_handler)
from dynamo_tpu.llm.drain import (
    DRAIN_REFUSAL, DrainableService, WorkerDrainingError)
from dynamo_tpu.llm.migration import MigrationClient
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.llm.service import LocalEngineClient, priority_of
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime import flight_recorder
from dynamo_tpu.runtime.metrics import MetricsRegistry
from dynamo_tpu.runtime.rpc import RpcClient, RpcError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TINY = mcfg.get_config("tiny-test")
BS = 8
LONG_PROMPT = list(range(1, 36))   # 4 sealed blocks + 3-token tail


def _core(host_blocks=0, num_blocks=64):
    # test_prefix_share's exact tiny geometry (compile-cache reuse).
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=num_blocks, host_blocks=host_blocks,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))


class _Worker:
    """One in-process worker: engine + RPC server with kv_blocks, plus
    the device transfer plane (ISSUE 16: the worker ALWAYS starts one —
    local fabric when pjrt cross-host transfer is absent — so drain
    migration rides device-direct instead of the host-staged wire)."""

    def __init__(self, **core_kw):
        self._core_kw = core_kw

    async def start(self):
        from dynamo_tpu.llm.block_manager.device_transfer import (
            KV_OFFER_ENDPOINT, KV_PULLED_ENDPOINT, KvTransferPlane)
        from dynamo_tpu.runtime.rpc import RpcServer

        self.engine = InferenceEngine(_core(**self._core_kw))
        await self.engine.start()
        self.client = LocalEngineClient(self.engine)
        self.plane = KvTransferPlane(self.engine)
        self.plane.start()
        self.rpc = RpcServer()
        self.rpc.register(KV_BLOCKS_ENDPOINT,
                          make_kv_blocks_handler(self.engine))
        self.rpc.register(KV_OFFER_ENDPOINT,
                          self.plane.make_offer_handler())
        self.rpc.register(KV_PULLED_ENDPOINT,
                          self.plane.make_pulled_handler())
        self.address = await self.rpc.start()
        return self

    async def stop(self):
        await self.rpc.stop()
        self.plane.stop()
        await self.engine.stop()


def _run(coro, timeout=180):
    return asyncio.run(asyncio.wait_for(coro, timeout))


async def _collect(client, rid, prompt, sampling, annotations=None):
    req = PreprocessedRequest(request_id=rid, model="m",
                              token_ids=list(prompt), sampling=sampling,
                              annotations=dict(annotations or {}))
    out = []
    async for d in client.generate(req):
        out.extend(d.token_ids)
        if d.finished:
            assert d.finish_reason is not None
            assert d.finish_reason.value != "error"
            break
    return out


# ---------------------------------------------------------------------------
# Drain-migration: byte-identical streams, KV carried over kv_blocks


class _FleetRouter:
    """Two-worker routing stub: the draining worker until it drains,
    the survivor after (what the real instance-set watcher does when the
    drained worker's lease revokes)."""

    def __init__(self, drainable, survivor):
        self.drainable = drainable
        self.survivor = survivor

    async def generate(self, request):
        target = (self.survivor if self.drainable.draining
                  else self.drainable)
        async for d in target.generate(request):
            yield d


def _drain_scenario(sampling, drain_after_tokens):
    """Run the drain-migration scenario; returns (reference_tokens,
    migrated_tokens, fetcher, drainable, sched_b)."""

    async def main():
        wa = await _Worker().start()
        wb = await _Worker().start()
        rpc = RpcClient(wa.address)
        try:
            want = await _collect(wa.client, "ref", LONG_PROMPT, sampling)

            drainable = DrainableService(wa.client, kv_address=wa.address,
                                         block_size=BS)
            fetcher = PrefixFetcher(wb.engine, lambda a: rpc, BS,
                                    plane=wb.plane)
            survivor = PrefixShareClient(wb.client, fetcher)
            mc = MigrationClient(_FleetRouter(drainable, survivor),
                                 migration_limit=3, retry_delay=0.001)

            req = PreprocessedRequest(request_id="r1", model="m",
                                      token_ids=list(LONG_PROMPT),
                                      sampling=sampling)
            got = []
            drained = [False]
            async for d in mc.generate(req):
                got.extend(d.token_ids)
                if len(got) >= drain_after_tokens and not drained[0]:
                    drained[0] = True
                    # Planned drain mid-stream: the worker hands the
                    # request off with its KV; the client stream must
                    # not notice.
                    asyncio.ensure_future(drainable.drain(20.0))
                if d.finished:
                    break
            return want, got, fetcher, drainable, wb.engine.core.scheduler
        finally:
            await rpc.close()
            await wa.stop()
            await wb.stop()

    return _run(main())


def test_drain_migration_byte_identical_greedy():
    """A greedy stream handed off mid-decode is byte-identical to
    uninterrupted serving, and the KV moved over the kv_blocks plane:
    blocks pulled > 0, re-prefill fallbacks == 0 (the ISSUE 15
    acceptance pin)."""
    want, got, fetcher, drainable, sched_b = _drain_scenario(
        SamplingParams(max_tokens=20), drain_after_tokens=6)
    assert got == want, (got, want)
    assert drainable.migrated_out == 1
    # Plane counters pinned: KV crossed the wire, and the happy path
    # never fell back to re-prefill.
    assert fetcher.pulled_blocks > 0
    assert fetcher.fallbacks == 0
    assert fetcher.migrated_in == 1
    # ISSUE 16 satellite: the drain handoff rode the DEVICE plane —
    # every worker now starts a KvTransferPlane (local fabric when pjrt
    # is absent), so the carried KV moved device-direct, not host-staged.
    assert fetcher.device_pulled_blocks > 0
    # The survivor prefix-matched the carried KV at admission: it
    # prefilled only the unsealed tail, not the whole stream.
    assert sched_b.prefix_hit_tokens >= 4 * BS


def test_drain_migration_seeded_stream_keeps_contract():
    """A SEEDED stochastic stream survives the handoff byte-identically:
    SamplingParams.seed_offset keeps the (seed, token-index) law on the
    resuming worker."""
    want, got, fetcher, _, _ = _drain_scenario(
        SamplingParams(max_tokens=16, temperature=0.8, seed=1234),
        drain_after_tokens=5)
    assert got == want, (got, want)
    assert fetcher.fallbacks == 0


def test_drain_refusal_is_retryable_and_idle_drain_instant():
    """New admissions during a drain are refused with the retryable
    marker; an idle worker drains instantly."""

    class _Dead:
        async def generate(self, request):
            raise AssertionError("must not be reached")
            yield  # pragma: no cover

    async def main():
        d = DrainableService(_Dead(), block_size=BS)
        t0 = time.monotonic()
        assert await d.drain(5.0) is True
        assert time.monotonic() - t0 < 1.0
        with pytest.raises(WorkerDrainingError) as ei:
            async for _ in d.generate(PreprocessedRequest(
                    request_id="x", model="m", token_ids=[1, 2],
                    sampling=SamplingParams(max_tokens=2))):
                pass
        assert DRAIN_REFUSAL in str(ei.value)

    _run(main())


# ---------------------------------------------------------------------------
# MigrationClient hardening (satellite): backoff, counters, drain refusal


def test_migration_backoff_is_jittered_exponential():
    mc = MigrationClient(None, retry_delay=0.1, max_retry_delay=2.0)
    for attempt, base in ((0, 0.1), (3, 0.8), (10, 2.0)):  # capped at max
        for _ in range(20):
            d = mc._backoff(attempt)
            assert base * 0.5 <= d <= base * 1.5, (attempt, d)
    # Jitter actually varies (not a fixed delay like the old 0.05 s).
    assert len({round(mc._backoff(1), 9) for _ in range(8)}) > 1


def test_migration_counter_reasons_and_drain_refusal_retry():
    """death → retry with backoff; a drain-refusal RpcError retries too;
    dynamo_migrations_total{reason} counts each rung."""

    class _Flaky:
        def __init__(self):
            self.calls = 0

        async def generate(self, request):
            self.calls += 1
            if self.calls == 1:
                raise ConnectionError("boom")
            if self.calls == 2:
                raise RpcError(f"refused: {DRAIN_REFUSAL}")
            yield TokenDelta(request_id=request.request_id,
                             token_ids=[7, 8], finished=True,
                             finish_reason=None)

    async def main():
        registry = MetricsRegistry()
        inner = _Flaky()
        mc = MigrationClient(inner, migration_limit=3, retry_delay=0.001,
                             registry=registry)
        req = PreprocessedRequest(request_id="r", model="m",
                                  token_ids=[1, 2, 3],
                                  sampling=SamplingParams(max_tokens=4))
        out = []
        async for d in mc.generate(req):
            out.extend(d.token_ids)
        assert out == [7, 8]
        assert inner.calls == 3
        assert mc.migrations == 2
        counter = registry.counter("migrations_total")
        assert counter.value({"reason": "death"}) == 1
        assert counter.value({"reason": "drain_refused"}) == 1

    _run(main())


def test_migration_budget_exhausted_raises():
    class _AlwaysDead:
        async def generate(self, request):
            raise ConnectionError("dead fleet")
            yield  # pragma: no cover

    async def main():
        mc = MigrationClient(_AlwaysDead(), migration_limit=2,
                             retry_delay=0.001)
        with pytest.raises(ConnectionError):
            async for _ in mc.generate(PreprocessedRequest(
                    request_id="r", model="m", token_ids=[1],
                    sampling=SamplingParams(max_tokens=4))):
                pass

    _run(main())


# ---------------------------------------------------------------------------
# QoS: priority classes, burn-triggered preemption, demote-then-resume


def test_priority_annotation_parse():
    def req(**ann):
        return PreprocessedRequest(request_id="r", model="m",
                                   token_ids=[1],
                                   sampling=SamplingParams(),
                                   annotations=dict(**ann))

    assert priority_of(req()) == 1
    assert priority_of(req(priority="best_effort")) == 0
    assert priority_of(req(priority="interactive")) == 2
    assert priority_of(req(priority="0")) == 0
    assert priority_of(req(priority="9")) == 2       # clamped
    assert priority_of(req(priority="garbage")) == 1  # forgiving


def _pump(core, got, stop, max_steps=600):
    """Step `core`, accumulating token_ids per request into `got`, until
    stop() is true (checked after each step's deltas are folded in)."""
    for _ in range(max_steps):
        for d in core.step():
            got.setdefault(d.request_id, []).extend(d.token_ids)
        if stop():
            return
    raise AssertionError(f"condition never met; got {got}")


def _reference_run(prompt, sampling, priority=0):
    core = _core(host_blocks=32)
    core.add_request("be", list(prompt), sampling, priority=priority)
    got = {}
    _pump(core, got, lambda: not core._requests)
    return got["be"]


def test_qos_burn_preempts_best_effort_demotes_then_resumes():
    """SLO burn >= 1 sheds a running best-effort request: its sealed KV
    demotes to the host tier (not lost), the standard request takes the
    machine, and when the burn clears the best-effort stream resumes via
    tier onboard — final output byte-identical to undisturbed serving."""
    want = _reference_run(LONG_PROMPT, SamplingParams(max_tokens=12))

    core = _core(host_blocks=32)
    pressure = [0.0]
    core.scheduler.qos_pressure_fn = lambda: pressure[0]
    core.add_request("be", list(LONG_PROMPT), SamplingParams(max_tokens=12),
                     priority=0)
    got = {"be": [], "std": []}

    # Let the best-effort stream decode a few tokens (blocks seal).
    _pump(core, got, lambda: len(got["be"]) >= 6)

    # Burn ignites; a standard-class request arrives.
    pressure[0] = 2.0
    core.add_request("std", list(range(100, 120)),
                     SamplingParams(max_tokens=6), priority=1)
    _pump(core, got, lambda: len(got["std"]) >= 6)
    sched = core.scheduler
    assert sched.qos_preemptions >= 1
    assert core.qos_demoted_blocks >= 1          # demoted, not lost
    host = core.allocator.manager.host
    assert len(host.registry.by_hash) >= 1       # blocks live in G2
    # Held while burning: the best-effort request made no progress past
    # the shed point.
    be_frozen = len(got["be"])
    for _ in range(10):
        for d in core.step():
            got.setdefault(d.request_id, []).extend(d.token_ids)
    assert len(got["be"]) == be_frozen

    # Burn clears: resume = tier onboard (not re-prefill), stream
    # completes byte-identical.
    pressure[0] = 0.0
    onboarded_before = core.allocator.manager.onboarded_blocks
    _pump(core, got, lambda: not core._requests)
    assert got["be"] == want, (got["be"], want)
    assert core.allocator.manager.onboarded_blocks > onboarded_before
    assert len(got["std"]) == 6


def test_qos_capacity_preemption_prefers_lower_class():
    """A capacity-blocked standard request displaces the newest
    best-effort request instead of waiting behind it (no SLO monitor
    involved — pure priority preemption)."""
    core = _core(host_blocks=32, num_blocks=12)  # 11 usable pages
    core.add_request("be", list(range(1, 41)),   # 6 pages at admission
                     SamplingParams(max_tokens=16), priority=0)
    got = {"be": [], "std": []}
    _pump(core, got, lambda: len(got["be"]) >= 1)

    core.add_request("std", list(range(200, 248)),   # needs 7 pages
                     SamplingParams(max_tokens=4), priority=1)
    _pump(core, got, lambda: not core._requests)
    assert core.scheduler.qos_preemptions >= 1
    assert len(got["std"]) == 4                  # standard got through
    assert len(got["be"]) == 16                  # best-effort completed after


# ---------------------------------------------------------------------------
# e2e chaos: kill -9 under load, control-plane partition


_seq = [0]


def _spawn_mock_worker(tmp_path, cp_port: int, name: str,
                       speedup: float = 1.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    _seq[0] += 1
    log = open(tmp_path / f"chaos_worker_{_seq[0]}.log", "w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.worker",
         "--control-plane", f"127.0.0.1:{cp_port}",
         "--mocker", "--model-name", name,
         "--block-size", "8",
         "--speedup-ratio", str(speedup)],
        env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT, text=True)
    proc._logfile = log  # type: ignore[attr-defined]
    return proc


async def _wait_prefix(cp, prefix, n, timeout=60):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            found = await cp.get_prefix(prefix)
        except (ConnectionError, RuntimeError, OSError):
            found = {}   # control plane mid-restart: keep polling
        if len(found) >= n:
            return found
        await asyncio.sleep(0.2)
    raise TimeoutError(f"never saw {n} entries under {prefix}")


async def _stream_request(session, base, model, rid_tag, max_tokens,
                          on_token=None):
    """One streaming chat request; returns (content_chunks,
    finish_reason)."""
    tokens = 0
    finish = None
    async with session.post(f"{base}/v1/chat/completions", json={
            "model": model,
            "messages": [{"role": "user", "content": f"chaos {rid_tag}"}],
            "max_tokens": max_tokens, "stream": True}) as r:
        assert r.status == 200, await r.text()
        async for raw in r.content:
            line = raw.decode().strip()
            if not line.startswith("data:") or line == "data: [DONE]":
                continue
            chunk = json.loads(line[5:])
            choice = chunk["choices"][0]
            if choice.get("delta", {}).get("content"):
                tokens += 1
                if on_token is not None:
                    on_token(tokens)
            if choice.get("finish_reason"):
                finish = choice["finish_reason"]
    return tokens, finish


@pytest.mark.e2e
def test_kill9_under_load_zero_failed_requests(tmp_path):
    """kill -9 one of two loaded workers: every concurrent stream
    completes (zero failed requests per the outcome counter), and the
    episode is asserted from flight-recorder DUMP CONTENTS plus the
    reaped status_endpoints entry — not from logs."""
    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from tools.dynamo_top import collect
    from tools.trace_merge import load_flight_dump

    workers = []
    rec = flight_recorder.configure(service="chaos-frontend", enabled=True)
    rec.reset()

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        registry = MetricsRegistry()
        watcher = ModelWatcher(runtime, models, migration_limit=3,
                               registry=registry)
        await watcher.start()
        svc = HttpService(models, registry=registry)
        http_port = await svc.start()

        workers.append(_spawn_mock_worker(tmp_path, cp_port, "chaos-model"))
        workers.append(_spawn_mock_worker(tmp_path, cp_port, "chaos-model"))
        await _wait_prefix(cp, "models/chaos-model/", 2)
        await _wait_prefix(cp, "status_endpoints/", 2)
        await watcher.wait_for_model("chaos-model", timeout=10)

        base = f"http://127.0.0.1:{http_port}"
        killed = [False]
        killed_pid = workers[0].pid

        def maybe_kill(tokens_seen):
            # Early trigger: the widest mid-flight window for the other
            # streams under CI contention.
            if tokens_seen >= 3 and not killed[0]:
                killed[0] = True
                workers[0].send_signal(signal.SIGKILL)

        async with ClientSession() as s:
            results = await asyncio.gather(*[
                _stream_request(s, base, "chaos-model", i, 24,
                                on_token=(maybe_kill if i == 0 else None))
                for i in range(6)])
        assert killed[0]
        # Reap the OS zombie: signal-0 pid probing (the status-endpoint
        # reaper's liveness test) sees zombie children as alive.
        workers[0].wait()
        for tokens, finish in results:
            assert finish == "length", results
            assert tokens >= 12, results  # streams actually progressed

        # 1) Zero failed requests, machine-checked via the outcome
        # counter the SLO error-rate objective reads.
        outcomes = svc.request_metrics.outcomes
        assert outcomes.value({"status": "error"}) == 0
        assert outcomes.value({"status": "ok"}) >= 6
        # 2) The migration evidence is in the flight-recorder dump.
        dump_path = str(tmp_path / "chaos_dump.jsonl")
        assert rec.dump("chaos_test", path=dump_path,
                        min_interval_s=0.0) == dump_path
        events = load_flight_dump(dump_path)
        migrates = [e for e in events if e.get("kind") == "migrate"]
        assert migrates, f"no migrate events in dump: {events[:5]}"
        assert any(e.get("reason") == "death" for e in migrates)
        # 3) The frontend counted the migration hops by reason.
        assert registry.counter("migrations_total").value(
            {"reason": "death"}) >= 1
        assert 'dynamo_migrations_total{reason="death"}' \
            in registry.expose()
        # 4) The kill -9'd worker's stale status registration reaps
        # (its pid is provably dead on loopback).
        snap = {}
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            snap = await collect(f"127.0.0.1:{cp_port}", timeout=2.0)
            if any(r.get("reaped") and r.get("pid") == killed_pid
                   for r in snap.get("processes", [])):
                break
            await asyncio.sleep(0.5)
        assert any(r.get("reaped") and r.get("pid") == killed_pid
                   for r in snap.get("processes", [])), snap

        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()
        await cp_server.stop()

    try:
        _run(main())
    finally:
        rec.configure(enabled=False)
        rec.reset()
        for w in workers:
            if w.poll() is None:
                w.kill()


@pytest.mark.e2e
def test_control_plane_partition_recovery(tmp_path):
    """Partition the control plane mid-stream (kill -9 + restart on the
    same port/store): the in-flight stream — worker↔frontend RPC is a
    direct connection — completes; after recovery the worker's lease
    re-registers and fresh requests serve.  Zero failed requests."""
    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneClient

    store = str(tmp_path / "cp.json")
    procs = []

    def start_cp(port):
        log = open(tmp_path / f"cp_{len(procs)}.log", "w+")
        p = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.control_plane_service",
             "--port", str(port), "--store", f"file:{store}"],
            env=dict(os.environ, PYTHONPATH=REPO), cwd=REPO,
            stdout=log, stderr=subprocess.STDOUT, text=True)
        p._logfile = log  # type: ignore[attr-defined]
        procs.append(p)
        return p

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    cp_port = s.getsockname()[1]
    s.close()

    async def main():
        from dynamo_tpu.runtime.distributed import DistributedRuntime

        cp_proc = start_cp(cp_port)
        cp = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                cp = ControlPlaneClient("127.0.0.1", cp_port)
                await cp.start()
                break
            except OSError:
                await asyncio.sleep(0.3)
        assert cp is not None
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        watcher = ModelWatcher(runtime, models, migration_limit=3)
        await watcher.start()
        svc = HttpService(models)
        http_port = await svc.start()

        procs.append(_spawn_mock_worker(tmp_path, cp_port, "part-model"))
        await _wait_prefix(cp, "models/part-model/", 1)
        await watcher.wait_for_model("part-model", timeout=10)
        base = f"http://127.0.0.1:{http_port}"

        partitioned = [False]

        def partition(tokens_seen):
            if tokens_seen == 4 and not partitioned[0]:
                partitioned[0] = True
                cp_proc.send_signal(signal.SIGKILL)

        async with ClientSession() as s:
            tokens, finish = await _stream_request(
                s, base, "part-model", "p0", 30, on_token=partition)
            assert partitioned[0]
            # The stream rode out the partition on its direct RPC.
            assert finish == "length" and tokens >= 15

            cp_proc.wait()
            start_cp(cp_port)
            # Worker lease recovery re-registers the same instance; the
            # frontend watch replays it.  A fresh request then serves.
            await _wait_prefix(cp, "models/part-model/", 1, timeout=90)
            tokens2, finish2 = await _stream_request(
                s, base, "part-model", "p1", 6)
            assert finish2 == "length" and tokens2 >= 3

        outcomes = svc.request_metrics.outcomes
        assert outcomes.value({"status": "error"}) == 0
        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()

    try:
        _run(main(), timeout=240)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
            log = getattr(p, "_logfile", None)
            if log:
                log.flush()


@pytest.mark.e2e
def test_worker_sigterm_drain_hands_off_stream(tmp_path):
    """SIGTERM a loaded worker (mocker, so the handoff carries no KV
    hint): the in-flight stream migrates to the survivor with reason
    "drain" — not "death" — the drained worker exits 0 on its own, and a
    control-plane drain command drains the second worker the same way."""
    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    workers = []

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        registry = MetricsRegistry()
        watcher = ModelWatcher(runtime, models, migration_limit=3,
                               registry=registry)
        await watcher.start()
        svc = HttpService(models, registry=registry)
        http_port = await svc.start()

        workers.append(_spawn_mock_worker(tmp_path, cp_port, "drain-model"))
        workers.append(_spawn_mock_worker(tmp_path, cp_port, "drain-model"))
        await _wait_prefix(cp, "models/drain-model/", 2)
        await watcher.wait_for_model("drain-model", timeout=10)
        base = f"http://127.0.0.1:{http_port}"

        terminated = [False]

        def sigterm_one(tokens_seen):
            # Early trigger: the widest mid-flight window for the other
            # streams under CI contention.
            if tokens_seen >= 2 and not terminated[0]:
                terminated[0] = True
                workers[0].send_signal(signal.SIGTERM)

        async with ClientSession() as s:
            # Worker 0 drains mid-load; with worker 1 surviving, every
            # stream must complete (the drain handoff or — racing the
            # drain window — a retryable refusal re-routes them).
            results = await asyncio.gather(*[
                _stream_request(s, base, "drain-model", i, 24,
                                on_token=(sigterm_one if i == 0 else None))
                for i in range(4)])
        assert terminated[0]
        for tokens, finish in results:
            assert finish == "length", results       # zero failed requests
        drains = registry.counter("migrations_total").value(
            {"reason": "drain"})
        refusals = registry.counter("migrations_total").value(
            {"reason": "drain_refused"})
        assert drains + refusals >= 1, registry.expose()
        # The drained worker exits on its own, cleanly (rc 0), inside
        # the drain budget — no SIGKILL involved.
        assert await asyncio.to_thread(workers[0].wait, 60) == 0

        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()
        await cp_server.stop()

    try:
        _run(main(), timeout=240)
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()


@pytest.mark.e2e
def test_control_plane_drain_command(tmp_path):
    """`cp.put(drain/<pid>)` drains a worker without any signal — the
    container/remote-host path: it leaves routing and exits 0."""
    from dynamo_tpu.llm.drain import drain_key_pid
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)

    workers = []

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        workers.append(_spawn_mock_worker(tmp_path, cp_port, "cmd-model"))
        await _wait_prefix(cp, "models/cmd-model/", 1)

        await cp.put(drain_key_pid(workers[0].pid), {"reason": "test"})
        rc = await asyncio.to_thread(workers[0].wait, 60)
        assert rc == 0
        # The instance record left with the worker (lease revoked on
        # drain, not just expiry).
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if not await cp.get_prefix("models/cmd-model/"):
                break
            await asyncio.sleep(0.2)
        assert not await cp.get_prefix("models/cmd-model/")
        await cp.close()
        await cp_server.stop()

    try:
        _run(main(), timeout=180)
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()


# ---------------------------------------------------------------------------
# Planner drain accounting (satellite): clean drain vs force-kill


def test_connector_counts_force_kill_distinct_from_clean_drain(tmp_path):
    from dynamo_tpu.planner.connector import LocalConnector
    from dynamo_tpu.planner.core import planner_metrics_text

    async def main():
        conn = LocalConnector("127.0.0.1:1", drain_timeout_s=1.0,
                              log_dir=str(tmp_path))
        # A worker that honors SIGTERM → clean drain.
        good = subprocess.Popen([sys.executable, "-c",
                                 "import time; time.sleep(60)"])
        # A worker that ignores SIGTERM → drain timeout → force-kill.
        # Handshake on stdout so SIGTERM can't race the handler install.
        bad = subprocess.Popen([sys.executable, "-u", "-c",
                                "import signal, time;"
                                "signal.signal(signal.SIGTERM,"
                                " signal.SIG_IGN);"
                                "print('armed', flush=True);"
                                "time.sleep(60)"],
                               stdout=subprocess.PIPE, text=True)
        assert bad.stdout.readline().strip() == "armed"
        conn._procs = [good, bad]
        await conn.remove_worker()   # pops `bad` (newest) → force-kill
        await conn.remove_worker()   # pops `good` → clean drain
        assert conn.force_kills == 1
        assert conn.clean_drains == 1
        text = planner_metrics_text(object(), conn)
        assert 'dynamo_planner_drains_total{outcome="clean"} 1' in text
        assert 'dynamo_planner_drains_total{outcome="force_kill"} 1' in text

    _run(main())


def test_migrate_annotation_cleared_on_death_retry():
    """A death-retry must not chase the previous hop's migrate hint —
    the re-issued request drops MIGRATE_ANNOTATION unless a fresh
    migrate delta carried one."""

    class _DieOnce:
        def __init__(self):
            self.calls = 0
            self.seen = []

        async def generate(self, request):
            self.calls += 1
            self.seen.append(dict(request.annotations))
            if self.calls == 1:
                yield TokenDelta(request_id=request.request_id,
                                 token_ids=[5], finished=False)
                raise ConnectionError("died mid-stream")
            yield TokenDelta(request_id=request.request_id,
                             token_ids=[6], finished=True)

    async def main():
        inner = _DieOnce()
        mc = MigrationClient(inner, retry_delay=0.001)
        req = PreprocessedRequest(
            request_id="r", model="m", token_ids=[1, 2],
            sampling=SamplingParams(max_tokens=4),
            annotations={MIGRATE_ANNOTATION:
                         '{"address": "stale:1", "covered_tokens": 8}'})
        out = []
        async for d in mc.generate(req):
            out.extend(d.token_ids)
        assert out == [5, 6]
        assert MIGRATE_ANNOTATION in inner.seen[0]       # first attempt
        assert MIGRATE_ANNOTATION not in inner.seen[1]   # cleared on retry
        # Budget + seed bookkeeping on the re-issue.
        assert inner.calls == 2

    _run(main())
