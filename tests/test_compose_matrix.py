"""The feature-composition grid (ISSUE 12): every (feature × mesh) cell
of the README "Sharded serving" matrix is either exercised token-exact
against the meshless oracle HERE, or declared impossible in the ONE
capability table (parallel.sharding.plane_capability) with a pointed
error this file asserts — no silent gaps.

The matrix used to be a code grid (per-combo step builders + engine
rejection lists); the PlaneSpec refactor collapsed it to this test grid.
One shared tiny geometry (identical to tests/test_sharded_serving.py's)
keeps the compiled-shape set compile-cache-friendly; the heaviest cells
are slow-marked so the warm tier-1 suite stays inside its budget.  The
lockstep-2proc column runs as subprocess pairs in
tests/test_multihost.py (`fused_int8` is the grid's multihost cell).
"""

import jax
import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.parallel import MeshConfig, make_mesh
from dynamo_tpu.parallel.sharding import PlaneSpec, plane_capability

# SAME geometry as tests/test_sharded_serving.py — the grid's engines
# lower to already-cached HLO wherever the cell's program shape repeats.
SCHED = dict(max_seqs=4, block_size=8, max_pages_per_seq=8,
             max_prefill_chunk=16, decode_buckets=(2, 4),
             prefill_buckets=(8, 16))

PROMPTS = {"a": [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
           "b": list(range(20, 34))}

MESHES = {
    "tp2": (MeshConfig(tp=2), {}),
    "dp2": (MeshConfig(dp=2), {}),
    "dp_local": (MeshConfig(tp=2, dp=2), dict(dp_attention=True)),
    "sp2": (MeshConfig(sp=2, tp=2), dict(sp_prefill_threshold=8)),
    "pp2": (MeshConfig(pp=2), {}),
    "ep2": (MeshConfig(dp=2, ep=2), {}),
    "ep2tp2": (MeshConfig(dp=2, ep=2, tp=2), {}),
}


def _run_cell(mesh_name=None, kv_quant="none", spec=0, decode_window=1,
              model="tiny-test", **extra):
    kwargs = dict(enable_prefix_cache=False)
    mesh = None
    if mesh_name is not None:
        mesh_cfg, mesh_kwargs = MESHES[mesh_name]
        mesh = make_mesh(mesh_cfg, jax.devices()[:mesh_cfg.size])
        kwargs.update(mesh_kwargs)
    kwargs.update(extra)
    core = EngineCore(EngineConfig(
        model=mcfg.get_config(model), num_blocks=64, mesh=mesh,
        kv_quant=kv_quant, speculative_tokens=spec,
        decode_window=decode_window, window_pipeline_depth=2,
        scheduler=SchedulerConfig(**SCHED), **kwargs))
    for rid, toks in PROMPTS.items():
        core.add_request(rid, toks, SamplingParams(max_tokens=12))
    outputs = {}
    for _ in range(300):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    assert not core._requests, "engine did not finish"
    return core, outputs


@pytest.fixture(scope="module")
def oracle():
    """Meshless single-step greedy output — the one parity reference
    every exercised cell must match byte-identically."""
    _, out = _run_cell()
    return out


# (cell id, engine kwargs, extra post-run asserts key) — each cell is a
# NEW composition this PR opened (the pre-existing yes-cells keep their
# pins in test_sharded_serving.py / test_kv_quant.py).
CELLS = {
    # int8 × spec × head-sharded tp: quantized verify chunks.
    "tp2+int8+spec": dict(mesh_name="tp2", kv_quant="int8", spec=3),
    # int8 × dp window: replicated-cache dp with quantized windows.
    "dp2+int8+window": dict(mesh_name="dp2", kv_quant="int8",
                            decode_window=4),
    # ISSUE 12 leg 5: spec verify resolves rows to the owning shard's
    # slot range under dp-attention locality.
    "dp_local+spec": dict(mesh_name="dp_local", spec=3),
    # ISSUE 12 leg 1: quantized ring-SP exchange, then int8 decode.
    "sp2+int8+window": dict(mesh_name="sp2", kv_quant="int8",
                            decode_window=4),
    # ISSUE 19: pallas × ring-SP — the flash ring kernel (double-
    # buffered RDMA exchange under the fold, interpret mode on CPU)
    # serves the sp prefill; the ring-path AND kernel-path counters
    # are asserted so an XLA-ring fallback can't pass silently.
    "sp2+pallas": dict(mesh_name="sp2", use_pallas_decode=True),
    # ISSUE 19: sp_prefill × pallas × int8 — int8 rows + scales ride
    # the kernel's RDMA streams and dequantize in VMEM.
    "sp2+pallas+int8": dict(mesh_name="sp2", use_pallas_decode=True,
                            kv_quant="int8"),
    # ISSUE 12 leg 3: the pp decode window (schedule-looping program).
    "pp2+window": dict(mesh_name="pp2", decode_window=4),
    # ISSUE 12 leg 3: the all-in-one fused pp greedy step.
    "pp2+fused": dict(mesh_name="pp2", decode_window=1),
    # ISSUE 12 leg 2: int8 through the stacked pp layout.
    "pp2+int8": dict(mesh_name="pp2", kv_quant="int8", decode_window=1),
}

SLOW_CELLS = {
    # spec × ring-SP mesh (the sp axis idles during decode; the matrix
    # row claims yes, so it gets a pin).
    "sp2+spec": dict(mesh_name="sp2", spec=3),
    # int8 × spec × dp-attention locality — the heaviest three-way cell.
    "dp_local+int8+spec": dict(mesh_name="dp_local", kv_quant="int8",
                               spec=3),
    # int8 × pp × window.
    "pp2+int8+window": dict(mesh_name="pp2", kv_quant="int8",
                            decode_window=4),
}

# MoE row of the matrix (ISSUE 17): every exclusion this PR killed
# becomes an exercised cell against the tiny-moe meshless dense oracle.
MOE_CELLS = {
    # moe × decode window (meshless dense).
    "moe+window": dict(model="tiny-moe", decode_window=4),
    # moe × fused greedy through the GROUPED fast path (interpret on
    # CPU) — the ops-level byte-identity surviving the fused program.
    "moe+grouped": dict(model="tiny-moe", moe_mode="grouped"),
    # grouped × decode window.
    "moe+grouped+window": dict(model="tiny-moe", moe_mode="grouped",
                               decode_window=4),
    # moe × int8 KV × window (vs the int8 meshless oracle: int8 KV is
    # lossy and the router's top-k amplifies it, so the honest parity
    # reference shares the quantizer and pins the PLANE composition).
    "moe+int8": dict(model="tiny-moe", kv_quant="int8", decode_window=4),
    # moe × packed ragged prefill (the exclusion killed in the engine).
    "moe+packed": dict(model="tiny-moe", packed_prefill=True),
    # moe × head-sharded tp (dense GSPMD expert einsums).
    "moe+tp2": dict(model="tiny-moe", mesh_name="tp2"),
    # moe × ep dispatch (all-to-all over the ep axis).
    "moe+ep2": dict(model="tiny-moe", mesh_name="ep2"),
}

MOE_SLOW_CELLS = {
    # ep × tp dispatch: tp-sharded expert MLPs under the all-to-all.
    "moe+ep2+tp2": dict(model="tiny-moe", mesh_name="ep2tp2"),
    # dispatch × decode window × int8 KV — the heaviest MoE cell.
    "moe+ep2+int8+window": dict(model="tiny-moe", mesh_name="ep2",
                                kv_quant="int8", decode_window=4),
}


def _assert_cell(name, kwargs, oracle):
    core, out = _run_cell(**kwargs)
    assert out == oracle, f"cell {name} diverged from the meshless oracle"
    # The cell must have run the plane it claims, not a fallback.
    if kwargs.get("spec"):
        assert core.counters.spec_dispatches > 0, \
            f"cell {name} never dispatched a speculative verify"
    if kwargs.get("mesh_name") == "sp2":
        assert core.sp_prefill_count == len(PROMPTS), \
            f"cell {name} prefill skipped the ring path"
        assert core.counters.ring_exchange_bytes_modeled > 0
        # Kernel-path attribution (ISSUE 19): pallas sp cells must have
        # run the flash ring kernel, non-pallas cells the XLA ring.
        want_kernel = len(PROMPTS) if kwargs.get("use_pallas_decode") \
            else 0
        assert core.counters.ring_kernel_prefills == want_kernel, \
            f"cell {name} ran the wrong ring implementation"
    if kwargs.get("decode_window", 1) > 1:
        assert core.counters.window_dispatches > 0, \
            f"cell {name} never dispatched a decode window"
    elif not kwargs.get("spec"):
        assert core._greedy_fused is not None, \
            f"cell {name} single-step decode did not take the fused path"
    if kwargs.get("packed_prefill"):
        assert core.counters.packed_prefill_dispatches > 0, \
            f"cell {name} never dispatched a packed prefill"
    if kwargs.get("model") == "tiny-moe":
        load = core.snapshot_expert_load()
        assert load is not None and int(load.sum()) > 0, \
            f"cell {name} lost the expert-load telemetry"
        assert core.moe_dropped_tokens == 0, \
            f"cell {name} dropped tokens at exact capacity"


@pytest.mark.parametrize("name", sorted(CELLS))
def test_composition_cell(name, oracle):
    _assert_cell(name, CELLS[name], oracle)


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(SLOW_CELLS))
def test_composition_cell_slow(name, oracle):
    _assert_cell(name, SLOW_CELLS[name], oracle)


@pytest.fixture(scope="module")
def moe_oracle():
    """tiny-moe meshless single-step dense output — the MoE row's parity
    reference (moe_dense is exact; grouped is byte-identical to it)."""
    _, out = _run_cell(model="tiny-moe")
    return out


@pytest.fixture(scope="module")
def moe_int8_oracle():
    """The int8-KV MoE reference: int8 cells share the quantizer with
    their oracle so the cell pins the plane composition, not the
    quantizer's (real, router-amplified) loss."""
    _, out = _run_cell(model="tiny-moe", kv_quant="int8")
    return out


def _moe_ref(kw, moe_oracle, moe_int8_oracle):
    return moe_int8_oracle if kw.get("kv_quant") == "int8" else moe_oracle


@pytest.mark.parametrize("name", sorted(MOE_CELLS))
def test_moe_composition_cell(name, moe_oracle, moe_int8_oracle):
    kw = MOE_CELLS[name]
    _assert_cell(name, kw, _moe_ref(kw, moe_oracle, moe_int8_oracle))


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(MOE_SLOW_CELLS))
def test_moe_composition_cell_slow(name, moe_oracle, moe_int8_oracle):
    kw = MOE_SLOW_CELLS[name]
    _assert_cell(name, kw, _moe_ref(kw, moe_oracle, moe_int8_oracle))


def test_pp_fused_step_counters():
    """The pp half of the r5 single-step cliff is dead (ISSUE 12 leg 3):
    steady pp single-step decode is ONE fused stage-program dispatch
    with ONE host sync and zero new compiled shapes per engine
    iteration — the same pin the meshless and tp paths carry."""
    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, decode_window=1, enable_prefix_cache=False,
        scheduler=SchedulerConfig(**SCHED)))
    for rid, toks in PROMPTS.items():
        core.add_request(rid, toks, SamplingParams(max_tokens=30))
    for _ in range(6):   # prefill + warm the fused program
        core.step()
    assert core._greedy_fused is not None
    base = core.counters.snapshot()
    n = 8
    for _ in range(n):
        core.step()
    d = core.counters.delta(base)
    assert d["single_step_dispatches"] == n
    assert d["host_syncs"] == n, "fused pp step must cost 1 sync"
    assert d["xla_cache_misses"] == 0, "steady pp shape recompiled"


def test_sp_ring_exchange_bytes_halve_under_int8():
    """Modeled ring traffic honesty (ISSUE 12 satellite): the quantized
    ring exchange moves int8 rows + f32 scales instead of full-precision
    chunks, so the per-chip `ring_exchange_bytes_modeled` series must
    shrink by exactly the packed-payload ratio — the sp analog of the
    kv_quant traffic_ratio the gate floors pin."""
    cfg = mcfg.get_config("tiny-test")
    _, _ = (None, None)
    core_bf, _ = _run_cell(mesh_name="sp2")
    core_i8, _ = _run_cell(mesh_name="sp2", kv_quant="int8")
    bf = core_bf.counters.ring_exchange_bytes_modeled
    i8 = core_i8.counters.ring_exchange_bytes_modeled
    assert bf > 0 and i8 > 0
    H, D = cfg.num_kv_heads, cfg.head_dim
    itemsize = jax.numpy.dtype(core_bf.cache_cfg.dtype).itemsize
    want = (H * D + 4 * H) / (H * D * itemsize)
    assert abs(i8 / bf - want) < 1e-6


def test_per_chip_modeled_bytes_pp_sp():
    """tp2 parity discipline (PR 9) extended to pp2/sp2 (ISSUE 12
    satellite): a pp2 engine's per-chip effective_bytes_per_token HALVES
    vs meshless (each stage sweeps its layer slice for all rows) — int8
    included, where the numerator also carries the stacked scale
    buffers; an sp2(+tp2) engine divides by dp·tp ONLY (the sp axis
    replicates decode — dividing by it would be flattering, not
    honest)."""
    meshless, _ = _run_cell()
    b0 = meshless.counters.effective_bytes_per_token
    assert b0 > 0

    pp2, _ = _run_cell(mesh_name="pp2")
    assert pp2.kv_traffic_shards == 2 and pp2.kv_shard_count == 2
    assert abs(pp2.counters.effective_bytes_per_token / b0 - 0.5) < 1e-6

    meshless_i8, _ = _run_cell(kv_quant="int8")
    pp2_i8, _ = _run_cell(mesh_name="pp2", kv_quant="int8")
    b0_i8 = meshless_i8.counters.effective_bytes_per_token
    assert b0_i8 > 0
    assert abs(pp2_i8.counters.effective_bytes_per_token / b0_i8
               - 0.5) < 1e-6

    sp2, _ = _run_cell(mesh_name="sp2")  # sp2 × tp2 mesh
    assert sp2.kv_traffic_shards == 2  # dp*tp — tp halves, sp does NOT
    assert abs(sp2.counters.effective_bytes_per_token / b0 - 0.5) < 1e-6

    # Residency honesty under pp+int8: per-chip block bytes report the
    # stacked pages AND scale buffers divided by the stage count.
    from dynamo_tpu.runtime.metrics import KvCacheMetrics, MetricsRegistry

    kvm = KvCacheMetrics(MetricsRegistry())
    kvm.observe_engine(pp2_i8)
    got = kvm.kv_bytes_per_block.value(labels={"kv_quant": "int8"})
    assert got == pp2_i8.cache_cfg.bytes_per_block / 2


def test_declared_impossible_cells_are_pointed():
    """Acceptance: every matrix '—' that remains is DECLARED in the one
    capability table, and serving code raises that exact reason — the
    grid asserts both halves so a silently-rejecting cell can't hide."""
    tp2 = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    pp2 = make_mesh(MeshConfig(pp=2), jax.devices()[:2])

    # spec × pp: declared (stage program banks one sampled row).
    cap = plane_capability(pp2, PlaneSpec(spec=True))
    assert not cap.ok and "spec" in cap.reason
    with pytest.raises(ValueError, match="pp") as ei:
        EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=64, mesh=pp2,
            speculative_tokens=3, enable_prefix_cache=False,
            scheduler=SchedulerConfig(**SCHED)))
    assert str(ei.value) == cap.reason

    # spec × multihost: loudly versioned out of the lockstep stream.
    cap = plane_capability(tp2, PlaneSpec(spec=True), multihost=True)
    assert not cap.ok and "lockstep" in cap.reason

    # pallas × plain dp_attention (no locality): pages span shards.
    cap = plane_capability(
        tp2, PlaneSpec(use_pallas=True, dp_attention=True))
    assert not cap.ok and "locality" in cap.reason
    dpl = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    with pytest.raises(ValueError, match="locality") as ei:
        EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=64, mesh=dpl,
            dp_attention=True, dp_attention_local=False,
            use_pallas_decode=True, enable_prefix_cache=False,
            scheduler=SchedulerConfig(**SCHED)))
    assert str(ei.value) == cap.reason

    # pallas × pp: the kernel is not wired into the stage scan; auto
    # keeps pp on the gather path, explicit True raises.
    cap = plane_capability(pp2, PlaneSpec(use_pallas=True))
    assert not cap.ok and "stage scan" in cap.reason
    # pallas × multihost: unaudited shard_map custom calls — declared;
    # auto keeps lockstep meshes on the gather path.
    cap_mh = plane_capability(tp2, PlaneSpec(use_pallas=True),
                              multihost=True)
    assert not cap_mh.ok and "lockstep" in cap_mh.reason
    with pytest.raises(ValueError, match="stage scan") as ei:
        EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=64, mesh=pp2,
            use_pallas_decode=True, enable_prefix_cache=False,
            scheduler=SchedulerConfig(**SCHED)))
    assert str(ei.value) == cap.reason

    # embeddings / multimodal × pp and × multihost: declared.
    for role in ("embed", "mm"):
        assert not plane_capability(pp2, PlaneSpec(role=role)).ok
        assert not plane_capability(tp2, PlaneSpec(role=role),
                                    multihost=True).ok
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64, mesh=pp2,
        enable_prefix_cache=False, scheduler=SchedulerConfig(**SCHED)))
    cap = plane_capability(pp2, PlaneSpec(role="embed"))
    with pytest.raises(ValueError) as ei:
        core.embed_tokens([[1, 2, 3]])
    assert str(ei.value) == cap.reason

    # pp × multihost: declared.
    assert not plane_capability(pp2, PlaneSpec(), multihost=True).ok

    # moe × pp: declared (the stage scan stacks per-stage weights into
    # one batched pytree; its body has no expert branch) — and the
    # engine raises the table's reason verbatim at construction.
    cap = plane_capability(pp2, PlaneSpec(moe=True))
    assert not cap.ok and "expert" in cap.reason
    with pytest.raises(ValueError) as ei:
        EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-moe"), num_blocks=64, mesh=pp2,
            enable_prefix_cache=False, scheduler=SchedulerConfig(**SCHED)))
    assert str(ei.value) == cap.reason

    # moe × ring-SP prefill: the sp token chunking conflicts with the
    # dp×ep token dispatch — declared; the engine consults the table
    # and keeps MoE prefill on the padded plane (no error, no ring).
    sp2 = make_mesh(MeshConfig(sp=2, tp=2), jax.devices()[:4])
    cap = plane_capability(sp2, PlaneSpec(role="sp_prefill", moe=True))
    assert not cap.ok and "ring" in cap.reason

    # Every EXERCISED cell above must be capability-table-OK — a cell
    # that runs here but is declared impossible (or vice versa) means
    # the table and the grid drifted.  The MoE cells fold their `moe`
    # bit into the plane exactly the way the engine does.
    for name, kw in {**CELLS, **SLOW_CELLS, **MOE_CELLS,
                     **MOE_SLOW_CELLS}.items():
        if kw.get("mesh_name") is None:
            continue  # meshless cells never consult the table
        mesh_cfg, mesh_kwargs = MESHES[kw["mesh_name"]]
        mesh = make_mesh(mesh_cfg, jax.devices()[:mesh_cfg.size])
        plane = PlaneSpec(
            quant=kw.get("kv_quant") == "int8",
            spec=bool(kw.get("spec")),
            window=kw.get("decode_window", 1),
            fused=kw.get("decode_window", 1) <= 1,
            use_pallas=bool(kw.get("use_pallas_decode")),
            dp_attention=bool(mesh_kwargs.get("dp_attention")),
            dp_local=bool(mesh_kwargs.get("dp_attention")),
            moe=kw.get("model") == "tiny-moe")
        cap = plane_capability(mesh, plane)
        assert cap.ok, f"grid cell {name} is declared impossible: " \
                       f"{cap.reason}"
        if kw.get("mesh_name") == "sp2":
            # The sp cells ALSO consult the table with the sp_prefill
            # role (the engine's gate for building the ring step) —
            # including pallas × sp_prefill, the cell ISSUE 19 composed.
            sp_plane = PlaneSpec(
                role="sp_prefill", quant=plane.quant,
                use_pallas=plane.use_pallas,
                moe=kw.get("model") == "tiny-moe")
            cap = plane_capability(mesh, sp_plane)
            assert cap.ok, f"sp grid cell {name} declared impossible: " \
                           f"{cap.reason}"
