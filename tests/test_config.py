"""Layered config (defaults < TOML < env < flags) + trace context."""

import argparse
import asyncio
import os
import subprocess
import sys
import time

import pytest

from dynamo_tpu.runtime.config import (
    apply_to_parser_defaults,
    load_layered_config,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_layers_precedence(tmp_path, monkeypatch):
    toml = tmp_path / "dynamo.toml"
    toml.write_text("""
block_size = 16
namespace = "from-toml"

[worker]
num_blocks = 1024
""")
    monkeypatch.setenv("DYN_CONFIG", str(toml))
    monkeypatch.setenv("DYN_NAMESPACE", '"from-env"')
    cfg = load_layered_config(
        {"block_size": 64, "namespace": "dynamo", "num_blocks": 512,
         "metrics_interval": 1.0},
        section="worker")
    assert cfg["block_size"] == 16          # toml top-level beats default
    assert cfg["num_blocks"] == 1024        # toml [worker] section
    assert cfg["namespace"] == "from-env"   # env beats toml
    assert cfg["metrics_interval"] == 1.0   # default survives


def test_env_value_parsing(monkeypatch):
    monkeypatch.setenv("DYN_HTTP_PORT", "9090")
    monkeypatch.setenv("DYN_MOCKER", "true")
    monkeypatch.setenv("DYN_MODEL_NAME", "plain-string")
    cfg = load_layered_config(
        {"http_port": 8080, "mocker": False, "model_name": "x"})
    assert cfg["http_port"] == 9090 and cfg["http_port"] != "9090"
    assert cfg["mocker"] is True
    assert cfg["model_name"] == "plain-string"


def test_flags_stay_top_layer(monkeypatch):
    monkeypatch.setenv("DYN_BLOCK_SIZE", "32")
    p = argparse.ArgumentParser()
    p.add_argument("--block-size", type=int, default=64)
    apply_to_parser_defaults(p, load_layered_config({"block_size": 64}))
    assert p.parse_args([]).block_size == 32          # env layer
    assert p.parse_args(["--block-size", "8"]).block_size == 8  # flag wins


def test_bad_toml_is_loud(tmp_path, monkeypatch):
    bad = tmp_path / "bad.toml"
    bad.write_text("not valid [toml")
    monkeypatch.setenv("DYN_CONFIG", str(bad))
    with pytest.raises(ValueError, match="bad config file"):
        load_layered_config({"x": 1})


@pytest.mark.e2e
def test_trace_id_spans_frontend_and_worker_logs():
    """One X-Request-Id must be grep-able in BOTH process logs (reference
    distributed trace context, logging.rs:73-79)."""
    from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneServer

    async def main():
        srv = ControlPlaneServer()
        port = await srv.start()
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
                   PYTHONUNBUFFERED="1")
        env.pop("DYN_CONFIG", None)
        logs = {}
        procs = {}
        for name, argv in (
                ("worker", ["-m", "dynamo_tpu.worker",
                            "--control-plane", f"127.0.0.1:{port}",
                            "--mocker", "--model-name", "m",
                            "--block-size", "8"]),
                ("frontend", ["-m", "dynamo_tpu.frontend",
                              "--control-plane", f"127.0.0.1:{port}",
                              "--http-port", "18432"])):
            logs[name] = open(f"/tmp/trace_test_{name}_{os.getpid()}.log",
                              "w+")
            procs[name] = subprocess.Popen(
                [sys.executable, *argv], env=env, cwd=REPO,
                stdout=logs[name], stderr=subprocess.STDOUT)
        try:
            import aiohttp

            trace_id = "trace-e2e-12345"
            deadline = time.monotonic() + 40
            status = None
            while time.monotonic() < deadline:
                await asyncio.sleep(1.0)
                try:
                    async with aiohttp.ClientSession() as s:
                        async with s.post(
                                "http://127.0.0.1:18432/v1/completions",
                                json={"model": "m", "prompt": "hello",
                                      "max_tokens": 4},
                                headers={"X-Request-Id": trace_id}) as r:
                            status = r.status
                            await r.read()
                    if status == 200:
                        break
                except aiohttp.ClientError:
                    continue
            assert status == 200
            await asyncio.sleep(0.5)
            for name in ("frontend", "worker"):
                logs[name].flush()
                logs[name].seek(0)
                content = logs[name].read()
                assert trace_id in content, f"{name} log lacks trace id"
        finally:
            for pr in procs.values():
                pr.terminate()
            for pr in procs.values():
                try:
                    pr.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pr.kill()
            for f in logs.values():
                f.close()
            await srv.stop()

    asyncio.run(asyncio.wait_for(main(), 120))
