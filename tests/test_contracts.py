"""Runtime thread-affinity contracts (runtime/contracts.py).

Pure-CPU and engine-build-free (tier-1 wall-time discipline): the
decorators are exercised on tiny stub classes and, for the
InferenceEngine integration, via subprocess-free direct checks of the
module's registry — never by building an EngineCore.
"""

import asyncio
import importlib
import os
import subprocess
import sys
import threading

import pytest

from dynamo_tpu.runtime import contracts
from dynamo_tpu.runtime.contracts import ContractViolation

pytestmark = pytest.mark.skipif(
    not contracts.ENABLED,
    reason="suite must run with DYNAMO_CONTRACTS=1 (conftest sets it)")


class FakeCore:
    @contracts.engine_thread_only
    def step(self):
        return "stepped"

    @contracts.engine_thread_only
    def export(self):
        return "exported"


def _call_in_thread(fn):
    """Run fn() on a fresh thread; return (result, exception)."""
    box = {}

    def run():
        try:
            box["result"] = fn()
        except BaseException as e:  # noqa: BLE001 - test harness
            box["exc"] = e

    t = threading.Thread(target=run)
    t.start()
    t.join(5.0)
    return box.get("result"), box.get("exc")


# -- engine_thread_only ----------------------------------------------------


def test_engine_thread_only_pins_first_caller():
    core = FakeCore()
    assert core.step() == "stepped"          # pins THIS thread
    assert core.export() == "exported"       # same thread: fine
    _, exc = _call_in_thread(core.step)
    assert isinstance(exc, ContractViolation)
    assert "engine-thread-only" in str(exc)
    # The violating thread's name is in the message (debuggability).
    assert "Thread-" in str(exc) or "thread" in str(exc).lower()


def test_engine_thread_only_per_instance():
    a, b = FakeCore(), FakeCore()
    assert a.step() == "stepped"
    # A DIFFERENT instance pins independently: another thread may own it.
    result, exc = _call_in_thread(b.step)
    assert exc is None and result == "stepped"


def test_release_owner_transfers_ownership():
    core = FakeCore()
    core.step()                              # pinned to main thread
    contracts.release_owner(core)
    result, exc = _call_in_thread(core.step)  # new owner re-pins
    assert exc is None and result == "stepped"
    # ...and now the MAIN thread is the violator.
    with pytest.raises(ContractViolation):
        core.step()
    contracts.release_owner(core)            # leave main unpinned again
    core.step()


def test_release_owner_tolerates_none_and_foreign():
    contracts.release_owner(None, object(), FakeCore())  # no raise


# -- never_engine_thread ---------------------------------------------------


class Sampler:
    @contracts.never_engine_thread
    def sample(self):
        return "sampled"

    @contracts.never_engine_thread
    async def pull(self):
        return "pulled"

    @contracts.never_engine_thread
    async def stream(self):
        yield 1
        yield 2


def test_never_engine_thread_allows_unregistered_threads():
    s = Sampler()
    assert s.sample() == "sampled"
    result, exc = _call_in_thread(s.sample)
    assert exc is None and result == "sampled"


def test_never_engine_thread_raises_on_engine_thread():
    s = Sampler()

    def as_engine():
        contracts.register_engine_thread()
        try:
            s.sample()
        finally:
            contracts.unregister_engine_thread()

    _, exc = _call_in_thread(as_engine)
    assert isinstance(exc, ContractViolation)
    assert "never run on the engine thread" in str(exc)


def test_unregister_clears_engine_identity():
    s = Sampler()

    def once_engine():
        contracts.register_engine_thread()
        contracts.unregister_engine_thread()
        return s.sample()                    # no longer an engine thread

    result, exc = _call_in_thread(once_engine)
    assert exc is None and result == "sampled"


def test_async_flavors_check_on_calling_thread():
    s = Sampler()

    async def ok():
        assert await s.pull() == "pulled"
        assert [x async for x in s.stream()] == [1, 2]

    asyncio.run(ok())

    def engine_loop():
        contracts.register_engine_thread()
        try:
            with pytest.raises(ContractViolation):
                asyncio.run(s.pull())

            async def drain():
                return [x async for x in s.stream()]

            with pytest.raises(ContractViolation):
                asyncio.run(drain())
        finally:
            contracts.unregister_engine_thread()

    _, exc = _call_in_thread(engine_loop)
    assert exc is None


# -- hot_path --------------------------------------------------------------


def test_hot_path_is_a_pure_marker():
    calls = []

    @contracts.hot_path
    def fast(x):
        calls.append(x)
        return x * 2

    # Never wrapped, even with contracts ON: identical function object
    # semantics, only the marker attribute added.
    assert fast.__dynamo_contract__ == "hot_path"
    assert fast(21) == 42 and calls == [21]


# -- zero-overhead off mode -----------------------------------------------


def test_decorators_are_noops_when_disabled():
    """With DYNAMO_CONTRACTS unset the decorators must return the
    ORIGINAL function object — no wrapper on the step loop.  Checked in
    a subprocess so this suite's enabled-mode import is untouched."""
    code = (
        "import os; os.environ.pop('DYNAMO_CONTRACTS', None)\n"
        "from dynamo_tpu.runtime import contracts\n"
        "assert not contracts.ENABLED\n"
        "def f(self): return 1\n"
        "assert contracts.engine_thread_only(f) is f\n"
        "assert contracts.never_engine_thread(f) is f\n"
        "assert contracts.hot_path(f) is f\n"
        "assert f.__dynamo_contract__ == 'hot_path'\n"
        "print('noop-ok')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=60,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert out.returncode == 0, out.stderr
    assert "noop-ok" in out.stdout


def test_enabled_mode_wraps():
    """In this process (DYNAMO_CONTRACTS=1) the thread decorators DO
    wrap, and the wrapper advertises the contract for introspection."""
    assert contracts.ENABLED
    assert FakeCore.step.__dynamo_contract__ == "engine_thread_only"
    assert Sampler.pull.__dynamo_contract__ == "never_engine_thread"
    # functools.wraps preserved identity metadata.
    assert FakeCore.step.__name__ == "step"


def test_annotated_modules_import_cleanly():
    """The real annotated modules (engine, pools, slo, metrics) import
    and their decorated methods carry the marker — without building an
    engine."""
    from dynamo_tpu.engine.engine import EngineCore, InferenceEngine
    from dynamo_tpu.llm.block_manager.manager import KvBlockManager
    from dynamo_tpu.llm.block_manager.pool import BlockPool
    from dynamo_tpu.runtime.metrics import KvCacheMetrics
    from dynamo_tpu.runtime.slo import SloMonitor

    assert EngineCore.step.__dynamo_contract__ == "engine_thread_only"
    assert EngineCore.import_blocks.__dynamo_contract__ == \
        "engine_thread_only"
    assert InferenceEngine.run_in_engine.__dynamo_contract__ == \
        "never_engine_thread"
    assert BlockPool.allocate.__dynamo_contract__ == "engine_thread_only"
    assert KvBlockManager.close.__dynamo_contract__ == \
        "never_engine_thread"
    assert SloMonitor.tick.__dynamo_contract__ == "never_engine_thread"
    assert KvCacheMetrics.observe_engine.__dynamo_contract__ == \
        "never_engine_thread"


def test_block_pool_contracts_live():
    """A real BlockPool (host-only object, no engine) enforces the pin:
    allocate on one thread, then allocate from another raises."""
    pool = BlockPoolFactory()
    pool.allocate(1)
    _, exc = _call_in_thread(lambda: pool.allocate(1))
    assert isinstance(exc, ContractViolation)
    contracts.release_owner(pool)
    result, exc = _call_in_thread(lambda: pool.allocate(1))
    assert exc is None


def BlockPoolFactory():
    from dynamo_tpu.llm.block_manager.pool import BlockPool

    return BlockPool(8, name="test-pool")


def test_slo_tick_refused_on_engine_thread():
    """SloMonitor.tick asserts off-engine-thread: the eviction bias
    reads last_max_burn instead of recomputing windows on the step
    loop."""
    from dynamo_tpu.runtime.slo import SloMonitor, SloObjective

    mon = SloMonitor([(SloObjective("x"), lambda: (0.0, 0.0))])
    mon.tick(now=0.0)                        # fine off-engine

    def as_engine():
        contracts.register_engine_thread()
        try:
            mon.tick(now=1.0)
        finally:
            contracts.unregister_engine_thread()

    _, exc = _call_in_thread(as_engine)
    assert isinstance(exc, ContractViolation)


def test_module_reimport_respects_env(tmp_path):
    """ENABLED is an import-time decision — documented contract."""
    # importlib.reload would re-decorate already-imported modules
    # inconsistently; just assert the flag matches the env this suite
    # was started with.
    assert os.environ.get("DYNAMO_CONTRACTS") == "1"
    assert contracts.ENABLED is True
    assert contracts._env_enabled() is True
    mod = importlib.import_module("dynamo_tpu.runtime.contracts")
    assert mod is contracts
