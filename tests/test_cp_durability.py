"""Control-plane durability — VERDICT r4 next-6.

The reference survives broker death via etcd quorum + NATS JetStream;
here: FileBackend snapshots (unleased config + durable queue items) and
client-side session-loss replay (Endpoint re-registration).  The e2e
kill -9s the standalone control-plane service, restarts it on the same
port + store, and asserts: config survived, un-acked queue items
redeliver, and a live worker re-registers under its original instance
id without being restarted itself.
"""

import asyncio
import os
import signal
import subprocess
import sys
import time

import pytest

from dynamo_tpu.runtime.control_plane import ControlPlaneState
from dynamo_tpu.runtime.kv_store import FileBackend

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_queue_items_survive_state_restart(tmp_path):
    path = str(tmp_path / "cp.json")
    # Production queue names contain '/' (llm/disagg.py:
    # "{namespace}/prefill_queue") — the restore parse must split the
    # msg id from the right.
    q = "dynamo/prefill_queue"

    async def phase1():
        st = ControlPlaneState(backend=FileBackend(path))
        st.queue_push(q, {"job": 1})
        st.queue_push(q, {"job": 2})
        st.queue_push(q, {"job": 3})
        # Pop one WITHOUT ack (simulates a worker holding it at crash
        # time) and ack another.
        mid, payload = await st.queue_pop(q)
        assert payload == {"job": 1}
        mid2, payload2 = await st.queue_pop(q)
        st.queue_ack(q, mid2)

    asyncio.run(phase1())

    async def phase2():
        st = ControlPlaneState(backend=FileBackend(path))
        # job 2 was acked → gone; jobs 1 (popped, unacked) and 3 redeliver.
        assert st.queue_len(q) == 2
        got = []
        for _ in range(2):
            _, p = await st.queue_pop(q)
            got.append(p["job"])
        assert sorted(got) == [1, 3]

    asyncio.run(phase2())


def test_queue_restore_preserves_fifo_order(tmp_path):
    """Message ids above 9 must not restore before 2 (lexicographic key
    order vs numeric FIFO)."""
    path = str(tmp_path / "cp.json")

    async def phase1():
        st = ControlPlaneState(backend=FileBackend(path))
        for j in range(12):
            st.queue_push("jobs", {"job": j})

    asyncio.run(phase1())

    async def phase2():
        st = ControlPlaneState(backend=FileBackend(path))
        got = []
        for _ in range(12):
            _, p = await st.queue_pop("jobs")
            got.append(p["job"])
        assert got == list(range(12)), got
        # New pushes continue past the restored ids.
        st.queue_push("jobs", {"job": "new"})
        mid, _ = await st.queue_pop("jobs")
        assert mid > 12 or mid == 13

    asyncio.run(phase2())


def test_unleased_config_survives_but_leases_do_not(tmp_path):
    path = str(tmp_path / "cp.json")
    st = ControlPlaneState(backend=FileBackend(path))
    st.put("config/threshold", {"max_local_prefill_length": 128})
    lease = st.lease_grant()
    st.put("instances/ns/c/e:1", {"address": "x"}, lease=lease)

    st2 = ControlPlaneState(backend=FileBackend(path))
    assert st2.get("config/threshold") == {"max_local_prefill_length": 128}
    assert st2.get("instances/ns/c/e:1") is None  # leased: died with proc


@pytest.mark.e2e
def test_kill9_restart_worker_reregisters(tmp_path):
    from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneClient

    store = str(tmp_path / "cp.json")
    procs = []
    logs = []

    def start_cp(port):
        log = open(tmp_path / f"cp_{len(logs)}.log", "w+")
        logs.append(log)
        p = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.control_plane_service",
             "--port", str(port), "--store", f"file:{store}"],
            env=dict(os.environ, PYTHONPATH=REPO), cwd=REPO,
            stdout=log, stderr=subprocess.STDOUT, text=True)
        procs.append(p)
        return p

    def start_worker(port):
        log = open(tmp_path / "worker.log", "w+")
        logs.append(log)
        p = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--control-plane", f"127.0.0.1:{port}",
             "--mocker", "--model-name", "dur-model", "--block-size", "8"],
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO),
            cwd=REPO, stdout=log, stderr=subprocess.STDOUT, text=True)
        procs.append(p)
        return p

    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    async def main():
        cp_proc = start_cp(port)
        cli = None
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            try:
                cli = ControlPlaneClient("127.0.0.1", port)
                await cli.start()
                break
            except OSError:
                await asyncio.sleep(0.3)
        assert cli is not None, "control plane never came up"
        await cli.put("config/knob", {"v": 42})
        await cli.queue_push("jobs", {"job": "a"})

        start_worker(port)
        instances = {}
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            instances = await cli.get_prefix("instances/")
            if instances:
                break
            await asyncio.sleep(0.5)
        assert instances, "worker never registered"
        orig_key = next(iter(instances))

        # kill -9 the control plane; restart on the same port + store.
        cp_proc.send_signal(signal.SIGKILL)
        cp_proc.wait()
        await asyncio.sleep(1.0)
        start_cp(port)

        # Our own client reconnects; config + queue survived; the WORKER
        # (never restarted) re-registers under the same instance key.
        deadline = time.monotonic() + 60
        knob = None
        while time.monotonic() < deadline:
            try:
                knob = await cli.get("config/knob")
                break
            except (ConnectionError, RuntimeError):
                await asyncio.sleep(0.5)
        assert knob == {"v": 42}, "unleased config lost"
        assert await cli.queue_len("jobs") == 1, "queue item lost"

        deadline = time.monotonic() + 60
        back = {}
        while time.monotonic() < deadline:
            back = await cli.get_prefix("instances/")
            if orig_key in back:
                break
            await asyncio.sleep(0.5)
        assert orig_key in back, (
            f"worker did not re-register; instances: {list(back)}")
        await cli.close()

    try:
        asyncio.run(asyncio.wait_for(main(), timeout=240))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.flush()
            log.seek(0)
            out = log.read()
            if out:
                print(f"--- {log.name} ---")
                print(out[-2000:])
