"""Workload-analysis suite: hasher ↔ tokens.py parity, prefix analyzer
predictions vs the mocker's measured hit rate (the e2e the router bench
rests on), sampler fit→resample→refit round-trip, and the CLI."""

import asyncio
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.data_generator.hasher import (  # noqa: E402
    TraceHasher,
    hash_token_trace,
)
from benchmarks.data_generator.prefix_analyzer import (  # noqa: E402
    analyze_trace,
)
from benchmarks.data_generator.sampler import (  # noqa: E402
    TraceSampler,
    fit_and_resample,
)
from benchmarks.data_generator.synthesizer import (  # noqa: E402
    TraceRecord,
    load_trace,
    synthesize_prefix_heavy,
    tokens_for_record,
)


# ---------------------------------------------------------------------------
# hasher


def test_hasher_chain_parity_with_tokens_py():
    """The hasher's block partition must be the serving stack's: same
    chained hashes as TokenBlockSequence/compute_block_hashes, remapped
    injectively to local ids."""
    from dynamo_tpu.tokens import TokenBlockSequence, compute_block_hashes

    block = 16
    toks_a = list(range(1, 1 + 3 * block + 5))      # 3 full blocks + tail
    toks_b = toks_a[: 2 * block] + [999] * block    # shares 2-block prefix

    th = TraceHasher(block_size=block)
    ids_a = th.hash_tokens(toks_a)
    ids_b = th.hash_tokens(toks_b)

    # Partition parity: one id per FULL block, the partial tail unhashed.
    chain_a = compute_block_hashes(toks_a, block)
    assert len(ids_a) == len(chain_a) == 3
    seq = TokenBlockSequence(toks_a, block_size=block)
    assert len(ids_a) == len(seq.block_hashes)

    # Chain semantics: shared prefix → same ids; divergence → new ids,
    # and ids are assigned first-seen dense (0, 1, 2, ...).
    assert ids_a[:2] == ids_b[:2]
    assert ids_a[2] != ids_b[2]
    assert ids_a == [0, 1, 2] and ids_b == [0, 1, 3]

    # Injectivity: same local id ⇔ same global chain hash.
    chain_b = compute_block_hashes(toks_b, block)
    assert chain_a[:2] == chain_b[:2] and chain_a[2] != chain_b[2]

    # Divergence EARLIER in the stream changes every downstream id even
    # when later blocks' tokens are identical (chained, not content hash).
    toks_c = [7] + toks_a[1:]
    ids_c = th.hash_tokens(toks_c)
    assert ids_c[0] != ids_a[0] and ids_c[1] != ids_a[1]


def test_hash_token_trace_records():
    block = 8
    shared = list(range(1, 1 + 2 * block))
    entries = [
        {"input_ids": shared + [41] * block, "output_length": 3},
        {"input_ids": shared + [42] * block, "timestamp": 5.0},
    ]
    recs = hash_token_trace(entries, block_size=block)
    assert recs[0].input_length == 3 * block
    assert recs[0].output_length == 3 and recs[1].output_length == 1
    assert recs[0].hash_ids[:2] == recs[1].hash_ids[:2]
    assert recs[0].hash_ids[2] != recs[1].hash_ids[2]
    assert recs[1].timestamp == 5.0


# ---------------------------------------------------------------------------
# prefix analyzer


def test_analyzer_theoretical_and_bounded_rates():
    block = 16
    recs = synthesize_prefix_heavy(12, num_roots=2, context_blocks=4,
                                   suffix_tokens=0, output_tokens=2,
                                   block_size=block)
    rep = analyze_trace(recs, block)
    # 2 roots x 4 blocks unique; first visit of each root misses, the
    # rest fully hit.
    assert rep.unique_blocks == 8
    assert rep.reused_tokens_infinite == (12 - 2) * 4 * block
    assert rep.theoretical_hit_rate == pytest.approx(10 / 12)
    d = rep.to_dict()
    assert d["isl"]["mean"] == 4 * block
    assert d["shared_prefix"]["num_roots"] == 2
    assert d["shared_prefix"]["depth"]["p50"] == 4

    # A bounded cache big enough for everything matches infinite...
    full = analyze_trace(recs, block, cache_blocks=8)
    assert full.bounded_hit_rate == pytest.approx(rep.theoretical_hit_rate)
    assert full.bounded_evictions == 0
    # ...and one that fits a single root thrashes when roots interleave.
    tight = analyze_trace(recs, block, cache_blocks=4)
    assert tight.bounded_hit_rate < rep.theoretical_hit_rate
    assert tight.bounded_evictions > 0


def test_analyzer_prediction_matches_mocker_measurement():
    """The tentpole e2e: on a synthesized trace the analyzer's predicted
    prefix-cache hit rate matches the mocker engine's MEASURED rate
    within ±5 points (ISSUE 1 acceptance).  One engine, pool large
    enough not to evict → the infinite-cache prediction is the right
    comparand."""
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.llm.mocker.engine import MockEngine, MockEngineArgs
    from dynamo_tpu.llm.preprocessor import PreprocessedRequest

    block = 32
    records = synthesize_prefix_heavy(
        30, num_roots=3, context_blocks=6, suffix_tokens=24,
        output_tokens=4, interval_ms=1.0, block_size=block)
    predicted = analyze_trace(records, block).theoretical_hit_rate
    assert predicted > 0.5  # prefix-heavy by construction

    async def replay() -> float:
        eng = MockEngine(MockEngineArgs(
            block_size=block, num_blocks=4096, speedup_ratio=1000.0))
        input_tokens = 0
        try:
            for i, rec in enumerate(records):
                toks = tokens_for_record(rec, block, unique_seed=i)
                input_tokens += len(toks)
                async for d in eng.generate(PreprocessedRequest(
                        request_id=f"r{i}", model="m", token_ids=toks,
                        sampling=SamplingParams(
                            max_tokens=rec.output_length))):
                    if d.finished:
                        break
            return eng.kv.hit_blocks * block / input_tokens
        finally:
            await eng.stop()

    measured = asyncio.run(asyncio.wait_for(replay(), 120))
    assert abs(measured - predicted) <= 0.05, (measured, predicted)


# ---------------------------------------------------------------------------
# sampler


def test_sampler_roundtrip_fit_resample_refit():
    """fit → resample → refit is (near) a fixed point: the resampled
    trace's distributions match the source's."""
    import random

    rng = random.Random(3)
    src = []
    ts = 0.0
    for i in range(300):
        ts += rng.expovariate(1 / 50.0)             # ~50ms inter-arrival
        src.append(TraceRecord(
            timestamp=ts,
            input_length=rng.choice([128, 256, 256, 512, 1024]),
            output_length=rng.randint(1, 64),
            hash_ids=[]))
    fit1 = TraceSampler.fit(src)
    out = fit1.sample(3000, seed=1)
    fit2 = TraceSampler.fit(out)

    for attr in ("isl", "osl", "interval_ms"):
        d1, d2 = getattr(fit1, attr), getattr(fit2, attr)
        assert d2.mean == pytest.approx(d1.mean, rel=0.1), attr
        for q in (0.5, 0.9):
            assert d2.quantile(q) == pytest.approx(
                d1.quantile(q), rel=0.15, abs=2.0), (attr, q)

    # Knobs: speedup compresses arrivals, multiplier scales prompts.
    fast = fit1.sample(500, speedup_ratio=2.0, seed=2)
    assert TraceSampler.fit(fast).interval_ms.mean == pytest.approx(
        fit1.interval_ms.mean / 2.0, rel=0.2)
    big = fit1.sample(500, prompt_len_multiplier=2.0, seed=2)
    assert TraceSampler.fit(big).isl.mean == pytest.approx(
        2.0 * fit1.isl.mean, rel=0.2)

    # hash_unique mode: zero-reuse workload at the same lengths.
    uniq = fit1.sample(50, seed=4, hash_unique=True)
    rep = analyze_trace(uniq, fit1.block_size)
    assert rep.theoretical_hit_rate == 0.0
    assert fit_and_resample(src, 10)  # one-shot wrapper works


# ---------------------------------------------------------------------------
# CLI


def test_cli_synthesize_analyze_pipeline(tmp_path, capsys):
    from benchmarks.data_generator.cli import main

    trace = tmp_path / "t.jsonl"
    rc = main(["synthesize", "--requests", "20", "--roots", "2",
               "--context-blocks", "3", "--block-size", "16",
               "--out", str(trace)])
    assert rc == 0
    recs = load_trace(str(trace))
    assert len(recs) == 20

    rc = main(["analyze", "--trace", str(trace), "--block-size", "16",
               "--cache-blocks", "6"])
    assert rc == 0
    report = json.loads(capsys.readouterr().out)
    assert report["num_requests"] == 20
    assert 0.0 < report["theoretical_hit_rate"] <= 1.0
    assert report["bounded_cache"]["cache_blocks"] == 6

    big = tmp_path / "big.jsonl"
    rc = main(["sample", "--trace", str(trace), "--requests", "100",
               "--block-size", "16", "--out", str(big)])
    assert rc == 0
    assert len(load_trace(str(big))) == 100

    rc = main(["pipeline", "--requests", "20", "--roots", "2",
               "--context-blocks", "3", "--block-size", "16"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["predicted_hit_rate"] == \
        out["analysis"]["theoretical_hit_rate"]


def test_cli_hash_roundtrip(tmp_path, capsys):
    from benchmarks.data_generator.cli import main

    block = 8
    shared = list(range(1, 1 + 2 * block))
    raw = tmp_path / "raw.jsonl"
    with open(raw, "w") as f:
        for tail in (41, 42):
            f.write(json.dumps(
                {"input_ids": shared + [tail] * block}) + "\n")
    hashed = tmp_path / "hashed.jsonl"
    rc = main(["hash", "--tokens", str(raw), "--block-size", str(block),
               "--out", str(hashed)])
    assert rc == 0
    recs = load_trace(str(hashed))
    assert recs[0].hash_ids[:2] == recs[1].hash_ids[:2]
    assert recs[0].hash_ids[2] != recs[1].hash_ids[2]


# ---------------------------------------------------------------------------
# router bench wiring


def test_router_bench_reports_predicted_hit_rate():
    """router_bench output must carry the analyzer prediction next to
    each mode's measured rate (measured - predicted per mode)."""
    from benchmarks.router_bench import run

    class Args:
        trace = None
        requests = 40
        workers = 2
        roots = 4
        context_blocks = 6
        suffix = 16
        osl = 4
        interval_ms = 1.0
        trace_block = 32
        speedup = 1000.0
        engine_blocks = 768

    result = asyncio.run(asyncio.wait_for(run(Args()), 300))
    assert 0.0 < result["predicted_hit_rate"] <= 1.0
    assert result["predicted_hit_rate_bounded"] is not None
    for mode in ("rr", "kv"):
        assert result[mode]["hit_rate_vs_predicted"] == pytest.approx(
            result[mode]["cache_hit_rate"]
            - result["predicted_hit_rate"], abs=1e-6)
    # With pools big enough to hold everything, KV routing should land
    # within a few points of the theoretical ceiling.
    assert result["kv"]["cache_hit_rate"] >= \
        result["predicted_hit_rate"] - 0.1
