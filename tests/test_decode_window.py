"""Decode-window dispatch pipeline: _inflight ordering, preempt/finish
with windows in flight, and the serving-loop overhead counters (ISSUE 2
CPU proxies: <= 1 host sync per steady-state window, 0 compiled-shape
cache misses after warmup).
"""

import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.models import config as mcfg

TINY = mcfg.get_config("tiny-test")


def _engine(**kw) -> EngineCore:
    defaults = dict(
        model=TINY,
        num_blocks=64,
        enable_prefix_cache=False,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=16,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16)),
    )
    defaults.update(kw)
    return EngineCore(EngineConfig(**defaults))


def _run(core: EngineCore, max_steps=600):
    outputs, finished = {}, {}
    for _ in range(max_steps):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
            if d.finished:
                finished[d.request_id] = d.finish_reason
        if not core._requests:
            break
    return outputs, finished


def test_inflight_syncs_in_dispatch_order():
    """Windows sync strictly FIFO: tokens drained from a deep pipeline
    must equal the single-step greedy stream (any reorder of in-flight
    windows would interleave the sequence wrongly)."""
    core = _engine(decode_window=2, window_pipeline_depth=4)
    core.add_request("a", [5, 6, 7, 8, 9, 10], SamplingParams(max_tokens=24))
    outputs = {}
    deep = 0
    for _ in range(600):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        deep = max(deep, len(core._inflight))
        if not core._requests:
            break
    assert deep >= 3, "pipeline never filled; test geometry is wrong"

    ref_core = _engine(decode_window=1)
    ref_core.add_request("a", [5, 6, 7, 8, 9, 10],
                         SamplingParams(max_tokens=24))
    ref_out, _ = _run(ref_core)
    assert outputs["a"] == ref_out["a"]


def test_drain_inflight_flushes_fifo():
    """_drain_inflight empties the queue in order and leaves no entries."""
    core = _engine(decode_window=2, window_pipeline_depth=4)
    core.add_request("a", [5, 6, 7, 8], SamplingParams(max_tokens=40))
    tokens = []
    for _ in range(50):
        for d in core.step():
            tokens.extend(d.token_ids)
        if len(core._inflight) >= 3:
            break
    assert len(core._inflight) >= 3
    n_inflight = len(core._inflight)
    before = core.counters.window_syncs
    drained = core._drain_inflight()
    assert core._inflight == []
    assert core.counters.window_syncs - before == n_inflight
    tokens += [t for d in drained for t in d.token_ids]
    # Drained tokens continue the same greedy stream.
    ref_core = _engine(decode_window=1)
    ref_core.add_request("a", [5, 6, 7, 8], SamplingParams(max_tokens=40))
    ref_out, _ = _run(ref_core)
    assert tokens == ref_out["a"][: len(tokens)]


def test_finish_mid_window_discards_overshoot():
    """max_tokens landing inside a dispatched window: the stream stops at
    exactly max_tokens and the in-flight overshoot is discarded."""
    for mt in (3, 5, 7):
        core = _engine(decode_window=4, window_pipeline_depth=2)
        core.add_request("a", [5, 6, 7, 8], SamplingParams(max_tokens=mt))
        outputs, finished = _run(core)
        assert len(outputs["a"]) == mt, (mt, outputs)
        assert finished["a"] is not None
        assert core._inflight == []


def test_preempt_with_windows_in_flight_is_greedy_invisible():
    """Page exhaustion mid-window-mode drains the pipeline and preempts
    through the single-step path; the recompute must not change any
    greedy stream (tight 24-block engine vs roomy 128-block engine)."""
    def run(num_blocks):
        core = _engine(num_blocks=num_blocks, decode_window=2,
                       window_pipeline_depth=2)
        core.add_request("a", list(range(1, 10)),
                         SamplingParams(max_tokens=32))
        core.add_request("b", list(range(20, 30)),
                         SamplingParams(max_tokens=32))
        return _run(core)

    tight_out, tight_fin = run(24)
    roomy_out, _ = run(128)
    for rid in ("a", "b"):
        assert rid in tight_fin
        # A LENGTH finish from true OOM may truncate; whatever was
        # produced must prefix-match the undisturbed stream.
        n = len(tight_out[rid])
        assert n > 0
        assert tight_out[rid] == roomy_out[rid][:n]


def test_cancel_with_windows_in_flight():
    core = _engine(decode_window=2, window_pipeline_depth=4)
    core.add_request("a", [5, 6, 7, 8], SamplingParams(max_tokens=64))
    core.add_request("b", [9, 10, 11, 12], SamplingParams(max_tokens=64))
    for _ in range(30):
        core.step()
        if len(core._inflight) >= 2:
            break
    assert len(core._inflight) >= 2
    core.cancel("a")
    outputs, finished = _run(core)
    assert finished["a"].value == "cancelled"
    assert "b" in finished
    assert core._inflight == []


def test_steady_state_one_sync_per_window_no_recompiles():
    """The ISSUE 2 counting proxy: over >= 20 steady-state window steps,
    at most one host sync per window and ZERO compiled-shape cache
    misses (the single-step cliff's suspects, now observable).

    Runs with TRACING ENABLED at sampling=1.0 and a bound trace context
    (the worst case): the absolute counter ceilings below double as the
    ISSUE 3 "tracing adds zero host syncs" guarantee, and the steady
    windows must also record ZERO spans — request-lifecycle spans land
    once at first token (during warmup here), never per window."""
    from dynamo_tpu.runtime import tracing

    K = 2
    core = _engine(
        decode_window=K, window_pipeline_depth=2,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=32,
            max_prefill_chunk=128,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(16, 128)),
        num_blocks=128)
    tracer = tracing.get_tracer()
    try:
        tracer.reset()
        tracer.configure(enabled=True, sampling=1.0)
        tracer.bind("a", tracing.TraceContext("t-steady", "s0"))
        # Prompt sized so the page-bucket width stays in one power-of-two
        # band for the whole measured range (a width flip is a legitimate
        # recompile and would make the zero-miss assertion meaningless).
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):  # prefill + window warmup (fills the pipeline)
            core.step()
        assert core._inflight, "window pipeline not running after warmup"
        # Warmup recorded the once-per-request lifecycle spans
        # (queue-wait, prefill, TTFT) and nothing else.
        assert tracer.spans_recorded == 3, tracer.spans_recorded

        base = core.counters.snapshot()
        spans0 = tracer.spans_recorded
        for _ in range(20):
            core.step()
        d = core.counters.delta(base)
        steady_spans = tracer.spans_recorded - spans0
    finally:
        tracer.enabled = False
        tracer.reset()
    assert d["window_dispatches"] == 20, d
    assert d["xla_cache_misses"] == 0, d
    assert d["host_syncs"] <= d["window_dispatches"], d
    # No full window-state rebuilds: only page-growth table refreshes
    # (one new page every block_size/K dispatches) touch the device.
    assert d["h2d_uploads"] <= 20 * K // 8 + 1, d
    assert d["single_step_dispatches"] == 0, d
    # Tracing was on the whole time and added nothing to the window loop.
    assert steady_spans == 0, steady_spans


def test_fused_greedy_single_step_matches_windows():
    """The non-window path's fused greedy step (forward + argmax in one
    program) produces the same streams as the window path."""
    prompts = {
        "a": [5, 6, 7, 8, 9, 10],
        "b": list(range(30, 41)),
    }

    def run(window):
        core = _engine(decode_window=window)
        for rid, p in prompts.items():
            core.add_request(rid, p, SamplingParams(max_tokens=12))
        out, _ = _run(core)
        return out

    single = run(1)
    windowed = run(4)
    assert single == windowed
    # And the single-step engine actually took the fused path.
    core = _engine(decode_window=1)
    for rid, p in prompts.items():
        core.add_request(rid, p, SamplingParams(max_tokens=4))
    _run(core)
    assert core.counters.single_step_dispatches > 0
    assert core._greedy_fused is not None


def test_profile_decode_emits_phase_breakdown_json():
    """ISSUE 2 CPU proxy: the extended profiler emits the per-phase
    breakdown JSON (kernel / non-attention / sampling / host sync /
    scheduler) on a CPU-only tiny geometry."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "profile_decode.py"),
         "--model", "tiny-test", "--batch", "2", "--ctx", "16",
         "--block", "8", "--width", "4", "--window", "2",
         "--no-probes", "--json"],
        capture_output=True, text=True, timeout=280,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 JAX_COMPILATION_CACHE_DIR=os.environ.get(
                     "JAX_COMPILATION_CACHE_DIR",
                     "/tmp/dynamo_tpu_test_xla_cache")),
        cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    phases = out["phases"]
    for key in ("window_ms_per_tok", "weights_ms", "sampling_ms",
                "host_sync_ms", "scheduler_ms", "kernel_ms",
                "non_attention_ms"):
        assert key in phases, key
    assert phases["window_ms_per_tok"] > 0
    assert phases["scheduler_ms"] > 0


def test_profile_decode_moe_emits_moe_phase():
    """ISSUE 17 satellite: `--moe` profiles the MoE fast-decode plane
    via the gated bench section (one methodology) — dense vs grouped
    step slopes, bitwise parity, the [E+1] load histogram, and modeled
    expert-weight bytes (grouped streams only active experts)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "profile_decode.py"),
         "--model", "tiny-moe", "--batch", "4", "--ctx", "16",
         "--block", "8", "--width", "4", "--window", "2", "--moe",
         "--no-probes", "--no-kernel", "--json"],
        capture_output=True, text=True, timeout=280,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 JAX_COMPILATION_CACHE_DIR=os.environ.get(
                     "JAX_COMPILATION_CACHE_DIR",
                     "/tmp/dynamo_tpu_test_xla_cache")),
        cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    moe = out["moe"]
    assert moe["model"] == "tiny-moe"
    assert moe["token_parity"] is True
    assert moe["int8_parity"] is True
    assert moe["dropped_tokens"] == 0
    assert sum(moe["expert_load"]) == 4 * 2   # batch x top-k, no drops
    assert moe["dense_step_ms"] > 0 and moe["grouped_step_ms"] > 0
    # Grouped streams only experts with assignments — never more
    # weight bytes than the every-expert dense oracle.
    assert (0 < moe["grouped_expert_weight_bytes"]
            <= moe["dense_expert_weight_bytes"])


def test_profile_decode_tp_emits_sharded_phases():
    """ISSUE 9 satellite: `--tp 2` profiles the SHARDED decode phases on
    a CPU host (virtual devices forced pre-jax-init), so the sharded gap
    is attributable per phase; kernel_ms reflects the per-shard
    geometry.  (`--kv-quant int8 --tp` composition is covered by the
    engine-level sharded int8 tests and the bench_gate smoke — one
    fewer sharded-window compile keeps this inside the tier-1 budget.)"""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "profile_decode.py"),
         "--model", "tiny-test", "--batch", "2", "--ctx", "16",
         "--block", "8", "--width", "4", "--window", "2", "--tp", "2",
         "--no-probes", "--json"],
        capture_output=True, text=True, timeout=280,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 JAX_COMPILATION_CACHE_DIR=os.environ.get(
                     "JAX_COMPILATION_CACHE_DIR",
                     "/tmp/dynamo_tpu_test_xla_cache")),
        cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["tp"] == 2
    phases = out["phases"]
    assert phases["window_ms_per_tok"] > 0
    assert phases["kernel_ms"] > 0
    # Modeled bytes are PER CHIP under --tp (the measured times are
    # per-chip sharded times — whole-model bytes would inflate derived
    # mbu by tp).
    from dynamo_tpu.bench.decode_wall import kv_quant_traffic
    from dynamo_tpu.models import config as mcfg

    full = kv_quant_traffic(mcfg.get_config("tiny-test"),
                            block_size=8, batch=2, ctx=16)
    assert out["kv_bytes_per_step"] == full["kv_bytes_per_step_bf16"] // 2


@pytest.mark.slow
def test_profile_decode_pp_emits_stage_phases():
    """ISSUE 12 satellite: `--pp 2` profiles the fused pp stage programs
    (the schedule-looping decode window over the stacked layout) and
    divides modeled bytes by the stage count — the engine's
    kv_traffic_shards discipline (slow: one more subprocess compile)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "tools", "profile_decode.py"),
         "--model", "tiny-test", "--batch", "2", "--ctx", "16",
         "--block", "8", "--width", "4", "--window", "2", "--pp", "2",
         "--no-probes", "--no-kernel", "--json"],
        capture_output=True, text=True, timeout=280,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 JAX_COMPILATION_CACHE_DIR=os.environ.get(
                     "JAX_COMPILATION_CACHE_DIR",
                     "/tmp/dynamo_tpu_test_xla_cache")),
        cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    assert out["pp"] == 2
    assert out["modeled_byte_shards"] == 2
    assert "window_ms_per_tok" in out["phases"]
    from dynamo_tpu.bench.decode_wall import kv_quant_traffic
    from dynamo_tpu.models import config as mcfg

    full = kv_quant_traffic(mcfg.get_config("tiny-test"),
                            block_size=8, batch=2, ctx=16)
    assert out["kv_bytes_per_step"] == full["kv_bytes_per_step_bf16"] // 2


def test_counters_expose_dict():
    core = _engine(decode_window=2)
    core.add_request("a", [5, 6, 7, 8], SamplingParams(max_tokens=6))
    _run(core)
    d = core.counters.to_dict()
    assert set(d) == {"host_syncs", "xla_cache_misses",
                      "window_dispatches", "window_syncs",
                      "single_step_dispatches", "prefill_dispatches",
                      "packed_prefill_dispatches", "spec_dispatches",
                      "h2d_uploads", "kv_read_bytes_modeled",
                      "decode_tokens_emitted",
                      "ring_exchange_bytes_modeled",
                      "ring_kernel_prefills"}
    assert d["prefill_dispatches"] >= 1
    assert d["xla_cache_misses"] >= 1  # cold engine must compile
