"""K8s manifest renderer + operator pipeline DSL + out= matrix.

VERDICT r4 missing #3 (operator-shaped deploy), #7 (generic operator
graph), #8 (out= matrix).
"""

import asyncio
import os

import pytest
import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _spec():
    from dynamo_tpu.launcher.launcher import load_graph

    return load_graph(os.path.join(REPO, "examples", "disagg_graph.toml"))


def test_render_graph_manifests(tmp_path):
    from dynamo_tpu.deploy import render_to_dir

    files = render_to_dir(_spec(), "example/dynamo-tpu:v1",
                          str(tmp_path), tpu_chips_per_worker=4,
                          graph_toml=os.path.join(
                              REPO, "examples", "disagg_graph.toml"))
    assert files
    docs = []
    for f in files:
        with open(f) as fh:
            doc = yaml.safe_load(fh)  # valid YAML or this raises
        assert doc["apiVersion"] and doc["kind"] and doc["metadata"]["name"]
        docs.append(doc)

    kinds = [d["kind"] for d in docs]
    assert "PersistentVolumeClaim" in kinds      # durable cp store
    assert kinds.count("Deployment") >= 4        # cp + frontend + 2 workers
    assert "ConfigMap" in kinds

    by_kn = {(d["kind"], d["metadata"]["name"]): d for d in docs}
    cp = by_kn[("Deployment", "dynamo-dynamo-control-plane")]
    c = cp["spec"]["template"]["spec"]["containers"][0]
    assert c["command"] == ["python", "-m",
                            "dynamo_tpu.control_plane_service"]
    assert "--store" in c["args"]

    decode = by_kn[("Deployment", "dynamo-dynamo-decode")]
    dc = decode["spec"]["template"]["spec"]["containers"][0]
    assert "--control-plane" in dc["args"]
    assert dc["args"][dc["args"].index("--control-plane") + 1] \
        == "dynamo-dynamo-control-plane:7411"
    assert dc["resources"]["limits"]["google.com/tpu"] == "4"
    # Workers must advertise a routable RPC address (127.0.0.1 default
    # would make cross-pod routing dial the wrong pod).
    assert dc["args"][dc["args"].index("--rpc-host") + 1] == "$(POD_IP)"
    assert any(e["name"] == "POD_IP" for e in dc["env"])

    assert ("Service", "dynamo-dynamo-frontend") in by_kn
    fe = by_kn[("Deployment", "dynamo-dynamo-frontend")]
    fc = fe["spec"]["template"]["spec"]["containers"][0]
    # Frontend must bind the wildcard or kube-proxy can't reach it.
    assert fc["args"][fc["args"].index("--http-host") + 1] == "0.0.0.0"
    # The graph pins --http-port 8000; container/Service ports match it.
    assert fc["ports"] == [{"containerPort": 8000}]
    fs = by_kn[("Service", "dynamo-dynamo-frontend")]
    assert fs["spec"]["ports"][0]["targetPort"] == 8000


def test_render_multihost_statefulset(tmp_path):
    """--num-processes N workers render as StatefulSet + headless Service
    with rank-0 DNS coordinator/lockstep targets (the LWS-shaped
    multinode topology, reference graph.go:145)."""
    from dynamo_tpu.deploy import render_graph
    from dynamo_tpu.launcher.launcher import GraphSpec, ServiceSpec

    spec = GraphSpec(namespace="mh", services=[ServiceSpec(
        name="decode", module="dynamo_tpu.worker",
        args=["--model", "llama-3-8b", "--tp", "8",
              "--num-processes", "2", "--process-id=0",
              "--model-name", "my model"])])
    docs = render_graph(spec, "img:v1", tpu_chips_per_worker=4)
    sts = [d for d in docs if d["kind"] == "StatefulSet"]
    assert len(sts) == 1
    st = sts[0]
    assert st["spec"]["replicas"] == 2
    assert st["spec"]["serviceName"] == "dynamo-mh-decode-ranks"
    shell_args = st["spec"]["template"]["spec"]["containers"][0]["args"][0]
    assert "--coordinator dynamo-mh-decode-0.dynamo-mh-decode-ranks:9876" \
        in shell_args
    assert "--process-id ${HOSTNAME##*-}" in shell_args
    # '--process-id=0' (the '=' form) must be stripped, and args with
    # spaces shell-quoted.
    assert "--process-id=0" not in shell_args
    assert "'my model'" in shell_args
    headless = [d for d in docs if d["kind"] == "Service"
                and d["spec"].get("clusterIP") == "None"]
    assert len(headless) == 1


def test_crd_schema_is_valid_yaml():
    path = os.path.join(REPO, "deploy", "k8s", "crds",
                        "dynamographdeployment.yaml")
    with open(path) as f:
        doc = yaml.safe_load(f)
    assert doc["kind"] == "CustomResourceDefinition"
    props = (doc["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
             ["properties"]["spec"]["properties"])
    assert "services" in props and "image" in props


def test_pipeline_dsl_composes_custom_operator():
    """A new operator is one callable (FnOp), not bespoke plumbing."""
    from dynamo_tpu.runtime.pipeline import MigrationOp, Pipeline

    class FakeDelta:
        def __init__(self, tid):
            self.token_ids = [tid]
            self.finished = tid == 2
            self.finish_reason = "stop" if tid == 2 else None

    class Sink:
        async def generate(self, request):
            for t in (0, 1, 2):
                yield FakeDelta(t)

    seen = []

    def counting(inner):
        class Count:
            async def generate(self, request):
                async for d in inner.generate(request):
                    seen.extend(d.token_ids)
                    yield d

        return Count()

    async def main():
        from dynamo_tpu.llm.preprocessor import PreprocessedRequest
        from dynamo_tpu.engine.sampling import SamplingParams

        pipe = Pipeline([MigrationOp(limit=0), counting])
        assert "MigrationOp" in pipe.describe()
        client = await pipe.attach(Sink())
        req = PreprocessedRequest(request_id="r", model="m",
                                  token_ids=[1], sampling=SamplingParams())
        out = []
        async for d in client.generate(req):
            out.extend(d.token_ids)
        assert out == [0, 1, 2] and seen == [0, 1, 2]

    asyncio.run(main())


@pytest.mark.e2e
def test_out_dyn_static_remote():
    """`--out dyn://ns/component/endpoint` attaches the frontend to a
    remote endpoint without model discovery (reference StaticRemote)."""
    import subprocess
    import sys
    import time

    from aiohttp import ClientSession

    from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneServer

    procs = []
    logs = []

    def spawn(name, mod, extra):
        log = open(f"/tmp/dynout_{os.getpid()}_{name}.log", "w+")
        logs.append(log)
        p = subprocess.Popen(
            [sys.executable, "-m", mod] + extra,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO),
            cwd=REPO, stdout=log, stderr=subprocess.STDOUT, text=True)
        procs.append(p)
        return p

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp_addr = f"127.0.0.1:{cp_port}"
        spawn("worker", "dynamo_tpu.worker",
              ["--control-plane", cp_addr, "--mocker",
               "--model-name", "whatever", "--block-size", "8"])
        spawn("frontend", "dynamo_tpu.frontend",
              ["--control-plane", cp_addr,
               "--out", "dyn://dynamo/backend/generate",
               "--model-name", "static-remote", "--http-port", "18471"])

        base = "http://127.0.0.1:18471"
        async with ClientSession() as s:
            deadline = time.monotonic() + 90
            body = None
            while time.monotonic() < deadline:
                try:
                    async with s.post(
                            f"{base}/v1/chat/completions",
                            json={"model": "static-remote",
                                  "messages": [{"role": "user",
                                                "content": "hi"}],
                                  "max_tokens": 4}) as r:
                        body = await r.json()
                        if r.status == 200:
                            break
                except Exception:
                    pass
                await asyncio.sleep(1.0)
            assert body and body.get("choices"), body
            assert body["usage"]["completion_tokens"] == 4
        await cp_server.stop()

    try:
        asyncio.run(asyncio.wait_for(main(), timeout=180))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.flush(); log.seek(0)
            out = log.read()
            if out and "Traceback" in out:
                print(f"--- {log.name} ---"); print(out[-2000:])
