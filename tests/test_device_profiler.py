"""Device-truth profiling plane (ISSUE 20): XLA cost-analysis harvest
riding first-seen dispatch shapes, the modeled-vs-measured drift
auditor's band/PAGE state machine, the steady-window zero-overhead pin,
the /debug/deviceprofile surfaces, on-demand bounded capture, and
trace_merge's --device lane merging.

Engine-backed tests share the test_decode_window / bench_gate tiny
geometry (and test_packed_prefill's GEOM for the prewarm pin) so every
EngineCore build hits the persistent XLA compile cache — tier-1 budget
discipline.
"""

import asyncio
import gzip
import json
import os
import re
import subprocess
import sys
import time

import pytest

from dynamo_tpu.runtime import device_profiler, flight_recorder
from dynamo_tpu.runtime.device_profiler import (
    DriftAuditor,
    PAGE_STRIKES,
    ProgramCostRegistry,
    profile_key_instance,
    profile_key_pid,
    program_label,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def profiler(tmp_path):
    """The module singleton, enabled into a tmp capture dir and restored
    to the disabled default afterwards (other tests pin plane-off
    behavior)."""
    prof = device_profiler.get_profiler()
    prof.reset()
    prof.configure(enabled=True, service="test",
                   dump_dir=str(tmp_path))
    yield prof
    prof.reset()
    prof.configure(enabled=False, service="dynamo",
                   max_capture_ms=device_profiler.DEFAULT_MAX_CAPTURE_MS,
                   band_hi=device_profiler.DEFAULT_BAND_HI,
                   band_lo=device_profiler.DEFAULT_BAND_LO)
    prof.dump_dir = None


@pytest.fixture()
def recorder(tmp_path):
    rec = flight_recorder.get_recorder()
    rec.reset()
    rec.configure(enabled=True, ring_size=512, dump_dir=str(tmp_path),
                  service="test")
    yield rec
    rec.reset()
    rec.configure(enabled=False, service="dynamo",
                  ring_size=flight_recorder.DEFAULT_RING)
    rec.dump_dir = None


def _tiny_engine(**kw):
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg

    defaults = dict(
        model=mcfg.get_config("tiny-test"), num_blocks=128,
        enable_prefix_cache=False, decode_window=2,
        window_pipeline_depth=2,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=32,
            max_prefill_chunk=128, decode_buckets=(1, 2, 4, 8),
            prefill_buckets=(16, 128)))
    defaults.update(kw)
    return EngineCore(EngineConfig(**defaults))


# -- registry ----------------------------------------------------------------


def test_program_label_matches_dispatch_identity():
    assert program_label("prefill", (1, 128, 16, False, False)) \
        == "prefill:1,128,16,False,False"
    assert program_label("window", (True, 1, 16)) == "window:True,1,16"


def test_registry_record_tags_and_topk():
    reg = ProgramCostRegistry()
    reg.record("window:True,1,16", flops=100.0, bytes_accessed=1000.0)
    reg.record("decode1g:1,16", flops=50.0, bytes_accessed=600.0,
               optimal_s=2e-6)
    reg.record("prefill:1,128,16,False,False", flops=9000.0,
               bytes_accessed=8000.0)
    assert reg.size() == 3
    assert reg.get("decode1g:1,16")["optimal_s"] == 2e-6
    assert reg.get("window:True,1,16")["optimal_s"] is None
    # tag_values keys on the label prefix before the first ':'.
    assert reg.tag_values("bytes_accessed", "window") == [1000.0]
    assert sorted(reg.tag_values("bytes_accessed",
                                 "decode1", "decode1g")) == [600.0]
    assert reg.mean_for_tags("bytes_accessed", "nope") is None
    top = reg.top_by("bytes_accessed", 2)
    assert [label for label, _ in top] == [
        "prefill:1,128,16,False,False", "window:True,1,16"]
    reg.reset()
    assert reg.size() == 0


def test_profile_command_keys():
    assert profile_key_pid(123) == "profile/123"
    assert profile_key_instance(7) == "profile/instance/7"


# -- leg 1: harvest at the dispatch sites ------------------------------------


def test_harvest_lands_real_engine_programs(profiler):
    """Serving a request with the plane enabled harvests XLA cost
    analysis for every first-seen dispatch shape — prefill and the
    decode window at minimum — with real nonzero flops/bytes, and the
    registry identity matches note_dispatch's (tag, sig) key."""
    from dynamo_tpu.engine.sampling import SamplingParams

    core = _tiny_engine()
    core.add_request("a", list(range(1, 71)), SamplingParams(max_tokens=24))
    for _ in range(40):
        core.step()
        if not core._requests:
            break
    assert profiler.harvest_failures == 0
    tags = {label.split(":", 1)[0] for label, _ in profiler.registry.items()}
    assert {"prefill", "window"} <= tags
    for label, costs in profiler.registry.items():
        assert costs["flops"] > 0, label
        assert costs["bytes_accessed"] > 0, label
    # Every registry label corresponds to a seen dispatch shape.
    seen = {program_label(k[0], tuple(k[1:]))
            for k in core.counters._seen_shapes}
    assert {label for label, _ in profiler.registry.items()} <= seen


def test_prewarm_shapes_land_in_registry(profiler):
    """The --prewarm-prefill bugfix pin: prewarmed packed shapes reach
    the cost registry through the same first-seen path as serving
    dispatches — prewarming must not create a permanently-dark program
    set (and the harvest must run BEFORE the donating dispatch)."""
    from dynamo_tpu.engine.scheduler import SchedulerConfig

    core = _tiny_engine(
        packed_prefill=True, decode_window=0, window_pipeline_depth=0,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=16,
            max_prefill_chunk=32, decode_buckets=(1, 2, 4, 8),
            prefill_buckets=(8, 16, 32)))
    shapes = core.packed_prefill_shape_set()
    assert core.prewarm_prefill() == len(shapes)
    want = {program_label("prefill_packed", s) for s in shapes}
    got = {label for label, _ in profiler.registry.items()
           if label.startswith("prefill_packed:")}
    assert got == want
    assert profiler.harvest_failures == 0


def test_harvest_disabled_and_unlowerable_are_noops(profiler):
    profiler.enabled = False
    assert profiler.harvest("t", (1,), lambda x: x, (1,)) is False
    profiler.enabled = True
    # Plain callables without .lower (sharded/pp step makers) degrade
    # silently — no failure counted, serving never at risk.
    assert profiler.harvest("t", (1,), lambda x: x, (1,)) is False
    assert profiler.harvest_failures == 0
    assert profiler.registry.size() == 0


# -- leg 2: drift auditor ----------------------------------------------------


def test_drift_auditor_band_and_page_state_machine(recorder):
    """Out-of-band observations must persist for PAGE_STRIKES
    consecutive scrapes before paging (one mid-warmup blip must not
    dump the ring); the PAGE records a drift_page event + async ring
    dump; returning in band records drift_ok and re-arms."""
    aud = DriftAuditor(band_hi=1.25)
    # In-band: ok, no strikes.
    assert aud.observe("kv_decode", 0.5, 1.0) == 0.5
    assert aud.states()["kv_decode"] == {
        "ratio": 0.5, "state": "ok", "strikes": 0}
    # Two strikes, then a recovery: the episode resets, never pages.
    assert aud.observe("kv_decode", 2.0, 1.0) == 2.0
    assert aud.observe("kv_decode", 2.0, 1.0) == 2.0
    assert aud.states()["kv_decode"]["strikes"] == 2
    assert aud.observe("kv_decode", 1.0, 1.0) == 1.0
    assert aud.states()["kv_decode"]["strikes"] == 0
    assert not aud.paged()
    # PAGE_STRIKES consecutive out-of-band: PAGE once, with evidence.
    for _ in range(PAGE_STRIKES):
        aud.observe("kv_decode", 3.0, 1.0)
    assert aud.paged()
    ev = [e for e in recorder.events() if e["kind"] == "drift_page"]
    assert len(ev) == 1
    assert ev[0]["series"] == "kv_decode" and ev[0]["ratio"] == 3.0
    # The dump rides a short-lived thread: poll for it.
    deadline = time.monotonic() + 5.0
    while recorder.dumps_written == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert recorder.last_dump_path is not None
    header = json.loads(open(recorder.last_dump_path).readline())
    assert header["reason"] == "drift_page"
    # Still out of band: no re-page spam.
    aud.observe("kv_decode", 3.0, 1.0)
    assert len([e for e in recorder.events()
                if e["kind"] == "drift_page"]) == 1
    # Recovery: drift_ok event, state ok.
    aud.observe("kv_decode", 1.0, 1.0)
    assert not aud.paged()
    assert [e for e in recorder.events()
            if e["kind"] == "drift_ok"][-1]["series"] == "kv_decode"


def test_drift_auditor_unobservable_pairs():
    aud = DriftAuditor()
    assert aud.observe("s", 1.0, 0.0) is None     # no denominator yet
    assert aud.observe("s", -1.0, 1.0) is None    # nonsense modeled
    assert aud.ratios() == {} and aud.states() == {}


# -- the zero-overhead pin + audit on a live engine --------------------------


def test_steady_window_profiler_on_is_byte_identical(profiler):
    """THE overhead acceptance pin: 20 steady window steps with the
    plane ENABLED produce the exact same EngineStepCounters deltas as
    plane-off (the harvest rides first-seen shapes only — compile
    events, never the steady window) — and the audit over that run
    lands the kv_decode ratio INSIDE the one-sided band (modeled KV
    bytes are a component of XLA's totals, so honest means < band_hi)."""
    from dynamo_tpu.engine.sampling import SamplingParams

    def steady_run():
        core = _tiny_engine()
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):   # prefill + window warmup
            core.step()
        base = core.counters.snapshot()
        for _ in range(20):
            core.step()
        return core, core.counters.delta(base)

    profiler.enabled = False
    _, d_off = steady_run()
    profiler.enabled = True
    core_on, d_on = steady_run()
    assert d_on == d_off, (d_on, d_off)           # byte-identical
    assert d_on["window_dispatches"] == 20
    assert profiler.registry.size() > 0
    ratios = profiler.audit_engine(core_on)
    assert 0 < ratios["kv_decode"] <= profiler.auditor.band_hi
    assert all(st["state"] == "ok"
               for st in profiler.auditor.states().values())
    # audit_engine is scrape-time: it must not touch the engine counters.
    assert core_on.counters.delta(core_on.counters.snapshot()) \
        == {k: 0 for k in d_on}


def test_audit_engine_disabled_or_counterless_is_empty(profiler):
    profiler.enabled = False
    assert profiler.audit_engine(object()) == {}
    profiler.enabled = True
    assert profiler.audit_engine(object()) == {}


# -- surfaces ----------------------------------------------------------------


def test_metrics_lines_and_debug_payload(profiler):
    profiler.registry.record("window:True,1,16", flops=100.0,
                             bytes_accessed=1000.0)
    profiler.auditor.observe("kv_decode", 0.25, 1.0)
    lines = profiler.metrics_lines()
    text = "\n".join(lines)
    assert "dynamo_program_registry_size 1" in text
    assert ('dynamo_program_flops{program="window:True,1,16"} 100.0'
            in text)
    assert ('dynamo_program_bytes_accessed{program="window:True,1,16"} '
            '1000.0' in text)
    assert ('dynamo_modeled_vs_measured_ratio{series="kv_decode"} 0.25'
            in text)
    p = profiler.debug_payload()
    assert p["enabled"] is True and p["pid"] == os.getpid()
    assert p["registry_size"] == 1
    assert p["drift"]["kv_decode"]["state"] == "ok"
    assert p["captures"] == 0


def test_capture_disabled_refuses_and_enabled_lands_files(profiler,
                                                          tmp_path):
    profiler.enabled = False
    res = profiler.capture(50)
    assert res["ok"] is False and "disabled" in res["error"]
    profiler.enabled = True
    profiler.max_capture_ms = 60
    res = profiler.capture(5000)          # clamped to max_capture_ms
    assert res["ok"] is True, res
    assert res["ms"] == 60
    assert res["dir"].startswith(str(tmp_path))
    assert os.path.basename(res["dir"]) \
        == f"deviceprofile_test_{os.getpid()}"
    assert any(f.endswith(".trace.json.gz") for f in res["files"])
    meta = json.load(open(os.path.join(res["dir"], "capture_meta.json")))
    assert meta["service"] == "test" and meta["pid"] == os.getpid()
    assert meta["wall_end"] >= meta["wall_start"]
    assert profiler.captures == 1
    assert profiler.last_capture_dir == res["dir"]


def test_debug_deviceprofile_routes(profiler):
    """Both process surfaces serve the SAME payload shape (worker
    StatusServer + frontend HttpService); a bad/nonpositive ms is a
    400; ?ms= on a disabled plane is a 503 with the refusal."""
    import aiohttp

    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.status import StatusServer

    profiler.registry.record("window:True,1,16", flops=1.0,
                             bytes_accessed=2.0)

    async def main():
        status = StatusServer()
        sport = await status.start()
        svc = HttpService(ModelManager())
        fport = await svc.start()
        try:
            async with aiohttp.ClientSession() as s:
                for port in (sport, fport):
                    async with s.get("http://127.0.0.1:%d"
                                     "/debug/deviceprofile" % port) as r:
                        assert r.status == 200
                        body = await r.json()
                    assert body["enabled"] is True
                    assert body["registry_size"] == 1
                    assert "window:True,1,16" in body["programs"]
                for bad in ("bogus", "0", "-5"):
                    async with s.get(
                            f"http://127.0.0.1:{sport}/debug/"
                            f"deviceprofile?ms={bad}") as r:
                        assert r.status == 400
                profiler.enabled = False
                async with s.get(f"http://127.0.0.1:{sport}"
                                 "/debug/deviceprofile?ms=50") as r:
                    assert r.status == 503
                    body = await r.json()
                    assert "disabled" in body["error"]
        finally:
            await svc.stop()
            await status.stop()

    asyncio.run(asyncio.wait_for(main(), 60))


# -- trace_merge --device ----------------------------------------------------


def _synth_capture(tmp_path, service="worker-backend", pid=1234,
                   wall_start=1000.0):
    """A minimal device-capture directory: sidecar + one gzipped Chrome
    trace with a lane-name metadata row, two X events, and one
    degenerate no-ph row (jax really emits those)."""
    cap = tmp_path / f"deviceprofile_{service}_{pid}"
    prof_dir = cap / "plugins" / "profile" / "2026_01_01_00_00_00"
    prof_dir.mkdir(parents=True)
    (cap / "capture_meta.json").write_text(json.dumps(
        {"service": service, "pid": pid, "ms": 50,
         "wall_start": wall_start, "wall_end": wall_start + 0.05}))
    doc = {"displayTimeUnit": "ns", "traceEvents": [
        {"ph": "M", "pid": 7, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 10.0, "dur": 5.0,
         "name": "fusion.1"},
        {"ph": "X", "pid": 7, "tid": 1, "ts": 20.0, "dur": 2.5,
         "name": "copy.2"},
        {},
    ]}
    with gzip.open(prof_dir / "host.trace.json.gz", "wt") as f:
        json.dump(doc, f)
    return str(cap)


def test_trace_merge_device_lanes_anchored_and_deduped(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_merge

    cap = _synth_capture(tmp_path, wall_start=1000.0)
    merged = trace_merge.merge_payloads([{
        "service": "worker-backend", "traces": [{
            "trace_id": "t1", "service": "worker-backend", "spans": [
                {"name": "engine.prefill", "trace_id": "t1",
                 "span_id": "s1", "parent_id": None,
                 "service": "worker-backend", "ts": 1000.0, "dur": 0.5,
                 "attrs": {}}]}]}])
    captures = trace_merge.load_device_capture(cap)
    assert len(captures) == 1
    assert captures[0]["service"] == "worker-backend"
    assert captures[0]["wall_start"] == 1000.0
    # Load the SAME capture twice: the dedup key must collapse it.
    added = trace_merge.merge_device_events(
        merged, captures + trace_merge.load_device_capture(cap))
    assert added == 2                       # X events only, once each
    dev = [e for e in merged["traceEvents"] if e.get("cat") == "device"]
    assert {e["name"] for e in dev} == {"fusion.1", "copy.2"}
    # Re-anchored onto the wall clock: wall_start µs + relative ts.
    fusion = next(e for e in dev if e["name"] == "fusion.1")
    assert fusion["ts"] == pytest.approx(1000.0 * 1e6 + 10.0)
    # The device lane is a fresh named track, distinct from host pids.
    lane_meta = [e for e in merged["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"
                 and "device/" in str((e.get("args") or {}).get("name"))]
    assert len(lane_meta) == 1
    assert lane_meta[0]["args"]["name"] \
        == "worker-backend device//device:TPU:0"
    assert all(e["pid"] == lane_meta[0]["pid"] for e in dev)
    host_pids = {e["pid"] for e in merged["traceEvents"]
                 if e.get("ph") == "X" and e.get("cat") != "device"}
    assert lane_meta[0]["pid"] not in host_pids


def test_load_device_capture_without_sidecar_uses_dirname(tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_merge

    cap = _synth_capture(tmp_path, service="worker-prefill", pid=99)
    os.remove(os.path.join(cap, "capture_meta.json"))
    captures = trace_merge.load_device_capture(cap)
    assert captures[0]["service"] == "worker-prefill"
    assert captures[0]["wall_start"] is None
    # Un-anchored captures still merge (relative timestamps kept).
    merged = {"traceEvents": []}
    assert trace_merge.merge_device_events(merged, captures) == 2


def test_profile_trace_cli_exits_nonzero_without_trace_output(
        tmp_path, monkeypatch):
    """The retired-into-thin-CLI contract: a capture that lands no
    trace files must exit nonzero, not print an empty glob and read as
    success."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import profile_trace

    prof = device_profiler.get_profiler()
    monkeypatch.setattr(
        type(prof), "capture",
        lambda self, ms: {"ok": False, "error": "no plugin"})
    try:
        rc = profile_trace.main(
            ["--ms", "10", "--steps", "1", "--out-dir", str(tmp_path)])
    finally:
        prof.reset()
        prof.configure(enabled=False, service="dynamo")
        prof.dump_dir = None
    assert rc == 1


# -- live worker (slow) ------------------------------------------------------


@pytest.mark.slow
def test_deviceprofile_live_worker(tmp_path):
    """A REAL worker process serves the device-truth plane end to end:
    /metrics carries dynamo_program_registry_size, /debug/deviceprofile
    reports the plane enabled, a bad ms is a 400, and an on-demand
    ?ms=N capture lands real trace files under --flight-dump-dir in the
    deviceprofile_<service>_<pid> directory."""
    import aiohttp

    from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneServer

    async def main():
        srv = ControlPlaneServer()
        cp_port = await srv.start()
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        log = open(tmp_path / "worker.log", "w+")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--control-plane", f"127.0.0.1:{cp_port}",
             "--mocker", "--model-name", "dp-test", "--block-size", "8",
             "--flight-dump-dir", str(tmp_path)],
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT)
        try:
            deadline = time.monotonic() + 60
            text = ""
            while time.monotonic() < deadline:
                log.flush()
                log.seek(0)
                text = log.read()
                if "worker instance" in text:
                    break
                await asyncio.sleep(0.2)
            else:
                raise AssertionError("worker never started: "
                                     + open(tmp_path / "worker.log").read())
            m = re.search(r"worker status server on :(\d+)", text)
            assert m, text
            sport = int(m.group(1))
            async with aiohttp.ClientSession() as s:
                async with s.get(
                        f"http://127.0.0.1:{sport}/metrics") as r:
                    assert r.status == 200
                    metrics = await r.text()
                # The plane is on by default; the mocker compiles no
                # jitted programs, so the registry reports empty.
                assert "dynamo_program_registry_size 0" in metrics
                async with s.get(f"http://127.0.0.1:{sport}"
                                 "/debug/deviceprofile") as r:
                    assert r.status == 200
                    body = await r.json()
                assert body["enabled"] is True
                assert body["pid"] == proc.pid
                assert body["service"] == "worker-backend"
                async with s.get(f"http://127.0.0.1:{sport}"
                                 "/debug/deviceprofile?ms=nope") as r:
                    assert r.status == 400
                async with s.get(
                        f"http://127.0.0.1:{sport}"
                        "/debug/deviceprofile?ms=200",
                        timeout=aiohttp.ClientTimeout(total=60)) as r:
                    body = await r.json()
                    assert r.status == 200, body
                assert body["ok"] is True
                assert body["pid"] == proc.pid
                cap_dir = (tmp_path
                           / f"deviceprofile_worker-backend_{proc.pid}")
                assert str(cap_dir) == body["dir"]
                assert cap_dir.is_dir()
                assert (cap_dir / "capture_meta.json").exists()
                assert body["files"], body
        finally:
            proc.kill()
            proc.wait(timeout=20)
            log.close()
            await srv.stop()

    asyncio.run(asyncio.wait_for(main(), 150))
