"""Device-direct KV transfer plane (the NIXL analog, device edition).

Same-process: worker A stages G1-resident device blocks, worker B pulls
them device-to-device through the PJRT transfer service and serves the
prompt with prefill skipped — no numpy hop on either side.

Two-process: a holder process stages blocks and prints its descriptor; a
puller process in a separate OS process pulls over localhost — the CPU
stand-in for the cross-host DCN path (the driver's multi-chip dryrun
model, SURVEY §7 'riskiest novel component')."""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.device_transfer import (
    KV_OFFER_ENDPOINT,
    KvTransferPlane,
    pull_prefix_device,
    transfer_available,
)

pytestmark = pytest.mark.skipif(
    not transfer_available(),
    reason="jax.experimental.transfer not in this jax build")
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime.rpc import RpcClient, RpcServer
from dynamo_tpu.tokens import compute_block_hashes

TINY = mcfg.get_config("tiny-test")
BS = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _core():
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))


def test_device_pull_between_engines_same_process():
    prompt = list(range(40, 70))  # 3 sealed blocks + tail

    async def main():
        core_a, core_b = _core(), _core()
        eng_a, eng_b = InferenceEngine(core_a), InferenceEngine(core_b)
        await eng_a.start()
        await eng_b.start()

        plane_a = KvTransferPlane(eng_a)
        plane_a.start()
        plane_b = KvTransferPlane(eng_b)
        plane_b.start()

        server = RpcServer()
        server.register(KV_OFFER_ENDPOINT, plane_a.make_offer_handler())
        addr = await server.start()

        out_a = []
        async for d in eng_a.generate("a", prompt,
                                      SamplingParams(max_tokens=4)):
            out_a.extend(d.token_ids)

        client = RpcClient(addr)
        covered = await pull_prefix_device(eng_b, plane_b, client, prompt,
                                           BS)
        assert covered == 24  # 3 sealed blocks of 8
        assert plane_a.offers == 1
        assert plane_b.pulled_blocks == 3

        out_b = []
        async for d in eng_b.generate("b", prompt,
                                      SamplingParams(max_tokens=4)):
            out_b.extend(d.token_ids)
        assert out_b == out_a
        assert core_b.allocator.manager.device.hits >= 3

        # Unknown hashes: empty offer, puller reports 0 (fallback signal).
        covered = await pull_prefix_device(
            eng_b, plane_b, client, list(range(200, 216)), BS)
        assert covered == 0

        await client.close()
        await server.stop()
        await eng_a.stop()
        await eng_b.stop()
        return True

    assert asyncio.run(asyncio.wait_for(main(), timeout=120))


_HOLDER = r"""
import sys, json
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from dynamo_tpu.llm.block_manager.device_transfer import KvTransferPlane

plane = KvTransferPlane()
plane.start()
blocks = {{h: jnp.full((2, 2, 8, 16), h, jnp.float32) for h in (11, 22, 33)}}
meta = plane.stage(blocks, [11, 22, 33])
print("META " + json.dumps(meta), flush=True)
sys.stdin.readline()  # stay alive until the puller is done
"""

_PULLER = r"""
import sys, json, asyncio
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from dynamo_tpu.llm.block_manager.device_transfer import KvTransferPlane

meta = json.loads(sys.argv[1])
plane = KvTransferPlane()
plane.start()
blocks = asyncio.run(plane.pull(meta))
ok = sorted(blocks) == [11, 22, 33] and all(
    np.allclose(np.asarray(v), h) for h, v in blocks.items())
print("PULL_OK" if ok else "PULL_BAD", flush=True)
"""


@pytest.mark.e2e
def test_device_pull_across_processes():
    """The DCN-path dryrun: holder and puller are separate OS processes;
    blocks cross via the PJRT transfer service over localhost."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    holder = subprocess.Popen(
        [sys.executable, "-c", _HOLDER.format(repo=REPO)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = holder.stdout.readline().strip()
        assert line.startswith("META "), line
        meta = json.loads(line[5:])
        assert meta["uuid"] and meta["hashes"] == [11, 22, 33]

        out = subprocess.run(
            [sys.executable, "-c", _PULLER.format(repo=REPO),
             json.dumps(meta)],
            capture_output=True, text=True, timeout=120, env=env)
        assert "PULL_OK" in out.stdout, (out.stdout, out.stderr[-2000:])
    finally:
        try:
            holder.stdin.write("\n")
            holder.stdin.flush()
        except Exception:
            pass
        holder.terminate()
        holder.wait(timeout=10)


@pytest.mark.e2e
@pytest.mark.parametrize("prefill_tp,decode_tp", [(1, 2), (2, 1)])
def test_disagg_reshards_kv_between_tp_degrees(prefill_tp, decode_tp,
                                               tmp_path):
    """VERDICT r4 next-5 'done': disagg moves KV device-direct between
    workers with DIFFERENT tp degrees — extract gathers the canonical
    block from the holder's sharding, inject scatters into the puller's
    (the block_copy.cu layout-transpose analog, `disagg_serving.md:96`)."""
    import time

    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    procs = []

    def spawn(name, extra):
        log = open(tmp_path / f"{name}.log", "w+")
        p = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--model", "tiny-test", "--block-size", "8",
             "--decode-window", "4"] + extra,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO),
            cwd=REPO, stdout=log, stderr=subprocess.STDOUT, text=True)
        p._log = log
        procs.append(p)
        return p

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        watcher = ModelWatcher(runtime, models, migration_limit=0)
        await watcher.start()
        svc = HttpService(models)
        http_port = await svc.start()

        cp_addr = f"127.0.0.1:{cp_port}"
        decode = spawn("decode", [
            "--control-plane", cp_addr, "--model-name", "reshard",
            "--role", "decode", "--max-local-prefill", "8",
            "--tp", str(decode_tp)])
        spawn("prefill", ["--control-plane", cp_addr,
                          "--role", "prefill",
                          "--tp", str(prefill_tp)])
        await watcher.wait_for_model("reshard", timeout=180)

        base = f"http://127.0.0.1:{http_port}"
        async with ClientSession() as s:
            async with s.post(f"{base}/v1/chat/completions", json={
                    "model": "reshard",
                    "messages": [{"role": "user",
                                  "content": "a prompt long enough to "
                                             "cross the remote prefill "
                                             "threshold easily"}],
                    "max_tokens": 8}) as r:
                body = await r.json()
                assert r.status == 200, body
                assert body["choices"][0]["message"]["content"]

        # The SUCCESS line is "... onboarded from HOST (device-direct)";
        # the failure path logs "device-direct pull ... failed" — assert
        # the parenthesised success marker so a broken plane can't pass.
        deadline = time.monotonic() + 15
        log = ""
        while time.monotonic() < deadline:
            decode._log.flush()
            decode._log.seek(0)
            log = decode._log.read()
            if "(device-direct)" in log:
                break
            await asyncio.sleep(0.5)
        assert "onboarded" in log, f"no remote prefill:\n{log[-3000:]}"
        assert "(device-direct)" in log, (
            f"KV did not move device-direct:\n{log[-3000:]}")

        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()
        await cp_server.stop()

    try:
        asyncio.run(asyncio.wait_for(main(), timeout=300))
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
            p._log.flush()
            p._log.seek(0)
            out = p._log.read()
            if out and ("Traceback" in out or "ERROR" in out):
                print(f"--- {p._log.name} (rc={p.poll()}) ---")
                print(out[-2500:])
