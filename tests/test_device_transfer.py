"""Device-direct KV data plane v2 (the NIXL analog, device edition).

Same-process: worker A stages G1-resident device blocks, worker B pulls
them device-to-device and serves the prompt with prefill skipped — no
numpy hop on either side.  On jax builds without the PJRT transfer
service the plane rides the local device_put fabric, so these tests run
(and the plane-choice counters are pinned) on the plain CPU rig.

Two-process: a holder process stages blocks and prints its descriptor; a
puller process in a separate OS process pulls over localhost — the CPU
stand-in for the cross-host DCN path (the driver's multi-chip dryrun
model, SURVEY §7 'riskiest novel component').  PJRT-only: the local
fabric cannot cross processes, so those tests skip without the service.
"""

import asyncio
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.device_transfer import (
    KV_OFFER_ENDPOINT,
    KV_PULLED_ENDPOINT,
    MAX_OUTSTANDING_OFFERS,
    KvTransferPlane,
    plane_counts,
    pull_prefix_device,
    transfer_available,
)
from dynamo_tpu.llm.block_manager.transfer import (
    KV_BLOCKS_ENDPOINT,
    make_kv_blocks_handler,
    sealed_hashes,
)
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime.rpc import RpcClient, RpcServer

TINY = mcfg.get_config("tiny-test")
BS = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LONG_PROMPT = list(range(1, 36))   # 4 sealed blocks + 3-token tail

pjrt_only = pytest.mark.skipif(
    not transfer_available(),
    reason="cross-process device transfer needs jax.experimental.transfer")


def _core(kv_quant="none"):
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64, kv_quant=kv_quant,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))


class _Holder:
    """One in-process donor worker: engine + plane + RPC server with the
    offer/ack/kv_blocks endpoints (what worker/main.py registers)."""

    async def start(self, kv_quant="none"):
        self.engine = InferenceEngine(_core(kv_quant))
        await self.engine.start()
        self.plane = KvTransferPlane(self.engine)
        self.plane.start()
        self.rpc = RpcServer()
        self.rpc.register(KV_OFFER_ENDPOINT, self.plane.make_offer_handler())
        self.rpc.register(KV_PULLED_ENDPOINT,
                          self.plane.make_pulled_handler())
        self.rpc.register(KV_BLOCKS_ENDPOINT,
                          make_kv_blocks_handler(self.engine))
        self.address = await self.rpc.start()
        return self

    async def stop(self):
        await self.rpc.stop()
        self.plane.stop()
        await self.engine.stop()


async def _collect(engine, rid, prompt, n=4):
    out = []
    async for d in engine.generate(rid, list(prompt),
                                   SamplingParams(max_tokens=n)):
        out.extend(d.token_ids)
    return out


def _count(plane: str) -> int:
    return sum(n for (p, _), n in plane_counts().items() if p == plane)


def _reasons(plane: str) -> dict:
    return {r: n for (p, r), n in plane_counts().items() if p == plane}


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, 180))


def test_device_pull_between_engines_same_process():
    prompt = list(range(40, 70))  # 3 sealed blocks + tail

    async def main():
        holder = await _Holder().start()
        eng_b = InferenceEngine(_core())
        await eng_b.start()
        plane_b = KvTransferPlane(eng_b)
        plane_b.start()
        client = RpcClient(holder.address)
        dev0 = _count("device")
        try:
            out_a = await _collect(holder.engine, "a", prompt)

            covered = await pull_prefix_device(eng_b, plane_b, client,
                                               prompt, BS)
            assert covered == 24  # 3 sealed blocks of 8
            assert holder.plane.offers == 1
            assert plane_b.pulled_blocks == 3
            assert _count("device") - dev0 == 1   # one batched round
            # The puller's ack (spawned off the pull's critical path)
            # retires the holder's offer accounting.
            for _ in range(200):
                if not holder.plane._outstanding:
                    break
                await asyncio.sleep(0.01)
            assert len(holder.plane._outstanding) == 0

            out_b = await _collect(eng_b, "b", prompt)
            assert out_b == out_a
            assert eng_b.core.allocator.manager.device.hits >= 3

            # Unknown hashes: refused offer ('not_resident'), puller
            # reports 0 — the fallback signal — and the reason is
            # counted against the host plane.
            covered = await pull_prefix_device(
                eng_b, plane_b, client, list(range(200, 216)), BS)
            assert covered == 0
            assert _reasons("host").get("not_resident", 0) >= 1
        finally:
            await client.close()
            await holder.stop()
            plane_b.stop()
            await eng_b.stop()

    _run(main())


def test_offer_ttl_and_refusal_split():
    """Stale-offer reclaim (ISSUE 13 satellite): offers carry a TTL;
    expired offers retire from the outstanding accounting (counted
    separately from cap refusals), so a puller that died between offer
    and pull cannot starve the cap forever."""
    import jax.numpy as jnp

    blocks = {h: jnp.zeros((2, 2, BS, 4), jnp.float32)
              for h in range(1, MAX_OUTSTANDING_OFFERS + 2)}

    # Default TTL: the cap refuses the 33rd offer.
    plane = KvTransferPlane()
    plane.start()
    first = plane.stage(blocks, [1])
    assert first is not None
    for h in range(2, MAX_OUTSTANDING_OFFERS + 1):
        assert plane.stage(blocks, [h]) is not None
    assert plane.stage(blocks, [MAX_OUTSTANDING_OFFERS + 1]) is None
    assert plane.last_refusal == "offer_cap"
    assert plane.refused_offers == 1 and plane.expired_offers == 0
    # An ack retires one slot and the next offer fits again.
    plane.mark_pulled(first["uuid"])
    assert plane.stage(blocks, [MAX_OUTSTANDING_OFFERS + 1]) is not None
    plane.stop()

    # TTL 0: hitting the cap expires the stale offers instead of
    # refusing — the cap stops lying about strandable memory.
    plane = KvTransferPlane(offer_ttl_s=0.0)
    plane.start()
    for h in range(1, MAX_OUTSTANDING_OFFERS + 1):
        assert plane.stage(blocks, [h]) is not None
    assert plane.stage(blocks, [MAX_OUTSTANDING_OFFERS + 1]) is not None
    assert plane.expired_offers == MAX_OUTSTANDING_OFFERS
    assert plane.refused_offers == 0
    assert len(plane._outstanding) == 1
    plane.stop()

    # Transport mismatch (a peer on a fabric this holder can't reach)
    # refuses with its own reason on every transport kind.
    plane = KvTransferPlane()
    plane.start()
    assert plane.stage(blocks, [1], peer_fabric="local:0") is None
    assert plane.last_refusal == "transport"
    assert plane.refused_offers == 1
    plane.stop()


@pytest.mark.slow
def test_int8_packed_block_device_pull_parity():
    """ISSUE 13 satellite: the packed int8 wire block [2, L, bs, F+4Hkv]
    crosses the device plane byte-identical to the host-staged path, and
    a mixed bf16<-int8 device offer is refused loudly at inject.

    Slow-marked (3 engine builds): tier-1 runs ~650-800 s against the
    870 s timeout, and its acceptance coverage (byte-identical outputs +
    pinned plane counters) stays in tier-1 via the bf16 eager/prefix
    e2e tests below; the int8 wire itself is also parity-checked by
    tests/test_kv_transfer.py on the host plane."""
    prompt = list(range(40, 70))

    async def main():
        holder = await _Holder().start("int8")
        eng_b = InferenceEngine(_core("int8"))
        await eng_b.start()
        plane_b = KvTransferPlane(eng_b)
        plane_b.start()
        eng_c = InferenceEngine(_core())          # bf16: must refuse
        await eng_c.start()
        plane_c = KvTransferPlane(eng_c)
        plane_c.start()
        client = RpcClient(holder.address)
        try:
            out_a = await _collect(holder.engine, "a", prompt)
            covered = await pull_prefix_device(eng_b, plane_b, client,
                                               prompt, BS)
            assert covered == 24

            hashes = sealed_hashes(prompt, BS)
            wire_shape = holder.engine.core.cache_cfg.block_wire_shape
            exp_a = await holder.engine.export_blocks(hashes)
            exp_b = await eng_b.export_blocks(hashes)
            assert set(exp_b) == set(hashes)
            for h in hashes:
                a, b = np.asarray(exp_a[h]), np.asarray(exp_b[h])
                assert a.dtype == b.dtype == np.int8
                assert a.shape == b.shape == wire_shape
                assert np.array_equal(a, b)   # byte-identical inject

            out_b = await _collect(eng_b, "b", prompt)
            assert out_b == out_a

            # Mixed-mode peer: the bf16 engine's inject must REFUSE the
            # packed int8 block — loudly, with nothing in the cache —
            # and the error propagates so the caller falls back to
            # LOCAL prefill (the host wire would refuse identically).
            with pytest.raises(ValueError, match="kv_quant"):
                await pull_prefix_device(eng_c, plane_c, client, prompt,
                                         BS)
            assert eng_c.core.allocator.manager.onboarded_blocks == 0
        finally:
            await client.close()
            await holder.stop()
            for plane, eng in ((plane_b, eng_b), (plane_c, eng_c)):
                plane.stop()
                await eng.stop()

    _run(main())


def test_eager_stream_rides_device_plane():
    """Acceptance e2e: eager streaming pulls sealed blocks
    device-to-device while 'prefill' announces progress — plane
    counters pinned, outputs byte-identical, zero host-staged blocks."""
    from dynamo_tpu.llm.block_manager.eager import EagerPuller

    async def main():
        holder = await _Holder().start()
        eng_b = InferenceEngine(_core())
        await eng_b.start()
        plane_b = KvTransferPlane(eng_b)
        plane_b.start()
        client = RpcClient(holder.address)
        dev0, host0 = _count("device"), _count("host")
        try:
            out_a = await _collect(holder.engine, "a", LONG_PROMPT)

            puller = EagerPuller(eng_b, lambda a: client, LONG_PROMPT,
                                 BS, plane=plane_b, batch_blocks=2)
            puller.on_progress(2, holder.address)
            await asyncio.sleep(0.05)      # first batch in flight
            puller.on_progress(4, holder.address)
            covered = await puller.finish(holder.address)

            assert covered == 4 * BS
            assert puller.covered_blocks == 4
            assert puller.device_blocks == 4       # ALL blocks device
            assert plane_b.pulled_blocks == 4
            assert _count("device") - dev0 >= 2    # two batched rounds
            assert _count("host") - host0 == 0     # never host-staged

            out_b = await _collect(eng_b, "b", LONG_PROMPT)
            assert out_b == out_a                  # byte-identical
            sched = eng_b.core.scheduler
            assert sched.prefix_hit_tokens == 4 * BS
        finally:
            await client.close()
            await holder.stop()
            plane_b.stop()
            await eng_b.stop()

    _run(main())


def test_prefix_fetcher_device_first_with_host_fallback():
    """Acceptance e2e: PrefixFetcher.pull probes the device plane first
    (counters pinned); a holder whose offer cap is exhausted degrades to
    the host-staged wire — same frontier accounting, request still
    lands."""
    from dynamo_tpu.llm.block_manager.prefix_share import PrefixFetcher

    async def main():
        holder = await _Holder().start()
        eng_b = InferenceEngine(_core())
        await eng_b.start()
        plane_b = KvTransferPlane(eng_b)
        plane_b.start()
        client = RpcClient(holder.address)
        try:
            out_a = await _collect(holder.engine, "a", LONG_PROMPT)

            dev0 = _count("device")
            fetcher = PrefixFetcher(eng_b, lambda a: client, BS,
                                    plane=plane_b, batch_blocks=2)
            covered = await fetcher.pull(LONG_PROMPT, holder.address,
                                         4 * BS)
            assert covered == 4 * BS
            assert fetcher.remote_hits == 1 and fetcher.fallbacks == 0
            assert fetcher.device_pulled_blocks == 4
            assert _count("device") - dev0 >= 2
            out_b = await _collect(eng_b, "b", LONG_PROMPT)
            assert out_b == out_a

            # Holder cap exhausted: every offer refused -> the SAME
            # pull covers everything over the host wire, reason counted.
            await eng_b.clear_kv_blocks()
            holder.plane._outstanding = {
                10_000 + i: (1, time.monotonic() + 999)
                for i in range(MAX_OUTSTANDING_OFFERS)}
            fetcher2 = PrefixFetcher(eng_b, lambda a: client, BS,
                                     plane=plane_b, batch_blocks=2)
            covered = await fetcher2.pull(LONG_PROMPT, holder.address,
                                          4 * BS)
            assert covered == 4 * BS               # request still lands
            assert fetcher2.device_pulled_blocks == 0
            assert fetcher2.fallbacks == 0
            assert _reasons("host").get("offer_cap", 0) >= 1
            out_b = await _collect(eng_b, "b2", LONG_PROMPT)
            assert out_b == out_a
        finally:
            await client.close()
            await holder.stop()
            plane_b.stop()
            await eng_b.stop()

    _run(main())


def test_mesh_pull_lands_on_inject_sharding():
    """ISSUE 13 bugfix: under a mesh, pulled blocks must land on the
    engine's inject sharding (replicated over the mesh), not pile onto
    jax.devices()[0] and double-copy at inject."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding

    from dynamo_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    core = EngineCore(EngineConfig(
        model=TINY, num_blocks=64, mesh=mesh,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))
    sharding = core.block_inject_sharding
    assert isinstance(sharding, NamedSharding)
    assert len(sharding.device_set) == 2

    holder = KvTransferPlane()
    holder.start()
    puller = KvTransferPlane(InferenceEngine(core))
    puller.start()
    wire = core.cache_cfg.block_wire_shape
    blocks = {7: jnp.zeros(wire, core.cache_cfg.block_wire_dtype)}
    meta = holder.stage(blocks, [7], peer_fabric=puller.fabric)
    assert meta is not None
    pulled = _run(puller.pull(meta))
    assert set(pulled[7].sharding.device_set) == set(sharding.device_set)
    holder.stop()
    puller.stop()

    # Meshless engines land on the cache's own device (the pre-fix
    # single-device behavior, still correct there).
    core1 = _core()
    assert len(core1.block_inject_sharding.device_set) == 1


def test_plane_counters_sampled_into_metrics_and_top():
    """Plane-choice observability (ISSUE 13 satellite): note_plane
    tallies sample into dynamo_kv_transfer_plane_total without
    double-counting, and `dynamo top` renders the device/host split."""
    import importlib.util

    from dynamo_tpu.runtime.metrics import KvCacheMetrics, MetricsRegistry

    reg = MetricsRegistry()
    kv = KvCacheMetrics(reg)
    counts = {("device", "eager"): 3, ("host", "offer_cap"): 1}
    kv.observe_transfer_plane(counts=counts)
    kv.observe_transfer_plane(counts=counts)   # same cumulatives: no inc
    text = reg.expose()
    assert ('dynamo_kv_transfer_plane_total'
            '{plane="device",reason="eager"} 3') in text
    assert ('dynamo_kv_transfer_plane_total'
            '{plane="host",reason="offer_cap"} 1') in text

    spec = importlib.util.spec_from_file_location(
        "dynamo_top", os.path.join(REPO, "tools", "dynamo_top.py"))
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    samples = [("dynamo_kv_transfer_plane_total",
                {"plane": "device", "reason": "eager"}, 3.0),
               ("dynamo_kv_transfer_plane_total",
                {"plane": "host", "reason": "offer_cap"}, 1.0)]
    row = top.summarize("worker-both", "127.0.0.1:1", samples, None)
    assert row["device_pulls"] == 3.0
    assert row["host_pulls"] == 1.0
    table = top.render_table({"control_plane": "cp", "processes": [row]})
    assert "PLANE" in table.splitlines()[1]
    assert "d3/h1" in table


_HOLDER = r"""
import sys, json
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from dynamo_tpu.llm.block_manager.device_transfer import KvTransferPlane

plane = KvTransferPlane()
plane.start()
blocks = {{h: jnp.full((2, 2, 8, 16), h, jnp.float32) for h in (11, 22, 33)}}
meta = plane.stage(blocks, [11, 22, 33])
print("META " + json.dumps(meta), flush=True)
sys.stdin.readline()  # stay alive until the puller is done
"""

_PULLER = r"""
import sys, json, asyncio
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from dynamo_tpu.llm.block_manager.device_transfer import KvTransferPlane

meta = json.loads(sys.argv[1])
plane = KvTransferPlane()
plane.start()
blocks = asyncio.run(plane.pull(meta))
ok = sorted(blocks) == [11, 22, 33] and all(
    np.allclose(np.asarray(v), h) for h, v in blocks.items())
print("PULL_OK" if ok else "PULL_BAD", flush=True)
"""


@pjrt_only
@pytest.mark.e2e
def test_device_pull_across_processes():
    """The DCN-path dryrun: holder and puller are separate OS processes;
    blocks cross via the PJRT transfer service over localhost."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    holder = subprocess.Popen(
        [sys.executable, "-c", _HOLDER.format(repo=REPO)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = holder.stdout.readline().strip()
        assert line.startswith("META "), line
        meta = json.loads(line[5:])
        assert meta["uuid"] and meta["hashes"] == [11, 22, 33]

        out = subprocess.run(
            [sys.executable, "-c", _PULLER.format(repo=REPO),
             json.dumps(meta)],
            capture_output=True, text=True, timeout=120, env=env)
        assert "PULL_OK" in out.stdout, (out.stdout, out.stderr[-2000:])
    finally:
        try:
            holder.stdin.write("\n")
            holder.stdin.flush()
        except Exception:
            pass
        holder.terminate()
        holder.wait(timeout=10)


@pjrt_only
@pytest.mark.e2e
@pytest.mark.parametrize("prefill_tp,decode_tp", [(1, 2), (2, 1)])
def test_disagg_reshards_kv_between_tp_degrees(prefill_tp, decode_tp,
                                               tmp_path):
    """VERDICT r4 next-5 'done': disagg moves KV device-direct between
    workers with DIFFERENT tp degrees — extract gathers the canonical
    block from the holder's sharding, inject scatters into the puller's
    (the block_copy.cu layout-transpose analog, `disagg_serving.md:96`)."""
    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    procs = []

    def spawn(name, extra):
        log = open(tmp_path / f"{name}.log", "w+")
        p = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--model", "tiny-test", "--block-size", "8",
             "--decode-window", "4"] + extra,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO),
            cwd=REPO, stdout=log, stderr=subprocess.STDOUT, text=True)
        p._log = log
        procs.append(p)
        return p

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        watcher = ModelWatcher(runtime, models, migration_limit=0)
        await watcher.start()
        svc = HttpService(models)
        http_port = await svc.start()

        cp_addr = f"127.0.0.1:{cp_port}"
        decode = spawn("decode", [
            "--control-plane", cp_addr, "--model-name", "reshard",
            "--role", "decode", "--max-local-prefill", "8",
            "--tp", str(decode_tp)])
        spawn("prefill", ["--control-plane", cp_addr,
                          "--role", "prefill",
                          "--tp", str(prefill_tp)])
        await watcher.wait_for_model("reshard", timeout=180)

        base = f"http://127.0.0.1:{http_port}"
        async with ClientSession() as s:
            async with s.post(f"{base}/v1/chat/completions", json={
                    "model": "reshard",
                    "messages": [{"role": "user",
                                  "content": "a prompt long enough to "
                                             "cross the remote prefill "
                                             "threshold easily"}],
                    "max_tokens": 8}) as r:
                body = await r.json()
                assert r.status == 200, body
                assert body["choices"][0]["message"]["content"]

        # The SUCCESS markers are "... onboarded from HOST
        # (device-direct)" / "(device-stream)"; the failure path logs
        # "device... pull ... failed" — assert the parenthesised success
        # marker so a broken plane can't pass.
        deadline = time.monotonic() + 15
        log = ""
        while time.monotonic() < deadline:
            decode._log.flush()
            decode._log.seek(0)
            log = decode._log.read()
            if "(device-direct)" in log or "(device-stream)" in log:
                break
            await asyncio.sleep(0.5)
        assert "onboarded" in log, f"no remote prefill:\n{log[-3000:]}"
        assert "(device-direct)" in log or "(device-stream)" in log, (
            f"KV did not move device-direct:\n{log[-3000:]}")

        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()
        await cp_server.stop()

    try:
        asyncio.run(asyncio.wait_for(main(), timeout=300))
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
            p._log.flush()
            p._log.seek(0)
            out = p._log.read()
            if out and ("Traceback" in out or "ERROR" in out):
                print(f"--- {p._log.name} (rc={p.poll()}) ---")
                print(out[-2500:])
