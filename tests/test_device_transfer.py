"""Device-direct KV transfer plane (the NIXL analog, device edition).

Same-process: worker A stages G1-resident device blocks, worker B pulls
them device-to-device through the PJRT transfer service and serves the
prompt with prefill skipped — no numpy hop on either side.

Two-process: a holder process stages blocks and prints its descriptor; a
puller process in a separate OS process pulls over localhost — the CPU
stand-in for the cross-host DCN path (the driver's multi-chip dryrun
model, SURVEY §7 'riskiest novel component')."""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.device_transfer import (
    KV_OFFER_ENDPOINT,
    KvTransferPlane,
    pull_prefix_device,
)
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime.rpc import RpcClient, RpcServer
from dynamo_tpu.tokens import compute_block_hashes

TINY = mcfg.get_config("tiny-test")
BS = 8
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _core():
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))


def test_device_pull_between_engines_same_process():
    prompt = list(range(40, 70))  # 3 sealed blocks + tail

    async def main():
        core_a, core_b = _core(), _core()
        eng_a, eng_b = InferenceEngine(core_a), InferenceEngine(core_b)
        await eng_a.start()
        await eng_b.start()

        plane_a = KvTransferPlane(eng_a)
        plane_a.start()
        plane_b = KvTransferPlane(eng_b)
        plane_b.start()

        server = RpcServer()
        server.register(KV_OFFER_ENDPOINT, plane_a.make_offer_handler())
        addr = await server.start()

        out_a = []
        async for d in eng_a.generate("a", prompt,
                                      SamplingParams(max_tokens=4)):
            out_a.extend(d.token_ids)

        client = RpcClient(addr)
        covered = await pull_prefix_device(eng_b, plane_b, client, prompt,
                                           BS)
        assert covered == 24  # 3 sealed blocks of 8
        assert plane_a.offers == 1
        assert plane_b.pulled_blocks == 3

        out_b = []
        async for d in eng_b.generate("b", prompt,
                                      SamplingParams(max_tokens=4)):
            out_b.extend(d.token_ids)
        assert out_b == out_a
        assert core_b.allocator.manager.device.hits >= 3

        # Unknown hashes: empty offer, puller reports 0 (fallback signal).
        covered = await pull_prefix_device(
            eng_b, plane_b, client, list(range(200, 216)), BS)
        assert covered == 0

        await client.close()
        await server.stop()
        await eng_a.stop()
        await eng_b.stop()
        return True

    assert asyncio.run(asyncio.wait_for(main(), timeout=120))


_HOLDER = r"""
import sys, json
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
from dynamo_tpu.llm.block_manager.device_transfer import KvTransferPlane

plane = KvTransferPlane()
plane.start()
blocks = {{h: jnp.full((2, 2, 8, 16), h, jnp.float32) for h in (11, 22, 33)}}
meta = plane.stage(blocks, [11, 22, 33])
print("META " + json.dumps(meta), flush=True)
sys.stdin.readline()  # stay alive until the puller is done
"""

_PULLER = r"""
import sys, json, asyncio
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from dynamo_tpu.llm.block_manager.device_transfer import KvTransferPlane

meta = json.loads(sys.argv[1])
plane = KvTransferPlane()
plane.start()
blocks = asyncio.run(plane.pull(meta))
ok = sorted(blocks) == [11, 22, 33] and all(
    np.allclose(np.asarray(v), h) for h, v in blocks.items())
print("PULL_OK" if ok else "PULL_BAD", flush=True)
"""


@pytest.mark.e2e
def test_device_pull_across_processes():
    """The DCN-path dryrun: holder and puller are separate OS processes;
    blocks cross via the PJRT transfer service over localhost."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    holder = subprocess.Popen(
        [sys.executable, "-c", _HOLDER.format(repo=REPO)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = holder.stdout.readline().strip()
        assert line.startswith("META "), line
        meta = json.loads(line[5:])
        assert meta["uuid"] and meta["hashes"] == [11, 22, 33]

        out = subprocess.run(
            [sys.executable, "-c", _PULLER.format(repo=REPO),
             json.dumps(meta)],
            capture_output=True, text=True, timeout=120, env=env)
        assert "PULL_OK" in out.stdout, (out.stdout, out.stderr[-2000:])
    finally:
        try:
            holder.stdin.write("\n")
            holder.stdin.flush()
        except Exception:
            pass
        holder.terminate()
        holder.wait(timeout=10)
