"""Disaggregated P/D e2e: prefill worker + decode worker + acked queue.

The round-3 milestone VERDICT asked for: long prompts measurably skip
decode-side prefill (asserted via the decode engine's onboard/hit
counters), short prompts stay local, remote failure falls back to local
prefill, and the threshold hot-reloads from the control plane.  Mirrors
`/root/reference/docs/architecture/disagg_serving.md:20-64`.
"""

import asyncio

import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.transfer import (
    KV_BLOCKS_ENDPOINT,
    make_kv_blocks_handler,
)
from dynamo_tpu.llm.disagg import (
    DisaggDecodeClient,
    disagg_config_key,
    prefill_queue_name,
    prefill_worker_loop,
)
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.llm.service import LocalEngineClient
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime.control_plane import InProcessControlPlane
from dynamo_tpu.runtime.rpc import RpcServer

TINY = mcfg.get_config("tiny-test")
BS = 8
NS = "test-disagg"


def _core():
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))


class _Worker:
    """One in-process worker: engine + RPC server with kv_blocks."""

    async def start(self):
        self.engine = InferenceEngine(_core())
        await self.engine.start()
        self.client = LocalEngineClient(self.engine)
        self.rpc = RpcServer()
        self.rpc.register(KV_BLOCKS_ENDPOINT,
                          make_kv_blocks_handler(self.engine))
        self.address = await self.rpc.start()
        return self

    async def stop(self):
        await self.rpc.stop()
        await self.engine.stop()


async def _collect(client, rid, prompt, n=4):
    req = PreprocessedRequest(request_id=rid, model="m",
                              token_ids=list(prompt),
                              sampling=SamplingParams(max_tokens=n))
    out = []
    async for d in client.generate(req):
        out.extend(d.token_ids)
        if d.finished:
            break
    return out


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


def test_disagg_long_prompt_skips_decode_prefill():
    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        await cp.put(disagg_config_key(NS), {"max_local_prefill_length": 12})

        prefill = await _Worker().start()
        decode = await _Worker().start()
        ploop = asyncio.create_task(prefill_worker_loop(
            cp, NS, prefill.client, prefill.address))

        dec = DisaggDecodeClient(decode.client, decode.engine, cp, NS, BS)
        await dec.start()
        try:
            # Reference output: same prompt served aggregated on a fresh
            # engine (prefill + decode in one place).
            ref = await _Worker().start()
            long_prompt = list(range(1, 28))  # 3 sealed blocks + tail
            want = await _collect(ref.client, "ref", long_prompt)
            await ref.stop()

            got = await _collect(dec, "r1", long_prompt)
            assert got == want
            assert dec.remote_prefills == 1 and dec.local_fallbacks == 0
            # 3 sealed blocks pulled from the prefill worker.
            assert dec.tokens_onboarded == 24
            mgr = decode.engine.core.allocator.manager
            assert mgr.onboarded_blocks == 3
            # Decode-side prefix hit: only the tail was prefilled locally.
            assert mgr.device.hits >= 3
            # The queue item was acked (nothing left in flight).
            assert await cp.queue_len(prefill_queue_name(NS)) == 0
            assert not cp.state._inflight_msgs

            # Short prompt: stays local, no extra remote prefill.
            short = list(range(100, 108))
            got_short = await _collect(dec, "r2", short)
            ref2 = await _Worker().start()
            assert got_short == await _collect(ref2.client, "ref2", short)
            await ref2.stop()
            assert dec.remote_prefills == 1  # unchanged
        finally:
            ploop.cancel()
            await dec.stop()
            await prefill.stop()
            await decode.stop()
            await cp.close()

    _run(main())


def test_disagg_falls_back_when_no_prefill_worker():
    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        await cp.put(disagg_config_key(NS), {"max_local_prefill_length": 12})
        decode = await _Worker().start()
        dec = DisaggDecodeClient(decode.client, decode.engine, cp, NS, BS,
                                 prefill_timeout=0.3)
        await dec.start()
        try:
            long_prompt = list(range(1, 28))
            ref = await _Worker().start()
            want = await _collect(ref.client, "ref", long_prompt)
            await ref.stop()
            got = await _collect(dec, "r1", long_prompt)
            assert got == want
            assert dec.local_fallbacks == 1
        finally:
            await dec.stop()
            await decode.stop()
            await cp.close()

    _run(main())


def test_disagg_threshold_hot_reload():
    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        decode = await _Worker().start()
        dec = DisaggDecodeClient(decode.client, decode.engine, cp, NS, BS)
        await dec.start()
        try:
            assert not dec.router.prefill_remotely(1000)  # disagg off
            await cp.put(disagg_config_key(NS),
                         {"max_local_prefill_length": 16})
            await asyncio.sleep(0.05)  # watch delivery
            assert dec.router.prefill_remotely(17)
            assert not dec.router.prefill_remotely(16)
            await cp.delete(disagg_config_key(NS))
            await asyncio.sleep(0.05)
            assert not dec.router.prefill_remotely(1000)
        finally:
            await dec.stop()
            await decode.stop()
            await cp.close()

    _run(main())


def test_disagg_device_direct_data_plane():
    """Disagg e2e where KV crosses on the DEVICE plane (VERDICT r3
    next-3): the decode side pulls the prefill worker's blocks through
    the PJRT transfer service — no host msgpack hop — with the
    host-staged plane untouched (device_pulls proves the path taken)."""
    from dynamo_tpu.llm.block_manager.device_transfer import (
        KV_OFFER_ENDPOINT, KV_PULLED_ENDPOINT, KvTransferPlane)

    # Runs on every build: PJRT transfer service where available, the
    # same-process device_put fabric otherwise (ISSUE 13).

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        await cp.put(disagg_config_key(NS), {"max_local_prefill_length": 12})

        prefill = await _Worker().start()
        prefill_plane = KvTransferPlane(prefill.engine)
        prefill_plane.start()
        prefill.rpc.register(KV_OFFER_ENDPOINT,
                             prefill_plane.make_offer_handler())
        prefill.rpc.register(KV_PULLED_ENDPOINT,
                             prefill_plane.make_pulled_handler())
        decode = await _Worker().start()
        decode_plane = KvTransferPlane(decode.engine)
        decode_plane.start()
        ploop = asyncio.create_task(prefill_worker_loop(
            cp, NS, prefill.client, prefill.address))

        dec = DisaggDecodeClient(decode.client, decode.engine, cp, NS, BS,
                                 transfer_plane=decode_plane)
        await dec.start()
        try:
            ref = await _Worker().start()
            long_prompt = list(range(1, 28))  # 3 sealed blocks + tail
            want = await _collect(ref.client, "ref", long_prompt)
            await ref.stop()

            got = await _collect(dec, "r1", long_prompt)
            assert got == want
            assert dec.remote_prefills == 1 and dec.local_fallbacks == 0
            assert dec.device_pulls == 1          # device path carried it
            assert dec.tokens_onboarded == 24
            # Eager streaming batches offers per seal announcement, so
            # the count depends on progress timing; what matters is the
            # device plane moved every block exactly once.
            assert prefill_plane.offers >= 1
            assert decode_plane.pulled_blocks == 3
            assert decode.engine.core.allocator.manager.onboarded_blocks == 3
        finally:
            ploop.cancel()
            await dec.stop()
            await prefill.stop()
            await decode.stop()
            await cp.close()

    _run(main())
