"""Disagg eager KV streaming (ISSUE 4): pulls begin BEFORE prefill-done,
mid-stream prefill-worker death falls back to local prefill reusing the
landed prefix, the real prefill_worker_loop publishes incremental
progress, and the seal-progress stream adds zero host syncs / zero spans
to the steady decode window.
"""

import asyncio
import time

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.transfer import (
    KV_BLOCKS_ENDPOINT,
    make_kv_blocks_handler,
)
from dynamo_tpu.llm.disagg import (
    PREFILL_DONE_SUBJECT,
    PREFILL_PROGRESS_SUBJECT,
    DisaggDecodeClient,
    disagg_config_key,
    prefill_queue_name,
    prefill_worker_loop,
)
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.llm.service import LocalEngineClient
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime.control_plane import InProcessControlPlane

TINY = mcfg.get_config("tiny-test")
BS = 8
NS = "test-disagg-stream"
LONG_PROMPT = list(range(1, 28))  # 3 sealed blocks + tail


def _core():
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))


class _Worker:
    """One in-process worker: engine + RPC server with kv_blocks."""

    async def start(self):
        from dynamo_tpu.runtime.rpc import RpcServer

        self.engine = InferenceEngine(_core())
        await self.engine.start()
        self.client = LocalEngineClient(self.engine)
        self.rpc = RpcServer()
        self.rpc.register(KV_BLOCKS_ENDPOINT,
                          make_kv_blocks_handler(self.engine))
        self.address = await self.rpc.start()
        return self

    async def stop(self):
        await self.rpc.stop()
        await self.engine.stop()


async def _collect(client, rid, prompt, n=4):
    req = PreprocessedRequest(request_id=rid, model="m",
                              token_ids=list(prompt),
                              sampling=SamplingParams(max_tokens=n))
    out = []
    async for d in client.generate(req):
        out.extend(d.token_ids)
        if d.finished:
            break
    return out


async def _reference_output(prompt, n=4):
    ref = await _Worker().start()
    try:
        return await _collect(ref.client, "ref", prompt, n)
    finally:
        await ref.stop()


async def _wait_for(pred, timeout=30.0, what="condition"):
    t0 = time.monotonic()
    while not pred():
        assert time.monotonic() - t0 < timeout, f"timed out on {what}"
        await asyncio.sleep(0.01)


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, 180))


async def _prefill_job(cp, worker):
    """Pop the job and run the actual prefill on `worker`'s engine (its
    blocks become resident + registered); returns (msg_id, rid)."""
    msg_id, job = await cp.queue_pop(prefill_queue_name(NS), 60)
    rid = job["request_id"]
    req = PreprocessedRequest(request_id=f"prefill-{rid}", model="m",
                              token_ids=list(job["token_ids"]),
                              sampling=SamplingParams(max_tokens=1))
    async for _ in worker.client.generate(req):
        pass
    return msg_id, rid


def test_eager_pulls_begin_before_prefill_done():
    """(a) The decode side pulls AND injects announced blocks while the
    remote prefill is (from its point of view) still running — the done
    message is withheld until the streamed blocks have landed."""

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        await cp.put(disagg_config_key(NS), {"max_local_prefill_length": 12})
        prefill = await _Worker().start()
        decode = await _Worker().start()
        dec = DisaggDecodeClient(decode.client, decode.engine, cp, NS, BS)
        await dec.start()
        mgr = decode.engine.core.allocator.manager

        async def scripted_prefill():
            msg_id, rid = await _prefill_job(cp, prefill)
            # Mid-prefill announcement: 2 of the 3 sealed blocks.
            await cp.publish(PREFILL_PROGRESS_SUBJECT, {
                "request_id": rid, "address": prefill.address,
                "sealed_blocks": 2})
            # Prefill-done is withheld until the decode side has pulled
            # and injected both announced blocks — the "before done"
            # ordering is therefore asserted, not raced.
            await _wait_for(lambda: mgr.onboarded_blocks >= 2,
                            what="streamed blocks landing")
            await cp.publish(PREFILL_DONE_SUBJECT, {
                "request_id": rid, "address": prefill.address,
                "prefill_s": 0.0})
            await cp.queue_ack(prefill_queue_name(NS), msg_id)

        task = asyncio.create_task(scripted_prefill())
        try:
            want = await _reference_output(LONG_PROMPT)
            got = await _collect(dec, "r1", LONG_PROMPT)
            await task
            assert got == want
            assert dec.remote_prefills == 1 and dec.local_fallbacks == 0
            assert dec.tokens_onboarded == 24
            # >= 2 blocks crossed the wire before the done message.
            assert dec.tokens_streamed >= 2 * BS
            assert dec.last_overlap_ratio >= 0.5   # 2 of 3 blocks early
            assert mgr.onboarded_blocks == 3
            assert mgr.device.hits >= 3   # decode prefill skipped them
            assert await cp.queue_len(prefill_queue_name(NS)) == 0
        finally:
            if not task.done():
                task.cancel()
            await dec.stop()
            await prefill.stop()
            await decode.stop()
            await cp.close()

    _run(main())


def test_midstream_death_falls_back_with_landed_prefix():
    """(b) The prefill worker streams part of the prefix, then dies (its
    RPC plane vanishes before the residual pull).  The decode side must
    fall back to local prefill WITHOUT losing the request, reusing the
    contiguous prefix that already landed."""

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        await cp.put(disagg_config_key(NS), {"max_local_prefill_length": 12})
        prefill = await _Worker().start()
        decode = await _Worker().start()
        dec = DisaggDecodeClient(decode.client, decode.engine, cp, NS, BS)
        await dec.start()
        mgr = decode.engine.core.allocator.manager

        async def dying_prefill():
            _msg_id, rid = await _prefill_job(cp, prefill)
            await cp.publish(PREFILL_PROGRESS_SUBJECT, {
                "request_id": rid, "address": prefill.address,
                "sealed_blocks": 2})
            await _wait_for(lambda: mgr.onboarded_blocks >= 2,
                            what="streamed blocks landing")
            # Death mid-stream: the RPC plane goes away, then the done
            # announcement points at the dead address — the residual
            # pull must fail over to local prefill.  (No ack either:
            # at-least-once redelivery is the queue's job.)
            await prefill.rpc.stop()
            await cp.publish(PREFILL_DONE_SUBJECT, {
                "request_id": rid, "address": prefill.address,
                "prefill_s": 0.0})

        task = asyncio.create_task(dying_prefill())
        try:
            want = await _reference_output(LONG_PROMPT)
            got = await _collect(dec, "r1", LONG_PROMPT)
            await task
            assert got == want                     # no request loss
            assert dec.local_fallbacks == 1
            assert dec.remote_prefills == 0
            # Only the landed prefix was onboarded...
            assert dec.tokens_onboarded == 2 * BS
            assert mgr.onboarded_blocks == 2
            # ...and the local fallback prefill reused it (prefix hit).
            assert mgr.device.hits >= 2
        finally:
            if not task.done():
                task.cancel()
            await dec.stop()
            await prefill.engine.stop()   # rpc already stopped mid-test
            await decode.stop()
            await cp.close()

    _run(main())


def test_prefill_worker_loop_publishes_progress():
    """The REAL prefill_worker_loop end to end: incremental progress
    announcements ride the control plane as chunks seal, and the eager
    decode path onboards the full prefix."""

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        await cp.put(disagg_config_key(NS), {"max_local_prefill_length": 12})
        prefill = await _Worker().start()
        decode = await _Worker().start()
        ploop = asyncio.create_task(prefill_worker_loop(
            cp, NS, prefill.client, prefill.address))
        dec = DisaggDecodeClient(decode.client, decode.engine, cp, NS, BS)
        await dec.start()
        listener = await cp.subscribe(PREFILL_PROGRESS_SUBJECT)
        try:
            want = await _reference_output(LONG_PROMPT)
            got = await _collect(dec, "r1", LONG_PROMPT)
            assert got == want
            assert dec.remote_prefills == 1 and dec.local_fallbacks == 0
            assert dec.tokens_onboarded == 24
            # The loop published incremental progress for this rid (the
            # 27-token prompt prefills in two 16-token chunks, so the
            # first announcement carries a partial high-water mark).
            msgs = []

            def got_progress():
                while not listener._q.empty():
                    msgs.append(listener._q.get_nowait())
                return any(m.get("request_id") == "r1"
                           and m.get("address") == prefill.address
                           and 0 < m.get("sealed_blocks", 0) <= 3
                           for m in msgs)

            await _wait_for(got_progress, timeout=10,
                            what="progress announcement")
            assert await cp.queue_len(prefill_queue_name(NS)) == 0
        finally:
            listener.cancel()
            ploop.cancel()
            await dec.stop()
            await prefill.stop()
            await decode.stop()
            await cp.close()

    _run(main())


def test_seal_stream_adds_nothing_to_steady_window():
    """(c) The seal-progress sink fires in the steady decode window (a
    block seals every block_size tokens) yet adds ZERO host syncs, zero
    uploads, zero recompiles and zero spans — byte-identical
    EngineStepCounters deltas with and without the sink installed,
    tracing enabled at sampling 1.0 the whole time."""
    from dynamo_tpu.runtime import tracing

    def steady(with_sink):
        core = EngineCore(EngineConfig(
            model=TINY, num_blocks=128, decode_window=2,
            window_pipeline_depth=2,
            scheduler=SchedulerConfig(
                max_seqs=8, block_size=8, max_pages_per_seq=32,
                max_prefill_chunk=128,
                decode_buckets=(1, 2, 4, 8), prefill_buckets=(16, 128))))
        calls = []
        if with_sink:
            core.seal_sink = lambda rid, n: calls.append((rid, n))
        tracer = tracing.get_tracer()
        tracer.bind("a", tracing.TraceContext("t-seal", "s0"))
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):   # prefill + window warmup
            core.step()
        base = core.counters.snapshot()
        spans0 = tracer.spans_recorded
        calls_at_steady = len(calls)
        for _ in range(20):
            core.step()
        tracer.unbind("a")
        return (core.counters.delta(base),
                tracer.spans_recorded - spans0,
                len(calls) - calls_at_steady)

    tracer = tracing.get_tracer()
    try:
        tracer.reset()
        tracer.configure(enabled=True, sampling=1.0)
        d_off, spans_off, _ = steady(with_sink=False)
        d_on, spans_on, steady_calls = steady(with_sink=True)
    finally:
        tracer.enabled = False
        tracer.reset()

    # The sink DID fire during the measured steady window (40 decode
    # tokens seal 5 blocks at block_size 8)...
    assert steady_calls > 0
    # ...and changed nothing the device or tracer can observe.
    assert d_on == d_off, (d_on, d_off)
    assert spans_on == spans_off == 0
