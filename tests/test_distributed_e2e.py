"""Multi-process e2e: control plane + frontend + worker subprocesses.

Mirror of the reference's pytest e2e tier (SURVEY.md §4: real etcd + NATS
+ ManagedProcess workers — here our own control plane + real `python -m
dynamo_tpu.worker` subprocesses) including the fault-tolerance scenario of
`tests/fault_tolerance/test_request_migration.py`: kill a worker
mid-stream, assert the stream migrates to the survivor.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


_worker_seq = [0]


def _spawn_worker(cp_port: int, name: str, speedup: float = 10.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    _worker_seq[0] += 1
    # Log to a file, not a pipe: a filled pipe buffer would wedge the
    # worker, and a crashed worker's output must survive for diagnosis.
    log = open(f"/tmp/dynamo_tpu_test_worker_{os.getpid()}_{_worker_seq[0]}.log",
               "w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.worker",
         "--control-plane", f"127.0.0.1:{cp_port}",
         "--mocker", "--model-name", name,
         "--block-size", "8",
         "--speedup-ratio", str(speedup)],
        env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT, text=True)
    proc._logfile = log  # type: ignore[attr-defined]
    return proc


def _worker_log(proc) -> str:
    proc._logfile.flush()
    proc._logfile.seek(0)
    return proc._logfile.read()


async def _wait_port_instances(cp, prefix, n, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        found = await cp.get_prefix(prefix)
        if len(found) >= n:
            return found
        await asyncio.sleep(0.2)
    raise TimeoutError(f"never saw {n} entries under {prefix}")


@pytest.mark.e2e
def test_distributed_serving_and_migration():
    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    workers = []

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()

        # Frontend in-process: discovery + HTTP.
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        watcher = ModelWatcher(runtime, models, migration_limit=3)
        await watcher.start()
        svc = HttpService(models)
        http_port = await svc.start()

        # Two mock workers as real OS processes.  Slow decode (speedup 1)
        # so a mid-stream kill lands while generating.
        workers.append(_spawn_worker(cp_port, "mock-model", speedup=1.0))
        workers.append(_spawn_worker(cp_port, "mock-model", speedup=1.0))
        await _wait_port_instances(cp, "models/mock-model/", 2, timeout=60)
        await watcher.wait_for_model("mock-model", timeout=10)

        base = f"http://127.0.0.1:{http_port}"
        async with ClientSession() as s:
            # 1) Plain unary requests spread across workers.
            for i in range(4):
                async with s.post(f"{base}/v1/chat/completions", json={
                        "model": "mock-model",
                        "messages": [{"role": "user", "content": f"q{i}"}],
                        "max_tokens": 3}) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                    assert data["usage"]["completion_tokens"] == 3

            # 2) Long streaming request; kill one worker mid-stream.
            payload = {
                "model": "mock-model",
                "messages": [{"role": "user", "content": "long"}],
                "max_tokens": 60, "stream": True,
            }
            tokens_seen = 0
            killed = False
            finish_reason = None
            async with s.post(f"{base}/v1/chat/completions",
                              json=payload) as r:
                assert r.status == 200
                async for raw in r.content:
                    line = raw.decode().strip()
                    if not line.startswith("data:") or line == "data: [DONE]":
                        continue
                    chunk = json.loads(line[5:])
                    choice = chunk["choices"][0]
                    if choice.get("delta", {}).get("content"):
                        tokens_seen += 1
                        if tokens_seen == 5 and not killed:
                            # Kill both? No — kill ONE; migration should
                            # land the retry on the survivor.
                            workers[0].send_signal(signal.SIGKILL)
                            killed = True
                    if choice.get("finish_reason"):
                        finish_reason = choice["finish_reason"]
            assert killed
            assert finish_reason == "length"
            # The stream completed despite the kill; the resumed request
            # re-issues remaining budget, so total content tokens reach
            # (close to) max_tokens.  Chunk boundaries may merge bytes, so
            # assert on a safe lower bound.
            assert tokens_seen >= 30, f"only {tokens_seen} content chunks"

        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()
        await cp_server.stop()

    try:
        asyncio.run(asyncio.wait_for(main(), timeout=180))
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
            out = _worker_log(w)
            if out:
                print(f"--- worker output (rc={w.poll()}) ---")
                print(out[-3000:])


def _make_tiny_checkpoint(d):
    """Tiny HF-format Llama + byte-level tokenizer.json in directory d.
    Returns (hf_model, hf_tokenizer)."""
    import transformers
    import torch
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers

    cfg = transformers.LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=512, rope_theta=10_000.0,
        tie_word_embeddings=False, torch_dtype="float32")
    torch.manual_seed(7)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)

    alphabet = sorted(pre_tokenizers.ByteLevel.alphabet())
    vocab = {tok: i for i, tok in enumerate(alphabet)}
    tok = Tokenizer(models.BPE(vocab=vocab, merges=[]))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    tok.save(os.path.join(d, "tokenizer.json"))
    return model, tok


@pytest.mark.e2e
def test_real_checkpoint_served_across_processes(tmp_path):
    """The JAX engine (not the mocker) as a real worker subprocess serving
    an HF checkpoint: /v1/completions text must equal a local transformers
    greedy run decoded by the SAME tokenizer — proving weights, tokenizer
    artifact, and card all travel end-to-end (VERDICT r1: untested)."""
    import torch
    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    ckpt = str(tmp_path / "ckpt")
    hf_model, hf_tok = _make_tiny_checkpoint(ckpt)
    prompt = "hello tpu"
    n_out = 8
    ids = hf_tok.encode(prompt).ids
    with torch.no_grad():
        out = hf_model.generate(torch.tensor([ids]), max_new_tokens=n_out,
                                do_sample=False, eos_token_id=None,
                                pad_token_id=0)
    want_text = hf_tok.decode(out[0, len(ids):].tolist())

    workers = []

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models_mgr = ModelManager()
        watcher = ModelWatcher(runtime, models_mgr)
        await watcher.start()
        svc = HttpService(models_mgr)
        http_port = await svc.start()

        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        log = open(f"/tmp/dynamo_tpu_test_ckpt_worker_{os.getpid()}.log", "w+")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--control-plane", f"127.0.0.1:{cp_port}",
             "--model", ckpt, "--model-name", "tiny-llama",
             "--num-blocks", "64", "--block-size", "8"],
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
            text=True)
        proc._logfile = log
        workers.append(proc)

        await _wait_port_instances(cp, "models/tiny-llama/", 1, timeout=120)
        await watcher.wait_for_model("tiny-llama", timeout=10)

        base = f"http://127.0.0.1:{http_port}"
        async with ClientSession() as s:
            async with s.post(f"{base}/v1/completions", json={
                    "model": "tiny-llama", "prompt": prompt,
                    "max_tokens": n_out, "temperature": 0.0}) as r:
                assert r.status == 200, await r.text()
                data = await r.json()
        got_text = data["choices"][0]["text"]
        assert got_text == want_text, (got_text, want_text)

        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()
        await cp_server.stop()

    try:
        asyncio.run(asyncio.wait_for(main(), timeout=240))
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
            out = _worker_log(w)
            if out:
                print(f"--- worker output (rc={w.poll()}) ---")
                print(out[-3000:])
