"""Multi-process e2e: control plane + frontend + worker subprocesses.

Mirror of the reference's pytest e2e tier (SURVEY.md §4: real etcd + NATS
+ ManagedProcess workers — here our own control plane + real `python -m
dynamo_tpu.worker` subprocesses) including the fault-tolerance scenario of
`tests/fault_tolerance/test_request_migration.py`: kill a worker
mid-stream, assert the stream migrates to the survivor.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


_worker_seq = [0]


def _spawn_worker(cp_port: int, name: str, speedup: float = 10.0):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    _worker_seq[0] += 1
    # Log to a file, not a pipe: a filled pipe buffer would wedge the
    # worker, and a crashed worker's output must survive for diagnosis.
    log = open(f"/tmp/dynamo_tpu_test_worker_{os.getpid()}_{_worker_seq[0]}.log",
               "w+")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dynamo_tpu.worker",
         "--control-plane", f"127.0.0.1:{cp_port}",
         "--mocker", "--model-name", name,
         "--block-size", "8",
         "--speedup-ratio", str(speedup)],
        env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT, text=True)
    proc._logfile = log  # type: ignore[attr-defined]
    return proc


def _worker_log(proc) -> str:
    proc._logfile.flush()
    proc._logfile.seek(0)
    return proc._logfile.read()


async def _wait_port_instances(cp, prefix, n, timeout=30):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        found = await cp.get_prefix(prefix)
        if len(found) >= n:
            return found
        await asyncio.sleep(0.2)
    raise TimeoutError(f"never saw {n} entries under {prefix}")


@pytest.mark.e2e
def test_distributed_serving_and_migration():
    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    workers = []

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()

        # Frontend in-process: discovery + HTTP.
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        watcher = ModelWatcher(runtime, models, migration_limit=3)
        await watcher.start()
        svc = HttpService(models)
        http_port = await svc.start()

        # Two mock workers as real OS processes.  Slow decode (speedup 1)
        # so a mid-stream kill lands while generating.
        workers.append(_spawn_worker(cp_port, "mock-model", speedup=1.0))
        workers.append(_spawn_worker(cp_port, "mock-model", speedup=1.0))
        await _wait_port_instances(cp, "models/mock-model/", 2, timeout=60)
        await watcher.wait_for_model("mock-model", timeout=10)

        base = f"http://127.0.0.1:{http_port}"
        async with ClientSession() as s:
            # 1) Plain unary requests spread across workers.
            for i in range(4):
                async with s.post(f"{base}/v1/chat/completions", json={
                        "model": "mock-model",
                        "messages": [{"role": "user", "content": f"q{i}"}],
                        "max_tokens": 3}) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                    assert data["usage"]["completion_tokens"] == 3

            # 2) Long streaming request; kill one worker mid-stream.
            payload = {
                "model": "mock-model",
                "messages": [{"role": "user", "content": "long"}],
                "max_tokens": 60, "stream": True,
            }
            tokens_seen = 0
            killed = False
            finish_reason = None
            async with s.post(f"{base}/v1/chat/completions",
                              json=payload) as r:
                assert r.status == 200
                async for raw in r.content:
                    line = raw.decode().strip()
                    if not line.startswith("data:") or line == "data: [DONE]":
                        continue
                    chunk = json.loads(line[5:])
                    choice = chunk["choices"][0]
                    if choice.get("delta", {}).get("content"):
                        tokens_seen += 1
                        if tokens_seen == 5 and not killed:
                            # Kill both? No — kill ONE; migration should
                            # land the retry on the survivor.
                            workers[0].send_signal(signal.SIGKILL)
                            killed = True
                    if choice.get("finish_reason"):
                        finish_reason = choice["finish_reason"]
            assert killed
            assert finish_reason == "length"
            # The stream completed despite the kill; the resumed request
            # re-issues remaining budget, so total content tokens reach
            # (close to) max_tokens.  Chunk boundaries may merge bytes, so
            # assert on a safe lower bound.
            assert tokens_seen >= 30, f"only {tokens_seen} content chunks"

        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()
        await cp_server.stop()

    try:
        asyncio.run(asyncio.wait_for(main(), timeout=180))
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
            out = _worker_log(w)
            if out:
                print(f"--- worker output (rc={w.poll()}) ---")
                print(out[-3000:])
