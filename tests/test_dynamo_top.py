"""`tools/dynamo_top.py`: Prometheus parsing units + the mini-fleet e2e
(frontend + worker status servers discovered via status_endpoints/,
scraped by the real CLI in a subprocess with --once --json)."""

import asyncio
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import dynamo_top  # noqa: E402


def _run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# -- parsing units -----------------------------------------------------------


def test_parse_prom_names_labels_values():
    text = (
        "# HELP x y\n"
        "# TYPE x gauge\n"
        'x{a="1",b="two"} 3.5\n'
        "plain 7\n"
        'esc{v="a\\"b\\nc"} 1\n'
        "garbage line with no value trailing\n"
    )
    samples = dynamo_top.parse_prom(text)
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    assert by_name["x"] == [({"a": "1", "b": "two"}, 3.5)]
    assert by_name["plain"] == [({}, 7.0)]
    assert by_name["esc"][0][0]["v"] == 'a"b\nc'


def test_total_sums_matching_label_subsets():
    samples = [("m", {"tier": "device"}, 2.0),
               ("m", {"tier": "host"}, 3.0),
               ("other", {}, 9.0)]
    assert dynamo_top.total(samples, "m") == 5.0
    assert dynamo_top.total(samples, "m", tier="device") == 2.0
    assert dynamo_top.total(samples, "missing") is None


def test_hist_quantile_from_buckets():
    # 10 observations: 9 in le=0.01, 1 more by le=1.0 (across 2 label
    # sets to exercise aggregation).
    samples = [
        ("h_bucket", {"m": "a", "le": "0.01"}, 5.0),
        ("h_bucket", {"m": "a", "le": "1.0"}, 5.0),
        ("h_bucket", {"m": "a", "le": "+Inf"}, 5.0),
        ("h_bucket", {"m": "b", "le": "0.01"}, 4.0),
        ("h_bucket", {"m": "b", "le": "1.0"}, 5.0),
        ("h_bucket", {"m": "b", "le": "+Inf"}, 5.0),
    ]
    assert dynamo_top.hist_quantile(samples, "h", 0.5) == 0.01
    assert dynamo_top.hist_quantile(samples, "h", 0.99) == 1.0
    assert dynamo_top.hist_quantile([], "h", 0.5) is None
    # Overflow bucket: worst latencies clamp to the largest finite
    # bound (a number, not the no-data dash).
    overflow = [
        ("h_bucket", {"le": "1.0"}, 1.0),
        ("h_bucket", {"le": "+Inf"}, 10.0),
    ]
    assert dynamo_top.hist_quantile(overflow, "h", 0.99) == 1.0


def test_summarize_row_from_series():
    samples = [
        ("dynamo_worker_request_active_slots", {}, 3.0),
        ("dynamo_kv_pool_active_blocks",
         {"tier": "device", "pool": "G1-device"}, 30.0),
        ("dynamo_kv_pool_capacity_blocks",
         {"tier": "device", "pool": "G1-device"}, 60.0),
        ("dynamo_kv_prefix_cache_hits_tokens",
         {"tier": "device", "pool": "G1-device"}, 75.0),
        ("dynamo_kv_prefix_cache_misses_tokens",
         {"tier": "device", "pool": "G1-device"}, 25.0),
        ("dynamo_hbm_used_bytes", {"device": "0", "kind": "tpu"}, 2.0e9),
        ("dynamo_hbm_limit_bytes", {"device": "0", "kind": "tpu"}, 16e9),
    ]
    slo = {"enabled": True, "state": "WARN",
           "objectives": [{"burn_fast": 4.5}]}
    row = dynamo_top.summarize("worker-both", "127.0.0.1:1", samples, slo)
    assert row["inflight"] == 3.0
    assert row["kv_usage"] == 0.5
    assert row["prefix_hit_rate"] == 0.75
    assert row["hbm_used_bytes"] == 2.0e9
    assert row["slo_state"] == "WARN"
    assert row["slo_max_burn"] == 4.5


def test_capacity_headroom_from_profile_knee():
    """--profile wires the SLA profiler's knee concurrency into a
    per-row headroom: 1 at idle, 0 at the knee, negative past it."""
    samples = [("dynamo_worker_request_active_slots", {}, 3.0)]
    row = dynamo_top.summarize("w", "a:1", samples, None,
                               knee_concurrency=6.0)
    assert row["capacity_headroom"] == 0.5
    over = dynamo_top.summarize(
        "w", "a:1", [("dynamo_worker_request_active_slots", {}, 9.0)],
        None, knee_concurrency=6.0)
    assert over["capacity_headroom"] == pytest.approx(-0.5)
    # No knee / no inflight series → the column stays empty, never 0.
    assert dynamo_top.summarize("w", "a:1", samples,
                                None)["capacity_headroom"] is None
    assert dynamo_top.summarize("w", "a:1", [], None,
                                knee_concurrency=6.0)[
        "capacity_headroom"] is None
    # Frontend rows NEVER get headroom: their inflight gauge is the
    # fleet-wide total, which a per-worker knee would misread as
    # catastrophic overload (300 inflight / knee 6 → -4900%).
    fe = dynamo_top.summarize(
        "frontend", "a:1",
        [("dynamo_frontend_inflight_requests", {}, 300.0)],
        None, knee_concurrency=6.0)
    assert fe["inflight"] == 300.0
    assert fe["capacity_headroom"] is None


def test_summarize_engine_age_and_stalls():
    """ISSUE 14: the flight-recorder/watchdog series land in the row and
    the AGE/STL column renders them (with the `!` marker while the
    watchdog holds the worker stalled)."""
    samples = [
        ("dynamo_engine_last_step_age_seconds", {}, 12.3),
        ("dynamo_engine_stalls_total", {}, 2.0),
        ("dynamo_engine_stalled", {}, 1.0),
    ]
    row = dynamo_top.summarize("worker-both", "a:1", samples, None)
    assert row["engine_step_age_s"] == 12.3
    assert row["engine_stalls"] == 2.0
    assert row["engine_stalled"] == 1.0
    assert dynamo_top._fmt_age_stall(row) == "12.3s/2!"
    # Healthy worker: no marker.
    healthy = dynamo_top.summarize("worker-both", "a:1", [
        ("dynamo_engine_last_step_age_seconds", {}, 0.02),
        ("dynamo_engine_stalls_total", {}, 0.0),
        ("dynamo_engine_stalled", {}, 0.0)], None)
    assert dynamo_top._fmt_age_stall(healthy) == "0.0s/0"
    # Mocker/frontend rows (no engine series): the no-data dash.
    empty = dynamo_top.summarize("frontend", "a:1", [], None)
    assert dynamo_top._fmt_age_stall(empty) == "—"
    # The column is part of the rendered table.
    table = dynamo_top.render_table(
        {"control_plane": "x", "processes": [row]})
    assert "AGE/STL" in table
    assert "12.3s/2!" in table


def test_summarize_moe_expert_load():
    """ISSUE 17: the MoE expert-load series fold into the EXP column —
    active/total experts, max/mean imbalance, and a `!N` drop marker
    when capacity honesty counted dropped assignments."""
    samples = [
        ("dynamo_moe_expert_load", {"expert": "0"}, 10.0),
        ("dynamo_moe_expert_load", {"expert": "1"}, 30.0),
        ("dynamo_moe_expert_load", {"expert": "2"}, 0.0),
        ("dynamo_moe_expert_load", {"expert": "3"}, 20.0),
        ("dynamo_moe_dropped_tokens_total", {}, 0.0),
    ]
    row = dynamo_top.summarize("worker-both", "a:1", samples, None)
    assert row["moe_experts_active"] == 3
    assert row["moe_experts_total"] == 4
    assert row["moe_load_imbalance"] == pytest.approx(2.0)
    assert row["moe_dropped_tokens"] == 0.0
    assert dynamo_top._fmt_exp(row) == "3/4e 2.0x"
    # A lossy capacity cap must be visible at a glance.
    dropped = dynamo_top.summarize("worker-both", "a:1", samples[:-1] + [
        ("dynamo_moe_dropped_tokens_total", {}, 7.0)], None)
    assert dynamo_top._fmt_exp(dropped) == "3/4e 2.0x!7"
    # Dense workers publish no series: the no-data dash.
    dense = dynamo_top.summarize("worker-both", "a:1", [], None)
    assert dense["moe_experts_active"] is None
    assert dynamo_top._fmt_exp(dense) == "—"
    table = dynamo_top.render_table(
        {"control_plane": "x", "processes": [row]})
    assert "EXP" in table
    assert "3/4e 2.0x" in table


def test_knee_concurrency_extraction():
    prof = {"prefill": {}, "decode": {},
            "meta": {"capacity": {"knee_concurrency_per_worker": 2.5}}}
    assert dynamo_top.knee_concurrency_from_profile(prof) == 2.5
    # v1 profiles (planner/profiler.py) and kneeless sweeps → None.
    assert dynamo_top.knee_concurrency_from_profile(
        {"prefill": {}, "decode": {}}) is None
    assert dynamo_top.knee_concurrency_from_profile(
        {"meta": {"capacity": {"knee_concurrency_per_worker": None}}}
    ) is None


# -- mini-fleet e2e ----------------------------------------------------------


async def _mini_fleet():
    """A control plane + a worker-shaped status server + a frontend
    HttpService, all registered under status_endpoints/."""
    from dynamo_tpu.llm.block_manager.pool import BlockPool
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.metrics import (
        KvCacheMetrics, MetricsRegistry, RequestMetrics)
    from dynamo_tpu.runtime.slo import (
        SloMonitor, SloObjective, latency_source)
    from dynamo_tpu.runtime.status import (
        StatusServer, register_status_endpoint)

    srv = ControlPlaneServer()
    cp_port = await srv.start()
    cp = ControlPlaneClient("127.0.0.1", cp_port)
    await cp.start()

    # Worker: real BlockPool driven through an alloc/release cycle.
    wreg = MetricsRegistry()
    kvm = KvCacheMetrics(wreg)
    pool = BlockPool(16, name="G1-device", reserve_null=True)
    pages = pool.allocate(6)
    for i, p in enumerate(pages[:3]):
        pool.register(p, 0x100 + i)
    kvm.observe_pool(pool, "device")
    wrm = RequestMetrics(wreg)
    for v in (0.05, 0.1, 0.2):
        wrm.ttft.observe(v, labels={"model": "m"})
        wrm.tpot.observe(v / 10, labels={"model": "m"})
    wmon = SloMonitor(
        [(SloObjective("ttft_p99", threshold_s=0.5),
          latency_source(wrm.ttft, 0.5))], registry=wreg)
    wmon.tick()
    worker_status = StatusServer(registry=wreg, slo_fn=wmon.payload)
    wport = await worker_status.start()
    await register_status_endpoint(cp, "worker-both", wport)

    # Frontend: the real HttpService with an SLO monitor installed.
    svc = HttpService(ModelManager())
    svc.request_metrics.ttft.observe(0.03, labels={"model": "m"})
    svc.request_metrics.observe_outcome(ok=True)
    fmon = SloMonitor(
        [(SloObjective("ttft_p99", threshold_s=0.5),
          latency_source(svc.request_metrics.ttft, 0.5))],
        registry=svc.registry)
    svc.slo_monitor = fmon
    fport = await svc.start()
    await register_status_endpoint(cp, "frontend", fport)

    async def teardown():
        await svc.stop()
        await worker_status.stop()
        await cp.close()
        await srv.stop()

    return cp_port, teardown


def test_dynamo_top_once_json_covers_every_process():
    async def main():
        cp_port, teardown = await _mini_fleet()
        try:
            proc = await asyncio.create_subprocess_exec(
                sys.executable, os.path.join(REPO, "tools", "dynamo_top.py"),
                "--control-plane", f"127.0.0.1:{cp_port}",
                "--once", "--json",
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE, cwd=REPO)
            out, err = await asyncio.wait_for(proc.communicate(), 90)
            assert proc.returncode == 0, err.decode()[-2000:]
            snapshot = json.loads(out.decode())
        finally:
            await teardown()

        rows = {p["component"]: p for p in snapshot["processes"]}
        assert set(rows) == {"worker-both", "frontend"}
        for row in rows.values():
            assert not row.get("unreachable"), row
        worker = rows["worker-both"]
        # KV usage from the pool series: 6 active of 16 capacity.
        assert worker["kv_active_blocks"] == 6.0
        assert worker["kv_capacity_blocks"] == 16.0
        assert abs(worker["kv_usage"] - 6.0 / 16.0) < 1e-9
        assert worker["ttft_p50_s"] is not None
        assert worker["slo_state"] in ("OK", "WARN", "PAGE")
        front = rows["frontend"]
        assert front["slo_state"] in ("OK", "WARN", "PAGE")
        assert front["ttft_p50_s"] is not None

    _run(main())


def test_mesh_column_from_published_slice_spec():
    """ISSUE 16 satellite: a worker registering with its SliceSpec in
    the status extra gets a MESH cell rendered straight from the
    registration (`describe()` + role marker); a pre-topology worker
    (no extra) renders the no-data dash in the same table."""
    async def main():
        from dynamo_tpu.fleet.topology import parse_slice
        from dynamo_tpu.runtime.control_plane_tcp import (
            ControlPlaneClient, ControlPlaneServer)
        from dynamo_tpu.runtime.metrics import MetricsRegistry
        from dynamo_tpu.runtime.status import (
            StatusServer, register_status_endpoint)

        srv = ControlPlaneServer()
        cp_port = await srv.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        spec = parse_slice("sp2xtp2,int8,role=prefill")
        sliced = StatusServer(registry=MetricsRegistry())
        plain = StatusServer(registry=MetricsRegistry())
        sport = await sliced.start()
        pport = await plain.start()
        await register_status_endpoint(
            cp, "worker-prefill", sport,
            extra={"mesh": spec.describe(), "slice": spec.to_dict()})
        await register_status_endpoint(cp, "worker-old", pport)
        try:
            snapshot = await dynamo_top.collect(
                f"127.0.0.1:{cp_port}", timeout=2.0)
        finally:
            await sliced.stop()
            await plain.stop()
            await cp.close()
            await srv.stop()

        rows = {p["component"]: p for p in snapshot["processes"]}
        assert rows["worker-prefill"]["mesh"] == "sp2xtp2"
        assert rows["worker-prefill"]["slice_role"] == "prefill"
        assert rows["worker-old"]["mesh"] is None
        table = dynamo_top.render_table(snapshot)
        assert "MESH" in table
        assert "sp2xtp2:P" in table
        # The dash, not a crash, for the spec-less row.
        assert dynamo_top._fmt_mesh(rows["worker-old"]) == "—"
        assert dynamo_top._fmt_mesh(
            {"mesh": "tp2", "slice_role": "decode"}) == "tp2:D"
        assert dynamo_top._fmt_mesh(
            {"mesh": "single", "slice_role": "both"}) == "single"

    _run(main())


def test_collect_marks_dead_process_unreachable():
    """A registration owned by a LIVE pid (ours) that stops answering
    renders unreachable — and is NOT reaped (the process may be wedged,
    which is exactly when its row matters)."""
    async def main():
        from dynamo_tpu.runtime.control_plane_tcp import (
            ControlPlaneClient, ControlPlaneServer)
        from dynamo_tpu.runtime.status import (
            STATUS_ENDPOINTS_PREFIX, register_status_endpoint)

        srv = ControlPlaneServer()
        cp_port = await srv.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        # Advertised but nothing listening; pid = this (live) process.
        await register_status_endpoint(cp, "worker-ghost", 1)
        try:
            snapshot = await dynamo_top.collect(
                f"127.0.0.1:{cp_port}", timeout=1.0)
            remaining = await cp.get_prefix(f"{STATUS_ENDPOINTS_PREFIX}/")
        finally:
            await cp.close()
            await srv.stop()
        assert len(snapshot["processes"]) == 1
        assert snapshot["processes"][0]["unreachable"]
        assert snapshot["reaped"] == 0
        assert len(remaining) == 1     # live-pid registration kept

    _run(main())


def test_collect_reaps_dead_pid_registration():
    """ISSUE 14 satellite: a kill -9'd worker's stale status_endpoints
    entry (pid provably dead, loopback address) is DELETED on scrape and
    rendered once as a reaped row instead of UNREACHABLE forever."""
    import subprocess

    async def main():
        from dynamo_tpu.runtime.control_plane_tcp import (
            ControlPlaneClient, ControlPlaneServer)
        from dynamo_tpu.runtime.status import STATUS_ENDPOINTS_PREFIX

        # A pid that provably no longer exists.
        child = subprocess.Popen([sys.executable, "-c", "pass"])
        child.wait()
        dead_pid = child.pid

        srv = ControlPlaneServer()
        cp_port = await srv.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        key = f"{STATUS_ENDPOINTS_PREFIX}/worker-dead/{dead_pid}"
        await cp.put(key, {"address": "127.0.0.1:1",
                           "component": "worker-dead", "pid": dead_pid})
        try:
            snapshot = await dynamo_top.collect(
                f"127.0.0.1:{cp_port}", timeout=1.0)
            remaining = await cp.get_prefix(f"{STATUS_ENDPOINTS_PREFIX}/")
        finally:
            await cp.close()
            await srv.stop()
        assert snapshot["reaped"] == 1
        row = snapshot["processes"][0]
        assert row["reaped"] and row["pid"] == dead_pid
        assert remaining == {}         # key gone: no haunting next sweep
        # The reaped row renders as such (not UNREACHABLE).
        table = dynamo_top.render_table(snapshot)
        assert "REAPED" in table and "UNREACHABLE" not in table

    _run(main())


def test_registration_pid_dead_is_conservative():
    """Only loopback + provably-gone pids reap; everything ambiguous
    reads as alive."""
    from dynamo_tpu.runtime.status import registration_pid_dead

    assert not registration_pid_dead(None)
    assert not registration_pid_dead({"address": "127.0.0.1:1"})  # no pid
    # Live pid (ours) never reaps.
    assert not registration_pid_dead(
        {"address": "127.0.0.1:1", "pid": os.getpid()})
    # Foreign-host addresses are undecidable from here.
    assert not registration_pid_dead(
        {"address": "10.0.0.7:8080", "pid": 2 ** 22 - 1})
    # Loopback + dead pid reaps.
    import subprocess

    child = subprocess.Popen([sys.executable, "-c", "pass"])
    child.wait()
    assert registration_pid_dead(
        {"address": "127.0.0.1:1", "pid": child.pid})


def test_summarize_goodput_and_dominant_phase_why_column():
    """ISSUE 18: the frontend's ledger series fold into the WHY column —
    fleet goodput %% plus the dominant (non-decode) phase — and land in
    the --once --json snapshot for scripted checks."""
    samples = [
        ("dynamo_goodput_good_tokens_total", {}, 90.0),
        ("dynamo_goodput_tokens_total", {}, 100.0),
        ("dynamo_request_phase_seconds_sum", {"phase": "prefill"}, 4.0),
        ("dynamo_request_phase_seconds_sum", {"phase": "route"}, 0.5),
        ("dynamo_request_phase_seconds_sum", {"phase": "decode"}, 50.0),
    ]
    row = dynamo_top.summarize("frontend", "a:1", samples, None)
    assert row["goodput"] == pytest.approx(0.9)
    # decode excluded by construction: long generations would always win.
    assert row["dominant_phase"] == "prefill"
    assert dynamo_top._fmt_why(row) == "prefill 90%"
    table = dynamo_top.render_table(
        {"control_plane": "x", "processes": [row]})
    assert "WHY" in table
    assert "prefill 90%" in table
    # Ledger-less processes (workers, old frontends): the no-data dash.
    empty = dynamo_top.summarize("worker-both", "a:1", [], None)
    assert empty["goodput"] is None and empty["dominant_phase"] is None
    assert dynamo_top._fmt_why(empty) == "—"
