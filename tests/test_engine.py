"""Engine-core and scheduler tests on the tiny model (CPU devices).

The load-bearing property: batching must be semantically invisible —
greedy outputs of concurrent requests equal those of the same requests run
alone (padding discipline, slot isolation, chunked prefill).  This is the
engine-level analog of the reference's mocker-based routing tests
(SURVEY.md §4).
"""

import asyncio

import jax
import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import (
    BlockAllocator,
    FinishReason,
    Request,
    RequestState,
    Scheduler,
    SchedulerConfig,
)
from dynamo_tpu.models import config as mcfg

TINY = mcfg.get_config("tiny-test")


def small_engine(**kw) -> EngineCore:
    defaults = dict(
        model=TINY,
        num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16)),
    )
    defaults.update(kw)
    return EngineCore(EngineConfig(**defaults))


def run_to_completion(core: EngineCore, max_steps=500):
    outputs = {}
    finished = {}
    for _ in range(max_steps):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
            if d.finished:
                finished[d.request_id] = d.finish_reason
        if core.scheduler.num_active == 0 and not core._requests:
            break
    return outputs, finished


# -- scheduler unit tests ----------------------------------------------------


def _req(rid, prompt_len, max_tokens=4):
    return Request(request_id=rid, prompt_tokens=list(range(1, prompt_len + 1)),
                   sampling=SamplingParams(max_tokens=max_tokens))


def test_admission_respects_watermark():
    alloc = BlockAllocator(num_blocks=9)  # 8 usable
    sched = Scheduler(SchedulerConfig(
        max_seqs=4, block_size=8, max_pages_per_seq=4, watermark=0.3), alloc)
    # Each prompt of 15 tokens (+1) needs 2 pages; watermark = 2.4 blocks.
    for i in range(4):
        sched.add_request(_req(f"r{i}", 15))
    sched.plan()
    # 8 usable: r0 (2), r1 (2) admitted → free 4; admitting r2 would leave
    # 2 < 2.4 → blocked.
    admitted = [r.request_id for r in sched.running]
    assert admitted == ["r0", "r1"]
    assert alloc.free_blocks == 4


def test_chunked_prefill_budget():
    alloc = BlockAllocator(num_blocks=64)
    sched = Scheduler(SchedulerConfig(
        max_seqs=4, block_size=8, max_pages_per_seq=8,
        max_prefill_chunk=16, max_batched_tokens=24), alloc)
    sched.add_request(_req("a", 40))
    sched.add_request(_req("b", 40))
    plan = sched.plan()
    # Budget 24: a gets a 16-chunk, b gets the remaining 8 — packed into
    # ONE batched device call.
    assert [(w.request.request_id, w.length) for w in plan.prefill.items] == \
        [("a", 16), ("b", 8)]
    assert plan.prefill.rows == 2 and plan.prefill.chunk == 16
    for w in plan.prefill.items:
        sched.prefill_done(w)
    assert sched.running[0].prefilled == 16


def test_finish_releases_pages():
    alloc = BlockAllocator(num_blocks=16)
    sched = Scheduler(SchedulerConfig(
        max_seqs=2, block_size=8, max_pages_per_seq=8), alloc)
    sched.add_request(_req("a", 20))
    sched.plan()
    assert alloc.free_blocks < 15
    sched.finish(sched.running[0], FinishReason.STOP)
    assert alloc.free_blocks == 15


def test_too_long_prompt_rejected():
    alloc = BlockAllocator(num_blocks=16)
    sched = Scheduler(SchedulerConfig(
        max_seqs=2, block_size=8, max_pages_per_seq=2), alloc)
    req = _req("a", 20)  # 20 + 4 > 16 max context
    sched.add_request(req)
    assert req.state is RequestState.FINISHED
    assert req.finish_reason is FinishReason.LENGTH


# -- engine end-to-end -------------------------------------------------------


def test_single_request_generates():
    core = small_engine()
    core.add_request("r1", [5, 6, 7, 8], SamplingParams(max_tokens=6))
    outputs, finished = run_to_completion(core)
    assert len(outputs["r1"]) == 6
    assert finished["r1"] is FinishReason.LENGTH
    assert core.allocator.free_blocks == 63  # everything released


def test_batching_invisible_to_greedy_outputs():
    prompts = {
        "a": [1, 2, 3],
        "b": list(range(10, 31)),       # forces chunked prefill (21 > 16)
        "c": [9, 8, 7, 6, 5],
    }
    solo = {}
    for rid, p in prompts.items():
        core = small_engine()
        core.add_request(rid, p, SamplingParams(max_tokens=8))
        out, _ = run_to_completion(core)
        solo[rid] = out[rid]

    core = small_engine()
    for rid, p in prompts.items():
        core.add_request(rid, p, SamplingParams(max_tokens=8))
    batched, finished = run_to_completion(core)

    assert batched == solo
    assert all(r is FinishReason.LENGTH for r in finished.values())


def test_stop_token_finishes_early():
    core = small_engine()
    core.add_request("r1", [5, 6, 7, 8], SamplingParams(max_tokens=32))
    # Find what greedy emits first, then re-run with it as a stop token.
    outputs, _ = run_to_completion(core)
    first = outputs["r1"][0]

    core2 = small_engine()
    core2.add_request("r1", [5, 6, 7, 8],
                      SamplingParams(max_tokens=32, stop_token_ids=(first,)))
    outputs2, finished2 = run_to_completion(core2)
    assert outputs2["r1"] == [first]
    assert finished2["r1"] is FinishReason.STOP


def test_kv_events_emitted_with_chained_hashes():
    """Plain-allocator event contract: STORED on seal, REMOVED on finish
    (no residency after release).  Managed-cache semantics are tested in
    test_prefix_cache_* below."""
    from dynamo_tpu.tokens import compute_block_hashes

    events = []
    core = EngineCore(
        EngineConfig(
            model=TINY, num_blocks=64, enable_prefix_cache=False,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16,
                decode_buckets=(1, 2, 4), prefill_buckets=(8, 16)),
        ),
        kv_event_sink=events.append,
    )
    prompt = list(range(1, 20))  # 19 tokens → 2 complete blocks of 8
    core.add_request("r1", prompt, SamplingParams(max_tokens=6))
    run_to_completion(core)

    stored = [e for e in events if e.data.store is not None]
    removed = [e for e in events if e.data.remove is not None]
    assert stored and removed
    all_stored = [h for e in stored for h in e.data.store.block_hashes]
    # 19 prompt + 6 output = 25 tokens → 3 sealed blocks of 8.
    # Recompute expected hashes from the actual generated tokens:
    core2 = small_engine()
    core2.add_request("r1", prompt, SamplingParams(max_tokens=6))
    out, _ = run_to_completion(core2)
    expected = compute_block_hashes(prompt + out["r1"], block_size=8)[:3]
    assert all_stored == list(expected)
    # Removal covers exactly what was stored.
    assert sorted(h for e in removed for h in e.data.remove.block_hashes) == \
        sorted(all_stored)
    # Event ids strictly increasing.
    ids = [e.event_id for e in events]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)


def test_cancel_mid_stream():
    core = small_engine()
    core.add_request("r1", [1, 2, 3], SamplingParams(max_tokens=32))
    core.step()  # prefill + first token
    core.cancel("r1")
    deltas = core.step()
    assert any(d.finished and d.finish_reason is FinishReason.CANCELLED
               for d in deltas)
    assert core.allocator.free_blocks == 63


def test_async_engine_streams():
    async def main():
        core = small_engine()
        eng = InferenceEngine(core)
        await eng.start()
        try:
            got = []
            async for delta in eng.generate(
                    "r1", [5, 6, 7], SamplingParams(max_tokens=5)):
                got.extend(delta.token_ids)
                if delta.finished:
                    break
            return got
        finally:
            await eng.stop()

    got = asyncio.run(main())
    assert len(got) == 5


def test_async_engine_concurrent_requests():
    async def main():
        core = small_engine()
        eng = InferenceEngine(core)
        await eng.start()

        async def one(rid, prompt):
            toks = []
            async for d in eng.generate(rid, prompt,
                                        SamplingParams(max_tokens=4)):
                toks.extend(d.token_ids)
            return toks

        try:
            return await asyncio.gather(
                one("a", [1, 2, 3]), one("b", [4, 5, 6]), one("c", [7, 8]))
        finally:
            await eng.stop()

    a, b, c = asyncio.run(main())
    assert len(a) == len(b) == len(c) == 4


def test_engine_matches_single_forward_contract():
    """Engine greedy decode must equal re-prefilling the whole sequence from
    scratch each step (locks the decode position contract; ADVICE r1 found a
    +1 shift here that batching-invariance tests could not see)."""
    import jax.numpy as jnp

    from dynamo_tpu.engine import kv_cache as kvc
    from dynamo_tpu.models.llama import init_params, make_forward_step

    prompt = [5, 6, 7, 8, 9]
    n_out = 6

    core = small_engine()
    core.add_request("r1", prompt, SamplingParams(max_tokens=n_out))
    outputs, _ = run_to_completion(core)
    engine_out = outputs["r1"]

    # Ground truth: full fresh prefill of (prompt + generated-so-far) each
    # step; argmax of the last position's logits.
    cfg = TINY
    params = init_params(cfg, jax.random.key(0))
    step = jax.jit(make_forward_step(cfg, 8))
    ref_out = []
    toks = list(prompt)
    for _ in range(n_out):
        L = len(toks)
        pages = (L + 7) // 8
        cache = kvc.init_cache(
            kvc.KvCacheConfig.for_model(cfg, num_blocks=16, block_size=8))
        logits, _ = step(
            params, cache,
            jnp.asarray([toks], jnp.int32),
            jnp.arange(L, dtype=jnp.int32)[None, :],
            jnp.asarray([L], jnp.int32),
            jnp.asarray([list(range(1, pages + 1)) + [0] * (16 - pages)],
                        jnp.int32),
        )
        nxt = int(jnp.argmax(logits[0, L - 1]))
        ref_out.append(nxt)
        toks.append(nxt)

    assert engine_out == ref_out


def test_preemption_invisible_to_greedy_output():
    """Under block contention one request is preempted (recompute) — its
    final output must match an uncontended run exactly."""
    prompts = {"a": [1, 2, 3, 4, 5, 6, 7, 8], "b": [9, 10, 11, 12, 13, 14]}
    n_out = 30

    solo = {}
    for rid, p in prompts.items():
        core = small_engine(num_blocks=64)
        core.add_request(rid, p, SamplingParams(max_tokens=n_out))
        out, _ = run_to_completion(core)
        solo[rid] = out[rid]

    # 9 blocks → 8 usable pages of 8 tokens; two requests growing to
    # ~38 tokens each (5 pages) must collide and preempt.
    core = small_engine(num_blocks=9)
    for rid, p in prompts.items():
        core.add_request(rid, p, SamplingParams(max_tokens=n_out))
    batched, finished = run_to_completion(core, max_steps=2000)

    assert batched == solo
    assert all(r is FinishReason.LENGTH for r in finished.values())


def test_prefix_cache_hit_skips_prefill_and_matches():
    """Second identical prompt must hit G1 prefix blocks (live wiring of the
    managed block source — ADVICE r1 found it dead) and produce identical
    output."""
    prompt = list(range(1, 25))  # 3 sealed blocks of 8

    core = small_engine()
    core.add_request("a", prompt, SamplingParams(max_tokens=4))
    out_a, _ = run_to_completion(core)
    hits_before = core.allocator.manager.device.hits

    core.add_request("b", prompt, SamplingParams(max_tokens=4))
    out_b, _ = run_to_completion(core)
    assert core.allocator.manager.device.hits > hits_before
    assert out_b["b"] == out_a["a"]
    # The cached-prefix request recomputed only the last prompt token.


def test_managed_eviction_emits_removed_and_offloads():
    """Filling the pool evicts an earlier request's registered blocks →
    REMOVED KV events fire from the eviction hook, and with a G2 tier the
    block survives and onboards back on a later match."""
    events = []
    core = EngineCore(
        EngineConfig(
            model=TINY, num_blocks=9, host_blocks=16,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16,
                decode_buckets=(1, 2, 4), prefill_buckets=(8, 16)),
        ),
        kv_event_sink=events.append,
    )
    prompt_a = list(range(1, 17))  # 2 sealed blocks
    core.add_request("a", prompt_a, SamplingParams(max_tokens=2))
    out_a1, _ = run_to_completion(core)

    # Churn through enough distinct blocks to evict a's.
    for i in range(3):
        core.add_request(f"c{i}", [100 + 8 * i + j for j in range(16)],
                         SamplingParams(max_tokens=2))
        run_to_completion(core)

    removed = [h for e in events if e.data.remove is not None
               for h in e.data.remove.block_hashes]
    assert removed, "eviction must emit REMOVED events"
    assert core.allocator.manager.offloaded_blocks > 0

    # Re-running prompt_a onboards from G2 (hash-correct KV) and matches.
    onboarded_before = core.allocator.manager.onboarded_blocks
    core.add_request("a2", prompt_a, SamplingParams(max_tokens=2))
    out_a2, _ = run_to_completion(core)
    assert out_a2["a2"] == out_a1["a"]
    assert core.allocator.manager.onboarded_blocks > onboarded_before


def test_seeded_sampling_reproducible_across_batch_mix():
    """A seeded stochastic request must not depend on batch-mates."""
    seeded = dict(prompt=[3, 1, 4, 1, 5],
                  sampling=SamplingParams(temperature=0.9, seed=1234,
                                          max_tokens=6))

    core = small_engine()
    core.add_request("s", seeded["prompt"], seeded["sampling"])
    solo, _ = run_to_completion(core)

    core2 = small_engine()
    core2.add_request("other1", [9, 9, 9], SamplingParams(max_tokens=6))
    core2.add_request("s", seeded["prompt"], seeded["sampling"])
    core2.add_request("other2", [7, 7], SamplingParams(temperature=1.5,
                                                       max_tokens=6))
    mixed, _ = run_to_completion(core2)

    assert mixed["s"] == solo["s"]


def test_speculative_decode_matches_plain_greedy():
    """Prompt-lookup speculative decoding must be output-invisible: the
    accepted-token stream equals the plain engine's greedy output
    exactly, while accepting >0 drafted tokens on repetitive text."""
    # Repetitive prompt: n-gram lookup finds continuations to draft.
    prompt = [5, 6, 7, 8, 5, 6, 7, 8, 5, 6, 7, 8, 5, 6]
    n_out = 24

    plain = small_engine(num_blocks=64, decode_window=1)
    plain.add_request("a", prompt, SamplingParams(max_tokens=n_out))
    want, _ = run_to_completion(plain)

    spec = small_engine(num_blocks=64, speculative_tokens=3)
    spec.add_request("a", prompt, SamplingParams(max_tokens=n_out))
    got, _ = run_to_completion(spec)

    assert got["a"] == want["a"]
    stats = spec.metrics.spec_decode_stats
    assert stats is not None and stats.num_drafts > 0
    # The whole point: some drafts verified (repetitive text accepts).
    assert stats.num_accepted_tokens > 0


def test_speculative_decode_batched_and_preemption_safe():
    """Two concurrent requests under spec decoding, tight block budget:
    outputs still match solo runs (fallback path covers capacity
    refusals)."""
    prompts = {"a": [1, 2, 3, 1, 2, 3, 1, 2], "b": [9, 9, 8, 9, 9, 8]}
    n_out = 20
    solo = {}
    for rid, p in prompts.items():
        core = small_engine(num_blocks=64, decode_window=1)
        core.add_request(rid, p, SamplingParams(max_tokens=n_out))
        out, _ = run_to_completion(core)
        solo[rid] = out[rid]

    core = small_engine(num_blocks=10, speculative_tokens=3)
    for rid, p in prompts.items():
        core.add_request(rid, p, SamplingParams(max_tokens=n_out))
    got, _ = run_to_completion(core, max_steps=2000)
    assert got == solo


def test_mixed_budget_caps_prefill_when_decoding():
    """VERDICT r4 weak #4: with streams decoding, prefill gets at most
    mixed_prefill_tokens per step, not max_batched_tokens."""
    alloc = BlockAllocator(num_blocks=64)
    sched = Scheduler(SchedulerConfig(
        max_seqs=4, block_size=8, max_pages_per_seq=8,
        max_prefill_chunk=16, max_batched_tokens=64,
        mixed_prefill_tokens=8), alloc)
    sched.add_request(_req("dec", 8))
    plan = sched.plan()
    for w in plan.prefill.items:
        sched.prefill_done(w)
    assert sched.running[0].state.value == "decode"
    sched.add_request(_req("new1", 40))
    sched.add_request(_req("new2", 40))
    plan = sched.plan()
    assert plan.decode is not None
    assert sum(w.length for w in plan.prefill.items) <= 8
    # Without decode streams the full budget applies.
    sched.finish(sched.running[0], FinishReason.LENGTH)
    plan = sched.plan()
    assert sum(w.length for w in plan.prefill.items) > 8


def test_mixed_prefill_controller_modeled_interference():
    """ISSUE 4 satellite: the adaptive (duty, chunk) controller.  Pure
    model, CPU-runnable — pins (a) the calibration anchor (the static r5
    geometry reproduces its measured 0.778), (b) every non-floored plan
    models at/above the 0.85 target, (c) floor semantics (prefill never
    starves, even when tiny fleets can't reach the target)."""
    from dynamo_tpu.engine.scheduler import MixedPrefillController

    ctl = MixedPrefillController()
    # (a) Calibration: r5 ran duty 2 + 128-token chunks behind 32 rows x
    # window 8 and measured interference 0.778.
    assert abs(ctl.modeled_interference(2, 32, 8, 128) - 0.778) < 0.01
    # (b) The same serving geometry with a deep backlog now plans to the
    # target instead of undershooting it.
    duty, chunk = ctl.plan(32, 8, 512)
    assert chunk >= ctl.floor_tokens
    assert ctl.modeled_interference(duty, 32, 8, chunk) >= ctl.target
    # Small backlogs ride the smallest duty that affords them whole.
    duty_small, chunk_small = ctl.plan(32, 8, 64)
    assert chunk_small == 64 and duty_small <= duty
    assert ctl.modeled_interference(duty_small, 32, 8, 64) >= ctl.target
    # More decode rows afford a faster prefill cadence at equal target.
    duty_big_fleet, _ = ctl.plan(64, 8, 512)
    assert duty_big_fleet <= duty
    # (c) Floor: a tiny fleet can never satisfy the target, but the chunk
    # bottoms out at floor_tokens (prefill must progress) at max duty.
    duty_tiny, chunk_tiny = ctl.plan(1, 2, 512)
    assert chunk_tiny == ctl.floor_tokens and duty_tiny == ctl.max_duty
    # Degenerate inputs never divide by zero or return negative chunks.
    assert ctl.plan(0, 8, 512) == (1, 512)
    assert ctl.plan(32, 8, 0) == (1, 0)


def test_adaptive_mixed_budget_drives_scheduler():
    """The engine installs the controller's chunk budget as the
    scheduler's mixed-budget override while decoding with a prefill
    backlog, and clears it when either side empties."""
    core = small_engine(
        decode_window=4, window_pipeline_depth=2, num_blocks=128,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=16,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16)))
    assert core._mixed_ctl is not None  # adaptive is the default
    core.add_request("dec", list(range(1, 10)),
                     SamplingParams(max_tokens=48))
    early: list = []
    for _ in range(6):   # prefill + enter window mode
        early.extend(t for d in core.step() for t in d.token_ids)
    assert core.scheduler.mixed_budget_override is None  # no backlog
    core.add_request("inj", list(range(20, 44)),
                     SamplingParams(max_tokens=4))
    early.extend(t for d in core.step() for t in d.token_ids
                 if d.request_id == "dec")
    ov = core.scheduler.mixed_budget_override
    assert ov is not None and ov >= core.scheduler.config.mixed_prefill_floor
    assert core._mixed_duty == core._mixed_ctl.max_duty  # tiny fleet: floored
    out, fin = run_to_completion(core)
    assert len(early) + len(out["dec"]) == 48 and len(out["inj"]) == 4
    # Off switch restores the static path.
    core2 = small_engine(decode_window=4, mixed_prefill_adaptive=False)
    assert core2._mixed_ctl is None
    core2.add_request("a", [1, 2, 3], SamplingParams(max_tokens=4))
    core2.step()
    assert core2.scheduler.mixed_budget_override is None
    assert core2._mixed_duty == core2.config.mixed_prefill_duty


def test_windows_continue_through_prefill_injection():
    """Decode windows must keep running while injected prompts prefill
    (bounded chunks ride behind each window), and every stream must
    still produce exactly max_tokens unique-positioned tokens."""
    core = small_engine(
        num_blocks=128,
        decode_window=4,
        window_pipeline_depth=2,
        enable_prefix_cache=False,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=16,
            max_prefill_chunk=16, mixed_prefill_tokens=16,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16)))
    n_out = 96
    for i in range(2):
        core.add_request(f"steady{i}", list(range(1, 12)),
                         SamplingParams(max_tokens=n_out))
    outputs: dict = {}
    windows_during_prefill = 0
    injected = False
    for _ in range(600):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        steady_progress = len(outputs.get("steady0", []))
        if not injected and steady_progress >= 8:
            for i in range(4):
                core.add_request(f"inj{i}", list(range(20, 50)),
                                 SamplingParams(max_tokens=n_out))
            injected = True
        if injected and core._inflight and any(
                r.state is RequestState.PREFILL
                for r in core.scheduler.running):
            windows_during_prefill += 1
        if injected and not core._requests:
            break
    assert not core._requests, "requests stalled"
    assert not core._pending_batches and not core._pending_first
    for rid, toks in outputs.items():
        assert len(toks) == n_out, (rid, len(toks))
    # The point of the machinery: at least one window dispatched while
    # injected prompts were still prefilling (no full-batch stall).
    assert windows_during_prefill > 0


def test_mixed_injection_preserves_greedy_stream():
    """A steady greedy stream's tokens must be unaffected by a mid-flight
    injection (same tokens as an undisturbed run)."""
    def run(inject: bool):
        core = small_engine(
            num_blocks=128,
            decode_window=4,
            window_pipeline_depth=2,
            enable_prefix_cache=False,
            scheduler=SchedulerConfig(
                max_seqs=8, block_size=8, max_pages_per_seq=16,
                max_prefill_chunk=16, mixed_prefill_tokens=16,
                decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16)))
        core.add_request("s", list(range(1, 12)),
                         SamplingParams(max_tokens=64))
        out: list = []
        injected = False
        for _ in range(600):
            for d in core.step():
                if d.request_id == "s":
                    out.extend(d.token_ids)
            if inject and not injected and len(out) >= 8:
                core.add_request("j", list(range(20, 44)),
                                 SamplingParams(max_tokens=8))
                injected = True
            if not core._requests:
                break
        return out

    assert run(inject=True) == run(inject=False)
