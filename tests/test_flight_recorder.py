"""Flight recorder + stall watchdog (ISSUE 14): ring semantics, dump
triggers, the injected-engine-stall detection path, the recorder-on
steady-window zero-overhead pin, the /debug/flightrecorder surfaces,
and trace_merge's --flight instant-event merging.

Engine-backed tests share ONE tiny geometry (the test_decode_window /
bench_gate steady config) so every EngineCore build hits the persistent
XLA compile cache — tier-1 budget discipline.
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import time

import pytest

from dynamo_tpu.runtime import flight_recorder
from dynamo_tpu.runtime.flight_recorder import FlightRecorder, StallWatchdog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def recorder(tmp_path):
    """The module singleton, enabled into a tmp dump dir and restored to
    the disabled default afterwards (other tests pin recorder-off
    behavior)."""
    rec = flight_recorder.get_recorder()
    rec.reset()
    rec.configure(enabled=True, ring_size=512, dump_dir=str(tmp_path),
                  service="test")
    yield rec
    rec.reset()
    rec.configure(enabled=False, service="dynamo",
                  ring_size=flight_recorder.DEFAULT_RING)
    rec.dump_dir = None


def _tiny_engine(**kw):
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg

    defaults = dict(
        model=mcfg.get_config("tiny-test"), num_blocks=128,
        enable_prefix_cache=False, decode_window=2,
        window_pipeline_depth=2,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=32,
            max_prefill_chunk=128, decode_buckets=(1, 2, 4, 8),
            prefill_buckets=(16, 128)))
    defaults.update(kw)
    return EngineCore(EngineConfig(**defaults))


# -- ring semantics ----------------------------------------------------------


def test_ring_records_wraps_and_orders(recorder):
    small = FlightRecorder(enabled=True, ring_size=8)
    for i in range(13):
        small.record("k", i=i)
    ev = small.events()
    assert len(ev) == 8
    assert [e["i"] for e in ev] == list(range(5, 13))   # oldest dropped
    assert small.events_written == 13
    assert [e["i"] for e in small.events(3)] == [10, 11, 12]
    # n <= 0 = envelope only, never the whole ring by slice degeneracy.
    assert small.events(0) == [] and small.events(-3) == []
    # Every event carries the uniform envelope.
    assert all({"seq", "ts", "kind"} <= set(e) for e in ev)


def test_disabled_recorder_is_a_noop_but_record_always_is_not():
    rec = FlightRecorder(enabled=False, ring_size=8)
    rec.record("never", x=1)
    assert rec.events() == [] and rec.events_written == 0
    rec.record_always("stall", age_s=1.0)
    assert [e["kind"] for e in rec.events()] == ["stall"]


def test_heartbeat_age(recorder):
    rec = FlightRecorder()
    assert rec.last_step_age_s() is None     # never stepped ≠ stalled
    rec.beat()
    age = rec.last_step_age_s()
    assert age is not None and age < 1.0


def test_dump_writes_header_and_events_and_throttles(recorder, tmp_path):
    recorder.record("admit", rid="r1", prompt=64)
    recorder.record("window", bucket=8, width=16, lag=1)
    path = recorder.dump("unit_test", min_interval_s=0.0)
    assert path and os.path.dirname(path) == str(tmp_path)
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["flight_dump"] is True
    assert lines[0]["reason"] == "unit_test"
    assert lines[0]["pid"] == os.getpid()
    assert lines[0]["events"] == 2
    assert [l["kind"] for l in lines[1:]] == ["admit", "window"]
    assert lines[1]["rid"] == "r1"
    # Per-reason throttle: an immediate re-dump of the same reason is
    # suppressed; a different reason is not.
    assert recorder.dump("unit_test", min_interval_s=60.0) is None
    assert recorder.dump("other_reason", min_interval_s=60.0) is not None
    assert recorder.dumps_written == 2


def test_debug_payload_shape(recorder):
    recorder.record("kv_plane", plane="device", reason="eager")
    p = recorder.debug_payload(16)
    assert p["enabled"] is True
    assert p["service"] == "test"
    assert p["pid"] == os.getpid()
    assert p["stalls"] == 0
    assert p["events"][-1]["kind"] == "kv_plane"
    assert p["events_written"] == 1


# -- stall watchdog ----------------------------------------------------------


def test_watchdog_check_once_is_deterministic(recorder):
    """Stall declared iff heartbeat is old AND work is pending; one
    episode counts once; heartbeat resume re-arms."""
    pending = {"v": True}
    wd = StallWatchdog(recorder, lambda: pending["v"], stall_s=5.0)
    # Never stepped: starting, not stalled.
    assert wd.check_once(now=time.monotonic() + 100) is False
    recorder.beat()
    t0 = recorder.last_beat
    # Fresh heartbeat: fine.
    assert wd.check_once(now=t0 + 1.0) is False
    # Old heartbeat + pending work: stall (counted, dumped, recorded).
    assert wd.check_once(now=t0 + 6.0) is True
    assert wd.stalled and recorder.stalls == 1
    assert recorder.last_dump_path is not None
    assert any(e["kind"] == "stall" for e in recorder.events())
    # Same episode: no double count.
    assert wd.check_once(now=t0 + 60.0) is False
    assert recorder.stalls == 1
    # Heartbeat resumes: re-armed; a NEW wedge counts again.
    recorder.beat()
    assert wd.check_once(now=recorder.last_beat + 1.0) is False
    assert not wd.stalled
    assert wd.check_once(now=recorder.last_beat + 6.0) is True
    assert recorder.stalls == 2
    # Old heartbeat but NO pending work: an idle engine is at rest.
    pending["v"] = False
    recorder.beat()
    assert wd.check_once(now=recorder.last_beat + 60.0) is False
    assert recorder.stalls == 2


def test_watchdog_compile_grace_widens_threshold(recorder):
    """A first-seen-shape compile stamped at/after the last heartbeat
    widens the stall threshold to compile_grace_s (a 30 s XLA compile
    on a cold start is not a wedge); a wedge WITHOUT a preceding
    compile still pages at stall_s, and a wedge DURING a compile pages
    at the grace."""
    wd = StallWatchdog(recorder, lambda: True, stall_s=5.0,
                       compile_grace_s=60.0)
    recorder.last_beat = 100.0
    recorder.last_compile = 100.5       # current step is compiling
    assert wd.check_once(now=110.0) is False   # past stall_s: grace holds
    assert wd.check_once(now=161.0) is True    # past the grace: a wedge
    # Heartbeat advanced past the compile stamp: back to stall_s.
    recorder.last_beat = 200.0
    assert wd.check_once(now=201.0) is False   # recovered
    assert not wd.stalled
    assert wd.check_once(now=206.0) is True    # plain wedge at stall_s
    assert recorder.stalls == 2


def test_watchdog_pending_fn_exception_reads_as_idle(recorder):
    def boom():
        raise RuntimeError("racing teardown")

    wd = StallWatchdog(recorder, boom, stall_s=1.0)
    recorder.beat()
    assert wd.check_once(now=recorder.last_beat + 10.0) is False
    assert recorder.stalls == 0


def test_engine_stall_detected_by_live_watchdog(recorder, tmp_path):
    """THE acceptance path: a real engine with pending work stops
    stepping; the watchdog THREAD declares the stall within its window,
    increments the counter, and dumps — then the engine resumes and the
    watchdog re-arms."""
    from dynamo_tpu.engine.sampling import SamplingParams

    core = _tiny_engine()
    core.add_request("a", list(range(1, 71)),
                     SamplingParams(max_tokens=64))
    for _ in range(6):
        core.step()
    assert core.has_work                      # decode work in flight
    # compile_grace_s == stall_s: the last executed step may have
    # stamped a compile (new shape), and this test injects a WEDGE, not
    # a long compile — neutralize the grace so the window is exact.
    wd = StallWatchdog(recorder, lambda: core.has_work, stall_s=0.15,
                       interval_s=0.05, compile_grace_s=0.15)
    wd.start()
    try:
        # Engine thread "wedges": nobody calls step().
        deadline = time.monotonic() + 5.0
        while recorder.stalls == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert recorder.stalls == 1, "watchdog never declared the stall"
        assert wd.stalled
        dump = recorder.last_dump_path
        assert dump and os.path.exists(dump)
        rows = [json.loads(l) for l in open(dump)]
        assert rows[0]["reason"] == "stall"
        assert any(r.get("kind") == "stall" for r in rows[1:])
        # The ring carries the pre-stall story: the engine's own
        # dispatch events precede the stall marker.
        kinds = [r.get("kind") for r in rows[1:]]
        assert "window" in kinds or "prefill" in kinds
        # Engine recovers: stepping resumes, watchdog re-arms.
        for _ in range(3):
            core.step()
        deadline = time.monotonic() + 5.0
        while wd.stalled and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not wd.stalled
        assert recorder.stalls == 1           # no new episode
    finally:
        wd.stop()


# -- engine integration ------------------------------------------------------


def test_engine_records_admissions_dispatches_recompiles(recorder):
    from dynamo_tpu.engine.sampling import SamplingParams

    core = _tiny_engine()
    core.add_request("a", list(range(1, 71)), SamplingParams(max_tokens=24))
    for _ in range(40):
        core.step()
        if not core._requests:
            break
    kinds = {e["kind"] for e in recorder.events()}
    assert {"admit", "prefill", "window", "recompile"} <= kinds
    admit = next(e for e in recorder.events() if e["kind"] == "admit")
    assert admit["rid"] == "a" and admit["prompt"] == 70
    rec_ev = next(e for e in recorder.events() if e["kind"] == "recompile")
    assert rec_ev["tag"]                       # program named
    # Heartbeat stamped by step() itself.
    assert recorder.last_step_age_s() is not None


def test_steady_window_recorder_on_is_byte_identical():
    """The overhead pin (ISSUE 14 acceptance): 20 steady window steps
    with the recorder ENABLED produce the exact same EngineStepCounters
    deltas as recorder-off — 0 extra host syncs, 0 extra dispatches, 0
    recompiles — and stay inside the ring-write budget of one write per
    window dispatch (+1 periodic counters breadcrumb)."""
    from dynamo_tpu.engine.sampling import SamplingParams

    rec = flight_recorder.get_recorder()

    def steady_run(enabled):
        rec.reset()
        rec.enabled = enabled
        core = _tiny_engine()
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):   # prefill + window warmup
            core.step()
        base = core.counters.snapshot()
        writes0 = rec.events_written
        for _ in range(20):
            core.step()
        return core.counters.delta(base), rec.events_written - writes0

    try:
        d_off, w_off = steady_run(False)
        d_on, w_on = steady_run(True)
    finally:
        rec.reset()
        rec.enabled = False
    assert w_off == 0
    assert d_on == d_off, (d_on, d_off)        # byte-identical counters
    assert d_on["host_syncs"] == d_off["host_syncs"]
    assert d_on["window_dispatches"] == 20
    assert 0 < w_on <= d_on["window_dispatches"] + 1, w_on


# -- trigger integrations ----------------------------------------------------


def test_slo_page_transition_records_and_dumps(recorder):
    from dynamo_tpu.runtime.slo import PAGE, SloMonitor, SloObjective

    state = {"total": 0.0, "bad": 0.0}
    mon = SloMonitor(
        [(SloObjective("error_rate", objective=0.99),
          lambda: (state["total"], state["bad"]))],
        clock=lambda: 0.0)
    mon.tick(now=0.0)                      # baseline sample, state OK
    state.update(total=100.0, bad=100.0)   # everything failing
    payload = mon.tick(now=10.0)
    assert payload["state"] == PAGE
    ev = [e for e in recorder.events() if e["kind"] == "slo_state"]
    assert ev and ev[-1]["prev"] == "OK" and ev[-1]["state"] == PAGE
    # The PAGE dump rides a short-lived thread (the tick may run on the
    # serving event loop): poll for it.
    deadline = time.monotonic() + 5.0
    while recorder.dumps_written == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert recorder.last_dump_path is not None
    header = json.loads(open(recorder.last_dump_path).readline())
    assert header["reason"] == "slo_page"
    # Recovery transition records too (no dump needed for PAGE→OK).
    state.update(total=100000.0, bad=100.0)
    dumps_before = recorder.dumps_written
    mon.tick(now=20.0)
    time.sleep(0.1)
    ev = [e for e in recorder.events() if e["kind"] == "slo_state"]
    assert ev[-1]["prev"] == PAGE
    assert recorder.dumps_written == dumps_before


def test_scheduler_preempt_and_kv_plane_breadcrumbs(recorder):
    from dynamo_tpu.llm.block_manager import device_transfer

    device_transfer.note_plane("host", "no_plane")
    ev = recorder.events()
    assert ev[-1]["kind"] == "kv_plane"
    assert ev[-1]["plane"] == "host" and ev[-1]["reason"] == "no_plane"


# -- surfaces ----------------------------------------------------------------


def test_debug_flightrecorder_routes(recorder):
    """Both process surfaces serve the SAME payload shape: the worker's
    StatusServer and the frontend's HttpService."""
    import aiohttp

    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.status import StatusServer

    recorder.record("window", bucket=4, width=8, lag=1)

    async def main():
        status = StatusServer()
        sport = await status.start()
        svc = HttpService(ModelManager())
        fport = await svc.start()
        try:
            async with aiohttp.ClientSession() as s:
                for port in (sport, fport):
                    async with s.get(
                            "http://127.0.0.1:%d/debug/flightrecorder"
                            "?n=16" % port) as r:
                        assert r.status == 200
                        body = await r.json()
                    assert body["enabled"] is True
                    assert body["events"][-1]["kind"] == "window"
                    assert body["stalls"] == 0
                async with s.get(
                        f"http://127.0.0.1:{sport}/debug/flightrecorder"
                        "?n=bogus") as r:
                    assert r.status == 400
        finally:
            await svc.stop()
            await status.stop()

    asyncio.run(asyncio.wait_for(main(), 60))


def test_trace_merge_flight_events(recorder, tmp_path):
    """--flight merges recorder dumps as instant markers on the owning
    process's EXISTING track (shared service name), deduped across
    overlapping dumps."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_merge

    payload = {"service": "worker-backend", "enabled": True, "traces": [{
        "trace_id": "t1", "service": "worker-backend", "spans": [
            {"name": "engine.prefill", "trace_id": "t1", "span_id": "s1",
             "parent_id": None, "service": "worker-backend",
             "ts": 1000.0, "dur": 0.5, "attrs": {"rid": "r1"}}]}]}
    recorder.configure(service="worker-backend")
    recorder.record("window", bucket=8, width=16, lag=1)
    recorder.record("stall", age_s=12.0)
    dump = recorder.dump("stall", min_interval_s=0.0)

    merged = trace_merge.merge_payloads([payload])
    # Load the SAME dump twice: (service, seq) dedupe must collapse it.
    events = (trace_merge.load_flight_dump(dump)
              + trace_merge.load_flight_dump(dump))
    added = trace_merge.merge_flight_events(merged, events)
    assert added == 2
    inst = [e for e in merged["traceEvents"] if e["ph"] == "i"]
    assert {e["name"] for e in inst} == {"fr.window", "fr.stall"}
    span_pid = next(e["pid"] for e in merged["traceEvents"]
                    if e["ph"] == "X")
    # Instant markers ride the owning process's existing track.
    assert all(e["pid"] == span_pid for e in inst)
    assert all(e["cat"] == "flight" for e in inst)
    w = next(e for e in inst if e["name"] == "fr.window")
    assert w["args"]["bucket"] == 8


def test_trace_merge_flight_unknown_service_gets_new_track(recorder,
                                                           tmp_path):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import trace_merge

    recorder.configure(service="worker-prefill")
    recorder.record("admit", rid="r9", prompt=8, cached=0, new_pages=1)
    dump = recorder.dump("sigusr2", min_interval_s=0.0)
    merged = trace_merge.merge_payloads([{"service": "frontend",
                                          "traces": []}])
    added = trace_merge.merge_flight_events(
        merged, trace_merge.load_flight_dump(dump))
    assert added == 1
    names = {e["args"]["name"] for e in merged["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert "worker-prefill" in names


# -- live worker (slow) ------------------------------------------------------


@pytest.mark.slow
def test_sigusr2_dumps_live_worker(tmp_path):
    """kill -USR2 a REAL worker process → flight dump appears in
    --flight-dump-dir with the sigusr2 reason, parseable JSONL; the
    worker's /metrics carries the AGE/STL series and its StatusServer
    serves /debug/flightrecorder."""
    import re

    import aiohttp

    from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneServer

    async def main():
        srv = ControlPlaneServer()
        cp_port = await srv.start()
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        log = open(tmp_path / "worker.log", "w+")
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--control-plane", f"127.0.0.1:{cp_port}",
             "--mocker", "--model-name", "fr-test", "--block-size", "8",
             "--flight-dump-dir", str(tmp_path)],
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT)
        try:
            # Wait for the worker to finish starting (instance line).
            deadline = time.monotonic() + 60
            text = ""
            while time.monotonic() < deadline:
                log.flush()
                log.seek(0)
                text = log.read()
                if "worker instance" in text:
                    break
                await asyncio.sleep(0.2)
            else:
                raise AssertionError("worker never started: "
                                     + open(tmp_path / "worker.log").read())
            m = re.search(r"worker status server on :(\d+)", text)
            assert m, text
            sport = int(m.group(1))
            async with aiohttp.ClientSession() as s:
                async with s.get(
                        f"http://127.0.0.1:{sport}/metrics") as r:
                    assert r.status == 200
                    metrics = await r.text()
                # The stall series exist on every worker (the mocker
                # has no heartbeat, so only the counter/flag lines).
                assert "dynamo_engine_stalls_total 0" in metrics
                assert "dynamo_engine_stalled 0" in metrics
                async with s.get(f"http://127.0.0.1:{sport}"
                                 "/debug/flightrecorder?n=8") as r:
                    assert r.status == 200
                    fr = await r.json()
                assert fr["enabled"] is True
                assert fr["pid"] == proc.pid
                assert fr["service"] == "worker-backend"
            dump_path = tmp_path / f"flight_worker-backend_{proc.pid}.jsonl"
            proc.send_signal(signal.SIGUSR2)
            deadline = time.monotonic() + 30
            header = None
            while time.monotonic() < deadline:
                if dump_path.exists():
                    rows = [json.loads(l)
                            for l in open(dump_path) if l.strip()]
                    headers = [r for r in rows if r.get("flight_dump")]
                    if any(r["reason"] == "sigusr2" for r in headers):
                        header = next(r for r in headers
                                      if r["reason"] == "sigusr2")
                        break
                await asyncio.sleep(0.2)
            assert header is not None, "no sigusr2 dump appeared"
            assert header["pid"] == proc.pid
            assert header["service"] == "worker-backend"
        finally:
            # SIGKILL, not SIGTERM: the mocker worker's graceful drain
            # can hang when its control plane goes away (pre-existing —
            # the other e2e tests kill -9 too), and this test's subject
            # is the SIGUSR2 dump, which already happened.
            proc.kill()
            proc.wait(timeout=20)
            log.close()
            await srv.stop()

    asyncio.run(asyncio.wait_for(main(), 150))
