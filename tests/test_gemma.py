"""Gemma-2 family: our engine must reproduce a `transformers`
Gemma2ForCausalLM forward (gelu_tanh MLP, (1+w) norms, post-norms, query
pre-attn scaling, attention + final logit soft caps, scaled embeddings)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def gemma_checkpoint(tmp_path_factory):
    d = tmp_path_factory.mktemp("tiny_hf_gemma2")
    cfg = transformers.Gemma2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=4,
        head_dim=8,
        max_position_embeddings=512,
        rms_norm_eps=1e-6,
        rope_theta=10_000.0,
        attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0,
        query_pre_attn_scalar=16,
        sliding_window=256,
        tie_word_embeddings=True,
        attn_implementation="eager",
        torch_dtype="float32",
    )
    torch.manual_seed(0)
    model = transformers.Gemma2ForCausalLM(cfg)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


def test_gemma_config_mapping(gemma_checkpoint):
    import json

    from dynamo_tpu.models.loader import config_from_hf

    d, _ = gemma_checkpoint
    with open(f"{d}/config.json") as f:
        cfg = config_from_hf(json.load(f), name="tiny-gemma2")
    assert cfg.activation == "gelu_tanh"
    assert cfg.attn_soft_cap == 50.0
    assert cfg.final_soft_cap == 30.0
    assert cfg.post_norms and cfg.rms_offset and cfg.embed_scale
    assert cfg.query_scale == pytest.approx(16 ** -0.5)
    assert cfg.max_context == 256  # clamped to the sliding window
    assert cfg.tie_embeddings


def test_gemma_logits_match_transformers(gemma_checkpoint):
    import jax.numpy as jnp

    from dynamo_tpu.engine import kv_cache as kvc
    from dynamo_tpu.models.llama import make_forward_step
    from dynamo_tpu.models.loader import load_params

    d, hf_model = gemma_checkpoint
    cfg, params = load_params(d, dtype=jnp.float32)

    T = 17
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, T))

    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()

    block_size = 8
    cache = kvc.init_cache(kvc.KvCacheConfig.for_model(
        cfg, num_blocks=16, block_size=block_size, dtype=jnp.float32))
    step = make_forward_step(cfg, block_size)
    bt = jnp.asarray([[1, 2, 3, 0, 0, 0, 0, 0]], jnp.int32)
    ours, _ = step(params, cache,
                   jnp.asarray(tokens, jnp.int32),
                   jnp.arange(T, dtype=jnp.int32)[None, :],
                   jnp.asarray([T], jnp.int32), bt)

    np.testing.assert_allclose(np.asarray(ours)[0], hf_logits[0],
                               rtol=2e-3, atol=2e-3)
    assert (np.asarray(ours)[0].argmax(-1) == hf_logits[0].argmax(-1)).all()


def test_gemma_engine_generates_like_transformers(gemma_checkpoint):
    import jax.numpy as jnp

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models.loader import load_params

    d, hf_model = gemma_checkpoint
    cfg, params = load_params(d, dtype=jnp.float32)

    prompt = [3, 14, 15, 92, 6, 53]
    n_out = 8
    with torch.no_grad():
        hf_out = hf_model.generate(
            torch.tensor([prompt]), max_new_tokens=n_out, do_sample=False,
            eos_token_id=None, pad_token_id=0)
    want = hf_out[0, len(prompt):].tolist()

    core = EngineCore(
        EngineConfig(model=cfg, num_blocks=64,
                     cache_dtype=jnp.float32,
                     scheduler=SchedulerConfig(
                         max_seqs=4, block_size=8, max_pages_per_seq=8,
                         max_prefill_chunk=16,
                         decode_buckets=(1, 2, 4),
                         prefill_buckets=(8, 16))),
        params=params)
    core.add_request("r", prompt, SamplingParams(max_tokens=n_out))
    got = []
    for _ in range(100):
        for delta in core.step():
            got.extend(delta.token_ids)
        if not core._requests:
            break
    assert got == want


def test_gemma_preset_serves_sharded():
    """tiny-gemma preset runs under a tp mesh (pspecs cover post-norms)."""
    import jax

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    def run(mesh):
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-gemma"), num_blocks=64, mesh=mesh,
            enable_prefix_cache=False,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16, decode_buckets=(2, 4),
                prefill_buckets=(8, 16))))
        core.add_request("g", [5, 6, 7, 8, 9], SamplingParams(max_tokens=6))
        out = []
        for _ in range(200):
            for d in core.step():
                out.extend(d.token_ids)
            if not core._requests:
                break
        return out

    want = run(None)
    got = run(make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4]))
    assert got == want and len(want) == 6
