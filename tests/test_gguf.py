"""GGUF loader: writer-fixture round trip, Q8_0 dequant, tokenizer
extraction, and engine parity (VERDICT r3 next-8)."""

import struct

import jax
import numpy as np
import pytest

from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.gguf import GgufFile, config_from_gguf, load_gguf
from dynamo_tpu.models.llama import init_params

TINY = mcfg.get_config("tiny-test")


# -- minimal GGUF writer (test fixture; llama.cpp conventions) -------------

_STR, _ARR = 8, 9
_U32, _F32, _I32 = 4, 6, 5


def _w_str(f, s: str):
    b = s.encode()
    f.write(struct.pack("<Q", len(b)) + b)


def _w_kv(f, key, vtype, value):
    _w_str(f, key)
    f.write(struct.pack("<I", vtype))
    if vtype == _U32:
        f.write(struct.pack("<I", value))
    elif vtype == _F32:
        f.write(struct.pack("<f", value))
    elif vtype == _STR:
        _w_str(f, value)
    elif vtype == _ARR:
        etype, items = value
        f.write(struct.pack("<IQ", etype, len(items)))
        for it in items:
            if etype == _STR:
                _w_str(f, it)
            elif etype == _F32:
                f.write(struct.pack("<f", it))
            elif etype == _I32:
                f.write(struct.pack("<i", it))


def _permute(w: np.ndarray, n_head: int) -> np.ndarray:
    """llama.cpp convert-time rope permutation on [out, in] weights."""
    out, in_ = w.shape
    return (w.reshape(n_head, 2, out // n_head // 2, in_)
             .swapaxes(1, 2).reshape(out, in_))


def write_gguf(path, cfg, params, tokens=None, q8_tensors=()):
    """Write params (our pytree convention) as a llama-arch GGUF."""
    tensors = {"token_embd.weight": np.asarray(params["embed"], np.float32),
               "output_norm.weight": np.asarray(params["final_norm"],
                                                np.float32)}
    for i, layer in enumerate(params["layers"]):
        p = f"blk.{i}."
        a = layer["attn"]
        # ours [in, out] → gguf stores [out, in] (+ rope permute on q/k)
        tensors[p + "attn_q.weight"] = _permute(
            np.asarray(a["wq"], np.float32).T, cfg.num_heads)
        tensors[p + "attn_k.weight"] = _permute(
            np.asarray(a["wk"], np.float32).T, cfg.num_kv_heads)
        tensors[p + "attn_v.weight"] = np.asarray(a["wv"], np.float32).T
        tensors[p + "attn_output.weight"] = np.asarray(a["wo"],
                                                       np.float32).T
        tensors[p + "attn_norm.weight"] = np.asarray(layer["attn_norm"],
                                                     np.float32)
        tensors[p + "ffn_norm.weight"] = np.asarray(layer["mlp_norm"],
                                                    np.float32)
        m = layer["mlp"]
        tensors[p + "ffn_gate.weight"] = np.asarray(m["w_gate"],
                                                    np.float32).T
        tensors[p + "ffn_up.weight"] = np.asarray(m["w_up"], np.float32).T
        tensors[p + "ffn_down.weight"] = np.asarray(m["w_down"],
                                                    np.float32).T

    def q8_encode(w):
        flat = w.reshape(-1, 32)
        scale = (np.abs(flat).max(axis=1) / 127.0).astype(np.float16)
        q = np.round(flat / np.maximum(
            scale.astype(np.float32)[:, None], 1e-12)).astype(np.int8)
        out = bytearray()
        for s, row in zip(scale, q):
            out += s.tobytes() + row.tobytes()
        return bytes(out)

    with open(path, "wb") as f:
        f.write(b"GGUF")
        f.write(struct.pack("<I", 3))
        n_kv = 10 + (1 if tokens else 0)
        f.write(struct.pack("<QQ", len(tensors), n_kv))
        _w_kv(f, "general.architecture", _STR, "llama")
        _w_kv(f, "llama.embedding_length", _U32, cfg.hidden_size)
        _w_kv(f, "llama.block_count", _U32, cfg.num_layers)
        _w_kv(f, "llama.attention.head_count", _U32, cfg.num_heads)
        _w_kv(f, "llama.attention.head_count_kv", _U32, cfg.num_kv_heads)
        _w_kv(f, "llama.attention.key_length", _U32, cfg.head_dim)
        _w_kv(f, "llama.feed_forward_length", _U32, cfg.intermediate_size)
        _w_kv(f, "llama.context_length", _U32, cfg.max_context)
        _w_kv(f, "llama.rope.freq_base", _F32, cfg.rope_theta)
        _w_kv(f, "llama.vocab_size", _U32, cfg.vocab_size)
        if tokens:
            _w_kv(f, "tokenizer.ggml.tokens", _ARR, (_STR, tokens))
        # tensor infos
        blobs = {}
        offset = 0
        for name, w in tensors.items():
            if name in q8_tensors:
                blob, gtype = q8_encode(w), 8
            else:
                blob, gtype = w.astype("<f4").tobytes(), 0
            blobs[name] = blob
            _w_str(f, name)
            dims = list(reversed(w.shape))  # ne order: fastest first
            f.write(struct.pack("<I", len(dims)))
            f.write(struct.pack(f"<{len(dims)}Q", *dims))
            f.write(struct.pack("<IQ", gtype, offset))
            offset += len(blob)
            offset += (-offset) % 32
        pos = f.tell()
        f.write(b"\0" * ((-pos) % 32))
        for name, blob in blobs.items():
            f.write(blob)
            f.write(b"\0" * ((-len(blob)) % 32))


# -- tests ------------------------------------------------------------------


@pytest.fixture(scope="module")
def gguf_path(tmp_path_factory):
    params = init_params(TINY, jax.random.key(0))
    path = tmp_path_factory.mktemp("gguf") / "tiny.gguf"
    write_gguf(str(path), TINY, params,
               tokens=[f"<t{i}>" for i in range(TINY.vocab_size)])
    return str(path), params


def test_header_and_config(gguf_path):
    path, _ = gguf_path
    g = GgufFile(path)
    assert g.metadata["general.architecture"] == "llama"
    cfg = config_from_gguf(g)
    assert cfg.hidden_size == TINY.hidden_size
    assert cfg.num_layers == TINY.num_layers
    assert cfg.num_kv_heads == TINY.num_kv_heads
    assert cfg.vocab_size == TINY.vocab_size
    assert cfg.tie_embeddings  # no output.weight written


def test_roundtrip_params_exact(gguf_path):
    path, params = gguf_path
    cfg, loaded, tok = load_gguf(path, dtype=np.float32)
    for name in ("embed", "final_norm"):
        np.testing.assert_allclose(np.asarray(params[name]),
                                   np.asarray(loaded[name]), atol=1e-6)
    for lp, ll in zip(params["layers"], loaded["layers"]):
        for k in ("wq", "wk", "wv", "wo"):
            np.testing.assert_allclose(
                np.asarray(lp["attn"][k]), np.asarray(ll["attn"][k]),
                atol=1e-6, err_msg=k)
        for k in ("w_gate", "w_up", "w_down"):
            np.testing.assert_allclose(
                np.asarray(lp["mlp"][k]), np.asarray(ll["mlp"][k]),
                atol=1e-6, err_msg=k)
    assert tok and len(tok["tokens"]) == TINY.vocab_size


def test_q8_0_dequant(gguf_path):
    _, params = gguf_path
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".gguf") as f:
        write_gguf(f.name, TINY, params,
                   q8_tensors={"blk.0.ffn_up.weight"})
        _, loaded, _ = load_gguf(f.name, dtype=np.float32)
    want = np.asarray(params["layers"][0]["mlp"]["w_up"])
    got = np.asarray(loaded["layers"][0]["mlp"]["w_up"])
    # Q8_0 is lossy: per-32-block int8 with f16 scale → ~1% error.
    assert np.max(np.abs(want - got)) < 0.02 * max(np.max(np.abs(want)),
                                                   1e-6)


def test_gguf_serves_tokens(gguf_path):
    """VERDICT done-criterion: load the fixture and produce tokens —
    identical to the engine running the original pytree."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models.loader import resolve_model

    path, params = gguf_path
    cfg, loaded, spec, _ = resolve_model(path)
    assert spec["kind"] == "byte"

    def run(cfg_, params_):
        core = EngineCore(EngineConfig(
            model=cfg_, num_blocks=64, enable_prefix_cache=False,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16, decode_buckets=(1, 2, 4),
                prefill_buckets=(8, 16))), params=params_)
        core.add_request("g", [5, 6, 7, 8, 9], SamplingParams(max_tokens=6))
        out = []
        for _ in range(100):
            for d in core.step():
                out.extend(d.token_ids)
            if not core._requests:
                break
        return out

    got = run(cfg, loaded)
    want = run(TINY, params)
    assert got == want and len(got) == 6


def test_unsupported_quant_raises(gguf_path, tmp_path):
    path, params = gguf_path
    # Corrupt one tensor's type id to Q4_K (12).
    g = GgufFile(path)
    import shutil

    bad = tmp_path / "bad.gguf"
    shutil.copy(path, bad)
    # Easier: assert the reader's dequant guard directly.
    from dynamo_tpu.models.gguf import _dequant

    with pytest.raises(ValueError, match="Q4_K"):
        _dequant(b"", 12, 0)
