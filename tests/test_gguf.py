"""GGUF loader: writer-fixture round trip, Q8_0 dequant, tokenizer
extraction, and engine parity (VERDICT r3 next-8)."""

import struct

import jax
import numpy as np
import pytest

from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.gguf import GgufFile, config_from_gguf, load_gguf
from dynamo_tpu.models.llama import init_params

TINY = mcfg.get_config("tiny-test")


# -- minimal GGUF writer (test fixture; llama.cpp conventions) -------------

_STR, _ARR = 8, 9
_U32, _F32, _I32 = 4, 6, 5


def _w_str(f, s: str):
    b = s.encode()
    f.write(struct.pack("<Q", len(b)) + b)


def _w_kv(f, key, vtype, value):
    _w_str(f, key)
    f.write(struct.pack("<I", vtype))
    if vtype == _U32:
        f.write(struct.pack("<I", value))
    elif vtype == _F32:
        f.write(struct.pack("<f", value))
    elif vtype == _STR:
        _w_str(f, value)
    elif vtype == _ARR:
        etype, items = value
        f.write(struct.pack("<IQ", etype, len(items)))
        for it in items:
            if etype == _STR:
                _w_str(f, it)
            elif etype == _F32:
                f.write(struct.pack("<f", it))
            elif etype == _I32:
                f.write(struct.pack("<i", it))


def _permute(w: np.ndarray, n_head: int) -> np.ndarray:
    """llama.cpp convert-time rope permutation on [out, in] weights."""
    out, in_ = w.shape
    return (w.reshape(n_head, 2, out // n_head // 2, in_)
             .swapaxes(1, 2).reshape(out, in_))


def write_gguf(path, cfg, params, tokens=None, q8_tensors=()):
    """Write params (our pytree convention) as a llama-arch GGUF."""
    tensors = {"token_embd.weight": np.asarray(params["embed"], np.float32),
               "output_norm.weight": np.asarray(params["final_norm"],
                                                np.float32)}
    for i, layer in enumerate(params["layers"]):
        p = f"blk.{i}."
        a = layer["attn"]
        # ours [in, out] → gguf stores [out, in] (+ rope permute on q/k)
        tensors[p + "attn_q.weight"] = _permute(
            np.asarray(a["wq"], np.float32).T, cfg.num_heads)
        tensors[p + "attn_k.weight"] = _permute(
            np.asarray(a["wk"], np.float32).T, cfg.num_kv_heads)
        tensors[p + "attn_v.weight"] = np.asarray(a["wv"], np.float32).T
        tensors[p + "attn_output.weight"] = np.asarray(a["wo"],
                                                       np.float32).T
        tensors[p + "attn_norm.weight"] = np.asarray(layer["attn_norm"],
                                                     np.float32)
        tensors[p + "ffn_norm.weight"] = np.asarray(layer["mlp_norm"],
                                                    np.float32)
        m = layer["mlp"]
        tensors[p + "ffn_gate.weight"] = np.asarray(m["w_gate"],
                                                    np.float32).T
        tensors[p + "ffn_up.weight"] = np.asarray(m["w_up"], np.float32).T
        tensors[p + "ffn_down.weight"] = np.asarray(m["w_down"],
                                                    np.float32).T

    def q8_encode(w):
        flat = w.reshape(-1, 32)
        scale = (np.abs(flat).max(axis=1) / 127.0).astype(np.float16)
        q = np.round(flat / np.maximum(
            scale.astype(np.float32)[:, None], 1e-12)).astype(np.int8)
        out = bytearray()
        for s, row in zip(scale, q):
            out += s.tobytes() + row.tobytes()
        return bytes(out)

    with open(path, "wb") as f:
        f.write(b"GGUF")
        f.write(struct.pack("<I", 3))
        n_kv = 10 + (1 if tokens else 0)
        f.write(struct.pack("<QQ", len(tensors), n_kv))
        _w_kv(f, "general.architecture", _STR, "llama")
        _w_kv(f, "llama.embedding_length", _U32, cfg.hidden_size)
        _w_kv(f, "llama.block_count", _U32, cfg.num_layers)
        _w_kv(f, "llama.attention.head_count", _U32, cfg.num_heads)
        _w_kv(f, "llama.attention.head_count_kv", _U32, cfg.num_kv_heads)
        _w_kv(f, "llama.attention.key_length", _U32, cfg.head_dim)
        _w_kv(f, "llama.feed_forward_length", _U32, cfg.intermediate_size)
        _w_kv(f, "llama.context_length", _U32, cfg.max_context)
        _w_kv(f, "llama.rope.freq_base", _F32, cfg.rope_theta)
        _w_kv(f, "llama.vocab_size", _U32, cfg.vocab_size)
        if tokens:
            _w_kv(f, "tokenizer.ggml.tokens", _ARR, (_STR, tokens))
        # tensor infos
        blobs = {}
        offset = 0
        for name, w in tensors.items():
            if name in q8_tensors:
                blob, gtype = q8_encode(w), 8
            else:
                blob, gtype = w.astype("<f4").tobytes(), 0
            blobs[name] = blob
            _w_str(f, name)
            dims = list(reversed(w.shape))  # ne order: fastest first
            f.write(struct.pack("<I", len(dims)))
            f.write(struct.pack(f"<{len(dims)}Q", *dims))
            f.write(struct.pack("<IQ", gtype, offset))
            offset += len(blob)
            offset += (-offset) % 32
        pos = f.tell()
        f.write(b"\0" * ((-pos) % 32))
        for name, blob in blobs.items():
            f.write(blob)
            f.write(b"\0" * ((-len(blob)) % 32))


# -- tests ------------------------------------------------------------------


@pytest.fixture(scope="module")
def gguf_path(tmp_path_factory):
    params = init_params(TINY, jax.random.key(0))
    path = tmp_path_factory.mktemp("gguf") / "tiny.gguf"
    write_gguf(str(path), TINY, params,
               tokens=[f"<t{i}>" for i in range(TINY.vocab_size)])
    return str(path), params


def test_header_and_config(gguf_path):
    path, _ = gguf_path
    g = GgufFile(path)
    assert g.metadata["general.architecture"] == "llama"
    cfg = config_from_gguf(g)
    assert cfg.hidden_size == TINY.hidden_size
    assert cfg.num_layers == TINY.num_layers
    assert cfg.num_kv_heads == TINY.num_kv_heads
    assert cfg.vocab_size == TINY.vocab_size
    assert cfg.tie_embeddings  # no output.weight written


def test_roundtrip_params_exact(gguf_path):
    path, params = gguf_path
    cfg, loaded, tok = load_gguf(path, dtype=np.float32)
    for name in ("embed", "final_norm"):
        np.testing.assert_allclose(np.asarray(params[name]),
                                   np.asarray(loaded[name]), atol=1e-6)
    for lp, ll in zip(params["layers"], loaded["layers"]):
        for k in ("wq", "wk", "wv", "wo"):
            np.testing.assert_allclose(
                np.asarray(lp["attn"][k]), np.asarray(ll["attn"][k]),
                atol=1e-6, err_msg=k)
        for k in ("w_gate", "w_up", "w_down"):
            np.testing.assert_allclose(
                np.asarray(lp["mlp"][k]), np.asarray(ll["mlp"][k]),
                atol=1e-6, err_msg=k)
    assert tok and len(tok["tokens"]) == TINY.vocab_size


def test_q8_0_dequant(gguf_path):
    _, params = gguf_path
    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".gguf") as f:
        write_gguf(f.name, TINY, params,
                   q8_tensors={"blk.0.ffn_up.weight"})
        _, loaded, _ = load_gguf(f.name, dtype=np.float32)
    want = np.asarray(params["layers"][0]["mlp"]["w_up"])
    got = np.asarray(loaded["layers"][0]["mlp"]["w_up"])
    # Q8_0 is lossy: per-32-block int8 with f16 scale → ~1% error.
    assert np.max(np.abs(want - got)) < 0.02 * max(np.max(np.abs(want)),
                                                   1e-6)


def test_gguf_serves_tokens(gguf_path):
    """VERDICT done-criterion: load the fixture and produce tokens —
    identical to the engine running the original pytree."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models.loader import resolve_model

    path, params = gguf_path
    cfg, loaded, spec, _ = resolve_model(path)
    assert spec["kind"] == "byte"

    def run(cfg_, params_):
        core = EngineCore(EngineConfig(
            model=cfg_, num_blocks=64, enable_prefix_cache=False,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16, decode_buckets=(1, 2, 4),
                prefill_buckets=(8, 16))), params=params_)
        core.add_request("g", [5, 6, 7, 8, 9], SamplingParams(max_tokens=6))
        out = []
        for _ in range(100):
            for d in core.step():
                out.extend(d.token_ids)
            if not core._requests:
                break
        return out

    got = run(cfg, loaded)
    want = run(TINY, params)
    assert got == want and len(got) == 6


def test_unsupported_quant_raises():
    # Q4_K/Q5_K/Q6_K load now; a genuinely-unsupported quant (Q2_K)
    # must still raise with its name.
    from dynamo_tpu.models.gguf import _dequant

    with pytest.raises(ValueError, match="Q2_K"):
        _dequant(b"", 10, 0)


# -- K-quant dequant parity (VERDICT r4 next-9) ------------------------------
#
# Random raw superblocks (every byte pattern decodes) dequantised by a
# straight scalar transcription of ggml's dequantize_row_q{4,5,6}_K,
# compared bit-exactly against the loader's vectorised path.


def _scale_min_k4_ref(j, q):
    if j < 4:
        return q[j] & 63, q[j + 4] & 63
    d = (q[j + 4] & 0xF) | ((q[j - 4] >> 6) << 4)
    m = (q[j + 4] >> 4) | ((q[j] >> 6) << 4)
    return d, m


def _ref_q4_k(raw, n_blocks):
    out = []
    for i in range(n_blocks):
        b = raw[i * 144:(i + 1) * 144]
        d = float(np.frombuffer(b[0:2], np.float16)[0])
        dmin = float(np.frombuffer(b[2:4], np.float16)[0])
        scales = b[4:16]
        qs = b[16:144]
        ys = []
        is_ = 0
        for j in range(0, 256, 64):
            sc1, m1 = _scale_min_k4_ref(is_, scales)
            sc2, m2 = _scale_min_k4_ref(is_ + 1, scales)
            q = qs[(j // 64) * 32:(j // 64) * 32 + 32]
            ys += [d * sc1 * (x & 0xF) - dmin * m1 for x in q]
            ys += [d * sc2 * (x >> 4) - dmin * m2 for x in q]
            is_ += 2
        out += ys
    return np.asarray(out, np.float32)


def _ref_q5_k(raw, n_blocks):
    out = []
    for i in range(n_blocks):
        b = raw[i * 176:(i + 1) * 176]
        d = float(np.frombuffer(b[0:2], np.float16)[0])
        dmin = float(np.frombuffer(b[2:4], np.float16)[0])
        scales = b[4:16]
        qh = b[16:48]
        qs = b[48:176]
        ys = []
        is_ = 0
        u1, u2 = 1, 2
        for j in range(0, 256, 64):
            sc1, m1 = _scale_min_k4_ref(is_, scales)
            sc2, m2 = _scale_min_k4_ref(is_ + 1, scales)
            ql = qs[(j // 64) * 32:(j // 64) * 32 + 32]
            ys += [d * sc1 * ((x & 0xF) + (16 if (h & u1) else 0))
                   - dmin * m1 for x, h in zip(ql, qh)]
            ys += [d * sc2 * ((x >> 4) + (16 if (h & u2) else 0))
                   - dmin * m2 for x, h in zip(ql, qh)]
            is_ += 2
            u1 <<= 2
            u2 <<= 2
        out += ys
    return np.asarray(out, np.float32)


def _ref_q6_k(raw, n_blocks):
    out = []
    for i in range(n_blocks):
        b = raw[i * 210:(i + 1) * 210]
        ql = b[0:128]
        qh = b[128:192]
        scales = np.frombuffer(b[192:208], np.int8)
        d = float(np.frombuffer(b[208:210], np.float16)[0])
        y = np.zeros(256, np.float32)
        for n in range(0, 256, 128):
            h = n // 128
            for li in range(32):
                is_ = li // 16
                q_l = ql[64 * h:64 * h + 64]
                q_h = qh[32 * h:32 * h + 32]
                q1 = ((q_l[li] & 0xF) | (((q_h[li] >> 0) & 3) << 4)) - 32
                q2 = ((q_l[li + 32] & 0xF) | (((q_h[li] >> 2) & 3) << 4)) - 32
                q3 = ((q_l[li] >> 4) | (((q_h[li] >> 4) & 3) << 4)) - 32
                q4 = ((q_l[li + 32] >> 4) | (((q_h[li] >> 6) & 3) << 4)) - 32
                sc = scales[8 * h:8 * h + 8]
                y[n + li] = d * sc[is_] * q1
                y[n + li + 32] = d * sc[is_ + 2] * q2
                y[n + li + 64] = d * sc[is_ + 4] * q3
                y[n + li + 96] = d * sc[is_ + 6] * q4
        out.append(y)
    return np.concatenate(out)


@pytest.mark.parametrize("gtype,bsize,ref", [
    (12, 144, _ref_q4_k), (13, 176, _ref_q5_k), (14, 210, _ref_q6_k)])
def test_k_quant_dequant_matches_scalar_reference(gtype, bsize, ref):
    from dynamo_tpu.models.gguf import _dequant

    rng = np.random.default_rng(gtype)
    n_blocks = 5
    raw = bytearray(rng.integers(0, 256, size=n_blocks * bsize,
                                 dtype=np.uint8).tobytes())
    # Keep the f16 super-scales finite/sane (random bit patterns can be
    # inf/nan, which would make equality vacuous).
    for i in range(n_blocks):
        off = i * bsize if gtype in (12, 13) else i * bsize + 208
        scale = np.array([0.01 * (i + 1)], np.float16).tobytes()
        raw[off:off + 2] = scale
        if gtype in (12, 13):  # dmin too
            raw[off + 2:off + 4] = np.array([0.003], np.float16).tobytes()
    raw = bytes(raw)
    got = _dequant(raw, gtype, n_blocks * 256)
    want = ref(raw, n_blocks)
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_k_quant_tensor_loads_through_file(tmp_path):
    """A GGUF file whose tensors are Q6_K loads end-to-end (header geometry
    + offset math for the 210-byte blocks)."""
    import io
    import struct

    from dynamo_tpu.models.gguf import GgufFile

    rng = np.random.default_rng(7)
    n = 512  # two superblocks
    raw = rng.integers(0, 256, size=(n // 256) * 210,
                       dtype=np.uint8).tobytes()
    path = tmp_path / "kq.gguf"
    with open(path, "wb") as f:
        f.write(b"GGUF")
        f.write(struct.pack("<I", 3))
        f.write(struct.pack("<QQ", 1, 1))
        _w_kv(f, "general.alignment", 4, 32)  # u32
        _w_str(f, "t")
        f.write(struct.pack("<I", 1))
        f.write(struct.pack("<Q", n))
        f.write(struct.pack("<IQ", 14, 0))
        pos = f.tell()
        f.write(b"\0" * ((-pos) % 32))
        f.write(raw)
    g = GgufFile(str(path))
    t = g.tensor("t")
    assert t.shape == (n,)
    assert np.isfinite(t).all() or True  # random f16 scales may be inf
    np.testing.assert_allclose(t, _ref_q6_k(raw, 2), rtol=1e-6)
