"""HTTP frontend e2e over the real tiny engine (reference analog:
`lib/llm/tests/http-service.rs` + `http_metrics.rs`)."""

import asyncio
import json

import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.service import LocalEngineClient, ModelHandle, ModelManager
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.models import config as mcfg


async def _serve_tiny():
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=128,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=64,
            max_prefill_chunk=128,
            decode_buckets=(1, 2, 4, 8),
            prefill_buckets=(32, 64, 128))))
    engine = InferenceEngine(core)
    await engine.start()
    tok = ByteTokenizer()
    models = ModelManager()
    models.register(ModelHandle(
        name="tiny", tokenizer=tok,
        preprocessor=OpenAIPreprocessor(tok, default_max_tokens=8),
        client=LocalEngineClient(engine)))
    svc = HttpService(models)
    port = await svc.start()
    return svc, engine, port


@pytest.fixture
def server(event_loop=None):
    # One server per test; aiohttp needs a running loop, so wrap fully.
    holder = {}

    async def setup():
        holder["svc"], holder["engine"], holder["port"] = await _serve_tiny()

    async def teardown():
        await holder["svc"].stop()
        await holder["engine"].stop()

    return holder, setup, teardown


def _run(coro):
    return asyncio.run(coro)


def test_models_health_metrics_routes():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{base}/v1/models") as r:
                    assert r.status == 200
                    data = await r.json()
                    assert [m["id"] for m in data["data"]] == ["tiny"]
                async with s.get(f"{base}/health") as r:
                    assert r.status == 200
                async with s.get(f"{base}/live") as r:
                    assert r.status == 200
                async with s.get(f"{base}/metrics") as r:
                    assert r.status == 200
                    text = await r.text()
                    assert "dynamo_frontend_requests_total" in text or text
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_chat_completion_unary():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                payload = {
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "hello"}],
                    "max_tokens": 5,
                    "temperature": 0.0,
                }
                async with s.post(f"{base}/v1/chat/completions",
                                  json=payload) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                assert data["object"] == "chat.completion"
                assert data["usage"]["completion_tokens"] == 5
                assert data["choices"][0]["finish_reason"] == "length"
                assert data["choices"][0]["message"]["role"] == "assistant"

                # Unknown model → 404 with OpenAI error shape.
                payload["model"] = "nope"
                async with s.post(f"{base}/v1/chat/completions",
                                  json=payload) as r:
                    assert r.status == 404
                    err = await r.json()
                    assert err["error"]["type"] == "model_not_found"

                # Malformed body → 400.
                async with s.post(f"{base}/v1/chat/completions",
                                  json={"model": "tiny", "messages": []}) as r:
                    assert r.status == 400

                # Metrics recorded TTFT.
                async with s.get(f"{base}/metrics") as r:
                    text = await r.text()
                assert "dynamo_frontend_time_to_first_token_seconds_count" in text
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_chat_completion_streaming():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                payload = {
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4,
                    "temperature": 0.0,
                    "stream": True,
                }
                chunks = []
                done_seen = False
                async with s.post(f"{base}/v1/chat/completions",
                                  json=payload) as r:
                    assert r.status == 200
                    assert r.headers["Content-Type"].startswith("text/event-stream")
                    async for raw in r.content:
                        line = raw.decode().strip()
                        if not line:
                            continue
                        if line == "data: [DONE]":
                            done_seen = True
                            break
                        chunks.append(json.loads(line[5:]))
                assert done_seen
                assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
                finish = [c for c in chunks
                          if c["choices"][0].get("finish_reason")]
                assert finish and finish[-1]["choices"][0]["finish_reason"] == "length"
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_completions_route():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "abc",
                        "max_tokens": 3, "temperature": 0.0}) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                assert data["object"] == "text_completion"
                assert data["usage"] == {"prompt_tokens": 3,
                                         "completion_tokens": 3,
                                         "total_tokens": 6}
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_completions_streaming():
    """stream=true on /v1/completions must produce SSE text_completion
    chunks ending in [DONE] (ADVICE r1: it returned unary JSON)."""
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                chunks, done_seen = [], False
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "abc", "max_tokens": 4,
                        "temperature": 0.0, "stream": True}) as r:
                    assert r.status == 200
                    assert r.headers["Content-Type"].startswith(
                        "text/event-stream")
                    async for raw in r.content:
                        line = raw.decode().strip()
                        if not line:
                            continue
                        if line == "data: [DONE]":
                            done_seen = True
                            break
                        chunks.append(json.loads(line[5:]))
                assert done_seen
                assert all(c["object"] == "text_completion" for c in chunks)
                finish = [c for c in chunks
                          if c["choices"][0].get("finish_reason")]
                assert finish[-1]["choices"][0]["finish_reason"] == "length"
                # Text may be empty per-chunk (byte tokenizer jails partial
                # UTF-8); the structural contract is what matters here.
                assert all("text" in c["choices"][0] or
                           c["choices"][0].get("finish_reason")
                           for c in chunks)
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())
