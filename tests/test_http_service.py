"""HTTP frontend e2e over the real tiny engine (reference analog:
`lib/llm/tests/http-service.rs` + `http_metrics.rs`)."""

import asyncio
import json

import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.service import LocalEngineClient, ModelHandle, ModelManager
from dynamo_tpu.llm.tokenizer import ByteTokenizer
from dynamo_tpu.models import config as mcfg


async def _serve_tiny():
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=128,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=64,
            max_prefill_chunk=128,
            decode_buckets=(1, 2, 4, 8),
            prefill_buckets=(32, 64, 128))))
    engine = InferenceEngine(core)
    await engine.start()
    tok = ByteTokenizer()
    models = ModelManager()
    models.register(ModelHandle(
        name="tiny", tokenizer=tok,
        preprocessor=OpenAIPreprocessor(tok, default_max_tokens=8),
        client=LocalEngineClient(engine)))
    svc = HttpService(models)
    port = await svc.start()
    return svc, engine, port


@pytest.fixture
def server(event_loop=None):
    # One server per test; aiohttp needs a running loop, so wrap fully.
    holder = {}

    async def setup():
        holder["svc"], holder["engine"], holder["port"] = await _serve_tiny()

    async def teardown():
        await holder["svc"].stop()
        await holder["engine"].stop()

    return holder, setup, teardown


def _run(coro):
    return asyncio.run(coro)


def test_models_health_metrics_routes():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.get(f"{base}/v1/models") as r:
                    assert r.status == 200
                    data = await r.json()
                    assert [m["id"] for m in data["data"]] == ["tiny"]
                async with s.get(f"{base}/health") as r:
                    assert r.status == 200
                async with s.get(f"{base}/live") as r:
                    assert r.status == 200
                async with s.get(f"{base}/metrics") as r:
                    assert r.status == 200
                    text = await r.text()
                    assert "dynamo_frontend_requests_total" in text or text
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_chat_completion_unary():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                payload = {
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "hello"}],
                    "max_tokens": 5,
                    "temperature": 0.0,
                }
                async with s.post(f"{base}/v1/chat/completions",
                                  json=payload) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                assert data["object"] == "chat.completion"
                assert data["usage"]["completion_tokens"] == 5
                assert data["choices"][0]["finish_reason"] == "length"
                assert data["choices"][0]["message"]["role"] == "assistant"

                # Unknown model → 404 with OpenAI error shape.
                payload["model"] = "nope"
                async with s.post(f"{base}/v1/chat/completions",
                                  json=payload) as r:
                    assert r.status == 404
                    err = await r.json()
                    assert err["error"]["type"] == "model_not_found"

                # Malformed body → 400.
                async with s.post(f"{base}/v1/chat/completions",
                                  json={"model": "tiny", "messages": []}) as r:
                    assert r.status == 400

                # Metrics recorded TTFT.
                async with s.get(f"{base}/metrics") as r:
                    text = await r.text()
                assert "dynamo_frontend_time_to_first_token_seconds_count" in text
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_chat_completion_streaming():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                payload = {
                    "model": "tiny",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4,
                    "temperature": 0.0,
                    "stream": True,
                }
                chunks = []
                done_seen = False
                async with s.post(f"{base}/v1/chat/completions",
                                  json=payload) as r:
                    assert r.status == 200
                    assert r.headers["Content-Type"].startswith("text/event-stream")
                    async for raw in r.content:
                        line = raw.decode().strip()
                        if not line:
                            continue
                        if line == "data: [DONE]":
                            done_seen = True
                            break
                        chunks.append(json.loads(line[5:]))
                assert done_seen
                assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
                finish = [c for c in chunks
                          if c["choices"][0].get("finish_reason")]
                assert finish and finish[-1]["choices"][0]["finish_reason"] == "length"
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_completions_route():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "abc",
                        "max_tokens": 3, "temperature": 0.0}) as r:
                    assert r.status == 200, await r.text()
                    data = await r.json()
                assert data["object"] == "text_completion"
                assert data["usage"] == {"prompt_tokens": 3,
                                         "completion_tokens": 3,
                                         "total_tokens": 6}
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_completions_streaming():
    """stream=true on /v1/completions must produce SSE text_completion
    chunks ending in [DONE] (ADVICE r1: it returned unary JSON)."""
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                chunks, done_seen = [], False
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "abc", "max_tokens": 4,
                        "temperature": 0.0, "stream": True}) as r:
                    assert r.status == 200
                    assert r.headers["Content-Type"].startswith(
                        "text/event-stream")
                    async for raw in r.content:
                        line = raw.decode().strip()
                        if not line:
                            continue
                        if line == "data: [DONE]":
                            done_seen = True
                            break
                        chunks.append(json.loads(line[5:]))
                assert done_seen
                assert all(c["object"] == "text_completion" for c in chunks)
                finish = [c for c in chunks
                          if c["choices"][0].get("finish_reason")]
                assert finish[-1]["choices"][0]["finish_reason"] == "length"
                # Text may be empty per-chunk (byte tokenizer jails partial
                # UTF-8); the structural contract is what matters here.
                assert all("text" in c["choices"][0] or
                           c["choices"][0].get("finish_reason")
                           for c in chunks)
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_over_context_prompt_rejected_400():
    """Boundary validation (VERDICT r2 weak #7): a prompt the model can't
    fit returns a 400 error shape, not a silent zero-token LENGTH stop."""
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        handle = svc.models.get("tiny")
        handle.max_context = 64
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "x" * 100,
                        "max_tokens": 4}) as r:
                    assert r.status == 400
                    err = await r.json()
                    assert err["error"]["type"] == "invalid_request_error"
                    assert "maximum context length" in err["error"]["message"]
                # A prompt that fits but over-asks max_tokens is clamped,
                # not rejected: the stream finishes at the ceiling.
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "x" * 32,
                        "temperature": 0.0,
                        "max_tokens": 10_000}) as r:
                    assert r.status == 200
                    data = await r.json()
                    assert data["usage"]["completion_tokens"] <= 32
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_completions_logprobs():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "hello",
                        "temperature": 0.0, "max_tokens": 4,
                        "logprobs": 1}) as r:
                    assert r.status == 200
                    data = await r.json()
            lp = data["choices"][0]["logprobs"]
            assert len(lp["token_logprobs"]) == 4
            assert len(lp["tokens"]) == 4
            assert all(x <= 0.0 for x in lp["token_logprobs"])
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_chat_logprobs():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/chat/completions", json={
                        "model": "tiny",
                        "messages": [{"role": "user", "content": "hi"}],
                        "temperature": 0.0, "max_tokens": 3,
                        "logprobs": True}) as r:
                    assert r.status == 200
                    data = await r.json()
            entries = data["choices"][0]["logprobs"]["content"]
            assert len(entries) == 3
            assert all(e["logprob"] <= 0.0 for e in entries)
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_embeddings_route():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/embeddings", json={
                        "model": "tiny",
                        "input": ["hello world", "goodbye"]}) as r:
                    assert r.status == 200
                    data = await r.json()
            assert len(data["data"]) == 2
            dim = len(data["data"][0]["embedding"])
            assert dim == mcfg.get_config("tiny-test").hidden_size
            assert data["data"][1]["index"] == 1
            # Same input → same embedding (deterministic forward).
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/embeddings", json={
                        "model": "tiny", "input": "hello world"}) as r:
                    again = await r.json()
            assert again["data"][0]["embedding"] == \
                data["data"][0]["embedding"]
            assert data["usage"]["prompt_tokens"] > 0
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_streaming_logprobs_and_duplicate_trace_ids():
    """Stream chunks carry logprobs; two concurrent requests sharing an
    X-Request-Id header must both succeed (unique engine ids)."""
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "hey",
                        "temperature": 0.0, "max_tokens": 3,
                        "logprobs": 1, "stream": True}) as r:
                    assert r.status == 200
                    body = (await r.read()).decode()
            lps = []
            for line in body.splitlines():
                if line.startswith("data:") and "[DONE]" not in line:
                    d = json.loads(line[5:])
                    for c in d.get("choices", []):
                        lp = c.get("logprobs")
                        if lp:
                            lps.extend(lp["token_logprobs"])
            assert len(lps) == 3 and all(x <= 0.0 for x in lps)

            async def one():
                async with aiohttp.ClientSession() as s:
                    async with s.post(f"{base}/v1/completions", json={
                            "model": "tiny", "prompt": "abc",
                            "max_tokens": 4},
                            headers={"X-Request-Id": "dup-id"}) as r:
                        return r.status, await r.json()
            (s1, d1), (s2, d2) = await asyncio.gather(one(), one())
            assert s1 == 200 and s2 == 200
            assert d1["usage"]["completion_tokens"] == 4
            assert d2["usage"]["completion_tokens"] == 4
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_embeddings_base64_and_caps():
    import aiohttp
    import base64 as b64
    import numpy as np

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/embeddings", json={
                        "model": "tiny", "input": "hi",
                        "encoding_format": "base64"}) as r:
                    assert r.status == 200
                    data = await r.json()
                emb = data["data"][0]["embedding"]
                assert isinstance(emb, str)
                vec = np.frombuffer(b64.b64decode(emb), np.float32)
                assert vec.shape[0] == \
                    mcfg.get_config("tiny-test").hidden_size
                async with s.post(f"{base}/v1/embeddings", json={
                        "model": "tiny",
                        "input": ["x"] * 200}) as r:
                    assert r.status == 400
                    assert "too many" in (await r.json())["error"]["message"]
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_unknown_tool_parser_rejected_before_generation():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/chat/completions", json={
                        "model": "tiny",
                        "messages": [{"role": "user", "content": "hi"}],
                        "tools": [{"type": "function",
                                   "function": {"name": "f"}}],
                        "tool_call_parser": "bogus"}) as r:
                    assert r.status == 400
                    assert "tool_call_parser" in \
                        (await r.json())["error"]["message"]
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_streaming_tool_choice_forced():
    """Streamed tool calls (VERDICT r5 #8): with a pinned tool_choice the
    SSE stream must carry OpenAI-spec `delta.tool_calls` fragments — a
    header delta with index/id/type/function.name, then argument
    fragments — and finish with finish_reason "tool_calls"."""
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/chat/completions", json={
                        "model": "tiny",
                        "messages": [{"role": "user", "content": "hi"}],
                        "max_tokens": 4, "temperature": 0.0,
                        "stream": True,
                        "tools": [{"type": "function",
                                   "function": {"name": "emit"}}],
                        "tool_choice": {"type": "function",
                                        "function": {"name": "emit"}}}) as r:
                    assert r.status == 200
                    chunks, done_seen = [], False
                    async for raw in r.content:
                        line = raw.decode().strip()
                        if not line:
                            continue
                        if line == "data: [DONE]":
                            done_seen = True
                            break
                        chunks.append(json.loads(line[5:]))
                assert done_seen
                tc = [c["choices"][0]["delta"]["tool_calls"][0]
                      for c in chunks
                      if c["choices"][0]["delta"].get("tool_calls")]
                assert tc, "no tool_calls deltas in stream"
                head = tc[0]
                assert head["index"] == 0
                assert head["id"].startswith("call_")
                assert head["type"] == "function"
                assert head["function"]["name"] == "emit"
                assert head["function"]["arguments"] == ""
                # Later fragments append arguments only (no name/id).
                frags = [t for t in tc[1:] if "arguments"
                         in t.get("function", {})]
                assert frags, "no argument fragments streamed"
                args = "".join(t["function"]["arguments"] for t in frags)
                assert len(args) > 0
                finish = [c["choices"][0]["finish_reason"]
                          for c in chunks
                          if c["choices"][0].get("finish_reason")]
                assert finish[-1] == "tool_calls"
                # No content deltas leak the arguments text.
                content = "".join(
                    c["choices"][0]["delta"].get("content") or ""
                    for c in chunks)
                assert content == ""

                # Unary with the same pinned tool_choice: whole
                # completion becomes that call's arguments.
                async with s.post(f"{base}/v1/chat/completions", json={
                        "model": "tiny",
                        "messages": [{"role": "user", "content": "hi"}],
                        "max_tokens": 4, "temperature": 0.0,
                        "tools": [{"type": "function",
                                   "function": {"name": "emit"}}],
                        "tool_choice": {"type": "function",
                                        "function": {"name": "emit"}}}) as r:
                    assert r.status == 200
                    data = await r.json()
                choice = data["choices"][0]
                assert choice["finish_reason"] == "tool_calls"
                calls = choice["message"]["tool_calls"]
                assert calls[0]["function"]["name"] == "emit"
                assert calls[0]["function"]["arguments"] == args
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_responses_route():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/responses", json={
                        "model": "tiny", "input": "say hi",
                        "instructions": "be brief",
                        "max_output_tokens": 4}) as r:
                    assert r.status == 200
                    data = await r.json()
            assert data["object"] == "response"
            # max_output_tokens truncation reports incomplete (Responses
            # API status semantics); a natural stop would be completed.
            assert data["status"] in ("completed", "incomplete")
            msg = data["output"][0]
            assert msg["type"] == "message" and msg["role"] == "assistant"
            assert isinstance(msg["content"][0]["text"], str)
            assert data["usage"]["output_tokens"] == 4
            # Structured message input form.
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/responses", json={
                        "model": "tiny",
                        "input": [{"role": "user", "content": "hello"}],
                        "max_output_tokens": 2}) as r:
                    assert r.status == 200
            # Streaming: response.created → output_text.delta* →
            # response.completed (VERDICT r3 weak #6: unary-only).
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/responses", json={
                        "model": "tiny", "input": "say hi",
                        "stream": True, "max_output_tokens": 4}) as r:
                    assert r.status == 200
                    events, deltas, final = [], [], None
                    async for line in r.content:
                        line = line.decode().strip()
                        if line.startswith("event:"):
                            events.append(line[6:].strip())
                        elif line.startswith("data:"):
                            payload = json.loads(line[5:])
                            if payload["type"] == "response.output_text.delta":
                                deltas.append(payload["delta"])
                            elif payload["type"] == "response.completed":
                                final = payload["response"]
            assert events[0] == "response.created"
            assert "response.output_text.delta" in events
            assert events[-1] == "response.completed"
            assert final["usage"]["output_tokens"] == 4
            assert "".join(deltas) == final["output"][0]["content"][0]["text"]
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_responses_structured_parts_and_status():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                # Structured input_text parts + developer role must not be
                # dropped or 500.
                async with s.post(f"{base}/v1/responses", json={
                        "model": "tiny",
                        "input": [
                            {"role": "developer", "content": "be brief"},
                            {"role": "user", "content": [
                                {"type": "input_text", "text": "hello"}]}],
                        "max_output_tokens": 4}) as r:
                    assert r.status == 200
                    data = await r.json()
                # Length-truncated generations report incomplete.
                assert data["status"] == "incomplete"
                assert data["usage"]["input_tokens"] > 10  # parts rendered
                # Unknown role is a 400, not a 500.
                async with s.post(f"{base}/v1/responses", json={
                        "model": "tiny",
                        "input": [{"role": "alien", "content": "x"}]}) as r:
                    assert r.status == 400
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_n_greater_than_one_and_clear_kv_blocks():
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                # n=3 greedy → three identical choices with indexes 0..2.
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "hello",
                        "temperature": 0.0, "max_tokens": 3, "n": 3}) as r:
                    assert r.status == 200
                    data = await r.json()
                assert [c["index"] for c in data["choices"]] == [0, 1, 2]
                texts = [c["text"] for c in data["choices"]]
                assert texts[0] == texts[1] == texts[2]  # greedy
                assert data["usage"]["completion_tokens"] == 9
                # n>1 streaming multiplexes choices by index (reference
                # streams everything internally, openai.rs:222-226).
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "hello", "n": 2,
                        "temperature": 0.0, "max_tokens": 3,
                        "stream": True,
                        "stream_options": {"include_usage": True}}) as r:
                    assert r.status == 200
                    text_by_index = {0: [], 1: []}
                    usage = None
                    async for line in r.content:
                        line = line.decode().strip()
                        if not line.startswith("data:") or "[DONE]" in line:
                            continue
                        chunk = json.loads(line[5:])
                        if chunk.get("usage"):
                            usage = chunk["usage"]
                        for c in chunk.get("choices", []):
                            text_by_index[c["index"]].append(c["text"])
                    # Greedy twins: identical text, both streams chunked.
                    assert "".join(text_by_index[0]) == \
                        "".join(text_by_index[1])
                    assert text_by_index[0] and text_by_index[1]
                    assert usage["completion_tokens"] == 6
                # Prime the prefix cache, then flush it via the admin route.
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "b" * 40,
                        "max_tokens": 2}) as r:
                    assert r.status == 200
                async with s.post(f"{base}/clear_kv_blocks") as r:
                    assert r.status == 200
                    flushed = await r.json()
                assert flushed["tiny"]["status"] == "ok"
                assert flushed["tiny"]["cleared"] > 0
                # Flushing again: nothing left.
                async with s.post(f"{base}/clear_kv_blocks") as r:
                    assert (await r.json())["tiny"]["cleared"] == 0
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())


def test_debug_requests_serves_folded_ledgers():
    """ISSUE 18: a completed request's ledger lands on
    /debug/requests?n=K (phases + attribution summary) and the fold
    publishes the phase histograms + goodput counter pair on /metrics."""
    import aiohttp

    async def main():
        svc, engine, port = await _serve_tiny()
        base = f"http://127.0.0.1:{port}"
        try:
            async with aiohttp.ClientSession() as s:
                async with s.post(f"{base}/v1/completions", json={
                        "model": "tiny", "prompt": "hello ledger",
                        "max_tokens": 4, "temperature": 0.0}) as r:
                    assert r.status == 200

                async with s.get(f"{base}/debug/requests?n=5") as r:
                    assert r.status == 200
                    payload = await r.json()
                assert payload["folded"] == 1
                assert payload["ledger_enabled"] is True
                assert payload["goodput"] == 1.0   # no SLO thresholds set
                entry = payload["slowest"][0]
                assert entry["output_tokens"] == 4
                assert entry["slo_good"] is True
                phases = {st["phase"] for st in entry["stamps"]}
                # Local single-process serving: frontend receive + the
                # engine's first-token tiling must both be present.
                for phase in ("receive", "queue", "prefill", "first_token"):
                    assert phase in phases, (phase, phases)

                async with s.get(f"{base}/metrics") as r:
                    text = await r.text()
                assert 'dynamo_request_phase_seconds_count{phase="prefill"}' \
                    in text
                assert "dynamo_goodput_tokens_total 4" in text
                assert "dynamo_goodput_good_tokens_total 4" in text
        finally:
            await svc.stop()
            await engine.stop()

    _run(main())
