"""Memory-plane telemetry (KvCacheMetrics/HbmPoller), the real engine's
prefix-cache hit rate, and the metrics-exposition satellites (label
escaping, scrape-vs-observe locking)."""

import re
import threading

from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.pool import BlockPool
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime.metrics import (
    Counter, Gauge, HbmPoller, Histogram, KvCacheMetrics, MetricsRegistry)

TINY = mcfg.get_config("tiny-test")


# -- Prometheus label escaping (satellite) -----------------------------------


def test_label_value_escaping_round_trip():
    """Label values containing `"`, `\\`, and newlines must emit valid
    exposition that parses back to the original strings."""
    g = Gauge("t", "t")
    nasty = 'quo"te', "back\\slash", "new\nline", 'all\\"of\nit'
    for i, v in enumerate(nasty):
        g.set(float(i), labels={"k": v})
    lines = [ln for ln in g.expose() if not ln.startswith("#")]
    assert len(lines) == len(nasty)
    label_re = re.compile(r'^t\{k="((?:[^"\\]|\\.)*)"\} ')
    parsed = set()
    for ln in lines:
        m = label_re.match(ln)
        assert m, f"invalid exposition line: {ln!r}"
        raw = m.group(1)
        assert "\n" not in raw  # newline must be escaped, not literal
        parsed.add(raw.replace("\\n", "\n").replace('\\"', '"')
                   .replace("\\\\", "\\"))
    assert parsed == set(nasty)


def test_histogram_label_escaping():
    h = Histogram("h", "h", buckets=(1.0,))
    h.observe(0.5, labels={"model": 'a"b'})
    text = "\n".join(h.expose())
    assert 'model="a\\"b"' in text


# -- expose under concurrent mutation (satellite) ----------------------------


def test_histogram_expose_consistent_under_concurrent_observe():
    """A scrape racing observe() must never emit torn cumulative counts
    (bucket cum exceeding _count, or non-monotone cum)."""
    h = Histogram("h", "h", buckets=(0.001, 0.01, 0.1, 1.0))
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            h.observe((i % 40) / 10.0, labels={"m": str(i % 3)})
            i += 1

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(300):
            lines = h.expose()
            cums = {}
            counts = {}
            for ln in lines:
                if ln.startswith("#"):
                    continue
                name_labels, _, v = ln.rpartition(" ")
                if name_labels.startswith("h_bucket"):
                    key = re.sub(r',?le="[^"]*"', "", name_labels)
                    cum = float(v)
                    assert cum >= cums.get(key, 0.0), lines
                    cums[key] = cum
                elif name_labels.startswith("h_count"):
                    counts[name_labels] = float(v)
            for key, total in counts.items():
                bkey = key.replace("h_count", "h_bucket")
                assert cums.get(bkey, 0.0) == total, lines
    finally:
        stop.set()
        t.join()


def test_counter_gauge_expose_under_concurrent_mutation():
    c, g = Counter("c", "c"), Gauge("g", "g")
    stop = threading.Event()

    def hammer():
        i = 0
        while not stop.is_set():
            c.inc(labels={"k": str(i % 5)})
            g.set(i, labels={"k": str(i % 5)})
            i += 1

    t = threading.Thread(target=hammer)
    t.start()
    try:
        for _ in range(300):
            c.expose()
            g.expose()
    finally:
        stop.set()
        t.join()


# -- KvCacheMetrics over a real BlockPool ------------------------------------


def test_kv_metrics_block_pool_alloc_evict_release_cycle():
    registry = MetricsRegistry()
    kvm = KvCacheMetrics(registry)
    pool = BlockPool(4, name="G1-device", reserve_null=True)  # 3 usable

    [a] = pool.allocate(1)
    pool.register(a, 0xA)
    kvm.observe_pool(pool, "device")
    labels = {"tier": "device", "pool": "G1-device"}
    assert kvm.pool_capacity.value(labels) == 4
    assert kvm.pool_active.value(labels) == 1
    assert kvm.pool_free.value(labels) == 2
    assert kvm.evictions.value(labels) == 0

    pool.release([a])                      # → inactive (reusable)
    kvm.observe_pool(pool, "device")
    assert kvm.pool_active.value(labels) == 0
    assert kvm.pool_reusable.value(labels) == 3

    pool.allocate(3)                       # forces LRU eviction of 0xA
    assert pool.evictions == 1
    kvm.observe_pool(pool, "device")
    assert kvm.evictions.value(labels) == 1
    # Counter is delta-tracked: re-observing the same cumulative value
    # must not double count.
    kvm.observe_pool(pool, "device")
    assert kvm.evictions.value(labels) == 1

    text = registry.expose()
    for series in ("dynamo_kv_pool_capacity_blocks",
                   "dynamo_kv_pool_active_blocks",
                   "dynamo_kv_pool_reusable_blocks",
                   "dynamo_kv_pool_free_blocks",
                   "dynamo_kv_evictions_total"):
        assert f'{series}{{pool="G1-device",tier="device"}}' in text


# -- real engine: prefix hit rate + pool series ------------------------------


def _engine(**kw) -> EngineCore:
    defaults = dict(
        model=TINY,
        num_blocks=64,
        enable_prefix_cache=True,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=16,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16)),
    )
    defaults.update(kw)
    return EngineCore(EngineConfig(**defaults))


def _run(core, max_steps=600):
    outputs, finished = {}, {}
    for _ in range(max_steps):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
            if d.finished:
                finished[d.request_id] = d.finish_reason
        if not core._requests:
            break
    return outputs, finished


def test_real_engine_reports_prefix_cache_hit_rate_and_pool_series():
    """The acceptance pin: after a prefix-reuse workload the REAL engine
    (not the mocker) reports nonzero gpu_prefix_cache_hit_rate in
    ForwardPassMetrics and emits dynamo_kv_pool_* series."""
    core = _engine(decode_window=1)
    prompt = list(range(1, 25))            # 24 tokens → 3 sealed blocks

    core.add_request("a", prompt, SamplingParams(max_tokens=4))
    _run(core)
    assert core.metrics.kv_stats.gpu_prefix_cache_hit_rate == 0.0

    core.add_request("b", prompt, SamplingParams(max_tokens=4))
    _run(core)
    ks = core.metrics.kv_stats
    assert ks.gpu_prefix_cache_hit_rate > 0.3, ks
    # Request b's admission matched a's sealed prompt blocks: 23 of its
    # 24 prompt tokens skipped prefill (last one always recomputes).
    assert core.scheduler.prefix_hit_tokens == 23
    assert core.scheduler.prefix_miss_tokens == 25

    registry = MetricsRegistry()
    kvm = KvCacheMetrics(registry)
    kvm.observe_engine(core)
    text = registry.expose()
    assert ('dynamo_kv_pool_capacity_blocks{pool="G1-device",'
            'tier="device"} 64.0') in text
    assert ('dynamo_kv_prefix_cache_hits_tokens{pool="G1-device",'
            'tier="device"} 23.0') in text
    assert ('dynamo_kv_prefix_cache_misses_tokens{pool="G1-device",'
            'tier="device"} 25.0') in text
    # Sealed blocks stay resident (inactive) after finish → reusable.
    labels = {"tier": "device", "pool": "G1-device"}
    assert kvm.pool_reusable.value(labels) > 0


def test_host_tier_pool_series_after_offload():
    """G2 host tier shows up under tier="host" once sized > 0."""
    core = _engine(decode_window=1, host_blocks=8)
    registry = MetricsRegistry()
    kvm = KvCacheMetrics(registry)
    kvm.observe_engine(core)
    text = registry.expose()
    assert 'dynamo_kv_pool_capacity_blocks{pool="G2-host",tier="host"} 8.0' \
        in text
    close = getattr(core.allocator.manager, "close", None)
    if close:
        close()


def test_plain_allocator_engine_still_emits_device_series():
    core = _engine(enable_prefix_cache=False, decode_window=1)
    core.add_request("a", [1, 2, 3, 4], SamplingParams(max_tokens=2))
    _run(core)
    registry = MetricsRegistry()
    kvm = KvCacheMetrics(registry)
    kvm.observe_engine(core)
    text = registry.expose()
    assert 'dynamo_kv_pool_capacity_blocks{pool="plain",tier="device"} 63.0' \
        in text


# -- steady decode window pays nothing for telemetry -------------------------


def test_kv_telemetry_steady_window_zero_overhead():
    """The acceptance pin: per-step memory-plane sampling (hotter than
    any real scrape cadence) adds 0 host syncs and 0 dispatches to the
    steady decode window — EngineStepCounters.delta discipline."""

    def steady_run(observe: bool):
        core = _engine(
            decode_window=2, window_pipeline_depth=2, num_blocks=128,
            scheduler=SchedulerConfig(
                max_seqs=8, block_size=8, max_pages_per_seq=32,
                max_prefill_chunk=128,
                decode_buckets=(1, 2, 4, 8), prefill_buckets=(16, 128)))
        kvm = KvCacheMetrics(MetricsRegistry())
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):
            core.step()
        assert core._inflight, "window pipeline not running after warmup"
        base = core.counters.snapshot()
        for _ in range(20):
            core.step()
            if observe:
                kvm.observe_engine(core)
        return core.counters.delta(base)

    d_off = steady_run(False)
    d_on = steady_run(True)
    assert d_on["host_syncs"] == d_off["host_syncs"], (d_on, d_off)
    for key in ("window_dispatches", "single_step_dispatches",
                "prefill_dispatches", "h2d_uploads", "xla_cache_misses"):
        assert d_on[key] == d_off[key], (key, d_on, d_off)


# -- HBM poller --------------------------------------------------------------


def test_hbm_poller_cpu_fallback_emits_host_series():
    """CPU backend (no device memory_stats) → the host-RSS fallback
    keeps the dynamo_hbm_* family present."""
    registry = MetricsRegistry()
    kvm = KvCacheMetrics(registry)
    poller = HbmPoller(kvm, interval=999.0)
    poller.poll_once()
    text = registry.expose()
    assert "dynamo_hbm_used_bytes" in text
    used = [ln for ln in text.splitlines()
            if ln.startswith("dynamo_hbm_used_bytes{")]
    assert used, text
    assert float(used[0].rpartition(" ")[2]) > 0
