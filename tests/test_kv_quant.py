"""int8 quantized KV plane (ISSUE 6a).

Quality pins: the quantized cache must change HBM bytes, not outputs —
greedy decode on the tiny model is TOKEN-EXACT between bf16/f32 and int8
KV (both the single-step and fused-window paths), the Pallas dequant
kernel matches the XLA gather-dequant path, and the bytes accounting the
block manager / dynamo_kv_pool_* metrics report includes the scales.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.models import config as mcfg

TINY = mcfg.get_config("tiny-test")
BS = 8


def small_engine(**kw) -> EngineCore:
    defaults = dict(
        model=TINY,
        num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16)),
    )
    defaults.update(kw)
    return EngineCore(EngineConfig(**defaults))


def run_to_completion(core, max_steps=500):
    outputs = {}
    for _ in range(max_steps):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        if core.scheduler.num_active == 0 and not core._requests:
            break
    return outputs


# -- quantization primitives -------------------------------------------------


def test_quantize_roundtrip_error_bounded():
    x = jax.random.normal(jax.random.key(0), (32, 64), jnp.float32)
    q, s = kvc.quantize_kv_rows(x, num_kv_heads=4)
    assert q.dtype == jnp.int8 and s.shape == (32, 4)
    deq = kvc.dequantize_rows(q.reshape(32, 4, 16), s,
                              jnp.float32).reshape(32, 64)
    rel = (np.max(np.abs(np.asarray(deq) - np.asarray(x)))
           / np.max(np.abs(np.asarray(x))))
    # Symmetric per-token-per-head int8: worst-case error is half a
    # quantization step of the head max, ~0.4% relative.
    assert rel < 0.01


def test_quantize_zero_rows_safe():
    """All-zero rows (padding, null block) must not divide by zero and
    must dequantize back to exactly zero."""
    x = jnp.zeros((4, 32), jnp.float32)
    q, s = kvc.quantize_kv_rows(x, num_kv_heads=2)
    assert np.all(np.asarray(q) == 0)
    assert np.all(np.isfinite(np.asarray(s)))
    deq = kvc.dequantize_rows(q.reshape(4, 2, 16), s, jnp.float32)
    assert np.all(np.asarray(deq) == 0)


def test_write_gather_quant_matches_dequant():
    cfg = kvc.KvCacheConfig(num_blocks=4, block_size=BS, num_layers=1,
                            num_kv_heads=4, head_dim=16, kv_quant="int8")
    cache = kvc.init_cache(cfg)
    assert kvc.cache_is_quantized(cache)
    x = jax.random.normal(jax.random.key(1), (BS, cfg.feature_dim))
    slots = jnp.arange(BS, 2 * BS, dtype=jnp.int32)
    k2, v2, ks2, vs2 = kvc.write_kv_quant(
        cache["k"][0], cache["v"][0], cache["k_scale"][0],
        cache["v_scale"][0], slots, x, 2 * x)
    gk, gv = kvc.gather_kv_quant(k2, v2, ks2, vs2, slots[None, :], 4,
                                 out_dtype=jnp.float32)
    q, s = kvc.quantize_kv_rows(x, 4)
    want = kvc.dequantize_rows(q.reshape(BS, 4, 16), s, jnp.float32)
    np.testing.assert_allclose(np.asarray(gk[0]), np.asarray(want),
                               rtol=0, atol=0)


# -- bytes accounting (satellite: honest dynamo_kv_pool_* / HBM numbers) -----


def test_bytes_per_block_includes_scales():
    c16 = kvc.KvCacheConfig(num_blocks=4, block_size=64, num_layers=16,
                            num_kv_heads=8, head_dim=64)
    c8 = kvc.KvCacheConfig(num_blocks=4, block_size=64, num_layers=16,
                           num_kv_heads=8, head_dim=64, kv_quant="int8")
    F, H, L, bs = 512, 8, 16, 64
    assert c16.bytes_per_block == 2 * L * bs * F * 2
    # int8 pages + 4-byte f32 scale per (token, head) — NOT bare int8.
    assert c8.bytes_per_block == 2 * L * bs * (F + 4 * H)
    ratio = c8.bytes_per_block / c16.bytes_per_block
    assert ratio <= 0.55  # the gate floor at serving geometry
    # And the wire shape advertises the packed layout.
    assert c8.block_wire_shape == (2, L, bs, F + 4 * H)
    assert c8.block_wire_dtype == jnp.int8


def test_kv_metrics_report_true_block_bytes():
    from dynamo_tpu.runtime.metrics import KvCacheMetrics, MetricsRegistry

    core = small_engine(kv_quant="int8")
    reg = MetricsRegistry()
    kvm = KvCacheMetrics(reg)
    kvm.observe_engine(core)
    got = kvm.kv_bytes_per_block.value(labels={"kv_quant": "int8"})
    assert got == core.cache_cfg.bytes_per_block
    assert "dynamo_kv_bytes_per_block" in reg.expose()


# -- kernel parity -----------------------------------------------------------


def test_pallas_quant_kernel_matches_gather_path():
    from dynamo_tpu.ops.attention import paged_attention
    from dynamo_tpu.ops.pallas import paged_decode_attention

    B, Hq, Hkv, D, bs, P = 3, 8, 4, 16, 8, 4
    F = Hkv * D
    S = (1 + B * P) * bs
    ks = jax.random.split(jax.random.key(2), 3)
    kraw = jax.random.normal(ks[0], (S, F), jnp.float32)
    vraw = jax.random.normal(ks[1], (S, F), jnp.float32)
    q = jax.random.normal(ks[2], (B, Hq, D), jnp.float32)
    bt = np.zeros((B, P), np.int32)
    for i in range(B):
        bt[i] = np.arange(1 + i * P, 1 + (i + 1) * P)
    bt = jnp.asarray(bt)
    sl = jnp.asarray([9, 25, 32], jnp.int32)

    kq, ksc = kvc.quantize_kv_rows(kraw, Hkv)
    vq, vsc = kvc.quantize_kv_rows(vraw, Hkv)
    out = paged_decode_attention(q, kq, vq, bt, sl, block_size=bs,
                                 interpret=True, k_scale=ksc, v_scale=vsc)

    ctx_pos = jnp.broadcast_to(jnp.arange(P * bs, dtype=jnp.int32),
                               (B, P * bs))
    cslots = kvc.slots_for_positions(bt, ctx_pos, bs)
    kc, vc = kvc.gather_kv_quant(kq, vq, ksc, vsc, cslots, Hkv,
                                 out_dtype=jnp.float32)
    ref = paged_attention(q[:, None], kc, vc, (sl - 1)[:, None], ctx_pos,
                          sl)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_quant_requires_both_scales_and_int8():
    from dynamo_tpu.ops.pallas import paged_decode_attention

    q = jnp.zeros((1, 4, 16), jnp.float32)
    kc = jnp.zeros((16, 64), jnp.int8)
    s = jnp.ones((16, 4), jnp.float32)
    bt = jnp.zeros((1, 2), jnp.int32)
    sl = jnp.ones((1,), jnp.int32)
    with pytest.raises(ValueError, match="both k_scale and v_scale"):
        paged_decode_attention(q, kc, kc, bt, sl, block_size=8,
                               interpret=True, k_scale=s)
    with pytest.raises(ValueError, match="int8"):
        paged_decode_attention(q, kc.astype(jnp.float32),
                               kc.astype(jnp.float32), bt, sl,
                               block_size=8, interpret=True,
                               k_scale=s, v_scale=s)


def test_auto_pair_doubles_tile_for_int8():
    from dynamo_tpu.ops.pallas.paged_attention import auto_pair

    # Serving geometry: bf16 targets 256-token tiles, int8 512.
    assert auto_pair(64, 512, itemsize=2) == 4
    assert auto_pair(64, 512, itemsize=1) == 8


# -- engine quality pins -----------------------------------------------------


def test_greedy_decode_token_exact_bf16_vs_int8():
    """The quality pin: same prompt, greedy decode, token-for-token
    identical output across cache modes — on BOTH decode paths (fused
    single step and pipelined windows)."""
    prompt = list(range(1, 30))

    def outputs(**kw):
        core = small_engine(**kw)
        core.add_request("a", prompt, SamplingParams(max_tokens=12))
        return run_to_completion(core)

    want = outputs()
    assert outputs(kv_quant="int8") == want
    assert outputs(kv_quant="int8", decode_window=4,
                   window_pipeline_depth=2) == want
    assert len(want["a"]) == 12


def test_int8_engine_counts_fewer_effective_bytes():
    """The modeled effective-bytes-per-token series must reflect the
    quantized cache — same workload, strictly fewer bytes per token."""
    def run_mode(kv_quant):
        core = small_engine(kv_quant=kv_quant, decode_window=1)
        core.add_request("a", list(range(1, 30)),
                         SamplingParams(max_tokens=6))
        run_to_completion(core)
        return core.counters.effective_bytes_per_token

    b16 = run_mode("none")
    b8 = run_mode("int8")
    assert b8 > 0
    ratio = b8 / b16
    # tiny-test stores f32 (itemsize 4): int8+scales is 0.3125x.
    assert abs(ratio - (TINY.num_kv_heads * (TINY.head_dim + 4))
               / (TINY.num_kv_heads * TINY.head_dim * 4)) < 1e-6


def test_kv_quant_mesh_composition_gating():
    """ISSUE 12: int8 composes with EVERY mesh — the old pp/ring-SP
    rejections are gone (stacked scale buffers and the quantized ring
    exchange landed), construction succeeds and each layout's cache
    pytree carries its scale buffers; the capability table
    (parallel.sharding.plane_capability) is where any future impossible
    combo must be declared."""
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    sched = SchedulerConfig(
        max_seqs=8, block_size=BS, max_pages_per_seq=8,
        max_prefill_chunk=16,
        decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16))
    tp2 = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    core = EngineCore(EngineConfig(
        model=TINY, num_blocks=64, mesh=tp2, kv_quant="int8",
        enable_prefix_cache=False, scheduler=sched))
    assert kvc.cache_is_quantized(core.cache)
    assert core.kv_shard_count == 2

    pp2 = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    core_pp = EngineCore(EngineConfig(
        model=TINY, num_blocks=64, kv_quant="int8", mesh=pp2,
        enable_prefix_cache=False, scheduler=sched))
    assert kvc.cache_is_quantized(core_pp.cache)
    assert core_pp.cache["k_scale"].shape[0] == TINY.num_layers  # stacked

    sp2 = make_mesh(MeshConfig(sp=2), jax.devices()[:2])
    core_sp = EngineCore(EngineConfig(
        model=TINY, num_blocks=64, kv_quant="int8", mesh=sp2,
        enable_prefix_cache=False, scheduler=sched))
    assert kvc.cache_is_quantized(core_sp.cache)
    assert core_sp._sp_step is not None

    with pytest.raises(ValueError, match="kv_quant"):
        kvc.KvCacheConfig(num_blocks=4, block_size=8, num_layers=1,
                          num_kv_heads=2, head_dim=16, kv_quant="fp8")


def test_quantized_tier_offload_onboard_roundtrip():
    """G1→G2 offload and G2→G1 onboard move the PACKED block (pages +
    scales atomically): evicted quantized prefixes stay warm and serve
    identical outputs after onboarding."""
    prompt = list(range(1, 25))  # 3 sealed blocks
    core = small_engine(kv_quant="int8", num_blocks=8, host_blocks=16)
    core.add_request("a", prompt, SamplingParams(max_tokens=4))
    out_a = run_to_completion(core)["a"]
    # Force G1 pressure: new request churns pages, evicting a's blocks.
    core.add_request("churn", list(range(100, 140)),
                     SamplingParams(max_tokens=4))
    run_to_completion(core)
    mgr = core.allocator.manager
    assert mgr.offloaded_blocks > 0
    core.add_request("a2", prompt, SamplingParams(max_tokens=4))
    out_a2 = run_to_completion(core)["a2"]
    assert out_a2 == out_a
    assert mgr.onboarded_blocks > 0
