import random

import pytest

from dynamo_tpu.llm.kv_router import (
    ActiveSequencesMultiWorker,
    KvIndexer,
    KvRouter,
    KvRouterConfig,
    RadixTree,
    RouterEvent,
)
from dynamo_tpu.llm.kv_router.approx import ApproxKvIndexer
from dynamo_tpu.llm.kv_router.protocols import KvCacheEvent, KvCacheEventData
from dynamo_tpu.llm.kv_router.scheduler import (
    DefaultWorkerSelector,
    WorkerLoadSnapshot,
    softmax_sample,
)
from dynamo_tpu.tokens import compute_block_hashes

BS = 16


def ev(worker, eid, data):
    return RouterEvent(worker_id=worker, event=KvCacheEvent(event_id=eid, data=data))


def hashes(tokens):
    return compute_block_hashes(tokens, BS)


class TestRadixTree:
    def test_prefix_overlap_scoring(self):
        t = RadixTree()
        h = hashes(list(range(64)))  # 4 blocks
        t.store("w0", h[:4])
        t.store("w1", h[:2])
        scores = t.find_matches(h).scores
        assert scores == {"w0": 4, "w1": 2}

    def test_contiguity_required(self):
        t = RadixTree()
        h = hashes(list(range(64)))
        # w0 has blocks 0 and 2 but not 1: overlap stops at 1
        t.store("w0", [h[0], h[2]])
        assert t.find_matches(h).scores == {"w0": 1}

    def test_remove_and_clear(self):
        t = RadixTree()
        h = hashes(list(range(48)))
        t.store("w0", h)
        t.store("w1", h)
        t.remove("w0", [h[2]])
        assert t.find_matches(h).scores == {"w0": 2, "w1": 3}
        t.clear_worker("w1")
        assert t.find_matches(h).scores == {"w0": 2}
        assert t.workers() == ["w0"]

    def test_no_match(self):
        t = RadixTree()
        t.store("w0", hashes(list(range(32))))
        assert t.find_matches(hashes(list(range(100, 132)))).scores == {}


class TestKvIndexer:
    def test_event_application_and_staleness(self):
        idx = KvIndexer(block_size=BS)
        h = hashes(list(range(32)))
        idx.apply_event(ev("w0", 1, KvCacheEventData.stored(h)))
        assert idx.find_matches(h).scores == {"w0": 2}
        # stale event id: dropped
        idx.apply_event(ev("w0", 1, KvCacheEventData.cleared()))
        assert idx.find_matches(h).scores == {"w0": 2}
        assert idx.stale_events_dropped == 1
        # fresh clear applies
        idx.apply_event(ev("w0", 2, KvCacheEventData.cleared()))
        assert idx.find_matches(h).scores == {}

    def test_remove_worker_resets_cursor(self):
        idx = KvIndexer(block_size=BS)
        h = hashes(list(range(32)))
        idx.apply_event(ev("w0", 5, KvCacheEventData.stored(h)))
        idx.remove_worker("w0")
        # restarted worker starts over at event_id 1
        idx.apply_event(ev("w0", 1, KvCacheEventData.stored(h[:1])))
        assert idx.find_matches(h).scores == {"w0": 1}


class TestSelector:
    def test_overlap_wins_when_load_equal(self):
        sel = DefaultWorkerSelector()
        c = [
            WorkerLoadSnapshot("w0", overlap_blocks=3, decode_blocks=10),
            WorkerLoadSnapshot("w1", overlap_blocks=0, decode_blocks=10),
        ]
        assert sel.select(c, request_blocks=4).worker_id == "w0"

    def test_load_beats_small_overlap(self):
        sel = DefaultWorkerSelector()
        c = [
            WorkerLoadSnapshot("w0", overlap_blocks=1, decode_blocks=100),
            WorkerLoadSnapshot("w1", overlap_blocks=0, decode_blocks=0),
        ]
        assert sel.select(c, request_blocks=4).worker_id == "w1"

    def test_softmax_t0_tie_break_uniformish(self):
        rng = random.Random(0)
        costs = {"a": 1.0, "b": 1.0, "c": 2.0}
        picks = {softmax_sample(costs, 0.0, rng) for _ in range(50)}
        assert picks == {"a", "b"}

    def test_softmax_temperature_spreads(self):
        rng = random.Random(0)
        costs = {"a": 1.0, "b": 1.1}
        picks = [softmax_sample(costs, 10.0, rng) for _ in range(200)]
        assert 50 < picks.count("a") < 150  # both picked often

    def test_empty_candidates_raises(self):
        with pytest.raises(ValueError):
            softmax_sample({}, 0.0)


class TestActiveSequences:
    def test_lifecycle_accounting(self):
        a = ActiveSequencesMultiWorker(block_size=BS)
        a.add_request("r1", "w0", isl_tokens=64, overlap_blocks=2)
        # prefill cost excludes cached prefix: 64 - 2*16 = 32
        assert a.prefill_tokens() == {"w0": 32}
        assert a.decode_blocks() == {"w0": 4}
        a.mark_prefill_complete("r1")
        assert a.prefill_tokens() == {"w0": 0}
        a.push_token("r1")  # 65 tokens -> 5 blocks
        assert a.decode_blocks() == {"w0": 5}
        a.free("r1")
        assert a.decode_blocks() == {"w0": 0}

    def test_remove_worker_drops_requests(self):
        a = ActiveSequencesMultiWorker(block_size=BS)
        a.add_request("r1", "w0", 32, 0)
        a.remove_worker("w0")
        a.push_token("r1")  # no crash; attribution gone
        assert a.decode_blocks() == {}


class TestApproxIndexer:
    def test_ttl_assumed_residency(self):
        ax = ApproxKvIndexer(block_size=BS, ttl_secs=1000.0)
        h = hashes(list(range(48)))
        ax.process_routing_decision("w0", h[:2])
        assert ax.find_matches(h).scores == {"w0": 2}
        ax.remove_worker("w0")
        assert ax.find_matches(h).scores == {}

    def test_expiry(self, monkeypatch):
        ax = ApproxKvIndexer(block_size=BS, ttl_secs=10.0)
        t = [0.0]
        monkeypatch.setattr(ax, "_now", lambda: t[0])
        ax.process_routing_decision("w0", hashes(list(range(16))))
        t[0] = 5.0
        assert ax.find_matches(hashes(list(range(16)))).scores == {"w0": 1}
        t[0] = 11.0
        assert ax.find_matches(hashes(list(range(16)))).scores == {}


class TestKvRouter:
    def test_end_to_end_routing_prefers_cached_worker(self):
        r = KvRouter(KvRouterConfig(block_size=BS))
        toks = list(range(64))
        h = hashes(toks)
        r.apply_event(ev("w0", 1, KvCacheEventData.stored(h)))
        w, overlap = r.find_best_match("r1", toks, ["w0", "w1"])
        assert (w, overlap) == ("w0", 4)
        r.free("r1")

    def test_load_balancing_without_cache(self):
        r = KvRouter(KvRouterConfig(block_size=BS))
        # Route many distinct requests; optimistic accounting should spread them.
        counts = {"w0": 0, "w1": 0}
        for i in range(10):
            toks = list(range(i * 1000, i * 1000 + 64))
            w, _ = r.find_best_match(f"r{i}", toks, ["w0", "w1"])
            counts[w] += 1
        assert counts["w0"] == 5 and counts["w1"] == 5

    def test_approx_mode(self):
        r = KvRouter(KvRouterConfig(block_size=BS, use_kv_events=False))
        toks = list(range(64))
        w1, ov1 = r.find_best_match("r1", toks, ["w0", "w1"])
        assert ov1 == 0
        r.free("r1")
        # Same prefix routes back to the same worker via assumed residency.
        w2, ov2 = r.find_best_match("r2", toks, ["w0", "w1"])
        assert w2 == w1 and ov2 == 4
        r.free("r2")

    def test_no_workers_raises(self):
        r = KvRouter()
        with pytest.raises(ValueError):
            r.find_best_match("r", [1, 2, 3], [])

    def test_dead_worker_removed(self):
        r = KvRouter(KvRouterConfig(block_size=BS))
        toks = list(range(64))
        r.apply_event(ev("w0", 1, KvCacheEventData.stored(hashes(toks))))
        r.remove_worker("w0")
        w, overlap = r.find_best_match("r1", toks, ["w1"])
        assert (w, overlap) == ("w1", 0)


def test_malformed_event_does_not_advance_cursor():
    from dynamo_tpu.llm.kv_router.protocols import KvCacheEventData, KvEventKind

    idx = KvIndexer(block_size=BS)
    bad = ev("w0", 1, KvCacheEventData(KvEventKind.STORED, store=None))
    with pytest.raises(ValueError):
        idx.apply_event(bad)
    # corrected redelivery under the same event_id applies
    h = hashes(list(range(16)))
    idx.apply_event(ev("w0", 1, KvCacheEventData.stored(h)))
    assert idx.find_matches(h).scores == {"w0": 1}


def test_active_sequence_expiry_sweep():
    a = ActiveSequencesMultiWorker(block_size=BS)
    a.add_request("r1", "w0", 32, 0)
    assert a.expire_older_than(1e9) == 0
    assert a.expire_older_than(-1.0) == 1  # everything is "older"
    assert a.decode_blocks() == {"w0": 0}
    a.push_token("r1")  # attribution cleaned too
    assert a.decode_blocks() == {"w0": 0}


def test_outstanding_prefill_influences_cost():
    sel = DefaultWorkerSelector()
    c = [
        WorkerLoadSnapshot("busy", overlap_blocks=0, decode_blocks=0, prefill_blocks=50),
        WorkerLoadSnapshot("idle", overlap_blocks=0, decode_blocks=0, prefill_blocks=0),
    ]
    assert sel.select(c, request_blocks=4).worker_id == "idle"


def test_published_metrics_influence_cost():
    """A worker saturated per its PUBLISHED ForwardPassMetrics is avoided
    even when router-local accounting knows nothing about it (VERDICT r2
    weak #5: the telemetry pipeline was dead end-to-end)."""
    from dynamo_tpu.llm.kv_router.protocols import (
        ForwardPassMetrics, KvStats, WorkerStats)

    saturated = ForwardPassMetrics(
        worker_stats=WorkerStats(request_active_slots=64,
                                 num_requests_waiting=10),
        kv_stats=KvStats(kv_active_blocks=500, kv_total_blocks=512))
    sel = DefaultWorkerSelector()
    c = [
        WorkerLoadSnapshot("busy", overlap_blocks=0, decode_blocks=0,
                           prefill_blocks=0, metrics=saturated),
        WorkerLoadSnapshot("idle", overlap_blocks=0, decode_blocks=0,
                           prefill_blocks=0),
    ]
    assert sel.select(c, request_blocks=4).worker_id == "idle"
    # Router-local optimistic load still dominates when larger (our own
    # just-routed work is fresher than a 1s-old publication).
    c2 = [
        WorkerLoadSnapshot("a", decode_blocks=600, metrics=saturated),
        WorkerLoadSnapshot("b", decode_blocks=400),
    ]
    assert sel.select(c2, request_blocks=0).worker_id == "b"


def test_router_threads_metrics_to_selector():
    from dynamo_tpu.llm.kv_router.protocols import (
        ForwardPassMetrics, KvStats, WorkerStats)

    r = KvRouter(KvRouterConfig(block_size=BS))
    toks = list(range(BS * 2))
    saturated = ForwardPassMetrics(
        worker_stats=WorkerStats(num_requests_waiting=50),
        kv_stats=KvStats(kv_active_blocks=1000))
    w, _ = r.find_best_match("r1", toks, ["w_busy", "w_idle"],
                             update_states=False,
                             metrics={"w_busy": saturated})
    assert w == "w_idle"


def test_sharded_indexer_matches_flat():
    from dynamo_tpu.llm.kv_router.indexer import KvIndexer, KvIndexerSharded
    from dynamo_tpu.llm.kv_router.protocols import RouterEvent

    def stored(worker, eid, hashes, parent=None):
        return RouterEvent(worker_id=worker, event=KvCacheEvent(
            event_id=eid,
            data=KvCacheEventData.stored(hashes, parent_hash=parent)))

    flat, sharded = KvIndexer(8), KvIndexerSharded(8, n_shards=4)
    events = [stored(w, i + 1, [100 * w + i, 100 * w + i + 1])
              for w in range(1, 6) for i in range(3)]
    for ev in events:
        flat.apply_event(ev)
        sharded.apply_event(ev)
    for q in ([101], [100, 101], [301, 302], [999]):
        assert sharded.find_matches(q).scores == flat.find_matches(q).scores
    assert sorted(sharded.tree.workers()) == sorted(flat.tree.workers())
    sharded.remove_worker(3)
    flat.remove_worker(3)
    assert sharded.find_matches([301, 302]).scores == \
        flat.find_matches([301, 302]).scores


class TestQosRouting:
    """ISSUE 16 satellite: interactive requests avoid deep-queued
    workers; best-effort fills them; the all-busy fleet routes unbiased."""

    @staticmethod
    def _waiting(n):
        from dynamo_tpu.llm.kv_router.protocols import (
            ForwardPassMetrics, KvStats, WorkerStats)

        return ForwardPassMetrics(
            worker_stats=WorkerStats(num_requests_waiting=n),
            kv_stats=KvStats())

    def _candidates(self, busy_waiting=5, idle_waiting=0):
        # "busy" holds the request's whole prefix (cost 8*waiting=40);
        # "idle" must prefill 100 blocks from scratch (cost 100) — so
        # without the QoS penalty busy wins DESPITE its queue.
        return [
            WorkerLoadSnapshot("busy", overlap_blocks=100,
                               metrics=self._waiting(busy_waiting)),
            WorkerLoadSnapshot("idle", overlap_blocks=0,
                               metrics=self._waiting(idle_waiting)),
        ]

    def test_interactive_avoids_deep_queue(self):
        from dynamo_tpu.llm.kv_router.scheduler import INTERACTIVE_PRIORITY

        sel = DefaultWorkerSelector()
        picked = sel.select(self._candidates(), request_blocks=100,
                            priority=INTERACTIVE_PRIORITY)
        assert picked.worker_id == "idle"

    def test_best_effort_and_standard_unchanged(self):
        sel = DefaultWorkerSelector()
        for prio in (None, 0, 1):
            picked = sel.select(self._candidates(), request_blocks=100,
                                priority=prio)
            assert picked.worker_id == "busy", prio

    def test_all_busy_degenerate_routes_unbiased(self):
        # EVERY candidate over the threshold: the bias cancels and the
        # interactive request routes exactly like best-effort instead of
        # herding onto an arbitrary penalized pick.
        from dynamo_tpu.llm.kv_router.scheduler import INTERACTIVE_PRIORITY

        sel = DefaultWorkerSelector()
        c = self._candidates(busy_waiting=10, idle_waiting=10)
        picked = sel.select(c, request_blocks=100,
                            priority=INTERACTIVE_PRIORITY)
        assert picked.worker_id == "busy"


class TestTopologyAwareSelection:
    def test_small_slice_decode_load_weighs_heavier(self):
        # Equal decode blocks, but one candidate is a quarter-size
        # slice: its load is scaled up and the big slice wins.
        from dynamo_tpu.fleet.topology import SliceSpec

        sel = DefaultWorkerSelector()
        c = [
            WorkerLoadSnapshot(
                "small", decode_blocks=10,
                slice=SliceSpec(hbm_per_chip_bytes=1 << 30)),
            WorkerLoadSnapshot(
                "big", decode_blocks=10,
                slice=SliceSpec(mesh=(1, 1, 1, 1, 4),
                                hbm_per_chip_bytes=1 << 30)),
        ]
        assert sel.select(c, request_blocks=0).worker_id == "big"

    def test_sliceless_candidates_keep_plain_cost(self):
        sel = DefaultWorkerSelector()
        c = [
            WorkerLoadSnapshot("a", decode_blocks=10),
            WorkerLoadSnapshot("b", decode_blocks=20),
        ]
        assert sel.select(c, request_blocks=0).worker_id == "a"


class TestPickDonor:
    def _pick(self, scores, **kw):
        from dynamo_tpu.llm.kv_router.scheduler import pick_donor

        return pick_donor(scores, chosen="c", chosen_overlap=0,
                          request_blocks=8, **kw)

    def test_tie_break_is_stable_ascending_id(self):
        """Equal-overlap donors break on the STABLE id key, independent
        of dict iteration order (the old inline key ordered every int
        before every string and flapped between replica routers)."""
        for scores in ({2: 6, 10: 6}, {10: 6, 2: 6}):
            assert self._pick(dict(scores)).worker_id == 2
        for scores in ({"w1": 6, "w0": 6}, {"w0": 6, "w1": 6}):
            assert self._pick(dict(scores)).worker_id == "w0"
        # Mixed fleet: int lease ids order before string instance ids.
        assert self._pick({"w0": 6, 7: 6}).worker_id == 7

    def test_device_reachable_donor_beats_deeper_host_one(self):
        from dynamo_tpu.fleet.topology import SliceSpec

        slices = {
            "c": SliceSpec(fabric="local:1"),
            "near": SliceSpec(fabric="local:1"),
            "far": SliceSpec(fabric="local:9"),
        }
        hint = self._pick({"near": 6, "far": 8}, slices=slices)
        assert hint.worker_id == "near"
        # Without topology the deeper donor wins as before.
        assert self._pick({"near": 6, "far": 8}).worker_id == "far"

    def test_free_hbm_breaks_overlap_ties(self):
        from dynamo_tpu.fleet.topology import SliceSpec
        from dynamo_tpu.llm.kv_router.protocols import (
            ForwardPassMetrics, KvStats)

        slices = {
            "evicting": SliceSpec(hbm_per_chip_bytes=1000),
            "roomy": SliceSpec(hbm_per_chip_bytes=1000),
        }
        metrics = {"evicting": ForwardPassMetrics(
            kv_stats=KvStats(gpu_cache_usage_perc=0.95))}
        hint = self._pick({"evicting": 6, "roomy": 6},
                          slices=slices, metrics=metrics)
        assert hint.worker_id == "roomy"

    def test_floor_and_gain_gates_still_hold(self):
        assert self._pick({"w": 3}) is None  # under 50% floor
        from dynamo_tpu.llm.kv_router.scheduler import pick_donor

        assert pick_donor({"w": 5}, chosen="c", chosen_overlap=4,
                          request_blocks=8) is None  # gain < 2


def test_router_replica_sync_applies_remote_decisions():
    """A second frontend's published decision raises this router's view of
    that worker's load (reference ACTIVE_SEQUENCES_SUBJECT sync)."""
    import asyncio

    from dynamo_tpu.llm.kv_router.client import (
        ACTIVE_SEQS_SUBJECT, KvRoutedEngineClient)
    from dynamo_tpu.runtime.control_plane import InProcessControlPlane
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        runtime = DistributedRuntime(cp)
        client = KvRoutedEngineClient(None, runtime, block_size=8)
        await client.start()
        try:
            # Remote replica routes a big request to worker 1.
            await cp.publish(ACTIVE_SEQS_SUBJECT, {
                "router": "other", "kind": "add", "request_id": "r9",
                "worker": 1, "isl": 64, "overlap": 0, "expected": 64})
            await asyncio.sleep(0.05)
            w, _ = client.router.find_best_match(
                "mine", list(range(16)), [1, 2], update_states=False)
            assert w == 2  # worker 1 is loaded by the REMOTE decision
            # Remote free restores balance.
            await cp.publish(ACTIVE_SEQS_SUBJECT, {
                "router": "other", "kind": "free", "request_id": "r9"})
            await asyncio.sleep(0.05)
            assert client.router.active.decode_blocks().get(1, 0) == 0
            # Own echoes are ignored (no double counting).
            await cp.publish(ACTIVE_SEQS_SUBJECT, {
                "router": client._router_id, "kind": "add",
                "request_id": "x", "worker": 2, "isl": 64, "overlap": 0})
            await asyncio.sleep(0.05)
            assert client.router.active.prefill_tokens().get(2, 0) == 0
        finally:
            await client.stop()
            await cp.close()

    asyncio.run(main())
