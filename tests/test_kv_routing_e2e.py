"""KV-aware routing over the runtime, with mock engines as workers
(reference analog: `tests/router/test_router_e2e_with_mockers.py`).

Two mock workers serve behind KV routing; requests sharing a prefix must
stick to the worker holding that prefix's blocks (observable as prefix-
cache hits on exactly one mocker), while distinct-prefix load spreads.
"""

import asyncio

import pytest

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.llm.discovery import engine_wire_handler
from dynamo_tpu.llm.kv_router.client import KvRoutedEngineClient
from dynamo_tpu.llm.kv_router.protocols import RouterEvent
from dynamo_tpu.llm.mocker import MockEngine, MockEngineArgs
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.runtime.control_plane import InProcessControlPlane
from dynamo_tpu.runtime.distributed import DistributedRuntime

FAST = MockEngineArgs(num_blocks=256, block_size=8, speedup_ratio=100.0)


def _req(rid, tokens, max_tokens=4):
    return PreprocessedRequest(
        request_id=rid, model="m", token_ids=list(tokens),
        sampling=SamplingParams(max_tokens=max_tokens))


def test_kv_routing_prefix_stickiness_and_spread():
    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        runtime = DistributedRuntime(cp)
        # Two runtimes → two real RPC addresses, each serving a mock engine
        # that publishes KV events attributed to its instance id (like
        # dynamo_tpu.worker's event pump).
        rt2 = DistributedRuntime(cp)
        ep1 = (runtime.namespace("dyn").component("backend")
               .endpoint("generate"))
        ep2 = (rt2.namespace("dyn").component("backend")
               .endpoint("generate"))
        pend1, pend2 = [], []
        eng1 = MockEngine(FAST, kv_event_sink=pend1.append)
        eng2 = MockEngine(FAST, kv_event_sink=pend2.append)
        await eng1.start()
        await eng2.start()
        inst1 = await ep1.serve(engine_wire_handler(eng1))
        inst2 = await ep2.serve(engine_wire_handler(eng2))
        engines = {inst1.instance_id: eng1, inst2.instance_id: eng2}

        async def pump(pending, iid):
            while True:
                await asyncio.sleep(0.005)
                while pending:
                    ev = pending.pop(0)
                    await cp.publish("kv_events", RouterEvent(
                        worker_id=iid, event=ev).to_dict())

        pumps = [asyncio.create_task(pump(pend1, inst1.instance_id)),
                 asyncio.create_task(pump(pend2, inst2.instance_id))]

        client = await (runtime.namespace("dyn").component("backend")
                        .endpoint("generate").client())
        await client.wait_for_instances()
        kv = KvRoutedEngineClient(client, runtime, block_size=8)
        await kv.start()

        async def run_one(rid, tokens):
            out = []
            async for d in kv.generate(_req(rid, tokens)):
                out.extend(d.token_ids)
            return out

        # Phase 1: two distinct long prefixes → load spreads (each lands
        # somewhere; with empty caches the selector balances by load).
        prefix_a = list(range(100, 164))      # 8 blocks
        prefix_b = list(range(200, 264))
        await run_one("a0", prefix_a)
        await run_one("b0", prefix_b)
        await asyncio.sleep(0.05)             # let events index

        # Phase 2: repeats of each prefix must go to the worker that
        # already holds it (prefix-cache stickiness).
        for i in range(1, 4):
            await run_one(f"a{i}", prefix_a + [i])
            await run_one(f"b{i}", prefix_b + [i])
            await asyncio.sleep(0.02)

        hits = {iid: e.kv.hit_blocks for iid, e in engines.items()}
        total_hits = sum(hits.values())
        # 6 repeat requests × 8 shared blocks = 48 potential hits; routing
        # that ignored residency would average ~half.  Require most.
        assert total_hits >= 36, f"prefix hits too low: {hits}"

        for t in pumps:
            t.cancel()
        await kv.stop()
        await client.stop()
        await eng1.stop()
        await eng2.stop()
        await runtime.shutdown()
        await rt2.shutdown()
        await cp.close()

    asyncio.run(asyncio.wait_for(main(), timeout=60))
