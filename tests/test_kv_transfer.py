"""Cross-worker KV-block transfer (the NIXL-analog data plane).

Worker A prefills a prompt; worker B pulls A's sealed blocks over the RPC
plane, injects them, and serves the same prompt with the prefill skipped —
outputs must match exactly (hash-chained blocks guarantee the prefix is
identical).  This is the mechanism disaggregated P/D rides on.
"""

import asyncio

import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.transfer import (
    KV_BLOCKS_ENDPOINT,
    decode_block,
    encode_block,
    fetch_blocks,
    make_kv_blocks_handler,
    pull_prefix,
)
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime.rpc import RpcClient, RpcServer
from dynamo_tpu.tokens import compute_block_hashes

TINY = mcfg.get_config("tiny-test")
BS = 8


def _core(**kw):
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16)), **kw))


def _run(core, rid, prompt, n=4):
    core.add_request(rid, prompt, SamplingParams(max_tokens=n))
    out = []
    for _ in range(200):
        for d in core.step():
            out.extend(d.token_ids)
        if not core._requests:
            break
    return out


def test_block_wire_roundtrip():
    import ml_dtypes

    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    h, back = decode_block(encode_block(123, arr))
    assert h == 123 and back.dtype == arr.dtype
    np.testing.assert_array_equal(arr, back)
    # bf16 survives the wire (the real cache dtype).
    arr16 = arr.astype(ml_dtypes.bfloat16)
    _, back16 = decode_block(encode_block(5, arr16))
    assert back16.dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(arr16, back16)


def test_export_import_between_engines():
    """Direct engine-to-engine (no wire): B serves A's blocks after import
    with identical output and a prefix hit."""
    prompt = list(range(1, 25))  # 3 sealed blocks

    a = _core()
    out_a = _run(a, "a", prompt)
    hashes = compute_block_hashes(prompt, BS)
    blocks = a.export_blocks(hashes)
    assert len(blocks) == 3
    # Exported bytes are the actual device KV (shape [2, L, bs, Hkv, D]).
    shape = next(iter(blocks.values())).shape
    assert shape[0] == 2 and shape[2] == BS

    b = _core()
    assert b.import_blocks(blocks) == 3
    hits_before = b.allocator.manager.device.hits
    out_b = _run(b, "b", prompt)
    assert out_b == out_a
    assert b.allocator.manager.device.hits > hits_before


def test_transfer_over_rpc_plane():
    """Full wire path: A behind an RpcServer, B pulls via pull_prefix."""
    prompt = list(range(40, 70))  # 3 sealed blocks + tail

    async def main():
        core_a = _core()
        eng_a = InferenceEngine(core_a)
        await eng_a.start()
        server = RpcServer()
        server.register(KV_BLOCKS_ENDPOINT, make_kv_blocks_handler(eng_a))
        addr = await server.start()

        # A prefills (serve one request to populate + register blocks).
        out_a = []
        async for d in eng_a.generate("a", prompt, SamplingParams(max_tokens=4)):
            out_a.extend(d.token_ids)

        core_b = _core()
        eng_b = InferenceEngine(core_b)
        await eng_b.start()
        client = RpcClient(addr)
        covered = await pull_prefix(eng_b, client, prompt, BS)
        assert covered == 24  # 3 sealed blocks of 8

        out_b = []
        async for d in eng_b.generate("b", prompt, SamplingParams(max_tokens=4)):
            out_b.extend(d.token_ids)
        assert out_b == out_a
        assert core_b.allocator.manager.onboarded_blocks >= 0
        assert core_b.allocator.manager.device.hits >= 3

        # Missing hashes are absent, not errors.
        got = await fetch_blocks(client, [999999])
        assert got == {}

        await client.close()
        await server.stop()
        await eng_a.stop()
        await eng_b.stop()
        return True

    assert asyncio.run(asyncio.wait_for(main(), timeout=120))


def test_g4_remote_tier_onboards_peer_blocks():
    """G4 (remote) tier: a local-tier miss during admission matching
    consults the remote fetch hook and onboards the peer's blocks —
    the decode engine skips prefill for the fetched prefix."""
    prompt = list(range(1, 25))  # 3 sealed blocks

    a = _core()
    out_a = _run(a, "a", prompt)

    fetches = []

    def remote_fetch(block_hash):
        fetches.append(block_hash)
        got = a.export_blocks([block_hash])
        return got.get(block_hash)

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.scheduler import SchedulerConfig

    b = EngineCore(EngineConfig(
        model=TINY, num_blocks=64,
        remote_fetch_fn=remote_fetch,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))
    out_b = _run(b, "b", prompt)
    assert out_b == out_a
    assert len(fetches) == 3
    assert b.allocator.manager.remote_fetched_blocks == 3
    # The fetched prefix is registered locally: a second request hits G1,
    # no further remote fetches.
    out_b2 = _run(b, "b2", prompt)
    assert out_b2 == out_a
    assert len(fetches) == 3


def test_quantized_block_roundtrip_over_wire():
    """ISSUE 6 satellite: export→hash-chain→wire→import of PACKED int8
    blocks (pages + scales in one array) between two kv_quant=int8
    engines preserves bytes exactly and the puller serves the prompt
    with identical output and a prefix hit."""
    prompt = list(range(1, 25))  # 3 sealed blocks

    a = _core(kv_quant="int8")
    out_a = _run(a, "a", prompt)
    hashes = compute_block_hashes(prompt, BS)
    blocks = a.export_blocks(hashes)
    assert len(blocks) == 3
    # The packed wire block: int8, trailing dim F + 4*Hkv (scale bytes).
    for data in blocks.values():
        assert data.dtype == np.int8
        assert tuple(data.shape) == a.cache_cfg.block_wire_shape

    # Wire encode/decode is byte-exact for the packed format.
    for h, data in blocks.items():
        h2, back = decode_block(encode_block(h, data))
        assert h2 == h
        np.testing.assert_array_equal(back, data)

    b = _core(kv_quant="int8")
    assert b.import_blocks(blocks) == 3
    # Injected pages AND scales round-trip byte-identically: re-export
    # from B and compare raw arrays.
    blocks_b = b.export_blocks(hashes)
    for h in hashes:
        np.testing.assert_array_equal(blocks_b[h], blocks[h])
    hits_before = b.allocator.manager.device.hits
    out_b = _run(b, "b", prompt)
    assert out_b == out_a
    assert b.allocator.manager.device.hits > hits_before


def test_mixed_kv_quant_peers_fail_loudly():
    """A bf16 puller importing an int8 source's blocks (or vice versa)
    must surface a clear error — NOT cast garbage into live KV pages."""
    prompt = list(range(1, 25))

    src8 = _core(kv_quant="int8")
    _run(src8, "a", prompt)
    hashes = compute_block_hashes(prompt, BS)
    blocks8 = src8.export_blocks(hashes)

    dst16 = _core()
    with pytest.raises(ValueError, match="kv-quant|KV block format"):
        dst16.import_blocks(blocks8)
    # Nothing was registered: the bad blocks are not matchable, and no
    # slot leaked (inject failure releases the fresh slot).
    assert dst16.allocator.manager.device.registry.by_hash == {}
    assert dst16.allocator.manager.device.active_slots == 0

    # Reverse direction (bf16 source → int8 puller): same refusal, no
    # engine run needed — a wire-shaped float block is enough.
    dst8 = _core(kv_quant="int8")
    fake16 = {hashes[0]: np.zeros(dst16.cache_cfg.block_wire_shape,
                                  np.float32)}
    with pytest.raises(ValueError, match="kv-quant|KV block format"):
        dst8.import_blocks(fake16)


def test_async_offload_waits_for_inflight_bytes():
    """Eviction dispatches the extract and returns; a G2 reader arriving
    before the host copy lands must wait for THAT block's future (the
    async-offload ordering contract)."""
    import threading
    import time as _time

    from dynamo_tpu.llm.block_manager.manager import (
        KvBlockManager, TieredConfig)

    store = {1: None}
    release_gate = threading.Event()

    class SlowStaged:
        """Device-array stand-in whose host transfer blocks on a gate."""

        def __init__(self, value):
            self.value = value

        def __array__(self, dtype=None, copy=None):
            release_gate.wait(5)
            return np.full((2, 2), self.value, np.float32)

    injected = {}
    mgr = KvBlockManager(
        TieredConfig(device_blocks=4, host_blocks=4, block_size=8),
        extract_fn=lambda slot: SlowStaged(slot),
        inject_fn=lambda slot, data: injected.__setitem__(slot, np.array(data)))
    # Prime storage shape with a fast first offload.
    release_gate.set()
    [s0] = mgr.allocate(1)
    mgr.register(s0, 100)
    mgr.release([s0])
    mgr.allocate(3)  # evicts hash 100 → offload (fast path, shape known)
    assert mgr.offloaded_blocks == 1
    release_gate.clear()

    # Simulate an in-flight (not yet landed) host copy for hash 100 and
    # verify a G2 reader blocks on exactly that future.
    fut_done = []

    def land_slow():
        release_gate.wait(5)
        fut_done.append(True)

    mgr._pending_host[100] = mgr._offload_pool.submit(land_slow)
    t = threading.Thread(
        target=lambda: fut_done.append(mgr.export_block(100) is not None))
    t.start()
    _time.sleep(0.1)
    assert not fut_done  # reader is blocked on the pending offload
    release_gate.set()
    t.join(5)
    assert fut_done and fut_done[-1] is True  # waited, then read real bytes
