"""Declarative launcher (operator-lite, VERDICT r3 next-9): one graph
TOML brings up the disagg P/D topology; crashed replicas restart per
policy; teardown drains in reverse order."""

import asyncio
import os
import signal

import pytest

from dynamo_tpu.launcher import Launcher, load_graph

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _write_graph(tmp_path, body: str) -> str:
    path = tmp_path / "graph.toml"
    path.write_text(body)
    return str(path)


def test_graph_parsing(tmp_path):
    path = _write_graph(tmp_path, """
[graph]
namespace = "ns"
serve_control_plane = true

[services.frontend]
module = "dynamo_tpu.frontend"
args = ["--http-port", "0"]

[services.decode]
module = "dynamo_tpu.worker"
args = ["--mocker"]
replicas = 2
restart = "always"
""")
    spec = load_graph(path)
    assert spec.namespace == "ns"
    names = {s.name: s for s in spec.services}
    assert names["decode"].replicas == 2
    assert names["decode"].restart == "always"
    assert names["frontend"].restart == "on-failure"


def test_bad_restart_policy_rejected(tmp_path):
    path = _write_graph(tmp_path, """
[services.x]
module = "m"
restart = "sometimes"
""")
    with pytest.raises(ValueError, match="sometimes"):
        load_graph(path)


@pytest.mark.e2e
def test_graph_brings_up_disagg_topology(tmp_path):
    """One command: control plane + frontend + prefill/decode workers up,
    a chat completion served end-to-end, a killed worker restarted."""
    from aiohttp import ClientSession

    path = _write_graph(tmp_path, """
[graph]
namespace = "dynamo"
serve_control_plane = true

[services.frontend]
module = "dynamo_tpu.frontend"
args = ["--http-port", "39471"]
restart = "always"

[services.prefill]
module = "dynamo_tpu.worker"
args = ["--model", "tiny-test", "--model-name", "tiny",
        "--block-size", "8", "--role", "prefill"]
restart = "always"

[services.decode]
module = "dynamo_tpu.worker"
args = ["--model", "tiny-test", "--model-name", "tiny",
        "--block-size", "8", "--role", "decode",
        "--max-local-prefill", "8"]
restart = "always"
""")

    async def main():
        spec = load_graph(path)
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        launcher = Launcher(spec, env=env)
        await launcher.start()
        try:
            base = "http://127.0.0.1:39471"
            async with ClientSession() as s:
                for _ in range(120):
                    try:
                        async with s.get(f"{base}/health") as r:
                            if r.status == 200:
                                body = await r.json()
                                if "tiny" in body.get("models", []):
                                    break
                    except Exception:
                        pass
                    await asyncio.sleep(1.0)
                else:
                    raise AssertionError(
                        f"graph never became healthy: "
                        f"{launcher.status()}")
                async with s.post(f"{base}/v1/chat/completions", json={
                        "model": "tiny",
                        "messages": [{"role": "user",
                                      "content": "long enough to go "
                                                 "remote for prefill"}],
                        "max_tokens": 4}) as r:
                    assert r.status == 200, await r.text()

                # Supervision: kill the decode worker; the launcher
                # restarts it and the model becomes servable again.
                decode = next(rep for rep in launcher._replicas
                              if rep.svc.name == "decode")
                os.kill(decode.proc.pid, signal.SIGKILL)
                await asyncio.sleep(2.0)
                for _ in range(120):
                    if decode.restarts >= 1 and launcher.status()[
                            "decode[0]"]["alive"]:
                        break
                    await asyncio.sleep(1.0)
                assert decode.restarts >= 1
                for _ in range(120):
                    try:
                        async with s.post(
                                f"{base}/v1/chat/completions", json={
                                    "model": "tiny",
                                    "messages": [{"role": "user",
                                                  "content": "again"}],
                                    "max_tokens": 2}) as r:
                            if r.status == 200:
                                break
                    except Exception:
                        pass
                    await asyncio.sleep(1.0)
                else:
                    raise AssertionError("model never recovered after "
                                         "worker restart")
        finally:
            await launcher.stop()
            assert all(not s["alive"]
                       for s in launcher.status().values()), \
                launcher.status()

    asyncio.run(asyncio.wait_for(main(), 420))
