"""Request latency ledger e2e + contracts (ISSUE 18).

The acceptance spine: a 2-worker disaggregated cell (device KV plane)
fronted by KV routing must assemble ONE request's ledger out of every
hop — route / queue / prefill / kv_transfer(plane=device) / first_token
— with the TTFT-path phase durations summing to the measured TTFT
within tolerance, and byte-identical output to an aggregated reference.
Plus the tolerance contract (garbage wire ledgers drop the LEDGER,
never the request) and the overhead contract (steady-decode
EngineStepCounters byte-identical ledger-on vs ledger-off).
"""

import asyncio
import time

from dynamo_tpu.engine.engine import (
    EngineConfig,
    EngineCore,
    InferenceEngine,
    TokenDelta,
)
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.transfer import (
    KV_BLOCKS_ENDPOINT,
    make_kv_blocks_handler,
)
from dynamo_tpu.llm.discovery import (
    delta_from_wire,
    delta_to_wire,
    engine_wire_handler,
)
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.llm.service import LocalEngineClient
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime import ledger as ledger_mod
from dynamo_tpu.runtime import logutil
from dynamo_tpu.runtime.control_plane import InProcessControlPlane
from dynamo_tpu.runtime.ledger import (
    LedgerSink,
    RequestLedger,
    decode_wire,
)
from dynamo_tpu.runtime.metrics import MetricsRegistry
from dynamo_tpu.runtime.rpc import RpcServer

TINY = mcfg.get_config("tiny-test")
BS = 8
NS = "test-ledger"


def _core():
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))


class _Worker:
    async def start(self):
        self.engine = InferenceEngine(_core())
        await self.engine.start()
        self.client = LocalEngineClient(self.engine)
        self.rpc = RpcServer()
        self.rpc.register(KV_BLOCKS_ENDPOINT,
                          make_kv_blocks_handler(self.engine))
        self.address = await self.rpc.start()
        return self

    async def stop(self):
        await self.rpc.stop()
        await self.engine.stop()


def _req(rid, tokens, max_tokens=4):
    return PreprocessedRequest(
        request_id=rid, model="m", token_ids=list(tokens),
        sampling=SamplingParams(max_tokens=max_tokens))


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


# ---------------------------------------------------------------------------
# The acceptance e2e: KV-routed frontend → wire hop → disagg decode
# worker (device KV plane) → prefill worker; one ledger explains TTFT.


def test_ledger_e2e_disagg_device_cell_explains_ttft():
    from dynamo_tpu.llm.block_manager.device_transfer import (
        KV_OFFER_ENDPOINT, KV_PULLED_ENDPOINT, KvTransferPlane)
    from dynamo_tpu.llm.disagg import (
        DisaggDecodeClient, disagg_config_key, prefill_worker_loop)
    from dynamo_tpu.llm.kv_router.client import KvRoutedEngineClient
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        await cp.put(disagg_config_key(NS), {"max_local_prefill_length": 12})

        prefill = await _Worker().start()
        prefill_plane = KvTransferPlane(prefill.engine)
        prefill_plane.start()
        prefill.rpc.register(KV_OFFER_ENDPOINT,
                             prefill_plane.make_offer_handler())
        prefill.rpc.register(KV_PULLED_ENDPOINT,
                             prefill_plane.make_pulled_handler())
        decode = await _Worker().start()
        decode_plane = KvTransferPlane(decode.engine)
        decode_plane.start()
        ploop = asyncio.create_task(prefill_worker_loop(
            cp, NS, prefill.client, prefill.address))

        dec = DisaggDecodeClient(decode.client, decode.engine, cp, NS, BS,
                                 transfer_plane=decode_plane)
        await dec.start()

        # The worker leg of the wire: the disagg client served behind a
        # runtime endpoint, exactly how dynamo_tpu.worker exposes it.
        runtime = DistributedRuntime(cp)
        ep = (runtime.namespace("dyn").component("backend")
              .endpoint("generate"))
        await ep.serve(engine_wire_handler(dec))
        client = await (runtime.namespace("dyn").component("backend")
                        .endpoint("generate").client())
        await client.wait_for_instances()
        kv = KvRoutedEngineClient(client, runtime, block_size=BS)
        await kv.start()

        async def collect(req):
            """(tokens, measured ttft) through the routed front."""
            t0 = time.monotonic()
            ttft = None
            out = []
            async for d in kv.generate(req):
                if d.token_ids and ttft is None:
                    ttft = time.monotonic() - t0
                out.extend(d.token_ids)
                if d.finished:
                    break
            return out, ttft

        try:
            long_prompt = list(range(1, 28))    # 3 sealed blocks + tail

            # Reference output: same prompt, aggregated on a fresh
            # engine.  The ledger must be observation-only: the routed
            # disagg cell's bytes must match exactly.
            ref = await _Worker().start()
            want = []
            async for d in ref.client.generate(_req("ref", long_prompt)):
                want.extend(d.token_ids)
                if d.finished:
                    break
            await ref.stop()

            # Warm every path (jit compiles, remote-prefill machinery)
            # before the measured request.
            warm = _req("warm", list(range(200, 227)))
            ledger_mod.begin(warm)
            await collect(warm)

            req = _req("r1", long_prompt)
            led = ledger_mod.begin(req)
            got, ttft = await collect(req)

            assert got == want                       # byte-identical
            assert dec.device_pulls >= 1             # device plane used
            totals = led.phase_totals()
            for phase in ("route", "queue", "prefill", "first_token",
                          "prefill_remote", "kv_transfer"):
                assert phase in totals, (phase, totals)
            planes = [a.get("plane") for p, _t, _d, a in led.stamps
                      if p == "kv_transfer" and a]
            assert "device" in planes, led.stamps
            # The assembled TTFT-path phases must explain the measured
            # TTFT: no giant dark time, no over-claim (loose bounds —
            # CI wall clocks wobble).
            covered = sum(d for p, _t, d, _a in led.stamps
                          if p in ledger_mod.TTFT_PHASES)
            assert ttft is not None and ttft > 0
            assert 0.5 <= covered / ttft <= 1.15, (covered, ttft, totals)
        finally:
            ploop.cancel()
            await kv.stop()
            await client.stop()
            await dec.stop()
            await runtime.shutdown()
            await prefill.stop()
            await decode.stop()
            await cp.close()

    _run(main())


# ---------------------------------------------------------------------------
# Tolerance contract: garbage wire ledgers drop the LEDGER, never the
# request (rate-limited warn), through the real delta codec.


def test_garbage_wire_ledger_drops_ledger_never_request(caplog):
    logutil.reset()
    garbage = [
        "not-a-dict",
        ["a", "list"],
        {"stamps": "nope"},
        {"stamps": [["prefill", "NaN-ish", "x"]]},
        {"anchor": "z", "stamps": []},
        {"stamps": [[42, 0.0, 0.1]]},          # non-string phase
        {"stamps": [["p", 0.0, 0.1, [1, 2]]]},  # attrs not a dict
    ]
    for bad in garbage:
        assert decode_wire(bad, where="test") is None

    req = _req("tol", [1, 2, 3])
    led = ledger_mod.begin(req)
    led.stamp("receive", dur=0.001)
    for i, bad in enumerate(garbage):
        wire = delta_to_wire(TokenDelta(
            request_id="tol", token_ids=[5 + i], finished=(i == 0),
            ledger=bad))
        delta = delta_from_wire(wire)
        ledger_mod.absorb_delta(req, delta, where="test")
        # The delta (the request path) is untouched; only the ledger
        # payload was dropped, and it never merges garbage stamps.
        assert delta.token_ids == [5 + i]
        assert delta.ledger is None
    assert [p for p, *_ in led.stamps] == ["receive"]

    # Non-scalar attr VALUES inside an otherwise-valid payload are
    # filtered per-key, not fatal.
    ok = decode_wire({"anchor": 1.0, "stamps": [
        ["kv_transfer", 0.5, 0.2, {"plane": "device", "bad": [1, 2]}]]})
    assert ok is not None
    _anchor, stamps, _dropped = ok
    assert stamps[0][3] == {"plane": "device"}


def test_hop_ledger_wire_round_trip_and_gating():
    # begin_hop only fires for requests that opted in via annotation.
    bare = _req("h0", [1])
    assert ledger_mod.begin_hop(bare) is None

    front = _req("h1", [1, 2])
    fled = ledger_mod.begin(front)         # sets the wire annotation
    assert front.annotations[ledger_mod.LEDGER_ANNOTATION]
    fled.stamp("route", dur=0.010)

    # Worker side: fresh hop ledger, own anchor; rides the final delta.
    hop_req = _req("h1", [1, 2])
    hop_req.annotations = dict(front.annotations)
    hop = ledger_mod.begin_hop(hop_req)
    assert hop is not None
    hop.stamp("queue", dur=0.002)
    hop.stamp("prefill", dur=0.030, prompt_tokens=2)
    wire = delta_to_wire(TokenDelta(
        request_id="h1", token_ids=[9], finished=True,
        ledger=hop.to_wire()))
    delta = delta_from_wire(wire)
    ledger_mod.absorb_delta(front, delta, where="test")
    assert delta.ledger is None            # consumed exactly once
    totals = fled.phase_totals()
    assert totals["route"] == 0.010
    assert abs(totals["prefill"] - 0.030) < 1e-6
    assert any(a == {"prompt_tokens": 2}
               for p, _t, _d, a in fled.stamps if p == "prefill")

    # Disabled plane: begin() is a no-op end to end.
    ledger_mod.set_enabled(False)
    try:
        off = _req("h2", [1])
        assert ledger_mod.begin(off) is None
        assert ledger_mod.ledger_of(off) is None
    finally:
        ledger_mod.set_enabled(True)

    # Runaway stamper degrades to a drop counter, never unbounded wire.
    led = RequestLedger("cap")
    for i in range(ledger_mod.MAX_STAMPS + 6):
        led.stamp("p", dur=0.001)
    assert len(led.stamps) == ledger_mod.MAX_STAMPS
    assert led.dropped == 6


# ---------------------------------------------------------------------------
# Frontend fold: goodput attribution + /debug/requests payload.


def test_ledger_sink_goodput_and_dominant_phase():
    sink = LedgerSink(MetricsRegistry(), slo_ttft=0.5, slo_tpot=0.1)

    slow = RequestLedger("slow")
    slow.stamp("queue", dur=0.1)
    slow.stamp("prefill", dur=1.5)
    slow.stamp("decode", dur=30.0, n=100)   # excluded from attribution
    sink.fold(slow, ttft=1.6, tpot=0.02, output_tokens=100)

    fast = RequestLedger("fast")
    fast.stamp("prefill", dur=0.2)
    sink.fold(fast, ttft=0.2, tpot=0.01, output_tokens=50)

    err = RequestLedger("err")
    err.stamp("prefill", dur=0.1)
    sink.fold(err, ttft=0.1, tpot=0.01, output_tokens=10, ok=False)

    assert sink.goodput_total.value() == 160.0
    assert sink.goodput_good.value() == 50.0          # fast only
    assert abs(sink.goodput_ratio() - 50.0 / 160.0) < 1e-9
    # Burn attribution: decode excluded by default, prefill dominates.
    assert sink.dominant_phase() == "prefill"

    payload = sink.debug_payload(n=2)
    assert payload["folded"] == 3
    assert payload["dominant_phase"] == "prefill"
    assert [e["request_id"] for e in payload["slowest"]] == ["slow", "fast"]
    assert payload["slowest"][0]["slo_good"] is False  # blew TTFT SLO
    assert payload["ledger_enabled"] is True


# ---------------------------------------------------------------------------
# Overhead contract: steady-decode EngineStepCounters byte-identical
# ledger-on vs ledger-off (same pinning discipline as tracing/flight
# recorder — zero added host syncs, dispatches or recompiles).


def test_steady_decode_counters_byte_identical_on_vs_off():
    def steady_run(on: bool):
        ledger_mod.set_enabled(on)
        core = EngineCore(EngineConfig(
            model=TINY, num_blocks=64, enable_prefix_cache=False,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16, decode_buckets=(1, 2, 4),
                prefill_buckets=(8, 16))))
        core.add_request("s", list(range(1, 15)),
                         SamplingParams(max_tokens=32))
        for _ in range(4):   # prefill + settle
            core.step()
        base = core.counters.snapshot()
        for _ in range(12):
            core.step()
        return core.counters.delta(base)

    try:
        d_off = steady_run(False)
        d_on = steady_run(True)
    finally:
        ledger_mod.set_enabled(True)
    assert d_on == d_off, (d_on, d_off)
