"""dynamo-lint: rule fixtures, suppression handling, and the tree gate.

Pure-CPU, engine-build-free, no jax import needed — fixture snippets
are written to tmp_path and linted in-process via
`tools.dynamo_lint.run_lint`.  `test_tree_is_clean` IS the CI gate:
the repo has no external CI, so an unsuppressed finding anywhere in
`dynamo_tpu/`, `tools/` or `benchmarks/` fails tier-1.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.dynamo_lint import RULE_TABLE, main, run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def lint_source(tmp_path, source: str, name: str = "snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return run_lint([str(p)])


def codes(findings):
    return [f.code for f in findings]


# -- DL001: host syncs in @hot_path ---------------------------------------


def test_dl001_flags_each_sync_kind(tmp_path):
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime.contracts import hot_path

        @hot_path
        def steady(x, jax, np, fut):
            a = x.item()
            b = jax.device_get(x)
            x.block_until_ready()
            c = np.asarray(x)
            d = fut.result()
            return a, b, c, d
        """)
    assert codes(findings) == ["DL001"] * 5


def test_dl001_ignores_undecorated_and_host_literals(tmp_path):
    findings = lint_source(tmp_path, """\
        import numpy as np
        from dynamo_tpu.runtime.contracts import hot_path

        def cold(x):
            return x.item()            # no @hot_path: fine

        @hot_path
        def steady(rows):
            want = np.asarray([r - 1 for r in rows])   # host literal
            more = np.asarray((1, 2))                  # host literal
            return want, more
        """)
    assert findings == []


def test_dl001_excludes_nested_closures(tmp_path):
    findings = lint_source(tmp_path, """\
        import numpy as np
        from dynamo_tpu.runtime.contracts import hot_path

        @hot_path
        def dispatch(pool, out):
            def land():
                return np.asarray(out)   # runs on the offload thread
            pool.submit(land)
            pool.submit(np.asarray, out)  # np.asarray as ARG, not call
        """)
    assert findings == []


def test_dl001_checks_stacked_contract_decorators(tmp_path):
    """The hottest functions stack @engine_thread_only + @hot_path
    (EngineCore.step, BlockPool.allocate/release) — DL001 must scan
    them regardless of decorator order, and DL005 must still see the
    thread contract."""
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime.contracts import (
            engine_thread_only, hot_path, never_engine_thread)

        class Core:
            @engine_thread_only
            @hot_path
            def step(self, x):
                return x.item()

            @hot_path
            @engine_thread_only
            def seal(self, x):
                return x.item()

        class Sampler:
            @never_engine_thread
            def scrape(self, core):
                core.step(None)          # DL005: engine-only callee
        """)
    assert codes(findings) == ["DL001", "DL001", "DL005"]


def test_dl001_dotted_decorator(tmp_path):
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime import contracts

        class Core:
            @contracts.hot_path
            def step(self, x):
                return x.item()
        """)
    assert codes(findings) == ["DL001"]


# -- DL002: blocking calls in async def -----------------------------------


def test_dl002_flags_blocking_calls(tmp_path):
    findings = lint_source(tmp_path, """\
        import subprocess
        import time
        import urllib.request

        async def handler():
            time.sleep(0.1)
            subprocess.run(["ls"])
            subprocess.Popen(["ls"])
            urllib.request.urlopen("http://x")
        """)
    assert codes(findings) == ["DL002"] * 4


def test_dl002_allows_sync_defs_and_nested(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        def sync_fn():
            time.sleep(0.1)              # sync context: fine

        async def handler():
            def worker():
                time.sleep(0.1)          # runs via to_thread: fine
            await asyncio.to_thread(worker)
        """)
    assert findings == []


# -- DL003: silent exception swallowing -----------------------------------


def test_dl003_flags_silent_pass(tmp_path):
    findings = lint_source(tmp_path, """\
        def f():
            try:
                work()
            except Exception:
                pass
            try:
                work()
            except:
                pass
            try:
                work()
            except (ValueError, Exception):
                pass
        """)
    assert codes(findings) == ["DL003"] * 3


def test_dl003_allows_logged_and_narrow(tmp_path):
    findings = lint_source(tmp_path, """\
        def f(log):
            try:
                work()
            except Exception:
                log.warning("failed")    # logged: fine
            try:
                work()
            except ValueError:
                pass                     # narrow: fine
            try:
                work()
            except Exception:
                raise                    # re-raised: fine
        """)
    assert findings == []


# -- DL004: metrics discipline --------------------------------------------


def test_dl004_metric_naming(tmp_path):
    findings = lint_source(tmp_path, """\
        def build(registry):
            registry.counter("dynamo_requests_total")   # double prefix
            registry.gauge("Upper-Case")                # invalid name
            registry.histogram("request_ttft_seconds")  # fine
            Counter("kv_hits", "h")                     # missing prefix
            Counter("dynamo_kv_hits", "h")              # fine
        """)
    msgs = [(f.code, f.line) for f in findings]
    assert msgs == [("DL004", 2), ("DL004", 3), ("DL004", 5)]


def test_dl004_lock_discipline(tmp_path):
    findings = lint_source(tmp_path, """\
        import threading
        from collections import OrderedDict

        class Registry:
            def __init__(self):
                self._lock = threading.Lock()
                self._values = {}
                self._order = OrderedDict()
                self.public = {}

            def bad_write(self, k, v):
                self._values[k] = v

            def bad_mutate(self, k):
                self._order.pop(k, None)

            def good_write(self, k, v):
                with self._lock:
                    self._values[k] = v

            def read_ok(self, k):
                return self._values.get(k)

            def public_ok(self, k, v):
                self.public[k] = v       # not underscore-private

        class NoLock:
            def __init__(self):
                self._values = {}

            def free_write(self, k, v):
                self._values[k] = v      # class owns no _lock: fine
        """)
    assert [(f.code, f.line) for f in findings] == [
        ("DL004", 12), ("DL004", 15)]


# -- DL005: contract consistency ------------------------------------------


def test_dl005_conflicting_calls(tmp_path):
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime.contracts import (
            engine_thread_only, never_engine_thread)

        class Core:
            @engine_thread_only
            def step(self, sampler):
                sampler.observe_everything()

            @engine_thread_only
            def seal(self):
                self.step(None)          # same contract: fine

        class Sampler:
            @never_engine_thread
            def observe_everything(self):
                pass

            @never_engine_thread
            def scrape(self, core):
                core.step(None)
        """)
    assert codes(findings) == ["DL005", "DL005"]
    assert "observe_everything" in findings[0].message
    assert "step" in findings[1].message


def test_dl005_same_named_classes_do_not_collide(tmp_path):
    """Two `class Manager` definitions in different files with opposite
    contracts on the same method name: each file's `self.m()` resolves
    against ITS OWN class (path-qualified), and cross-object resolution
    falls back to the by-name table, which sees the ambiguity and
    skips — never a misattributed finding."""
    a = tmp_path / "a.py"
    a.write_text(textwrap.dedent("""\
        from dynamo_tpu.runtime.contracts import engine_thread_only

        class Manager:
            @engine_thread_only
            def sync(self):
                pass

            @engine_thread_only
            def drive(self):
                self.sync()              # own class: same contract, fine
        """))
    b = tmp_path / "b.py"
    b.write_text(textwrap.dedent("""\
        from dynamo_tpu.runtime.contracts import never_engine_thread

        class Manager:
            @never_engine_thread
            def sync(self):
                pass

            @never_engine_thread
            def scrape(self):
                self.sync()              # own class: same contract, fine
        """))
    assert run_lint([str(a), str(b)]) == []


def test_dl005_skips_ambiguous_and_generic_names(tmp_path):
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime.contracts import (
            engine_thread_only, never_engine_thread)

        class A:
            @engine_thread_only
            def fetch(self):
                pass

        class B:
            @never_engine_thread
            def fetch(self):             # same name, both contracts
                pass

            @never_engine_thread
            def runner(self, a, task):
                a.fetch()                # ambiguous: skipped
                task.cancel()            # generic stdlib name: skipped
        """)
    assert findings == []


# -- DL006: flight-recorder args in @hot_path ------------------------------


def test_dl006_flags_allocating_record_args(tmp_path):
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime.contracts import hot_path

        class Engine:
            @hot_path
            def step(self, bucket, req, flight_recorder):
                self.flight.record("w", msg=f"bucket {bucket}")
                self.flight.record("w", shape=[bucket, 2])
                self.flight.record("w", info={"b": bucket})
                self.flight.record("w", n=len(req.pages))
                self.flight.record("w", deep=self.a.b.c.d)
                self.flight.record("w", s=bucket + 1)
                # The inline singleton spelling must not evade the rule.
                flight_recorder.get_recorder().record("w", m=f"{bucket}")
        """)
    assert codes(findings) == ["DL006"] * 7


def test_dl006_allows_scalar_args_and_cold_paths(tmp_path):
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime.contracts import hot_path

        class Engine:
            @hot_path
            def step(self, bucket, width, work):
                fl = self.flight
                if fl.enabled:
                    fl.record("window", bucket=bucket, width=width,
                              pages=work.pages, neg=-1, tag="steady",
                              syncs=self.counters.host_syncs)
                fl.record_always("stall", age_s=bucket)

            def cold(self, req):
                # No @hot_path: formatting is allowed off the hot path.
                self.flight.record("admit", msg=f"req {req}",
                                   n=len(req.pages))

            @hot_path
            def other(self, sink, x):
                sink.record(f"not a recorder {x}")   # receiver not matched
        """)
    assert findings == []


def test_dl006_flags_allocating_ledger_stamp_args(tmp_path):
    """The request ledger's `.stamp(...)` (runtime/ledger.py) carries
    the same scalar-cheap hot-path contract as the flight recorder's
    `.record(...)` — allocating/formatting argument expressions inside
    @hot_path bodies trip DL006 on every recognized ledger receiver."""
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime.contracts import hot_path

        class Engine:
            @hot_path
            def step(self, led, hop, bucket, req):
                led.stamp("prefill", msg=f"bucket {bucket}")
                hop.stamp("queue", shape=[bucket, 2])
                self.ledger.stamp("route", n=len(req.pages))
                led.stamp("decode", s=bucket + 1)
        """)
    assert codes(findings) == ["DL006"] * 4
    assert "ledger stamp" in findings[0].message


def test_dl006_allows_scalar_ledger_stamps(tmp_path):
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime.contracts import hot_path

        class Engine:
            @hot_path
            def step(self, led, bucket, dur):
                if led is not None:
                    led.stamp("prefill", dur=dur, bucket=bucket,
                              cached=self.counters.cached, neg=-1,
                              tag="steady")

            def cold(self, led, req):
                # No @hot_path: formatting is allowed off the hot path.
                led.stamp("admit", worker=str(req.worker),
                          n=len(req.pages))

            @hot_path
            def other(self, sink, x):
                sink.stamp(f"not a ledger {x}")   # receiver not matched
        """)
    assert findings == []


def test_dl006_suppressible(tmp_path):
    findings = lint_source(tmp_path, """\
        from dynamo_tpu.runtime.contracts import hot_path

        @hot_path
        def step(flight, xs):
            # dynamo-lint: disable=DL006 one-time warmup event
            flight.record("warmup", shapes=[x for x in xs])
        """)
    assert findings == []


# -- suppression -----------------------------------------------------------


def test_suppression_same_line_and_above(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        async def a():
            time.sleep(1)  # dynamo-lint: disable=DL002 bench setup only

        async def b():
            # dynamo-lint: disable=DL002 deliberate throttle
            time.sleep(1)

        async def c():
            time.sleep(1)            # NOT suppressed
        """)
    assert [(f.code, f.line) for f in findings] == [("DL002", 11)]


def test_suppression_inside_except_body(tmp_path):
    findings = lint_source(tmp_path, """\
        def f():
            try:
                work()
            except Exception:
                # dynamo-lint: disable=DL003 best-effort metrics publish
                pass
        """)
    assert findings == []


def test_suppression_is_per_code(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        async def a():
            time.sleep(1)  # dynamo-lint: disable=DL003 wrong code
        """)
    assert codes(findings) == ["DL002"]


def test_suppression_multiple_codes(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        async def a():
            time.sleep(1)  # dynamo-lint: disable=DL001,DL002 reason here
        """)
    assert findings == []


# -- CLI / output modes ----------------------------------------------------


def test_cli_json_mode_and_exit_codes(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("async def f():\n    import time\n    time.sleep(1)\n")
    rc = main(["--json", str(bad)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["count"] == 1
    assert out["findings"][0]["code"] == "DL002"
    assert out["rules"] == RULE_TABLE

    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert main(["--json", str(good)]) == 0
    assert json.loads(capsys.readouterr().out)["count"] == 0


def test_cli_no_paths_is_usage_error(capsys):
    assert main([]) == 2


def test_unparseable_file_does_not_crash(tmp_path, capsys):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    assert run_lint([str(p)]) == []
    assert "cannot parse" in capsys.readouterr().err


# -- the gate --------------------------------------------------------------


def test_tree_is_clean():
    """Tier-1 IS the CI gate: the serving tree must carry zero
    unsuppressed findings.  On failure the formatted findings are the
    assertion message — fix the code or add a justified suppression."""
    paths = [os.path.join(REPO, d)
             for d in ("dynamo_tpu", "tools", "benchmarks")]
    findings = run_lint(paths)
    assert findings == [], "\n" + "\n".join(f.format() for f in findings)


@pytest.mark.slow
def test_cli_end_to_end_over_tree():
    """`python tools/dynamo_lint.py dynamo_tpu tools benchmarks` exits 0
    (the acceptance-criteria invocation, exercised as a subprocess)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "dynamo_lint.py"),
         "dynamo_tpu", "tools", "benchmarks"],
        capture_output=True, text=True, cwd=REPO, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 findings" in out.stdout
