"""Preprocessor, detokenizer (stop jail, UTF-8), OpenAI protocol codec."""

import asyncio

import pytest

from dynamo_tpu.engine.scheduler import FinishReason
from dynamo_tpu.llm.backend import StreamDetokenizer
from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
from dynamo_tpu.llm.protocols.openai import (
    ChatCompletionChunk,
    ChatCompletionRequest,
    ChatMessage,
    ChatStreamChoice,
    ChatChoiceDelta,
    CompletionRequest,
    sse_decode_line,
    sse_encode,
)
from dynamo_tpu.llm.tokenizer import ByteTokenizer, DecodeStream


TOK = ByteTokenizer()


# -- tokenizer / decode stream ----------------------------------------------


def test_byte_tokenizer_roundtrip():
    s = "héllo ☕ wörld"
    assert TOK.decode(TOK.encode(s)) == s


def test_decode_stream_holds_partial_utf8():
    stream = DecodeStream(TOK)
    data = "é☕".encode("utf-8")  # 2 + 3 bytes
    outs = [stream.push(b) for b in data]
    # No replacement chars ever emitted; text arrives only at char ends.
    assert "".join(outs) == "é☕"
    assert all("�" not in o for o in outs)
    assert outs[0] == ""   # first byte of é is incomplete


def test_decode_stream_flush():
    stream = DecodeStream(TOK)
    out = stream.push("a".encode()[0])
    assert out == "a"
    # Feed first byte of a 2-byte char, then flush: incomplete tail dropped.
    stream.push("é".encode()[0])
    assert stream.flush() == ""


def test_decode_stream_bounded_hold_on_invalid_bytes():
    """Invalid (non-UTF-8) bytes must burst out as U+FFFD after the
    4-byte hold window instead of stalling the stream to an empty flush
    (a pure-gibberish generation used to decode to NO text at all)."""
    stream = DecodeStream(TOK)
    outs = [stream.push(0xFF) for _ in range(6)]
    assert "�" in "".join(outs)
    assert stream.flush() == ""  # trailing incomplete tail still dropped


def test_decode_stream_valid_char_after_garbage_survives():
    """The burst keeps the newest token pending: a legitimate multi-byte
    char that starts right after a garbage run must decode intact."""
    stream = DecodeStream(TOK)
    data = [0xFF, 0xFF, 0xFF] + list("中".encode("utf-8"))
    text = "".join(stream.push(b) for b in data) + stream.flush()
    assert text.endswith("中")
    assert "�" in text  # the garbage run is represented, not dropped


# -- preprocessor ------------------------------------------------------------


def test_preprocess_chat_renders_template_and_defaults():
    pre = OpenAIPreprocessor(TOK, default_max_tokens=99)
    req = ChatCompletionRequest(
        model="m", messages=[ChatMessage(role="user", content="hi")])
    p = pre.preprocess_chat(req, "r1")
    assert "hi" in p.annotations["formatted_prompt"]
    assert "assistant" in p.annotations["formatted_prompt"]
    assert p.sampling.max_tokens == 99
    assert p.sampling.temperature == 1.0  # OpenAI default is stochastic
    assert p.sampling.stop_token_ids == (TOK.eos_id,)


def test_preprocess_completion_tokens_passthrough():
    pre = OpenAIPreprocessor(TOK)
    req = CompletionRequest(model="m", prompt=[1, 2, 3], max_tokens=5)
    p = pre.preprocess_completion(req, "r2")
    assert p.token_ids == [1, 2, 3]
    assert p.sampling.max_tokens == 5


def test_preprocess_stop_strings():
    pre = OpenAIPreprocessor(TOK)
    req = ChatCompletionRequest(
        model="m", messages=[ChatMessage(role="user", content="x")],
        stop=["END", "\n\n"])
    p = pre.preprocess_chat(req, "r3")
    assert p.stop_sequences == ["END", "\n\n"]


def test_request_validation():
    with pytest.raises(Exception):
        ChatCompletionRequest(model="m", messages=[])
    with pytest.raises(Exception):
        ChatCompletionRequest(
            model="m", messages=[ChatMessage(role="user", content="x")],
            temperature=5.0)


# -- stop-sequence jail ------------------------------------------------------


def _push_text(det, text):
    return det.push_tokens(TOK.encode(text))


def test_stop_jail_truncates_at_match():
    det = StreamDetokenizer(TOK, ["END"])
    d1 = _push_text(det, "hello ")
    assert d1.text == "hello "
    d2 = _push_text(det, "world EN")       # 'EN' could grow into 'END'
    assert d2.text == "world "             # EN held in jail
    d3 = _push_text(det, "D more")
    assert d3.finished and d3.finish_reason == "stop"
    assert d3.text == ""                   # END + trailing text swallowed


def test_stop_jail_releases_false_prefix():
    det = StreamDetokenizer(TOK, ["END"])
    d1 = _push_text(det, "an E")
    assert d1.text == "an "
    d2 = _push_text(det, "Nd?")            # 'ENd?' diverges from 'END'
    assert d2.text == "ENd?"
    d3 = det.finish(FinishReason.LENGTH)
    assert d3.finish_reason == "length"


def test_finish_flushes_jail():
    det = StreamDetokenizer(TOK, ["XYZ"])
    _push_text(det, "abcX")
    d = det.finish(FinishReason.STOP)
    assert d.text == "X"                   # jailed prefix released at end
    assert d.finish_reason == "stop"


# -- SSE codec ---------------------------------------------------------------


def test_sse_roundtrip():
    chunk = ChatCompletionChunk(
        id="c1", model="m",
        choices=[ChatStreamChoice(delta=ChatChoiceDelta(content="hi"))])
    wire = sse_encode(chunk)
    assert wire.startswith("data: ") and wire.endswith("\n\n")
    back = sse_decode_line(wire.strip())
    assert back["choices"][0]["delta"]["content"] == "hi"
    assert sse_decode_line("data: [DONE]") is None


# -- engine integration: rejected request must not hang ----------------------


def test_generate_rejected_request_terminates():
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg

    async def main():
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=64,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=4,
                max_prefill_chunk=16,
                decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))
        eng = InferenceEngine(core)
        await eng.start()
        try:
            # prompt+max_tokens > 32-token max context → admission reject.
            deltas = []
            async for d in eng.generate("r1", list(range(30)),
                                        SamplingParams(max_tokens=30)):
                deltas.append(d)
            return deltas
        finally:
            await eng.stop()

    deltas = asyncio.wait_for(main(), timeout=15)
    deltas = asyncio.run(deltas)
    assert deltas[-1].finished
    assert deltas[-1].finish_reason == FinishReason.LENGTH
    assert deltas[-1].token_ids == []


def test_chat_template_receives_tools():
    """Declared tools must reach the rendered prompt — a model that never
    sees the schemas can't call them."""
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import (
        ChatCompletionRequest, ChatMessage)
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    pre = OpenAIPreprocessor(ByteTokenizer())
    req = ChatCompletionRequest(
        model="m",
        messages=[ChatMessage(role="user", content="weather in Oslo?")],
        tools=[{"type": "function",
                "function": {"name": "get_weather",
                             "parameters": {"type": "object"}}}])
    text = pre.render_chat(req)
    assert "get_weather" in text
    # Without tools the system block is absent.
    req2 = ChatCompletionRequest(
        model="m", messages=[ChatMessage(role="user", content="hi")])
    assert "call these tools" not in pre.render_chat(req2)


def test_tool_choice_and_history_rendering():
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.protocols.openai import (
        ChatCompletionRequest, ChatMessage)
    from dynamo_tpu.llm.tokenizer import ByteTokenizer

    pre = OpenAIPreprocessor(ByteTokenizer())
    tools = [{"type": "function", "function": {"name": "get_weather"}},
             {"type": "function", "function": {"name": "get_time"}}]
    # tool_choice="none" hides the schemas for this turn.
    req = ChatCompletionRequest(
        model="m", messages=[ChatMessage(role="user", content="hi")],
        tools=tools, tool_choice="none")
    assert "get_weather" not in pre.render_chat(req)
    # Forcing one tool narrows the schema list.
    req = ChatCompletionRequest(
        model="m", messages=[ChatMessage(role="user", content="hi")],
        tools=tools,
        tool_choice={"type": "function", "function": {"name": "get_time"}})
    text = pre.render_chat(req)
    assert "get_time" in text and "get_weather" not in text
    # Assistant tool-call turns render their calls (multi-turn history).
    req = ChatCompletionRequest(
        model="m",
        messages=[
            ChatMessage(role="user", content="weather?"),
            ChatMessage(role="assistant", content=None, tool_calls=[
                {"id": "call_1", "type": "function",
                 "function": {"name": "get_weather",
                              "arguments": "{\"city\": \"Oslo\"}"}}]),
            ChatMessage(role="tool", content="12C"),
        ], tools=tools)
    text = pre.render_chat(req)
    assert "call_1" in text and "12C" in text


def test_echo_engine_out_matrix():
    """`--out echo` (reference dynamo-run out=echo, engines.rs:71):
    streams the prompt back, capped by max_tokens."""
    import asyncio

    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.llm.echo import EchoEngine
    from dynamo_tpu.llm.preprocessor import PreprocessedRequest

    async def main():
        eng = EchoEngine(delay_ms=0.1)
        req = PreprocessedRequest(
            request_id="e", model="m", token_ids=[7, 8, 9, 10],
            sampling=SamplingParams(max_tokens=3))
        toks, finish = [], None
        async for d in eng.generate(req):
            toks.extend(d.token_ids)
            if d.finished:
                finish = d.finish_reason
        assert toks == [7, 8, 9]
        assert str(finish.value) == "length"

    asyncio.run(main())


def test_frontend_out_matrix_builds_handles():
    """build_model_handle honors --out auto|echo|mocker."""
    import asyncio
    from types import SimpleNamespace

    from dynamo_tpu.frontend.main import build_model_handle

    def args(**kw):
        base = dict(out="auto", mocker=False, tokenizer=None,
                    model="tiny-test", model_name="m", num_blocks=64,
                    block_size=8, max_tokens_default=8, speedup_ratio=10.0)
        base.update(kw)
        return SimpleNamespace(**base)

    async def main():
        for out, want_client in (("echo", "EchoEngine"),
                                 ("mocker", "MockEngine"),
                                 ("auto", "LocalEngineClient")):
            handle, shutdown = await build_model_handle(args(out=out))
            assert type(handle.client).__name__ == want_client, out
            await shutdown()

    asyncio.run(asyncio.wait_for(main(), 120))
