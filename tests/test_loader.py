"""Checkpoint loading: our engine must reproduce a `transformers` forward.

Builds a tiny random Llama in HF format (save_pretrained → safetensors),
loads it through dynamo_tpu.models.loader, and checks greedy logits and
engine generation against the HF reference — the round-trip the reference
gets from `local_model.rs` + the engines it delegates to.
"""

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def hf_checkpoint(tmp_path_factory):
    d = tmp_path_factory.mktemp("tiny_hf_llama")
    cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=8,
        num_key_value_heads=4,
        max_position_embeddings=512,
        rms_norm_eps=1e-5,
        rope_theta=10_000.0,
        tie_word_embeddings=False,
        torch_dtype="float32",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg)
    model.eval()
    model.save_pretrained(d, safe_serialization=True)
    return str(d), model


def test_config_mapping(hf_checkpoint):
    from dynamo_tpu.models.loader import config_from_hf

    d, _ = hf_checkpoint
    with open(f"{d}/config.json") as f:
        cfg = config_from_hf(json.load(f), name="tiny-hf")
    assert cfg.vocab_size == 256
    assert cfg.num_layers == 2
    assert cfg.num_heads == 8 and cfg.num_kv_heads == 4
    assert cfg.head_dim == 8
    assert not cfg.is_moe


def test_greedy_logits_match_transformers(hf_checkpoint):
    import jax.numpy as jnp

    from dynamo_tpu.engine import kv_cache as kvc
    from dynamo_tpu.models.loader import load_params
    from dynamo_tpu.models.llama import make_forward_step

    d, hf_model = hf_checkpoint
    cfg, params = load_params(d, dtype=jnp.float32)

    T = 17
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, cfg.vocab_size, size=(1, T))

    with torch.no_grad():
        hf_logits = hf_model(torch.tensor(tokens)).logits.numpy()

    block_size = 8
    cache = kvc.init_cache(kvc.KvCacheConfig.for_model(
        cfg, num_blocks=16, block_size=block_size, dtype=jnp.float32))
    step = make_forward_step(cfg, block_size)
    bt = jnp.asarray([[1, 2, 3, 0, 0, 0, 0, 0]], jnp.int32)
    ours, _ = step(params, cache,
                   jnp.asarray(tokens, jnp.int32),
                   jnp.arange(T, dtype=jnp.int32)[None, :],
                   jnp.asarray([T], jnp.int32), bt)

    np.testing.assert_allclose(np.asarray(ours)[0], hf_logits[0],
                               rtol=2e-3, atol=2e-3)
    # Greedy argmax agreement at every position (the serving contract).
    assert (np.asarray(ours)[0].argmax(-1) == hf_logits[0].argmax(-1)).all()


def test_engine_generates_checkpoint_determined_text(hf_checkpoint):
    """Engine greedy continuation == transformers.generate greedy."""
    import jax.numpy as jnp

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models.loader import load_params

    d, hf_model = hf_checkpoint
    cfg, params = load_params(d, dtype=jnp.float32)

    prompt = [3, 14, 15, 92, 6, 53]
    n_out = 8
    with torch.no_grad():
        hf_out = hf_model.generate(
            torch.tensor([prompt]), max_new_tokens=n_out, do_sample=False,
            eos_token_id=None, pad_token_id=0)
    want = hf_out[0, len(prompt):].tolist()

    core = EngineCore(
        EngineConfig(model=cfg, num_blocks=64,
                     cache_dtype=jnp.float32,
                     scheduler=SchedulerConfig(
                         max_seqs=4, block_size=8, max_pages_per_seq=8,
                         max_prefill_chunk=16,
                         decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))),
        params=params)
    core.add_request("r", prompt, SamplingParams(max_tokens=n_out))
    got = []
    for _ in range(100):
        for delta in core.step():
            got.extend(delta.token_ids)
        if not core._requests:
            break
    assert got == want


def test_resolve_model_carries_tokenizer_artifact(tmp_path, hf_checkpoint):
    """tokenizer.json contents ride the model card (hf_inline spec)."""
    import shutil

    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.models.loader import resolve_model

    d, _ = hf_checkpoint
    ckpt = tmp_path / "ckpt"
    shutil.copytree(d, ckpt)
    # A minimal real tokenizer.json (byte-level BPE with no merges).
    from tokenizers import Tokenizer, models
    tok = Tokenizer(models.BPE())
    tok.save(str(ckpt / "tokenizer.json"))
    (ckpt / "tokenizer_config.json").write_text(json.dumps({
        "chat_template": "{{ messages }}",
    }))

    cfg, params, spec, template = resolve_model(str(ckpt))
    assert params is not None
    assert spec["kind"] == "hf_inline" and "json" in spec
    assert template == "{{ messages }}"
    card = ModelDeploymentCard(name="m", tokenizer_spec=spec,
                               chat_template=template)
    # Round-trip through the wire format (what discovery does remotely).
    card2 = ModelDeploymentCard.from_dict(
        json.loads(json.dumps(card.to_dict())))
    tk = card2.build_tokenizer()
    assert tk is not None


def test_hub_cache_resolution(tmp_path, monkeypatch):
    """`org/repo` names resolve through the local HF hub cache layout
    (models/hub.py — the hub.rs analog, cache-only in no-egress envs)."""
    import json

    from dynamo_tpu.models.hub import resolve_cached_repo

    cache = tmp_path / "hub"
    snap = cache / "models--acme--tiny" / "snapshots" / "abc123"
    snap.mkdir(parents=True)
    (cache / "models--acme--tiny" / "refs").mkdir()
    (cache / "models--acme--tiny" / "refs" / "main").write_text("abc123")
    (snap / "config.json").write_text(json.dumps({"hidden_size": 64}))

    got = resolve_cached_repo("acme/tiny", cache_dir=str(cache))
    assert got == str(snap)

    import pytest as _pytest

    with _pytest.raises(FileNotFoundError, match="not in the local"):
        resolve_cached_repo("acme/absent", cache_dir=str(cache))

    # resolve_model wires it through (monkeypatched cache root).
    monkeypatch.setenv("HF_HUB_CACHE", str(cache))
    from dynamo_tpu.models.loader import resolve_model

    with _pytest.raises(Exception):
        # Snapshot exists but isn't a complete checkpoint — the point is
        # it resolved INTO the snapshot dir (load_params fails there,
        # not a preset-name KeyError).
        resolve_model("acme/tiny")
