"""Namespace metrics aggregator (reference components/metrics analog)."""

import asyncio

from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.metrics_aggregator import MetricsAggregator, serve
from dynamo_tpu.runtime.control_plane import InProcessControlPlane


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, 60))


def _metrics(active, waiting, blocks, usage):
    return ForwardPassMetrics(
        worker_stats=WorkerStats(request_active_slots=active,
                                 num_requests_waiting=waiting),
        kv_stats=KvStats(kv_active_blocks=blocks,
                         gpu_cache_usage_perc=usage)).to_dict()


def test_aggregates_worker_metrics_and_hit_events():
    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        agg = MetricsAggregator(cp)
        await agg.start()
        try:
            await cp.publish("load_metrics", {
                "worker_id": 1, "metrics": _metrics(3, 1, 100, 0.5)})
            await cp.publish("load_metrics", {
                "worker_id": 2, "metrics": _metrics(5, 0, 200, 0.7)})
            await cp.publish("kv_hit_rate", {
                "worker_id": 1, "isl_blocks": 10, "overlap_blocks": 6})
            await asyncio.sleep(0.05)
            text = agg.expose()
            assert "dynamo_aggregate_workers 2" in text
            assert "dynamo_aggregate_request_active_slots 8" in text
            assert "dynamo_aggregate_requests_waiting 1" in text
            assert "dynamo_aggregate_kv_active_blocks 300" in text
            assert "dynamo_aggregate_kv_hit_isl_blocks_total 10" in text
            assert "dynamo_aggregate_kv_hit_overlap_blocks_total 6" in text
            # Re-publication replaces, not accumulates.
            await cp.publish("load_metrics", {
                "worker_id": 1, "metrics": _metrics(0, 0, 50, 0.1)})
            await asyncio.sleep(0.05)
            assert "dynamo_aggregate_kv_active_blocks 250" in agg.expose()
        finally:
            await agg.stop()
            await cp.close()

    _run(main())


def test_scrape_failure_counted_and_series_marked_stale():
    """A dead advertised endpoint must be VISIBLE: its last-good series
    stay behind a STALE comment (within stale_drop_secs), the failure
    counter increments, and after the drop window the series disappear."""
    async def main():
        from dynamo_tpu.runtime.metrics import MetricsRegistry
        from dynamo_tpu.runtime.status import (
            StatusServer, register_status_endpoint)

        cp = InProcessControlPlane()
        await cp.start()
        agg = MetricsAggregator(cp, stale_drop_secs=3600.0)
        reg = MetricsRegistry()
        reg.gauge("router_requests", "t").set(5.0)
        status = StatusServer(registry=reg)
        port = await status.start()
        addr = f"127.0.0.1:{port}"
        await register_status_endpoint(cp, "router", port)
        try:
            await agg._scrape_once()
            text = agg.expose()
            assert f"# scraped from {addr}\n" in text
            assert "dynamo_router_requests" in text
            assert "STALE" not in text

            await status.stop()            # process "crashes"
            await agg._scrape_once()
            text = agg.expose()
            # Series survive behind the staleness marker ...
            assert f"# scraped from {addr} (STALE: last success" in text
            assert "dynamo_router_requests" in text
            # ... and the failure is counted.
            assert agg._scrape_failures.value({"endpoint": addr}) == 1
            exposed = agg.registry.expose()
            assert "dynamo_aggregate_scrape_failures_total" in exposed

            # Past the drop window the dead target's series disappear.
            agg.stale_drop_secs = 0.0
            await agg._scrape_once()
            assert "dynamo_router_requests" not in agg.expose()
            assert agg._scrape_failures.value({"endpoint": addr}) == 2
        finally:
            await agg.stop()
            await cp.close()

    _run(main())


def test_unregistered_target_drops_immediately_without_stale():
    async def main():
        from dynamo_tpu.runtime.metrics import MetricsRegistry
        from dynamo_tpu.runtime.status import (
            STATUS_ENDPOINTS_PREFIX, StatusServer,
            register_status_endpoint)

        cp = InProcessControlPlane()
        await cp.start()
        agg = MetricsAggregator(cp)
        reg = MetricsRegistry()
        reg.gauge("planner_replicas", "t").set(1.0)
        status = StatusServer(registry=reg)
        port = await status.start()
        key = await register_status_endpoint(cp, "planner", port)
        try:
            await agg._scrape_once()
            assert "dynamo_planner_replicas" in agg.expose()
            await cp.delete(key)           # clean de-registration
            await agg._scrape_once()
            text = agg.expose()
            assert "dynamo_planner_replicas" not in text
            assert "STALE" not in text
        finally:
            await status.stop()
            await agg.stop()
            await cp.close()

    _run(main())


def test_scrape_reaps_dead_pid_registration():
    """ISSUE 14 satellite: an unreachable endpoint whose registration
    pid is provably dead gets its control-plane key DELETED (counted in
    dynamo_aggregate_endpoint_reaps_total) instead of being carried as
    STALE forever; live-pid failures keep the stale-carry behavior."""
    async def main():
        import subprocess
        import sys

        from dynamo_tpu.runtime.status import STATUS_ENDPOINTS_PREFIX

        child = subprocess.Popen([sys.executable, "-c", "pass"])
        child.wait()
        dead_pid = child.pid

        cp = InProcessControlPlane()
        await cp.start()
        agg = MetricsAggregator(cp)
        key = f"{STATUS_ENDPOINTS_PREFIX}/worker-dead/{dead_pid}"
        addr = "127.0.0.1:1"
        await cp.put(key, {"address": addr, "component": "worker-dead",
                           "pid": dead_pid})
        try:
            await agg._scrape_once()
            # Key deleted; reap counted; no scrape-failure/stale carry.
            assert await cp.get_prefix(
                f"{STATUS_ENDPOINTS_PREFIX}/") == {}
            assert agg._endpoint_reaps.value({"endpoint": addr}) == 1
            assert agg._scrape_failures.value({"endpoint": addr}) == 0
            assert "dynamo_aggregate_endpoint_reaps_total" in agg.expose()

            # Next sweep: nothing advertised, nothing re-reaped.
            await agg._scrape_once()
            assert agg._endpoint_reaps.value({"endpoint": addr}) == 1
        finally:
            await agg.stop()
            await cp.close()

    _run(main())


def test_fleet_ledger_merge_sums_phase_and_goodput_series():
    """ISSUE 18 satellite: the aggregator re-exposes the frontends'
    request-ledger series pre-summed — per-phase sum(_sum)/sum(_count)
    plus the goodput counter pair and their ratio."""
    async def main():
        from dynamo_tpu.runtime.ledger import LedgerSink, RequestLedger
        from dynamo_tpu.runtime.metrics import MetricsRegistry
        from dynamo_tpu.runtime.status import (
            StatusServer, register_status_endpoint)

        cp = InProcessControlPlane()
        await cp.start()
        agg = MetricsAggregator(cp)

        # Frontend A: one good request (no SLO thresholds set).
        reg_a = MetricsRegistry()
        sink_a = LedgerSink(reg_a)
        led_a = RequestLedger("req-a")
        led_a.stamp("queue", dur=0.25)
        led_a.stamp("prefill", dur=1.0)
        sink_a.fold(led_a, ttft=1.25, tpot=0.01, output_tokens=10)

        # Frontend B: one request that blows its TTFT SLO.
        reg_b = MetricsRegistry()
        sink_b = LedgerSink(reg_b, slo_ttft=0.5)
        led_b = RequestLedger("req-b")
        led_b.stamp("prefill", dur=2.0)
        sink_b.fold(led_b, ttft=2.0, tpot=0.01, output_tokens=8)

        servers = []
        try:
            for name, reg in (("frontend-a", reg_a), ("frontend-b", reg_b)):
                status = StatusServer(registry=reg)
                port = await status.start()
                servers.append(status)
                await register_status_endpoint(cp, name, port)

            await agg._scrape_once()
            text = agg.expose()

            def val(g, **labels):
                return g.value(labels=labels or None)

            assert val(agg._g_phase_sum, phase="prefill") == 3.0
            assert val(agg._g_phase_count, phase="prefill") == 2.0
            assert val(agg._g_phase_sum, phase="queue") == 0.25
            assert val(agg._g_goodput_good) == 10.0
            assert val(agg._g_goodput_total) == 18.0
            assert abs(val(agg._g_goodput) - 10.0 / 18.0) < 1e-9
            assert "dynamo_aggregate_request_phase_seconds_sum" in text
            assert "dynamo_aggregate_goodput_ratio" in text
        finally:
            for status in servers:
                await status.stop()
            await agg.stop()
            await cp.close()

    _run(main())


def test_http_exposition():
    async def main():
        import aiohttp

        cp = InProcessControlPlane()
        await cp.start()
        agg, runner, port = await serve(cp)
        try:
            await cp.publish("load_metrics", {
                "worker_id": 7, "metrics": _metrics(1, 0, 10, 0.2)})
            await asyncio.sleep(0.05)
            async with aiohttp.ClientSession() as s:
                async with s.get(f"http://127.0.0.1:{port}/metrics") as r:
                    assert r.status == 200
                    body = await r.text()
            assert "dynamo_aggregate_workers 1" in body
        finally:
            await agg.stop()
            await runner.cleanup()
            await cp.close()

    _run(main())
