"""Mock engine: prefix caching, eviction, events, streaming (reference
analog: mocker tests + `tests/router/test_router_e2e_with_mockers.py`
workload generation substrate)."""

import asyncio

import pytest

from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.llm.mocker import MockEngine, MockEngineArgs
from dynamo_tpu.llm.mocker.kv_manager import MockKvManager
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.tokens import compute_block_hashes


def _req(rid, tokens, max_tokens=4):
    return PreprocessedRequest(
        request_id=rid, model="mock", token_ids=list(tokens),
        sampling=SamplingParams(max_tokens=max_tokens))


FAST = MockEngineArgs(num_blocks=64, block_size=8, speedup_ratio=100.0)


async def _collect(engine, req):
    toks = []
    async for d in engine.generate(req):
        toks.extend(d.token_ids)
        if d.finished:
            return toks, d.finish_reason


# -- kv manager unit ---------------------------------------------------------


def test_kv_manager_prefix_reuse_and_lru_eviction():
    events = []
    kv = MockKvManager(4, 8, event_sink=events.append)
    h = [101, 102, 103]
    parents = [None, 101, 102]
    assert kv.acquire(h, parents) == 0            # all new
    kv.release(h)                                  # → inactive, resident
    assert kv.match_prefix(h) == 3
    assert kv.acquire(h, parents) == 3             # full reuse
    kv.release(h)

    # Now force eviction: 4-capacity, 3 resident inactive, acquire 2 new.
    assert kv.acquire([201, 202], [None, 201]) == 0
    assert kv.evicted_blocks >= 1
    removed = [e for e in events if e.data.remove is not None]
    assert removed, "eviction must emit REMOVED events"
    # Tail-first eviction: release() enqueues a sequence's blocks deepest-
    # first, so the leaf (103) is evicted before its ancestors — a parent
    # block is a useful cache prefix without its children, not vice versa.
    assert list(removed[0].data.remove.block_hashes) == [103]


def test_kv_manager_capacity_exhausted():
    kv = MockKvManager(2, 8)
    kv.acquire([1, 2], [None, 1])
    with pytest.raises(RuntimeError, match="capacity"):
        kv.acquire([3], [None])


# -- engine ------------------------------------------------------------------


def test_mock_engine_generates_deterministic_stream():
    async def main():
        eng = MockEngine(FAST)
        try:
            t1, r1 = await _collect(eng, _req("a", range(20), max_tokens=5))
            t2, r2 = await _collect(eng, _req("a", range(20), max_tokens=5))
            return t1, r1, t2
        finally:
            await eng.stop()

    t1, r1, t2 = asyncio.run(main())
    assert len(t1) == 5
    assert t1 == t2                       # same request id → same stream
    from dynamo_tpu.engine.scheduler import FinishReason
    assert r1 is FinishReason.LENGTH


def test_mock_engine_emits_chained_kv_events():
    async def main():
        events = []
        eng = MockEngine(FAST, kv_event_sink=events.append)
        try:
            prompt = list(range(30))       # 3 full blocks of 8 + tail
            await _collect(eng, _req("a", prompt, max_tokens=2))
            return events, prompt
        finally:
            await eng.stop()

    events, prompt = asyncio.run(main())
    stored = [h for e in events if e.data.store
              for h in e.data.store.block_hashes]
    expected = compute_block_hashes(prompt, block_size=8)[:3]
    assert stored[:3] == list(expected)


def test_mock_engine_prefix_cache_hit_across_requests():
    async def main():
        eng = MockEngine(FAST)
        try:
            shared = list(range(24))       # 3 blocks
            await _collect(eng, _req("a", shared + [100, 101], max_tokens=2))
            await _collect(eng, _req("b", shared + [200, 201], max_tokens=2))
            return eng.kv.hit_blocks, eng.kv.miss_blocks
        finally:
            await eng.stop()

    hits, misses = asyncio.run(main())
    assert hits >= 3                      # b reused a's 3 shared blocks


def test_mock_engine_concurrent_load_and_metrics():
    async def main():
        eng = MockEngine(FAST)
        try:
            reqs = [_collect(eng, _req(f"r{i}", range(i, i + 40), max_tokens=8))
                    for i in range(16)]
            outs = await asyncio.gather(*reqs)
            return outs, eng.metrics
        finally:
            await eng.stop()

    outs, metrics = asyncio.run(main())
    assert all(len(t) == 8 for t, _ in outs)
    assert metrics.kv_stats.kv_total_blocks == FAST.num_blocks
