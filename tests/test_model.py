"""Correctness of the unified prefill/decode step over the paged cache.

Ground truth: full-context causal attention.  The paged path (prefill in one
chunk, chunked prefill, token-by-token decode) must reproduce the same
logits — this is the TPU analog of vLLM's prefix-cache correctness tests the
reference relies on transitively.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.engine.sampling import sample
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_forward_step


def _setup(cfg, num_blocks=32, block_size=8):
    cache_cfg = kvc.KvCacheConfig.for_model(cfg, num_blocks=num_blocks,
                                            block_size=block_size,
                                            dtype=jnp.float32)
    cache = kvc.init_cache(cache_cfg)
    params = init_params(cfg, jax.random.key(0))
    step = make_forward_step(cfg, block_size)
    return params, cache, step, cache_cfg


def _block_table(start_block, num_pages, width):
    bt = np.zeros((width,), np.int32)
    bt[:num_pages] = np.arange(start_block, start_block + num_pages)
    return bt


@pytest.mark.parametrize("cfg_name", ["tiny-test", "tiny-moe"])
def test_decode_matches_prefill(cfg_name):
    cfg = mcfg.get_config(cfg_name)
    block_size = 8
    T = 21  # not a multiple of block_size on purpose
    params, cache, step, _ = _setup(cfg, block_size=block_size)

    tokens = jax.random.randint(jax.random.key(1), (1, T), 0, cfg.vocab_size)
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    bt = jnp.asarray(_block_table(1, 4, 8))[None, :]

    # Ground truth: whole sequence in one prefill chunk.
    full_logits, _ = step(params, cache, tokens, positions,
                          jnp.array([T], jnp.int32), bt)

    # Paged path: prefill first 10, then decode token-by-token.
    cache2 = kvc.init_cache(kvc.KvCacheConfig.for_model(
        cfg, num_blocks=32, block_size=block_size, dtype=jnp.float32))
    split = 10
    logits_a, cache2 = step(params, cache2, tokens[:, :split],
                            positions[:, :split],
                            jnp.array([split], jnp.int32), bt)
    outs = [logits_a]
    for t in range(split, T):
        logits_t, cache2 = step(params, cache2, tokens[:, t:t + 1],
                                positions[:, t:t + 1],
                                jnp.array([t + 1], jnp.int32), bt)
        outs.append(logits_t)
    paged_logits = jnp.concatenate(outs, axis=1)

    np.testing.assert_allclose(np.asarray(full_logits),
                               np.asarray(paged_logits),
                               rtol=2e-4, atol=2e-4)


def test_chunked_prefill_matches_full():
    cfg = mcfg.get_config("tiny-test")
    block_size = 8
    T = 24
    params, cache, step, _ = _setup(cfg, block_size=block_size)

    tokens = jax.random.randint(jax.random.key(2), (1, T), 0, cfg.vocab_size)
    positions = jnp.arange(T, dtype=jnp.int32)[None, :]
    bt = jnp.asarray(_block_table(1, 3, 8))[None, :]

    full_logits, _ = step(params, cache, tokens, positions,
                          jnp.array([T], jnp.int32), bt)

    cache2 = kvc.init_cache(kvc.KvCacheConfig.for_model(
        cfg, num_blocks=32, block_size=block_size, dtype=jnp.float32))
    chunks = [(0, 8), (8, 16), (16, 24)]
    outs = []
    for lo, hi in chunks:
        logits_c, cache2 = step(params, cache2, tokens[:, lo:hi],
                                positions[:, lo:hi],
                                jnp.array([hi], jnp.int32), bt)
        outs.append(logits_c)
    chunked = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(full_logits), np.asarray(chunked),
                               rtol=2e-4, atol=2e-4)


def test_batch_isolation_and_padding():
    """Two sequences with different lengths + one padding row must not
    contaminate each other; padding rows write only to the null block."""
    cfg = mcfg.get_config("tiny-test")
    block_size = 8
    params, cache, step, _ = _setup(cfg, block_size=block_size)

    t_a = jax.random.randint(jax.random.key(3), (1, 12), 0, cfg.vocab_size)
    t_b = jax.random.randint(jax.random.key(4), (1, 12), 0, cfg.vocab_size)

    bt_a = jnp.asarray(_block_table(1, 2, 8))[None, :]
    solo_logits, _ = step(params, kvc.init_cache(kvc.KvCacheConfig.for_model(
        cfg, num_blocks=32, block_size=block_size, dtype=jnp.float32)),
        t_a, jnp.arange(12, dtype=jnp.int32)[None, :],
        jnp.array([12], jnp.int32), bt_a)

    # Batch: seq A (blocks 1-2), seq B (blocks 3-4), padding row (null).
    tokens = jnp.concatenate([t_a, t_b, jnp.zeros((1, 12), jnp.int32)], axis=0)
    positions = jnp.broadcast_to(jnp.arange(12, dtype=jnp.int32), (3, 12))
    bts = jnp.stack([
        jnp.asarray(_block_table(1, 2, 8)),
        jnp.asarray(_block_table(3, 2, 8)),
        jnp.zeros((8,), jnp.int32),
    ])
    seq_lens = jnp.array([12, 12, 0], jnp.int32)
    batch_logits, _ = step(params, cache, tokens, positions, seq_lens, bts)

    np.testing.assert_allclose(np.asarray(solo_logits[0]),
                               np.asarray(batch_logits[0]),
                               rtol=2e-4, atol=2e-4)


def test_sampling_greedy_and_temperature():
    logits = jnp.asarray(np.array([[0.0, 5.0, 1.0], [9.0, 0.0, 0.0]], np.float32))
    out = sample(logits,
                 temperature=jnp.array([0.0, 0.0]),
                 top_k=jnp.array([0, 0], jnp.int32),
                 top_p=jnp.array([1.0, 1.0]),
                 key=jax.random.key(0))
    assert out.tolist() == [1, 0]

    # top_k=1 with temperature>0 degenerates to greedy.
    out = sample(logits,
                 temperature=jnp.array([1.0, 1.0]),
                 top_k=jnp.array([1, 1], jnp.int32),
                 top_p=jnp.array([1.0, 1.0]),
                 key=jax.random.key(1))
    assert out.tolist() == [1, 0]


def test_sampling_top_p_excludes_tail():
    # One dominant token (p≈0.95); top_p=0.5 must always pick it.
    logits = jnp.asarray(np.array([[8.0, 1.0, 1.0, 1.0]], np.float32))
    for seed in range(5):
        out = sample(logits,
                     temperature=jnp.array([1.0]),
                     top_k=jnp.array([0], jnp.int32),
                     top_p=jnp.array([0.5]),
                     key=jax.random.key(seed))
        assert out.tolist() == [0]
