"""MoE all-to-all dispatch vs the dense oracle (VERDICT r2 item 7).

Dense compute is exact by construction; dispatch with exact capacity must
reproduce it — standalone, and sharded over the 8-device CPU mesh's
dp×ep axes through the full forward step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_forward_step
from dynamo_tpu.ops import moe as moe_ops
from dynamo_tpu.parallel import (
    MeshConfig,
    cache_pspecs,
    make_mesh,
    make_sharded_step,
    param_pspecs,
    shard_pytree,
)

CFG = mcfg.get_config("tiny-moe")
BLOCK = 8


def _moe_params(key=0):
    p = init_params(CFG, jax.random.key(key), dtype=jnp.float32)
    return p["layers"][0]["moe"]


def test_dispatch_matches_dense_standalone():
    p = _moe_params()
    x = jax.random.normal(jax.random.key(1), (4, 16, CFG.hidden_size),
                          jnp.float32)
    want, load_d = moe_ops.moe_dense(CFG, p, x)
    got, load = moe_ops.moe_dispatch(CFG, p, x)  # exact capacity default
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-5, atol=2e-5)
    # Same routing → same per-expert counts; totals = N*k.
    np.testing.assert_array_equal(np.asarray(load), np.asarray(load_d))
    assert int(load.sum()) == 4 * 16 * CFG.num_experts_per_token


def test_dispatch_capacity_drops_overflow():
    """Tiny capacity must drop assignments (gate mass lost), not crash or
    corrupt other tokens."""
    p = _moe_params()
    x = jax.random.normal(jax.random.key(2), (2, 8, CFG.hidden_size),
                          jnp.float32)
    got, _ = moe_ops.moe_dispatch(CFG, p, x, capacity=1)
    assert np.isfinite(np.asarray(got)).all()


def test_sharded_dispatch_step_matches_dense_reference():
    """Full forward step, dp=2 x ep=4 (tp=1): dispatch path output equals
    the single-device dense step."""
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    batch, T = 8, 16  # batch divisible by dp*ep
    tokens = jax.random.randint(jax.random.key(5), (batch, T), 0,
                                CFG.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (batch, T))
    bt = np.zeros((batch, 8), np.int32)
    for i in range(batch):
        bt[i, :4] = np.arange(1 + 4 * i, 5 + 4 * i)
    seq_lens = jnp.full((batch,), T, jnp.int32)
    inputs = (tokens, positions, seq_lens, jnp.asarray(bt))
    sample_pos = jnp.full((batch,), T - 1, jnp.int32)

    ref_step = make_forward_step(CFG, BLOCK)
    ref_cache = kvc.init_cache(kvc.KvCacheConfig.for_model(
        CFG, num_blocks=64, block_size=BLOCK, dtype=jnp.float32))
    want, _ = ref_step(params, ref_cache, *inputs, sample_pos)

    mesh = make_mesh(MeshConfig(dp=2, ep=4), jax.devices())
    sharded = shard_pytree(params, param_pspecs(CFG, "dispatch"), mesh)
    cache = shard_pytree(
        kvc.init_cache(kvc.KvCacheConfig.for_model(
            CFG, num_blocks=64, block_size=BLOCK, dtype=jnp.float32)),
        cache_pspecs(CFG.num_layers), mesh)
    step = make_sharded_step(CFG, BLOCK, mesh, moe_mode="dispatch",
                             with_expert_load=True)
    got, _, load = step(sharded, cache, *inputs, sample_pos)

    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=5e-4, atol=5e-4)
    assert int(np.asarray(load).sum()) == (
        batch * T * CFG.num_experts_per_token * CFG.num_layers)


def test_dispatch_ep_tp_mesh_matches_dense_reference():
    """dp=2 x ep=2 x tp=2: dispatch with tp-sharded expert MLPs (F/tp
    slices, one psum on exit) matches the meshless dense oracle, with
    every assignment counted and nothing dropped at exact capacity."""
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    batch, T = 8, 16  # batch divisible by dp*ep
    tokens = jax.random.randint(jax.random.key(5), (batch, T), 0,
                                CFG.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (batch, T))
    bt = np.zeros((batch, 8), np.int32)
    for i in range(batch):
        bt[i, :4] = np.arange(1 + 4 * i, 5 + 4 * i)
    inputs = (tokens, positions, jnp.full((batch,), T, jnp.int32),
              jnp.asarray(bt))
    sample_pos = jnp.full((batch,), T - 1, jnp.int32)

    ref_step = make_forward_step(CFG, BLOCK)
    ref_cache = kvc.init_cache(kvc.KvCacheConfig.for_model(
        CFG, num_blocks=64, block_size=BLOCK, dtype=jnp.float32))
    want, _ = ref_step(params, ref_cache, *inputs, sample_pos)

    mesh = make_mesh(MeshConfig(dp=2, ep=2, tp=2), jax.devices())
    sharded = shard_pytree(params, param_pspecs(CFG, "dispatch"), mesh)
    cache = shard_pytree(
        kvc.init_cache(kvc.KvCacheConfig.for_model(
            CFG, num_blocks=64, block_size=BLOCK, dtype=jnp.float32)),
        cache_pspecs(CFG.num_layers), mesh)
    step = make_sharded_step(CFG, BLOCK, mesh, moe_mode="dispatch",
                             with_expert_load=True)
    got, _, load = step(sharded, cache, *inputs, sample_pos)

    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=5e-4, atol=5e-4)
    load = np.asarray(load)
    assert load.shape == (CFG.num_experts + 1,)
    assert int(load[:-1].sum()) == (
        batch * T * CFG.num_experts_per_token * CFG.num_layers)
    assert load[:-1].sum() > 0
    assert int(load[-1]) == 0  # exact capacity: nothing dropped


def test_grouped_matches_dense_bitwise():
    """The grouped-GEMM path is BYTE-identical to the dense oracle in
    f32 and bf16 (interpret mode on CPU): same routing, same expert
    math, and crucially the same expert-index-ordered combine."""
    p = _moe_params()
    for dt in (jnp.float32, jnp.bfloat16):
        pd = jax.tree.map(lambda a: a.astype(dt), p)
        x = jax.random.normal(jax.random.key(3), (2, 16, CFG.hidden_size),
                              jnp.float32).astype(dt)
        want, load_d = moe_ops.moe_dense(CFG, pd, x)
        got, load_g = moe_ops.moe_grouped(CFG, pd, x, interpret=True)
        assert (np.asarray(want) == np.asarray(got)).all(), (
            f"grouped diverged from dense oracle in {dt}")
        np.testing.assert_array_equal(np.asarray(load_g), np.asarray(load_d))
        assert int(load_g[-1]) == 0  # grouped is exact, never drops


def test_grouped_int8_matches_dense_on_dequantized_weights():
    """int8-weight grouped (dequant-in-VMEM) == dense oracle run on the
    host-dequantized weights, byte for byte — the same static-structure
    discipline as kv_quant: quantization changes the weights once, not
    the compute path's numerics."""
    from dynamo_tpu.ops.pallas import (
        dequantize_moe_params,
        moe_params_quantized,
        quantize_moe_params,
    )

    p = jax.tree.map(lambda a: a.astype(jnp.bfloat16), _moe_params())
    q = quantize_moe_params(p)
    assert moe_params_quantized(q) and not moe_params_quantized(p)
    x = jax.random.normal(jax.random.key(4), (2, 16, CFG.hidden_size),
                          jnp.float32).astype(jnp.bfloat16)
    want, load_d = moe_ops.moe_dense(
        CFG, dequantize_moe_params(q, jnp.bfloat16), x)
    got, load_g = moe_ops.moe_grouped(CFG, q, x, interpret=True)
    assert (np.asarray(want) == np.asarray(got)).all()
    np.testing.assert_array_equal(np.asarray(load_g), np.asarray(load_d))


def test_dispatch_stats_tail_counts_drops():
    """[E+1] stats contract: slots [:E] are the PRE-drop routing counts,
    the tail is the dropped-assignment count — zero at the exact default,
    honest (nonzero) under a bounding capacity."""
    p = _moe_params()
    x = jax.random.normal(jax.random.key(2), (2, 8, CFG.hidden_size),
                          jnp.float32)
    N = 2 * 8
    k = CFG.num_experts_per_token
    _, exact = moe_ops.moe_dispatch(CFG, p, x)
    assert exact.shape == (CFG.num_experts + 1,)
    assert int(exact[-1]) == 0
    assert int(exact[:-1].sum()) == N * k
    _, bounded = moe_ops.moe_dispatch(CFG, p, x, capacity=1)
    assert int(bounded[-1]) > 0
    # Routing is capacity-independent: same pre-drop counts either way.
    np.testing.assert_array_equal(np.asarray(bounded[:-1]),
                                  np.asarray(exact[:-1]))


def test_resolve_moe_mode_ladder():
    """The mode ladder's resolution rules and pointed errors."""
    from dynamo_tpu.parallel.sharding import resolve_moe_mode

    # Meshless auto on CPU → dense (grouped needs TPU + geometry).
    assert resolve_moe_mode(CFG, None) == "dense"
    assert resolve_moe_mode(CFG, None, "grouped") == "grouped"
    with pytest.raises(ValueError, match="needs a mesh with an ep axis"):
        resolve_moe_mode(CFG, None, "dispatch")
    with pytest.raises(ValueError, match="not in"):
        resolve_moe_mode(CFG, None, "bogus")
    mesh = make_mesh(MeshConfig(dp=4, ep=2), jax.devices())
    with pytest.raises(ValueError, match="meshless fast path"):
        resolve_moe_mode(CFG, mesh, "grouped")
    assert resolve_moe_mode(CFG, mesh) == "dispatch"
    mesh_d = make_mesh(MeshConfig(dp=8), jax.devices())
    assert resolve_moe_mode(CFG, mesh_d) == "dense"
    # Dense models short-circuit whatever the mesh looks like.
    assert resolve_moe_mode(mcfg.get_config("tiny-test"), mesh) == "dense"


def test_moe_decode_windows_match_single_step():
    """MoE decode windows (r5): the fused window threads the expert-load
    aux through its loop carry, so MoE serving gets the fast decode path
    — greedy output must match the single-step engine, and the telemetry
    must account for every windowed token."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg

    cfg = mcfg.get_config("tiny-moe")

    def run(window):
        core = EngineCore(EngineConfig(
            model=cfg, num_blocks=64, decode_window=window,
            enable_prefix_cache=False,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16,
                decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))
        core.add_request("a", [5, 6, 7, 8, 9, 10],
                         SamplingParams(max_tokens=10))
        core.add_request("b", list(range(20, 29)),
                         SamplingParams(max_tokens=10))
        out = {}
        for _ in range(300):
            for d in core.step():
                out.setdefault(d.request_id, []).extend(d.token_ids)
            if not core._requests:
                break
        assert not core._requests
        return out, core.snapshot_expert_load()

    single, load1 = run(window=1)
    windowed, loadw = run(window=4)
    assert windowed == single, "MoE window diverged from single-step"
    # Load telemetry accounts every processed token x top-k x layers.
    # (Window overshoot may process a few discarded tokens; the count
    # must be at least the single-step total and divisible by k*L.)
    kL = cfg.num_experts_per_token * cfg.num_layers
    assert int(load1.sum()) % kL == 0
    assert int(loadw.sum()) % kL == 0
    assert int(loadw.sum()) >= int(load1.sum()) > 0


def test_moe_sharded_window_over_ep_mesh():
    """The sharded MoE window compiles and serves over a dp x ep mesh
    with load telemetry flowing."""
    import jax

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = mcfg.get_config("tiny-moe")
    mesh = make_mesh(MeshConfig(dp=2, ep=2, tp=2), jax.devices())
    core = EngineCore(EngineConfig(
        model=cfg, num_blocks=64, mesh=mesh, decode_window=4,
        enable_prefix_cache=False,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=8, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(2, 4), prefill_buckets=(8, 16))))
    core.add_request("a", [5, 6, 7, 8, 9, 10],
                     SamplingParams(max_tokens=8))
    core.add_request("b", list(range(20, 29)),
                     SamplingParams(max_tokens=8))
    out = {}
    for _ in range(300):
        for d in core.step():
            out.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    assert not core._requests
    assert len(out["a"]) == 8 and len(out["b"]) == 8
    load = core.snapshot_expert_load()
    assert load is not None and int(load.sum()) > 0


def test_moe_dispatch_window_over_ep_mesh():
    """The DISPATCH-mode (shard_map all-to-all) window path: ep>1, tp=1
    resolves moe_mode='dispatch', and the window must still serve with
    correct telemetry."""
    import jax

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.parallel import MeshConfig, make_mesh
    from dynamo_tpu.parallel.sharding import resolve_moe_mode

    cfg = mcfg.get_config("tiny-moe")
    mesh = make_mesh(MeshConfig(dp=4, ep=2), jax.devices())
    assert resolve_moe_mode(cfg, mesh) == "dispatch"
    core = EngineCore(EngineConfig(
        model=cfg, num_blocks=128, mesh=mesh, decode_window=4,
        enable_prefix_cache=False,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(4, 8), prefill_buckets=(8, 16))))
    for i in range(4):
        core.add_request(f"r{i}", list(range(5 + i, 12 + i)),
                         SamplingParams(max_tokens=8))
    out = {}
    for _ in range(300):
        for d in core.step():
            out.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    assert not core._requests
    assert all(len(v) == 8 for v in out.values())
    load = core.snapshot_expert_load()
    kL = cfg.num_experts_per_token * cfg.num_layers
    assert int(load.sum()) > 0 and int(load.sum()) % kL == 0


def _serve_moe_engine(**over):
    """One meshless tiny-moe engine run with the file's shared geometry
    (compile-cache reuse): two short greedy requests, returns (tokens,
    engine)."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig

    cfg = dict(model=CFG, num_blocks=64, enable_prefix_cache=False,
               scheduler=SchedulerConfig(
                   max_seqs=4, block_size=8, max_pages_per_seq=8,
                   max_prefill_chunk=16,
                   decode_buckets=(1, 2, 4), prefill_buckets=(8, 16)))
    cfg.update(over)
    core = EngineCore(EngineConfig(**cfg))
    core.add_request("a", [5, 6, 7, 8, 9, 10], SamplingParams(max_tokens=8))
    core.add_request("b", list(range(20, 29)), SamplingParams(max_tokens=8))
    out = {}
    for _ in range(300):
        for d in core.step():
            out.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    assert not core._requests
    return out, core


def test_engine_grouped_mode_matches_dense():
    """A meshless engine serving with moe_mode='grouped' (interpret mode
    on CPU) emits the SAME greedy tokens as the dense oracle engine —
    the ops-level byte-identity surviving the full serving stack — and
    the expert-load telemetry flows either way."""
    dense_out, dense_core = _serve_moe_engine(moe_mode="dense")
    grp_out, grp_core = _serve_moe_engine(moe_mode="grouped")
    assert grp_out == dense_out, "grouped engine diverged from dense"
    for core in (dense_core, grp_core):
        load = core.snapshot_expert_load()
        assert load is not None and int(load.sum()) > 0
        assert core.moe_dropped_tokens == 0


def test_packed_prefill_serves_moe():
    """packed_prefill=True on a MoE model (the exclusion this PR kills):
    token parity with the padded plane, the packed plane actually used,
    and prefill assignments landing in the expert-load telemetry."""
    padded_out, _ = _serve_moe_engine(packed_prefill=False)
    packed_out, core = _serve_moe_engine(packed_prefill=True)
    assert packed_out == padded_out, "packed MoE prefill diverged"
    assert core.counters.packed_prefill_dispatches > 0
    load = core.snapshot_expert_load()
    assert load is not None and int(load.sum()) > 0
    assert core.moe_dropped_tokens == 0


def test_short_burst_publishes_expert_load_in_metrics():
    """Drain-edge telemetry publish: a burst that finishes in < 32 steps
    must still land its expert load in ForwardPassMetrics (what the
    worker's /metrics route reads).  The periodic step_count % 32 sync
    alone left short-lived traffic dark — the live worker served a chat
    completion and exported no dynamo_moe_expert_load series."""
    _, core = _serve_moe_engine(moe_mode="grouped")
    assert core.step_count < 32  # the repro precondition: no periodic sync
    m = core.metrics
    assert m.expert_load is not None and sum(m.expert_load) > 0
    assert m.moe_dropped_tokens == 0
