"""Multi-host (multi-process) engine execution — VERDICT r4 missing #1.

Two subprocesses (leader + follower), each with 4 virtual CPU devices and
gloo collectives, run ONE EngineCore over the 8-device global mesh in
SPMD lockstep; tokens must match the same engine run single-process on
the test's own 8-device mesh (identical mesh shape + shardings → same
computation graph, greedy decode → identical tokens).

Reference analog: multinode TP via srun/MPI inside TRT-LLM
(`components/backends/trtllm/multinode/srun_disaggregated.sh`), LWS
multinode in the operator (`internal/dynamo/graph.go:145`).
"""

import json
import os
import socket
import subprocess
import sys

import jax
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RUNNER = os.path.join(REPO, "tests", "mh_runner.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _clean_env() -> dict:
    env = dict(os.environ)
    # The runner sets its own platform/device-count flags (setup_cpu_rig);
    # drop the test process's 8-device forcing so each subprocess gets 4.
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_pair(mode: str, timeout: float = 300.0):
    coord, lock = _free_port(), _free_port()
    env = _clean_env()
    follower = subprocess.Popen(
        [sys.executable, RUNNER, "follower", str(coord), str(lock), mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    leader = subprocess.Popen(
        [sys.executable, RUNNER, "leader", str(coord), str(lock), mode],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        lo, _ = leader.communicate(timeout=timeout)
        fo, _ = follower.communicate(timeout=timeout)
    finally:
        for p in (leader, follower):
            if p.poll() is None:
                p.kill()
    assert leader.returncode == 0, f"leader failed:\n{lo}\n--follower--\n{fo}"
    assert follower.returncode == 0, f"follower failed:\n{fo}"
    tokens = None
    for line in lo.splitlines():
        if line.startswith("LEADER_TOKENS "):
            tokens = json.loads(line[len("LEADER_TOKENS "):])
    assert tokens is not None, f"no leader tokens in:\n{lo}"
    follower_rids = None
    for line in fo.splitlines():
        if line.startswith("FOLLOWER_DONE "):
            follower_rids = json.loads(line[len("FOLLOWER_DONE "):])
    assert follower_rids == [], \
        f"follower retained requests {follower_rids} (state diverged)"
    return tokens


def _single_process_reference(mode: str):
    """The same workload on the test process's own 8-device mesh."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.parallel import MeshConfig, make_mesh

    cfg = mcfg.get_config("tiny-test")
    mesh = make_mesh(MeshConfig(dp=2, tp=4), jax.devices())
    core = EngineCore(EngineConfig(
        model=cfg, num_blocks=64, mesh=mesh,
        dp_attention=(mode == "dp_attention"),
        enable_prefix_cache=(mode == "prefix"),
        kv_quant="int8" if mode == "fused_int8" else "none",
        decode_window=1 if mode == "fused_int8" else 4,
        scheduler=SchedulerConfig(block_size=16)))
    prompts = {
        "req-a": [1, 2, 3, 4, 5, 6, 7, 8],
        "req-b": [9, 8, 7, 6, 5],
        "req-c": [42, 43],
    }
    # fused_int8 keeps every request greedy so the single-step path
    # actually dispatches the fused program (a stochastic row would
    # route the whole batch through the plain step).
    sampled = ({} if mode == "fused_int8"
               else {"req-c": SamplingParams(temperature=0.8, top_k=20,
                                             seed=1234, max_tokens=12)})
    for rid, toks in prompts.items():
        core.add_request(rid, toks,
                         sampled.get(rid, SamplingParams(max_tokens=12)))
    out: dict = {rid: [] for rid in prompts}
    steps = 0
    while core.has_work and steps < 200:
        for d in core.step():
            out[d.request_id].extend(d.token_ids)
        steps += 1
    return out


@pytest.mark.parametrize("mode", ["plain", "prefix"])
def test_multihost_decode_matches_single_process(mode):
    got = _run_pair(mode)
    want = _single_process_reference(mode)
    for rid in want:
        assert got[rid] == want[rid], (
            f"{rid}: multihost {got[rid]} != single-process {want[rid]}")
    assert all(len(v) > 0 for v in got.values())


@pytest.mark.slow
def test_multihost_fused_int8_matches_single_process():
    """The lockstep-2proc cell of the composition grid (ISSUE 12 leg 4,
    tests/test_compose_matrix.py documents the full grid): int8 KV and
    the FUSED greedy single step both ride the audited command stream —
    the leader broadcasts step(), every process dispatches the same
    fused program over the quantized sharded cache, and the replicated
    [B] token output reads identically everywhere."""
    got = _run_pair("fused_int8")
    want = _single_process_reference("fused_int8")
    for rid in want:
        assert got[rid] == want[rid], (
            f"{rid}: multihost {got[rid]} != single-process {want[rid]}")
    assert all(len(v) > 0 for v in got.values())


@pytest.mark.e2e
def test_disagg_decode_on_two_process_mesh(tmp_path):
    """VERDICT r4 next-1 'done' criterion: a disagg e2e with DECODE on a
    2-process tp mesh — prefill runs on a separate single-process worker,
    KV onboards into the multi-process decode engine (import_blocks rides
    the lockstep channel so both ranks inject identically)."""
    import asyncio
    import time

    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    env = _clean_env()
    coord, lock = _free_port(), _free_port()
    procs = []

    def spawn(name, extra):
        log = open(tmp_path / f"{name}.log", "w+")
        p = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--model", "tiny-test", "--block-size", "8",
             "--decode-window", "4"] + extra,
            env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT,
            text=True)
        p._log = log
        procs.append(p)
        return p

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        watcher = ModelWatcher(runtime, models, migration_limit=0)
        await watcher.start()
        svc = HttpService(models)
        http_port = await svc.start()

        cp_addr = f"127.0.0.1:{cp_port}"
        mh = ["--multihost-cpu-devices", "1",
              "--coordinator", f"127.0.0.1:{coord}",
              "--num-processes", "2", "--tp", "2",
              "--lockstep", f"127.0.0.1:{lock}"]
        spawn("decode-follower", mh + ["--process-id", "1"])
        decode = spawn("decode-leader", mh + [
            "--process-id", "0", "--control-plane", cp_addr,
            "--model-name", "tiny-mh", "--role", "decode",
            "--max-local-prefill", "8"])
        spawn("prefill", ["--control-plane", cp_addr,
                          "--role", "prefill"])

        await watcher.wait_for_model("tiny-mh", timeout=180)
        base = f"http://127.0.0.1:{http_port}"
        async with ClientSession() as s:
            async with s.post(f"{base}/v1/chat/completions", json={
                    "model": "tiny-mh",
                    "messages": [{"role": "user",
                                  "content": "a fairly long prompt that "
                                             "exceeds the local prefill "
                                             "threshold for sure"}],
                    "max_tokens": 8}) as r:
                body = await r.json()
                assert r.status == 200, body
                assert body["choices"][0]["message"]["content"]

        # The decode leader must have onboarded remote-prefilled KV.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            decode._log.flush()
            decode._log.seek(0)
            log = decode._log.read()
            if "remote prefill" in log and "onboarded" in log:
                break
            await asyncio.sleep(0.5)
        assert "onboarded" in log, f"no remote prefill in decode log:\n{log}"

        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()
        await cp_server.stop()

    try:
        asyncio.run(asyncio.wait_for(main(), timeout=300))
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        time.sleep(1)
        for p in procs:
            if p.poll() is None:
                p.kill()
            p._log.flush()
            p._log.seek(0)
            out = p._log.read()
            if out:
                print(f"--- {p.args[-1]} (rc={p.poll()}) ---")
                print(out[-2500:])
