"""Multimodal skeleton (VERDICT r3 next-10): processor → encode worker →
LLM engine, with embeddings crossing the device transfer plane."""

import asyncio

import jax
import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.multimodal import (
    ENCODE_ENDPOINT,
    EncodeWorker,
    MultimodalProcessor,
    StubVisionEncoder,
)
from dynamo_tpu.models import config as mcfg

TINY = mcfg.get_config("tiny-test")


def _core(**kw):
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=8, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16)), **kw))


def _run(core, rid, prompt, embeds=None, n=6):
    core.add_request(rid, prompt, SamplingParams(max_tokens=n),
                     prompt_embeds=embeds)
    out = []
    for _ in range(200):
        for d in core.step():
            out.extend(d.token_ids)
        if not core._requests:
            break
    return out


def test_embeds_steer_generation():
    """Same placeholder prompt + different embeddings → different
    outputs; same embeddings → identical outputs (greedy)."""
    prompt = [0] * 8 + [5, 6, 7, 8]
    enc = StubVisionEncoder(TINY.hidden_size, n_tokens=8)
    e1, e2 = enc.encode("cat.png") * 30, enc.encode("dog.png") * 30

    out_a = _run(_core(), "a", prompt, e1)
    out_b = _run(_core(), "b", prompt, e1)
    out_c = _run(_core(), "c", prompt, e2)
    assert out_a == out_b
    assert out_a != out_c  # the image actually reaches the model


def test_embeds_span_chunked_prefill():
    """Embedding span larger than one prefill chunk still lands on the
    right positions (chunk offsets index into prompt_embeds)."""
    enc = StubVisionEncoder(TINY.hidden_size, n_tokens=24)
    emb = enc.encode("big.png") * 30
    prompt = [0] * 24 + list(range(40, 48))  # 32 tokens, chunks of 16

    full = _run(_core(), "a", prompt, emb)
    again = _run(_core(), "b", prompt, emb)
    assert full == again and len(full) == 6


def test_multimodal_prompts_do_not_poison_prefix_cache():
    """Two different images share placeholder tokens; the second must NOT
    prefix-hit the first's KV."""
    enc = StubVisionEncoder(TINY.hidden_size, n_tokens=8)
    prompt = [0] * 8 + [5, 6, 7, 8]
    core = _core()
    out1 = _run(core, "a", prompt, enc.encode("cat.png") * 30)
    hits_before = core.allocator.manager.device.hits
    out2 = _run(core, "b", prompt, enc.encode("dog.png") * 30)
    assert core.allocator.manager.device.hits == hits_before
    assert out1 != out2


def test_validation():
    core = _core()
    with pytest.raises(ValueError, match="prompt_embeds"):
        core.add_request("x", [1, 2], SamplingParams(max_tokens=1),
                         prompt_embeds=np.zeros((3, TINY.hidden_size)))
    with pytest.raises(ValueError, match="prompt_embeds"):
        core.add_request("y", [1, 2], SamplingParams(max_tokens=1),
                         prompt_embeds=np.zeros((2, 7)))


def test_pipeline_e2e_over_device_plane():
    """The full flow: processor parses image parts → encode worker stages
    embeddings on the device transfer plane → LLM engine generates."""
    from dynamo_tpu.llm.block_manager.device_transfer import (
        KvTransferPlane)

    # Runs on every build: the plane rides the PJRT transfer service
    # where available, the same-process device_put fabric otherwise.
    from dynamo_tpu.llm.service import LocalEngineClient
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.runtime.rpc import RpcClient, RpcServer

    async def main():
        encode_plane = KvTransferPlane()
        encode_plane.start()
        worker = EncodeWorker(StubVisionEncoder(TINY.hidden_size, 8),
                              transfer_plane=encode_plane)
        server = RpcServer()
        server.register(ENCODE_ENDPOINT, worker.make_handler())
        addr = await server.start()

        llm_plane = KvTransferPlane()
        llm_plane.start()
        rpc = RpcClient(addr)
        processor = MultimodalProcessor(ByteTokenizer(), rpc,
                                        transfer_plane=llm_plane)
        tokens, embeds = await processor.build([
            {"role": "user", "content": [
                {"type": "image_url",
                 "image_url": {"url": "http://x/cat.png"}},
                {"type": "text", "text": "describe"},
            ]}])
        assert embeds is not None and embeds.shape == (8, TINY.hidden_size)
        assert tokens[:8] == [0] * 8
        assert worker.encoded == 1
        assert llm_plane.pulled_blocks == 1  # crossed the device plane

        engine = InferenceEngine(_core())
        await engine.start()
        out = []
        async for d in engine.generate("mm", tokens,
                                       SamplingParams(max_tokens=5),
                                       prompt_embeds=embeds):
            out.extend(d.token_ids)
        assert len(out) == 5

        # A different image produces a different generation.
        tokens2, embeds2 = await processor.build([
            {"role": "user", "content": [
                {"type": "image_url",
                 "image_url": {"url": "http://x/dog.png"}},
                {"type": "text", "text": "describe"},
            ]}])
        out2 = []
        async for d in engine.generate("mm2", tokens2,
                                       SamplingParams(max_tokens=5),
                                       prompt_embeds=embeds2 * 30):
            out2.extend(d.token_ids)
        # (embeds scaled up to force visibly different logits on the
        # tiny random model)
        await engine.stop()
        await rpc.close()
        await server.stop()
        return True

    assert asyncio.run(asyncio.wait_for(main(), 120))


def test_http_image_parts_reach_engine():
    """VERDICT r4 next-7 'done': a chat request with an image part over
    HTTP produces a response that provably depends on the image (greedy:
    same image → same tokens, different image → different tokens), on
    the single-process frontend's in-process encoder."""
    import asyncio

    from aiohttp import ClientSession

    from dynamo_tpu.engine.engine import (
        EngineConfig, EngineCore, InferenceEngine)
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.multimodal import MultimodalAttach, StubVisionEncoder
    from dynamo_tpu.llm.preprocessor import OpenAIPreprocessor
    from dynamo_tpu.llm.service import (
        LocalEngineClient, ModelHandle, ModelManager)
    from dynamo_tpu.llm.tokenizer import ByteTokenizer
    from dynamo_tpu.models import config as mcfg

    cfg = mcfg.get_config("tiny-test")

    async def main():
        core = EngineCore(EngineConfig(
            model=cfg, num_blocks=160, enable_prefix_cache=False,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=32,
                max_prefill_chunk=64,
                decode_buckets=(1, 2, 4), prefill_buckets=(16, 32, 64))))
        engine = InferenceEngine(core)
        await engine.start()
        tokenizer = ByteTokenizer()
        models = ModelManager()
        models.register(ModelHandle(
            name="mm-test", tokenizer=tokenizer,
            preprocessor=OpenAIPreprocessor(tokenizer),
            client=LocalEngineClient(engine),
            max_context=cfg.max_context,
            multimodal=MultimodalAttach(
                local_encoder=StubVisionEncoder(cfg.hidden_size))))
        svc = HttpService(models)
        port = await svc.start()
        base = f"http://127.0.0.1:{port}"

        def req(url):
            return {
                "model": "mm-test",
                "messages": [{"role": "user", "content": [
                    {"type": "image_url", "image_url": {"url": url}},
                    {"type": "text", "text": "describe"},
                ]}],
                "max_tokens": 8, "temperature": 0,
            }

        async with ClientSession() as s:
            outs = []
            for url in ("http://x/cat.png", "http://x/cat.png",
                        "http://x/dog.png"):
                async with s.post(f"{base}/v1/chat/completions",
                                  json=req(url)) as r:
                    body = await r.json()
                    assert r.status == 200, body
                    outs.append(body["choices"][0]["message"]["content"])
            assert outs[0] == outs[1], "same image must decode identically"
            assert outs[0] != outs[2], "different image must steer output"

            # Text-only requests on the same model still work.
            async with s.post(f"{base}/v1/chat/completions", json={
                    "model": "mm-test",
                    "messages": [{"role": "user", "content": "hi"}],
                    "max_tokens": 4}) as r:
                assert r.status == 200, await r.text()
        await svc.stop()
        await engine.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


def test_http_image_parts_e2e_with_encode_worker():
    """Distributed variant: frontend discovers the model via the control
    plane; image embeddings come from a separate `--role encode` worker
    process (reference multimodal_v1 topology)."""
    import asyncio
    import os
    import subprocess
    import sys
    import time

    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    logs = []

    def spawn(name, extra):
        log = open(f"/tmp/dynamo_mm_{os.getpid()}_{name}.log", "w+")
        logs.append(log)
        p = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker"] + extra,
            env=dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo),
            cwd=repo, stdout=log, stderr=subprocess.STDOUT, text=True)
        procs.append(p)
        return p

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        watcher = ModelWatcher(runtime, models, migration_limit=0)
        await watcher.start()
        svc = HttpService(models)
        http_port = await svc.start()

        cp_addr = f"127.0.0.1:{cp_port}"
        spawn("llm", ["--control-plane", cp_addr, "--model", "tiny-test",
                      "--model-name", "mm-dist", "--block-size", "8"])
        spawn("enc", ["--control-plane", cp_addr, "--model", "tiny-test",
                      "--role", "encode"])
        await watcher.wait_for_model("mm-dist", timeout=120)

        base = f"http://127.0.0.1:{http_port}"
        payload = {
            "model": "mm-dist",
            "messages": [{"role": "user", "content": [
                {"type": "image_url", "image_url": {"url": "img://a"}},
                {"type": "text", "text": "what is this"},
            ]}],
            "max_tokens": 6, "temperature": 0,
        }
        async with ClientSession() as s:
            deadline = time.monotonic() + 60
            body = None
            while time.monotonic() < deadline:
                async with s.post(f"{base}/v1/chat/completions",
                                  json=payload) as r:
                    body = await r.json()
                    if r.status == 200:
                        break
                await asyncio.sleep(1.0)  # encode worker may still be up-coming
            assert body and body.get("choices"), body
            assert body["choices"][0]["message"]["content"]

        await watcher.stop()
        await svc.stop()
        await runtime.shutdown()
        await cp.close()
        await cp_server.stop()

    try:
        asyncio.run(asyncio.wait_for(main(), timeout=240))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for log in logs:
            log.flush(); log.seek(0)
            out = log.read()
            if out and "Traceback" in out:
                print(f"--- {log.name} ---"); print(out[-2000:])
