"""Native (C++) chained block hashing vs the Python oracle.

The byte layout contract: xxh3_64(parent_le64 || tokens_le_u32[]) —
identical in csrc/block_hash.cpp and tokens.hash_block.  Frontends and
workers may mix native/non-native builds, so equality here is a
CORRECTNESS property (mismatched hashes would silently kill prefix
routing), not an optimisation detail.
"""

import struct

import numpy as np
import pytest
import xxhash

from dynamo_tpu import native
from dynamo_tpu.tokens import ROOT_PARENT_HASH, compute_block_hashes, hash_block


def _python_chain(tokens, block_size, parent=ROOT_PARENT_HASH):
    arr = np.asarray(tokens, np.uint32)
    out, h = [], parent
    for i in range(len(arr) // block_size):
        h = hash_block(h, arr[i * block_size:(i + 1) * block_size])
        out.append(h)
    return out


def test_native_builds_and_matches_python():
    lib = native.get_lib()
    assert lib is not None, "native block-hash build failed (g++ baked in)"
    rng = np.random.default_rng(0)
    for n, bs in ((0, 8), (7, 8), (8, 8), (65, 8), (4096, 64), (100_000, 64)):
        toks = rng.integers(0, 2**32 - 1, size=n, dtype=np.uint32)
        want = _python_chain(toks, bs)
        got = native.chained_block_hashes(toks, bs, ROOT_PARENT_HASH)
        assert [int(x) for x in got] == want


def test_compute_block_hashes_uses_same_contract():
    toks = list(range(1, 257))
    got = compute_block_hashes(toks, 64)
    # Independent re-derivation straight from the documented layout.
    h = ROOT_PARENT_HASH
    want = []
    for i in range(4):
        x = xxhash.xxh3_64()
        x.update(struct.pack("<Q", h))
        x.update(np.asarray(toks[i * 64:(i + 1) * 64], np.uint32).tobytes())
        h = x.intdigest()
        want.append(h)
    assert got == want


def test_hash_one_block_native():
    toks = np.arange(64, dtype=np.uint32)
    got = native.hash_one_block(toks, ROOT_PARENT_HASH)
    if got is None:
        pytest.skip("native unavailable")
    assert got == hash_block(ROOT_PARENT_HASH, toks)


def test_native_perf_sanity():
    """The native chain must beat the per-block Python loop on a long
    prompt (the point of csrc/); generous 1.5x bar to avoid flakes."""
    import time

    if native.get_lib() is None:
        pytest.skip("native unavailable")
    toks = np.random.default_rng(1).integers(
        0, 2**31, size=200_000, dtype=np.uint32)

    t0 = time.perf_counter()
    for _ in range(3):
        native.chained_block_hashes(toks, 64, ROOT_PARENT_HASH)
    t_native = time.perf_counter() - t0

    t0 = time.perf_counter()
    _python_chain(toks, 64)
    t_python = time.perf_counter() - t0

    assert t_native / 3 < t_python / 1.5, (t_native / 3, t_python)
