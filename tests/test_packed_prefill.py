"""Packed ragged prefill plane (ISSUE 10).

Layers under test, cheapest first: the Pallas flash-prefill kernel
against the gather oracle (interpret mode, no engine), the scheduler's
pack sizing, the measured-cost EWMA calibration, and then the engine
plane end to end — packed vs padded token parity (bf16 AND int8, with
and without a cached prefix resident in the pool), the prewarm shape-set
pin, and the steady-decode-counters byte-identity with the plane idle.

Engine-build discipline (tier-1 timing budget): every engine test shares
ONE tiny geometry (`GEOM`) so the persistent XLA compile cache serves
repeated shapes across tests, and runs are a handful of short requests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.bench import gate
from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import (
    MixedPrefillController,
    PrefillWork,
    SchedulerConfig,
    pack_prefill_chunks,
)
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.ops.attention import paged_attention
from dynamo_tpu.ops.pallas import paged_prefill_attention
from dynamo_tpu.runtime.metrics import EngineStepCounters

TINY = mcfg.get_config("tiny-test")

# One shared geometry for every engine in this file (compile-cache reuse).
GEOM = dict(max_seqs=8, block_size=8, max_pages_per_seq=16,
            max_prefill_chunk=32, decode_buckets=(1, 2, 4, 8),
            prefill_buckets=(8, 16, 32))


def make_core(packed, kv_quant="none", prefix_cache=False, **over):
    cfg = dict(model=TINY, num_blocks=128, packed_prefill=packed,
               kv_quant=kv_quant, enable_prefix_cache=prefix_cache,
               scheduler=SchedulerConfig(**GEOM))
    cfg.update(over)
    return EngineCore(EngineConfig(**cfg))


def serve(core, rid, prompt, max_tokens=4):
    core.add_request(rid, prompt, SamplingParams(max_tokens=max_tokens))
    out = []
    for _ in range(400):
        for d in core.step():
            out.extend(d.token_ids)
        if not core._requests:
            break
    return out


def run_fleet(core, prompts, max_tokens=4):
    for i, p in enumerate(prompts):
        core.add_request(f"r{i}", p, SamplingParams(max_tokens=max_tokens))
    out = {}
    for _ in range(600):
        for d in core.step():
            out.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    return out


# -- kernel vs gather oracle -------------------------------------------------


def _oracle_segment(kc, vc, bt_row, seq_len, chunk_start, q_seg, bs, Hkv,
                    scales=None):
    P = bt_row.shape[0]
    C = P * bs
    ctx_pos = jnp.broadcast_to(jnp.arange(C, dtype=jnp.int32), (1, C))
    slots = kvc.slots_for_positions(bt_row[None], ctx_pos, bs)
    if scales is None:
        k_ctx, v_ctx = kvc.gather_kv(kc, vc, slots, Hkv)
    else:
        ks, vs = scales
        k_ctx, v_ctx = kvc.gather_kv_quant(kc, vc, ks, vs, slots, Hkv,
                                           out_dtype=jnp.bfloat16)
    ql = q_seg.shape[0]
    q_pos = jnp.arange(chunk_start, chunk_start + ql,
                       dtype=jnp.int32)[None]
    return paged_attention(q_seg[None], k_ctx, v_ctx, q_pos, ctx_pos,
                           jnp.asarray([seq_len], jnp.int32))[0]


def test_paged_prefill_kernel_matches_gather_oracle():
    """Packed multi-segment kernel == per-segment gather path: a full
    prompt, a residual chunk over a CACHED PREFIX (chunk_start > 0 —
    cached-prefix attention), and a pad segment; pad/gap rows come back
    zero."""
    rng = np.random.default_rng(0)
    Hq, Hkv, D, bs, P = 8, 4, 16, 8, 6
    S = 40 * bs
    kc = jnp.asarray(rng.normal(size=(S, Hkv * D)), jnp.bfloat16)
    vc = jnp.asarray(rng.normal(size=(S, Hkv * D)), jnp.bfloat16)
    segs = [(0, 24), (16, 9), (0, 0)]  # (chunk_start, q_len)
    T = 48
    starts, qlens, seqlens, off = [], [], [], 0
    q = np.zeros((T, Hq, D), np.float32)
    for cs, ql in segs:
        starts.append(off)
        qlens.append(ql)
        seqlens.append(cs + ql)
        if ql:
            q[off:off + ql] = rng.normal(size=(ql, Hq, D))
        off += -(-ql // 8) * 8
    bt = np.zeros((len(segs), P), np.int32)
    bt[0] = [3, 9, 17, 2, 25, 30]
    bt[1] = [11, 4, 21, 7, 0, 0]
    qj = jnp.asarray(q, jnp.float32)

    out = np.asarray(paged_prefill_attention(
        qj, kc, vc, jnp.asarray(bt), jnp.asarray(seqlens, jnp.int32),
        jnp.asarray(starts, jnp.int32), jnp.asarray(qlens, jnp.int32),
        block_size=bs, interpret=True))

    owned = set()
    for r, (cs, ql) in enumerate(segs):
        if not ql:
            continue
        ref = _oracle_segment(kc, vc, jnp.asarray(bt[r]), seqlens[r], cs,
                              qj[starts[r]:starts[r] + ql], bs, Hkv)
        np.testing.assert_allclose(
            out[starts[r]:starts[r] + ql], np.asarray(ref),
            rtol=3e-2, atol=3e-2)
        owned.update(range(starts[r], starts[r] + ql))
    pad_rows = sorted(set(range(T)) - owned)
    assert np.all(out[pad_rows] == 0)


def test_paged_prefill_kernel_int8_variant():
    """int8 pool + [S, Hkv] scales: dequant-in-VMEM numerics match the
    gather_kv_quant oracle, cached-prefix residual included."""
    rng = np.random.default_rng(1)
    Hq, Hkv, D, bs, P = 8, 4, 16, 8, 4
    S = 24 * bs
    kq, ks = kvc.quantize_kv_rows(
        jnp.asarray(rng.normal(size=(S, Hkv * D)), jnp.float32), Hkv)
    vq, vs = kvc.quantize_kv_rows(
        jnp.asarray(rng.normal(size=(S, Hkv * D)), jnp.float32), Hkv)
    segs = [(0, 16), (8, 5)]
    starts, qlens, seqlens, T = [0, 16], [16, 5], [16, 13], 24
    q = jnp.asarray(rng.normal(size=(T, Hq, D)), jnp.bfloat16)
    bt = np.zeros((2, P), np.int32)
    bt[0] = [3, 9, 1, 2]
    bt[1] = [11, 4, 0, 0]

    out = np.asarray(paged_prefill_attention(
        q, kq, vq, jnp.asarray(bt), jnp.asarray(seqlens, jnp.int32),
        jnp.asarray(starts, jnp.int32), jnp.asarray(qlens, jnp.int32),
        block_size=bs, interpret=True, k_scale=ks,
        v_scale=vs).astype(jnp.float32))
    for r, (cs, ql) in enumerate(segs):
        ref = _oracle_segment(kq, vq, jnp.asarray(bt[r]), seqlens[r], cs,
                              q[starts[r]:starts[r] + ql], bs, Hkv,
                              scales=(ks, vs))
        np.testing.assert_allclose(
            out[starts[r]:starts[r] + ql],
            np.asarray(ref.astype(jnp.float32)), rtol=4e-2, atol=4e-2)


# -- pack sizing + measured-cost calibration (deviceless) --------------------


def test_pack_prefill_chunks_budget_alignment_segments():
    def w(n):
        return PrefillWork(request=None, start=0, length=n)

    # Aligned lengths pack to the budget, order preserved (FCFS).
    packs = pack_prefill_chunks([w(9), w(16), w(7), w(30)], budget=32,
                                max_segments=8, align=8)
    assert [[x.length for x in p] for p in packs] == [[9, 16], [7], [30]]
    # Segment cap splits even when tokens fit.
    packs = pack_prefill_chunks([w(4)] * 5, budget=512, max_segments=2,
                                align=8)
    assert [len(p) for p in packs] == [2, 2, 1]
    # An oversize chunk still ships (its own pack), never dropped.
    packs = pack_prefill_chunks([w(600)], budget=512, max_segments=8)
    assert [[x.length for x in p] for p in packs] == [[600]]
    assert pack_prefill_chunks([], budget=512, max_segments=8) == []


def test_packed_bucket_lattice():
    sched = SchedulerConfig(**GEOM)
    assert sched.packed_buckets() == (32,)   # top covers max_prefill_chunk
    assert sched.bucket_for_packed(9) == 32
    assert sched.page_bucket_ladder() == (2, 4, 8, 16)
    serving = SchedulerConfig()              # defaults: chunk 512
    assert serving.packed_buckets() == (128, 512)
    assert serving.bucket_for_packed(96) == 128
    assert serving.bucket_for_packed(200) == 512
    assert serving.bucket_for_packed(9999) == 512  # clamped to top


def test_measured_cost_ewma_calibration():
    """ISSUE 10 satellite: the hardcoded cost_ratio=1.15 prior is
    replaced by the EWMA of measured packed-chunk cost — plain window
    intervals calibrate the decode token cost, mixed intervals attribute
    the excess to the chunk, and the controller's model queries follow
    the measurement."""
    c = EngineStepCounters()
    assert c.measured_prefill_cost_ratio is None
    c.note_window_interval(0.8, 8, 0)            # 0.1 s / decode token
    assert c.measured_prefill_cost_ratio is None  # no mixed sample yet
    c.note_window_interval(0.8 + 3.2, 8, 16)      # excess 3.2s / 16 tokens
    assert abs(c.measured_prefill_cost_ratio - 2.0) < 1e-6
    # Degenerate intervals are ignored, and a mixed interval before any
    # plain calibration is dropped (no decode baseline to subtract).
    c2 = EngineStepCounters()
    c2.note_window_interval(1.0, 8, 16)
    c2.note_window_interval(0.0, 8, 0)
    c2.note_window_interval(1.0, 0, 0)
    assert c2.measured_prefill_cost_ratio is None

    ctl = MixedPrefillController()
    assert ctl.effective_cost_ratio == ctl.cost_ratio == 1.15  # prior
    base_budget = ctl.budget_for(2, 32, 8)
    ctl.observe_cost_ratio(2.0)
    assert ctl.effective_cost_ratio == 2.0
    assert ctl.budget_for(2, 32, 8) < base_budget  # costlier chunk → less
    # EWMA smooths and the clamp bounds a poisoned interval.
    ctl.observe_cost_ratio(1e9)
    assert ctl.effective_cost_ratio <= 10.0
    # Interference model consumes the measured value too.
    lo = MixedPrefillController()
    hi = MixedPrefillController()
    hi.observe_cost_ratio(5.0)
    assert (hi.modeled_interference(2, 32, 8, 128)
            < lo.modeled_interference(2, 32, 8, 128))


def test_prefill_plane_gate_floor():
    """A TPU run whose packed plane stopped beating the padded one fails
    the absolute floor; CPU artifacts and sections without the ratio are
    skipped, never failed."""
    tpu = {"value": 1.0, "calibration_ok": True,
           "device": "TPU v5 lite0",
           "prefill_plane": {"packed_vs_padded_tok_s_ratio": 1.45}}
    assert gate.compare(tpu, tpu).ok
    slow = dict(tpu, prefill_plane={"packed_vs_padded_tok_s_ratio": 0.9})
    res = gate.compare(slow, slow)
    assert not res.ok and any(
        f["metric"] == "prefill_plane.packed_vs_padded_tok_s_ratio"
        for f in res.floor_failures)
    cpu = dict(tpu, device="TFRT_CPU_0",
               prefill_plane={"packed_vs_padded_tok_s_ratio": 0.3})
    assert gate.compare(cpu, cpu).ok
    missing = {k: v for k, v in tpu.items() if k != "prefill_plane"}
    res = gate.compare(missing, missing)
    assert res.ok and ("floor:prefill_plane.packed_vs_padded_tok_s_ratio"
                       in res.skipped)


# -- engine plane: token parity ----------------------------------------------

RAGGED_PROMPTS = [list(range(1, 40)), list(range(60, 69)),
                  list(range(100, 123))]


def test_prewarm_shape_set_and_packed_parity_bf16():
    """Two pins sharing one packed/padded engine pair (engine builds are
    the expensive unit in this file — tier-1 timing budget):

    1. The packed shape lattice is small by construction — pinned so a
       future change can't silently explode what --prewarm-prefill
       compiles — and serving a ragged fleet lands entirely inside the
       prewarmed set (no new packed-program shapes after startup).
    2. Packed ragged plane == padded-bucket oracle, token for token, on
       a ragged 3-prompt fleet (mixed chunk counts, mixed lengths)."""
    packed = make_core(True)
    shapes = packed.packed_prefill_shape_set()
    # GEOM: one packed token bucket (32) x page ladder (2, 4, 8, 16).
    assert shapes == [(32, 8, 2), (32, 8, 4), (32, 8, 8), (32, 8, 16)]
    assert packed.prewarm_prefill() == len(shapes)
    seen = {k for k in packed.counters._seen_shapes
            if k[0] == "prefill_packed"}
    assert seen == {("prefill_packed",) + s for s in shapes}

    out_packed = run_fleet(packed, RAGGED_PROMPTS, max_tokens=5)
    assert packed.counters.packed_prefill_dispatches > 0
    after = {k for k in packed.counters._seen_shapes
             if k[0] == "prefill_packed"}
    assert after == seen  # serving never compiled a new packed shape

    padded = make_core(False)
    # Padded-plane engines report 0 without touching the packed step.
    assert padded.prewarm_prefill() == 0
    out_padded = run_fleet(padded, RAGGED_PROMPTS, max_tokens=5)
    assert padded.counters.packed_prefill_dispatches == 0
    assert out_packed == out_padded


def test_packed_engine_token_parity_int8():
    # decode_window=1: the plane under test is prefill; skipping the
    # window-program compile keeps this inside the tier-1 time budget
    # (the bf16 test above covers packed prefill + window interleaving).
    out_packed = run_fleet(make_core(True, kv_quant="int8",
                                     decode_window=1),
                           RAGGED_PROMPTS, max_tokens=5)
    out_padded = run_fleet(make_core(False, kv_quant="int8",
                                     decode_window=1),
                           RAGGED_PROMPTS, max_tokens=5)
    assert out_packed == out_padded


def test_packed_cached_prefix_residual_parity():
    """With the tiered prefix cache resident, a repeat prompt's
    admission match leaves only a RESIDUAL chunk to prefill
    (chunk_start > 0, prior context = pool pages) — the packed plane
    must reproduce the padded plane's tokens through that path too."""
    prefix = list(range(1, 25))
    results = {}
    for packed in (True, False):
        # decode_window=1 for the same budget reason as the int8 test.
        core = make_core(packed, prefix_cache=True, decode_window=1)
        seed = serve(core, "seed", prefix + [30, 31])
        hits_before = core.scheduler.prefix_hit_tokens
        reuse = serve(core, "reuse", prefix + [40, 41, 42])
        assert core.scheduler.prefix_hit_tokens > hits_before  # real hit
        results[packed] = (seed, reuse,
                           core.scheduler.prefix_hit_tokens)
    assert results[True] == results[False]


# -- prewarm + idle-plane counters -------------------------------------------


def test_steady_decode_counters_identical_with_plane_idle():
    """The packed plane must cost the steady decode window NOTHING while
    idle: with prefill long finished, 20 window steps produce
    byte-identical counter deltas whether the plane is on or off."""
    deltas = {}
    for packed in (True, False):
        core = make_core(packed, decode_window=2, window_pipeline_depth=2)
        # prompt + max_tokens must fit max_context (16 pages x 8); the
        # budget must also outlast warmup + 20 windows so the cohort
        # stays in window mode for the whole pinned range.
        core.add_request("a", list(range(1, 41)),
                         SamplingParams(max_tokens=80))
        for _ in range(10):   # prefill + window warmup
            core.step()
        base = core.counters.snapshot()
        for _ in range(20):
            core.step()
        deltas[packed] = core.counters.delta(base)
        # The EWMA calibration rides the existing window syncs — plain
        # windows must have calibrated the decode token cost without
        # adding a single host sync (the delta equality below pins it).
        assert core.counters.decode_token_cost_ewma > 0
    assert deltas[True] == deltas[False]


def test_packed_bucket_config_validation():
    """Bad packed_prefill_buckets fail at construction (were a numpy
    broadcast ValueError inside the hot loop / a kernel PACK_ALIGN
    error at dispatch)."""
    with pytest.raises(ValueError, match="PACK_ALIGN"):
        SchedulerConfig(**{**GEOM, "packed_prefill_buckets": (12, 32)})
    # Top bucket must hold the align-rounded max_prefill_chunk: the
    # pack builder gives an over-budget chunk "a pack of its own" and
    # the dispatch buffer is sized to the top bucket.
    with pytest.raises(ValueError, match="cannot hold"):
        SchedulerConfig(**{**GEOM, "packed_prefill_buckets": (16,)})
    ok = SchedulerConfig(**{**GEOM, "packed_prefill_buckets": (16, 32)})
    assert ok.packed_buckets() == (16, 32)


def test_explicit_packed_rejects_ineligible_tpu_geometry(monkeypatch):
    """packed_prefill=True must apply the same mosaic_geometry_ok rule
    the auto path does — a pointed config error at construction, not a
    Mosaic lowering error on the first prefill.  (Off-TPU the kernel
    runs in interpret mode, so any geometry constructs — the bf16/int8
    parity tests above rely on that.)"""
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    # tiny-test geometry: F = num_kv_heads * head_dim is not 128-aligned.
    assert (TINY.num_kv_heads * TINY.head_dim) % 128 != 0
    with pytest.raises(ValueError, match="Mosaic-eligible"):
        make_core(True, decode_window=1)


def test_multihost_keeps_static_cost_prior():
    """The measured cost ratio is per-host wall clock; folding it into
    the controller EWMA on a multihost engine would diverge lockstep
    plans.  _plan_mixed_budget must skip observe_cost_ratio under _mh
    and keep the deterministic static prior."""
    core = make_core(False, decode_window=2)
    assert core._mixed_ctl is not None
    # Calibrate the counters so measured_prefill_cost_ratio is real.
    core.counters.note_window_interval(0.8, 8, 0)
    core.counters.note_window_interval(4.0, 8, 16)
    assert core.counters.measured_prefill_cost_ratio is not None
    prior = core._mixed_ctl.cost_ratio
    core._mh = True
    core._plan_mixed_budget()
    assert core._mixed_ctl.effective_cost_ratio == prior  # not folded
    core._mh = False
    core._plan_mixed_budget()
    assert core._mixed_ctl.effective_cost_ratio != prior  # folded now


def test_ratio_zeroed_on_parity_failure(monkeypatch):
    """A fast-but-wrong kernel must not pass the TPU ratio floor: when
    the planes' first tokens diverge, run_prefill_plane zeroes
    packed_vs_padded_tok_s_ratio (0 < the 1.2 floor) instead of
    reporting the throughput win."""
    from dynamo_tpu.bench import prefill_plane as pp

    class _FakeCore:
        counters = EngineStepCounters()

    calls = {"n": 0}

    def fake_run_waves(core, model_cfg, lens, waves):
        calls["n"] += 1
        # Different first tokens per plane (parity failure), packed
        # (second build) twice as fast as padded.
        toks = [{f"r{i}": calls["n"] for i in range(len(lens))}]
        return [50.0 * calls["n"], 100.0 * calls["n"]], toks

    monkeypatch.setattr(pp, "_build_core", lambda *a, **k: _FakeCore())
    monkeypatch.setattr(pp, "_run_waves", fake_run_waves)
    out = pp.run_prefill_plane(TINY, lens=[5, 7], waves=2)
    assert out["token_parity"] is False
    assert out["packed_vs_padded_tok_s_ratio"] == 0.0


def test_explicit_packed_rejects_misaligned_derived_buckets():
    """Token buckets DERIVED from prefill_buckets obey the kernel's
    PACK_ALIGN contract too (interpret mode included) — a misaligned
    ladder fails at construction, not as a kernel ValueError inside the
    hot loop."""
    sched = SchedulerConfig(**{**GEOM, "prefill_buckets": (12, 20),
                               "max_prefill_chunk": 20})
    assert sched.packed_buckets() == (20,)   # derived, misaligned
    with pytest.raises(ValueError, match="PACK_ALIGN"):
        EngineCore(EngineConfig(model=TINY, num_blocks=128,
                                packed_prefill=True, scheduler=sched))


def test_measure_prefill_attention_rejects_misaligned_geometry():
    """ctx must fill whole pages and chunk must land on PACK_ALIGN
    boundaries, or the two timed programs silently diverge (kernel
    reads past the block table, gather hits NULL_BLOCK)."""
    from dynamo_tpu.bench.prefill_plane import measure_prefill_attention

    with pytest.raises(ValueError, match="chunk <= ctx"):
        measure_prefill_attention(TINY, block_size=64, ctx=500,
                                  chunk=496, interpret=True)
