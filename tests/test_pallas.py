"""Pallas paged-decode kernel == XLA gathered-attention path.

Runs in interpreter mode on the CPU test mesh (pallas_call(interpret=True));
the same kernel compiles for real on TPU (bench.py exercises it).
"""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.ops.attention import paged_attention
from dynamo_tpu.ops.pallas import paged_decode_attention


def test_kernel_matches_xla_gather_path():
    B, Hq, Hkv, D, bs, P = 3, 8, 4, 64, 8, 4
    S = 32 * bs
    q = jax.random.normal(jax.random.key(1), (B, Hq, D), jnp.float32)
    kc = jax.random.normal(jax.random.key(2), (S, Hkv * D), jnp.bfloat16)
    vc = jax.random.normal(jax.random.key(3), (S, Hkv * D), jnp.bfloat16)
    # Non-contiguous, per-sequence page assignments.
    bt = jnp.asarray([[3, 9, 17, 2], [11, 4, 0, 0], [21, 0, 0, 0]],
                     jnp.int32)
    seq_lens = jnp.asarray([29, 9, 1], jnp.int32)

    out = paged_decode_attention(q, kc, vc, bt, seq_lens, block_size=bs,
                                 interpret=True)

    ctx_pos = jnp.broadcast_to(jnp.arange(P * bs, dtype=jnp.int32),
                               (B, P * bs))
    slots = kvc.slots_for_positions(bt, ctx_pos, bs)
    k_ctx, v_ctx = kvc.gather_kv(kc, vc, slots, Hkv)
    ref = paged_attention(q[:, None], k_ctx, v_ctx,
                          (seq_lens - 1)[:, None], ctx_pos, seq_lens)[:, 0]
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)


def test_engine_output_identical_with_pallas_decode():
    """Greedy engine output must not depend on the attention backend."""
    def run(use_pallas):
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=64,
            use_pallas_decode=use_pallas,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16,
                decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))))
        core.add_request("a", [5, 6, 7, 8, 9, 10], SamplingParams(max_tokens=5))
        core.add_request("b", list(range(20, 39)), SamplingParams(max_tokens=5))
        outputs = {}
        for _ in range(200):
            for d in core.step():
                outputs.setdefault(d.request_id, []).extend(d.token_ids)
            if not core._requests:
                break
        return outputs

    assert run(True) == run(False)
