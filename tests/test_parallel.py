"""Sharded step == unsharded step, on the virtual 8-device CPU mesh.

This is the round-trip that validates the GSPMD rules: same params, same
inputs, meshes of different shapes (tp-only, dp×tp, dp×ep×tp for MoE) must
all reproduce the single-device logits.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_forward_step
from dynamo_tpu.parallel import (
    MeshConfig,
    cache_pspecs,
    make_mesh,
    make_sharded_step,
    param_pspecs,
    shard_pytree,
)
from dynamo_tpu.parallel.sharding import resolve_moe_mode

BLOCK = 8


def _inputs(cfg, batch, T, key=5):
    tokens = jax.random.randint(jax.random.key(key), (batch, T), 0, cfg.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (batch, T))
    # Blocks: seq i uses pages [1 + 4i, 1 + 4i + 3]
    bt = np.zeros((batch, 8), np.int32)
    for i in range(batch):
        bt[i, :4] = np.arange(1 + 4 * i, 5 + 4 * i)
    seq_lens = jnp.full((batch,), T, jnp.int32)
    return tokens, positions, seq_lens, jnp.asarray(bt)


def _reference_logits(cfg, params, inputs, sample_positions, num_blocks=64):
    cache = kvc.init_cache(
        kvc.KvCacheConfig.for_model(cfg, num_blocks=num_blocks,
                                    block_size=BLOCK, dtype=jnp.float32))
    step = make_forward_step(cfg, BLOCK)
    logits, _ = step(params, cache, *inputs, sample_positions)
    return np.asarray(logits)


@pytest.mark.parametrize(
    "cfg_name,mesh_cfg",
    [
        ("tiny-test", MeshConfig(tp=4, dp=2)),
        ("tiny-test", MeshConfig(tp=2, dp=4)),
        ("tiny-moe", MeshConfig(dp=2, ep=2, tp=2)),
    ],
)
def test_sharded_step_matches_unsharded(cfg_name, mesh_cfg):
    cfg = mcfg.get_config(cfg_name)
    params = init_params(cfg, jax.random.key(0))
    batch, T = 4, 16
    inputs = _inputs(cfg, batch, T)
    sample_pos = jnp.full((batch,), T - 1, jnp.int32)
    want = _reference_logits(cfg, params, inputs, sample_pos)

    mesh = make_mesh(mesh_cfg, jax.devices()[: mesh_cfg.size])
    # Param layout must match the MoE mode the step resolves on this
    # mesh (ISSUE 17: auto picks dispatch on ep > 1 — replicated
    # router), same contract the engine follows.
    sharded = shard_pytree(
        params, param_pspecs(cfg, resolve_moe_mode(cfg, mesh)), mesh)
    cache = shard_pytree(
        kvc.init_cache(kvc.KvCacheConfig.for_model(
            cfg, num_blocks=64, block_size=BLOCK, dtype=jnp.float32)),
        cache_pspecs(cfg.num_layers), mesh)
    step = make_sharded_step(cfg, BLOCK, mesh)
    got, cache2 = step(sharded, cache, *inputs, sample_pos)

    np.testing.assert_allclose(want, np.asarray(got), rtol=5e-4, atol=5e-4)
    # Cache sharding must survive the step (donation keeps layout).
    assert (cache2["k"][0].sharding.spec
            == cache_pspecs(cfg.num_layers)["k"][0])


def test_worker_build_mesh_reads_sp_and_pp():
    """ISSUE 9 satellite: `--sp`/`--pp` are reachable from a real worker
    — build_mesh folds them into the MeshConfig instead of silently
    serving meshless while the operator believes the ring/pipeline paths
    are on."""
    from dynamo_tpu.worker.main import build_mesh, parse_args

    args = parse_args(["--control-plane", "127.0.0.1:1",
                       "--sp", "2", "--tp", "2"])
    mesh = build_mesh(args)
    assert dict(mesh.shape)["sp"] == 2 and dict(mesh.shape)["tp"] == 2

    args = parse_args(["--control-plane", "127.0.0.1:1", "--pp", "2"])
    mesh = build_mesh(args)
    assert dict(mesh.shape)["pp"] == 2

    # Meshless stays meshless: no axis asked for.
    args = parse_args(["--control-plane", "127.0.0.1:1"])
    assert build_mesh(args) is None


def test_mesh_validation():
    from dynamo_tpu.parallel.sharding import validate

    cfg = mcfg.get_config("tiny-test")
    mesh = make_mesh(MeshConfig(tp=8), jax.devices())
    with pytest.raises(ValueError, match="num_kv_heads"):
        validate(cfg, mesh)  # tp=8 > kv_heads=4

    with pytest.raises(ValueError, match="devices"):
        make_mesh(MeshConfig(tp=3), jax.devices())


def test_decode_after_sharded_prefill():
    """Prefill sharded, then decode sharded: positions advance, cache reused."""
    cfg = mcfg.get_config("tiny-test")
    params = init_params(cfg, jax.random.key(0))
    mesh = make_mesh(MeshConfig(tp=4, dp=2), jax.devices())
    step = make_sharded_step(cfg, BLOCK, mesh)

    batch, T = 2, 12
    tokens, positions, seq_lens, bt = _inputs(cfg, batch, T, key=7)
    full_inputs = (tokens, positions, jnp.full((batch,), T, jnp.int32), bt)
    want = _reference_logits(cfg, params, full_inputs,
                             jnp.full((batch,), T - 1, jnp.int32))

    sharded = shard_pytree(params, param_pspecs(cfg), mesh)
    cache = shard_pytree(
        kvc.init_cache(kvc.KvCacheConfig.for_model(
            cfg, num_blocks=64, block_size=BLOCK, dtype=jnp.float32)),
        cache_pspecs(cfg.num_layers), mesh)

    split = T - 1
    _, cache = step(sharded, cache, tokens[:, :split], positions[:, :split],
                    jnp.full((batch,), split, jnp.int32), bt,
                    jnp.full((batch,), split - 1, jnp.int32))
    got, _ = step(sharded, cache, tokens[:, split:], positions[:, split:],
                  jnp.full((batch,), T, jnp.int32), bt,
                  jnp.zeros((batch,), jnp.int32))
    np.testing.assert_allclose(want, np.asarray(got),
                               rtol=5e-4, atol=5e-4)


def test_dp_attention_allows_tp_beyond_kv_heads():
    """DP-attention (reference sglang --enable-dp-attention): tp=8 on a
    4-kv-head model — impossible head-sharded — matches the unsharded
    oracle with batch-sharded attention and slot-sharded KV."""
    cfg = mcfg.get_config("tiny-test")  # kv_heads=4
    params = init_params(cfg, jax.random.key(0))
    batch, T = 8, 16  # batch divisible by dp*tp = 8
    inputs = _inputs(cfg, batch, T, key=9)
    sample_pos = jnp.full((batch,), T - 1, jnp.int32)
    want = _reference_logits(cfg, params, inputs, sample_pos)

    mesh = make_mesh(MeshConfig(tp=8), jax.devices())
    from dynamo_tpu.parallel.sharding import param_pspecs as pps

    sharded = shard_pytree(params, pps(cfg, dp_attention=True), mesh)
    cache = shard_pytree(
        kvc.init_cache(kvc.KvCacheConfig.for_model(
            cfg, num_blocks=64, block_size=BLOCK, dtype=jnp.float32)),
        cache_pspecs(cfg.num_layers, dp_attention=True), mesh)
    step = make_sharded_step(cfg, BLOCK, mesh, dp_attention=True)
    got, cache2 = step(sharded, cache, *inputs, sample_pos)

    np.testing.assert_allclose(want, np.asarray(got), rtol=5e-4, atol=5e-4)
    # KV memory splits over tp on the SLOT axis.
    assert (cache2["k"][0].sharding.spec
            == cache_pspecs(cfg.num_layers, dp_attention=True)["k"][0])
    # Plain mode still refuses tp > kv_heads.
    with pytest.raises(ValueError, match="num_kv_heads"):
        make_sharded_step(cfg, BLOCK, mesh)
