"""Perf recorder + JSONL event record/replay (reference perf.rs,
recorder.rs, kv_router/recorder.rs)."""

import asyncio

import pytest

from dynamo_tpu.engine.engine import TokenDelta
from dynamo_tpu.llm.kv_router.protocols import (
    KvCacheEvent,
    KvCacheEventData,
    RouterEvent,
)
from dynamo_tpu.llm.kv_router.router import KvRouter, KvRouterConfig
from dynamo_tpu.llm.perf import (
    JsonlRecorder,
    StreamRecorder,
    replay_jsonl,
    replay_kv_events,
    record_kv_events,
)
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.runtime.control_plane import InProcessControlPlane


class FakeClient:
    async def generate(self, request):
        for i in range(5):
            await asyncio.sleep(0.01)
            yield TokenDelta(request.request_id, [i], finished=(i == 4))


def _req(rid):
    return PreprocessedRequest(request_id=rid, model="m", token_ids=[1, 2],
                               sampling=SamplingParams(max_tokens=5))


def test_stream_recorder_timings():
    async def main():
        rec = StreamRecorder(FakeClient())
        for rid in ("a", "b"):
            async for _ in rec.generate(_req(rid)):
                pass
        t = rec.timings["a"]
        assert t.finished and t.output_tokens == 5
        assert t.ttft is not None and t.ttft >= 0.005
        assert len(t.itls) == 4 and all(x >= 0.005 for x in t.itls)
        s = rec.summary()
        assert s["requests"] == 2 and s["output_tokens"] == 10
        assert s["itl_p50"] >= 0.005 and s["tok_s"] > 0

    asyncio.run(main())


def test_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    rec = JsonlRecorder(path)
    rec.record("a", {"x": 1})
    rec.record("b", {"y": [1, 2]})
    rec.close()
    events = list(replay_jsonl(path))
    assert [(k, p) for _, k, p in events] == [("a", {"x": 1}),
                                             ("b", {"y": [1, 2]})]
    assert events[0][0] <= events[1][0]


def test_kv_event_record_and_replay(tmp_path):
    """Live events recorded from the control plane rebuild an identical
    router index on replay."""
    path = str(tmp_path / "kv.jsonl")

    def stored(eid, hashes, parent=None):
        return RouterEvent(worker_id=7, event=KvCacheEvent(
            event_id=eid,
            data=KvCacheEventData.stored(hashes, parent_hash=parent)))

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        task = await record_kv_events(cp, path)
        live = KvRouter(KvRouterConfig(block_size=8))
        evs = [stored(1, [101, 102]), stored(2, [103], parent=102)]
        for ev in evs:
            live.apply_event(ev)
            await cp.publish("kv_events", ev.to_dict())
        await asyncio.sleep(0.1)
        task.cancel()
        try:
            await task
        except asyncio.CancelledError:
            pass
        await cp.close()

        replayed = KvRouter(KvRouterConfig(block_size=8))
        assert replay_kv_events(path, replayed) == 2
        for h in ([101], [101, 102], [101, 102, 103]):
            assert (replayed.indexer.find_matches(h).scores
                    == live.indexer.find_matches(h).scores)

    asyncio.run(main())
