"""Pipeline parallelism vs the single-device oracle (SURVEY §2.5 "PP")."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_forward_step
from dynamo_tpu.parallel import MeshConfig, make_mesh
from dynamo_tpu.parallel.pipeline import (
    init_pp_cache,
    make_pp_step,
    pp_cache_pspecs,
    pp_param_pspecs,
    stack_layer_params,
)
from dynamo_tpu.parallel.sharding import shard_pytree

CFG = mcfg.get_config("tiny-test")
BLOCK = 8


def _inputs(batch, T, key=5):
    tokens = jax.random.randint(jax.random.key(key), (batch, T), 0,
                                CFG.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (batch, T))
    bt = np.zeros((batch, 8), np.int32)
    for i in range(batch):
        bt[i, :4] = np.arange(1 + 4 * i, 5 + 4 * i)
    return (tokens, positions, jnp.full((batch,), T, jnp.int32),
            jnp.asarray(bt), jnp.full((batch,), T - 1, jnp.int32))


def _pp_setup(mesh, params):
    stacked = shard_pytree(stack_layer_params(params),
                           pp_param_pspecs(CFG), mesh)
    cache = shard_pytree(
        init_pp_cache(kvc.KvCacheConfig.for_model(
            CFG, num_blocks=64, block_size=BLOCK, dtype=jnp.float32)),
        pp_cache_pspecs(), mesh)
    return stacked, cache


@pytest.mark.parametrize("n_mb", [1, 2, 4])
def test_pp_step_matches_unsharded(n_mb):
    params = init_params(CFG, jax.random.key(0))
    batch, T = 4, 16
    inputs = _inputs(batch, T)

    ref_step = make_forward_step(CFG, BLOCK)
    ref_cache = kvc.init_cache(kvc.KvCacheConfig.for_model(
        CFG, num_blocks=64, block_size=BLOCK, dtype=jnp.float32))
    want, want_cache = ref_step(params, ref_cache, *inputs)

    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    stacked, cache = _pp_setup(mesh, params)
    step = make_pp_step(CFG, BLOCK, mesh, n_microbatches=n_mb)
    got, got_cache = step(stacked, cache, *inputs)

    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=5e-5, atol=5e-5)
    # Stage-owned KV must equal the oracle's per-layer cache.  Block 0
    # (the null block, slots [0, BLOCK)) is excluded: both paths dump
    # masked/padding writes there and its contents are junk BY DESIGN
    # (kv_cache.py docstring) — only real pages carry semantics.
    for li in range(CFG.num_layers):
        for side in ("k", "v"):
            np.testing.assert_allclose(
                np.asarray(want_cache[side][li])[BLOCK:],
                np.asarray(got_cache[side][li])[BLOCK:],
                rtol=5e-5, atol=5e-5)


def test_pp_prefill_then_decode():
    """Prefill through the pipeline, then decode one token through it —
    matching a full unsharded run (cache handoff across calls)."""
    params = init_params(CFG, jax.random.key(0))
    batch, T = 2, 12
    tokens, positions, seq_lens, bt, sample = _inputs(batch, T, key=7)

    ref_step = make_forward_step(CFG, BLOCK)
    ref_cache = kvc.init_cache(kvc.KvCacheConfig.for_model(
        CFG, num_blocks=64, block_size=BLOCK, dtype=jnp.float32))
    logits, ref_cache = ref_step(params, ref_cache, tokens, positions,
                                 seq_lens, bt, sample)
    nxt = jnp.argmax(logits, -1).astype(jnp.int32)[:, None]
    want, _ = ref_step(params, ref_cache, nxt,
                       jnp.full((batch, 1), T, jnp.int32),
                       jnp.full((batch,), T + 1, jnp.int32), bt,
                       jnp.zeros((batch,), jnp.int32))

    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    stacked, cache = _pp_setup(mesh, params)
    step = make_pp_step(CFG, BLOCK, mesh, n_microbatches=2)
    logits2, cache = step(stacked, cache, tokens, positions, seq_lens, bt,
                          sample)
    nxt2 = jnp.argmax(logits2, -1).astype(jnp.int32)[:, None]
    np.testing.assert_array_equal(np.asarray(nxt), np.asarray(nxt2))
    got, _ = step(stacked, cache, nxt2,
                  jnp.full((batch, 1), T, jnp.int32),
                  jnp.full((batch,), T + 1, jnp.int32), bt,
                  jnp.zeros((batch,), jnp.int32))
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=5e-5, atol=5e-5)


def test_pp_validations():
    mesh = make_mesh(MeshConfig(pp=8), jax.devices())
    with pytest.raises(ValueError, match="divide num_layers"):
        make_pp_step(CFG, BLOCK, mesh, 2)  # 8 stages > 2 layers
    moe = mcfg.get_config("tiny-moe")
    mesh2 = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    with pytest.raises(ValueError, match="dense models"):
        make_pp_step(moe, BLOCK, mesh2, 2)
