"""Planner: decision unit tests + e2e with real mocker worker processes.

VERDICT r2 item 5: load spike → worker count grows; drain → shrinks; no
dropped streams (graceful SIGTERM drain)."""

import asyncio
import os
import sys
import time

import pytest

from dynamo_tpu.llm.kv_router.protocols import (
    ForwardPassMetrics,
    KvStats,
    WorkerStats,
)
from dynamo_tpu.planner import LoadPlanner, LocalConnector, PlannerConfig
from dynamo_tpu.runtime.control_plane import InProcessControlPlane

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _metrics(waiting=0, usage=0.0):
    return ForwardPassMetrics(
        worker_stats=WorkerStats(num_requests_waiting=waiting),
        kv_stats=KvStats(gpu_cache_usage_perc=usage)).to_dict()


class FakeConnector:
    def __init__(self, n=1):
        self.n = n
        self.calls = []

    def replicas(self):
        return self.n

    async def add_worker(self):
        self.n += 1
        self.calls.append("up")

    async def remove_worker(self):
        self.n -= 1
        self.calls.append("down")


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, 120))


def test_plan_step_decisions():
    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        conn = FakeConnector(n=1)
        planner = LoadPlanner(cp, conn, PlannerConfig(
            min_replicas=1, max_replicas=3, kv_high=0.8, kv_low=0.3,
            predictor="constant"))
        try:
            # No observations → no decision.
            assert planner.plan_step() is None
            # Saturated usage → up.
            planner._watcher._metrics[1] = (
                ForwardPassMetrics.from_dict(_metrics(usage=0.95)),
                time.monotonic())
            assert planner.plan_step() == "up"
            # Queued requests → up even at low usage.
            planner._watcher._metrics[1] = (
                ForwardPassMetrics.from_dict(_metrics(waiting=3, usage=0.1)),
                time.monotonic())
            assert planner.plan_step() == "up"
            # Max replicas clamp.
            conn.n = 3
            assert planner.plan_step() is None
            # Idle two-worker fleet → down (survivor stays under kv_low).
            conn.n = 2
            planner._watcher._metrics[1] = (
                ForwardPassMetrics.from_dict(_metrics(usage=0.05)),
                time.monotonic())
            planner._watcher._metrics[2] = (
                ForwardPassMetrics.from_dict(_metrics(usage=0.05)),
                time.monotonic())
            assert planner.plan_step() == "down"
            # Min replicas clamp.
            conn.n = 1
            planner._watcher._metrics.pop(2)
            assert planner.plan_step() is None
            # Stale metrics are ignored entirely.
            planner._watcher._metrics[1] = (
                ForwardPassMetrics.from_dict(_metrics(usage=0.95)),
                time.monotonic() - 1e6)
            assert planner.plan_step() is None
        finally:
            await cp.close()

    _run(main())


class RoleConnector(FakeConnector):
    """FakeConnector with per-role replica pools (heterogeneous cell)."""

    def __init__(self, counts):
        super().__init__(n=sum(counts.values()))
        self.counts = dict(counts)

    def replicas(self, role=None):
        if role is None:
            return sum(self.counts.values())
        return self.counts.get(role, 0)


def test_placement_ok_is_the_slice_spec_consult():
    """ISSUE 16 acceptance: plan decisions provably consult the published
    SliceSpec — a mesh-blind assignment (decode role on the dedicated
    sp-prefill slice) is refused with the slice named, the matching
    assignment passes, and an unpublished worker stays placeable (mixed
    fleet, version skew)."""
    from dynamo_tpu.fleet.topology import parse_slice

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        slices = {"w-p": parse_slice("sp2xtp2,int8,role=prefill"),
                  "w-d": parse_slice("tp2,int8,role=decode"),
                  "w-old": None}
        planner = LoadPlanner(cp, RoleConnector(
            {"prefill": 1, "decode": 1}), PlannerConfig(
                min_replicas=1, max_replicas=4, predictor="constant",
                roles=("prefill", "decode")),
            slices_fn=lambda: slices)
        try:
            ok, reason = planner.placement_ok("decode", worker_id="w-p")
            assert not ok and "prefill" in reason
            assert planner.placement_ok("prefill", worker_id="w-p")[0]
            assert planner.placement_ok("decode", worker_id="w-d")[0]
            assert planner.placement_ok("decode", worker_id="w-old")[0]
            # topology() decodes wire dicts too (discovery hands the
            # planner the published metadata, not live objects).
            planner._slices_fn = lambda: {
                "w-p": parse_slice("sp2xtp2,role=prefill").to_dict()}
            spec = planner.topology()["w-p"]
            assert spec is not None and spec.role == "prefill"
            # A failing topology source degrades to topology-blind, not
            # a crashed planning loop.
            planner._slices_fn = lambda: 1 / 0
            assert planner.topology() == {}
        finally:
            await cp.close()

    _run(main())


def test_plan_step_down_vetoed_when_role_coverage_would_break():
    """Scale-down in heterogeneous-cell mode consults the topology: a
    "down" whose victim role's LAST placeable slice would leave that
    role unservable is vetoed; with a second slice of the role published
    the same pressure scales down normally."""
    from dynamo_tpu.fleet.topology import parse_slice

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        conn = RoleConnector({"prefill": 1, "decode": 2})
        slices = {"w-p": parse_slice("sp2xtp2,role=prefill"),
                  "w-d1": parse_slice("tp2,role=decode"),
                  "w-d2": parse_slice("tp2,role=decode")}
        planner = LoadPlanner(cp, conn, PlannerConfig(
            min_replicas=1, max_replicas=4, kv_high=0.8, kv_low=0.3,
            predictor="constant", roles=("prefill", "decode")),
            slices_fn=lambda: slices)
        idle = ForwardPassMetrics.from_dict(_metrics(usage=0.05))
        planner._watcher._metrics[1] = (idle, time.monotonic())
        planner._watcher._metrics[2] = (idle, time.monotonic())
        try:
            # Two decode slices: thinning the decode pool keeps every
            # role placeable → the down decision stands.
            assert planner.plan_step() == "down"
            # Only ONE decode slice still published: the load signal
            # still says "down" and plan_role targets decode (the
            # fattest pool), but dropping decode's last published slice
            # would leave the role unservable → vetoed.
            slices.pop("w-d2")
            assert planner.plan_role("down") == "decode"
            assert planner.plan_step() is None
        finally:
            await cp.close()

    _run(main())


@pytest.mark.e2e
def test_planner_e2e_scales_mocker_fleet():
    """Real control-plane server + LocalConnector spawning real mocker
    workers.  Load spike (published saturation) grows the fleet; idle
    shrinks it; a stream in flight during the drain completes."""
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient,
        ControlPlaneServer,
    )

    async def main():
        srv = ControlPlaneServer()
        port = await srv.start()
        cp = ControlPlaneClient("127.0.0.1", port)
        await cp.start()
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        connector = LocalConnector(
            f"127.0.0.1:{port}",
            worker_args=["--mocker", "--model-name", "m",
                         "--block-size", "8", "--metrics-interval", "10"],
            env=env)
        planner = LoadPlanner(cp, connector, PlannerConfig(
            min_replicas=1, max_replicas=2, kv_high=0.8, kv_low=0.3,
            adjustment_interval=0.3, predictor="constant"))
        await planner.start()

        async def instances():
            return len(await cp.get_prefix("instances/"))

        try:
            # min_replicas bootstraps the first worker.
            deadline = time.monotonic() + 30
            while connector.replicas() < 1 and time.monotonic() < deadline:
                await asyncio.sleep(0.1)
            assert connector.replicas() == 1
            while await instances() < 1 and time.monotonic() < deadline:
                await asyncio.sleep(0.2)
            assert await instances() == 1

            # Load spike: publish saturation (the metrics pump cadence in
            # the workers is slowed so these synthetic points dominate).
            for _ in range(4):
                await cp.publish("load_metrics", {
                    "worker_id": 1, "metrics": _metrics(waiting=5,
                                                        usage=0.95)})
                await asyncio.sleep(0.2)
            deadline = time.monotonic() + 30
            while connector.replicas() < 2 and time.monotonic() < deadline:
                await asyncio.sleep(0.2)
            assert connector.replicas() == 2
            while await instances() < 2 and time.monotonic() < deadline:
                await asyncio.sleep(0.2)
            assert await instances() == 2

            # Open a stream against the soon-to-be-drained fleet, then go
            # idle: scale-down must not drop it.
            from dynamo_tpu.runtime.distributed import DistributedRuntime

            runtime = DistributedRuntime(cp)
            endpoint = (runtime.namespace("dynamo").component("backend")
                        .endpoint("generate"))
            client = await endpoint.client("round_robin")
            await client.wait_for_instances()

            async def one_stream():
                toks = []
                async for d in client.round_robin({
                        "request_id": "s1", "token_ids": list(range(24)),
                        "sampling": {"max_tokens": 24}}):
                    toks.extend(d.get("token_ids", []))
                return toks

            stream_task = asyncio.create_task(one_stream())
            await asyncio.sleep(0.05)
            for _ in range(4):
                await cp.publish("load_metrics", {
                    "worker_id": 1, "metrics": _metrics(usage=0.02)})
                await cp.publish("load_metrics", {
                    "worker_id": 2, "metrics": _metrics(usage=0.02)})
                await asyncio.sleep(0.2)
            deadline = time.monotonic() + 30
            while connector.replicas() > 1 and time.monotonic() < deadline:
                await asyncio.sleep(0.2)
            assert connector.replicas() == 1

            toks = await asyncio.wait_for(stream_task, 30)
            assert len(toks) == 24  # stream survived the drain
            await client.stop()
            await runtime.shutdown()
        finally:
            await planner.stop()
            await connector.shutdown()
            await cp.close()
            await srv.stop()

    _run(main())


def test_ar_predictor_beats_moving_average_on_diurnal_load():
    """VERDICT r5 #9: the AR(p) rung must lead a periodic (diurnal) load
    curve better than the moving average — MA predicts the recent mean
    and is always half a swing late; AR extrapolates the oscillation."""
    import math
    import random

    from dynamo_tpu.planner.predictor import (
        ARPredictor,
        MovingAveragePredictor,
        make_predictor,
    )

    rng = random.Random(0)
    period = 48
    trace = [100 + 80 * math.sin(2 * math.pi * t / period)
             + rng.gauss(0, 2) for t in range(400)]
    ar = ARPredictor(order=8, window=128)
    ma = MovingAveragePredictor(window=5)
    se_ar = se_ma = 0.0
    n = 0
    for t, v in enumerate(trace):
        if t >= 2 * period:          # both fully warmed up
            se_ar += (ar.predict_next() - v) ** 2
            se_ma += (ma.predict_next() - v) ** 2
            n += 1
        ar.add_data_point(v)
        ma.add_data_point(v)
    rmse_ar = math.sqrt(se_ar / n)
    rmse_ma = math.sqrt(se_ma / n)
    # Decisively better, not marginally (observed ~2.6 vs ~22).
    assert rmse_ar < 0.5 * rmse_ma, (rmse_ar, rmse_ma)

    # Cold-start fallback rungs: usable from the first observation.
    cold = make_predictor("ar")
    assert cold.predict_next() == 0.0
    cold.add_data_point(7.0)
    assert cold.predict_next() == 7.0
    for v in (8.0, 9.0, 10.0):
        cold.add_data_point(v)
    assert cold.predict_next() >= 10.0  # trend rung sees the ramp
