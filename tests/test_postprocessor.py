"""Tool-call parser conformance (reference postprocessor/tool_calling)."""

import json

import pytest

from dynamo_tpu.llm.postprocessor import parse_tool_calls


def _one(calls):
    assert len(calls) == 1
    c = calls[0]
    assert c["type"] == "function" and c["id"].startswith("call_")
    return c["function"]["name"], json.loads(c["function"]["arguments"])


def test_hermes_format():
    text = ('thinking...\n<tool_call>\n{"name": "get_weather", '
            '"arguments": {"city": "Oslo"}}\n</tool_call>')
    content, calls = parse_tool_calls(text)
    assert _one(calls) == ("get_weather", {"city": "Oslo"})
    assert content == "thinking..."


def test_mistral_format():
    text = ('[TOOL_CALLS][{"name": "add", "arguments": {"a": 1, "b": 2}},'
            ' {"name": "sub", "arguments": {"a": 3, "b": 1}}]')
    content, calls = parse_tool_calls(text)
    assert len(calls) == 2
    assert calls[0]["function"]["name"] == "add"
    assert content == ""


def test_plain_json_and_fenced():
    content, calls = parse_tool_calls(
        '{"name": "f", "arguments": {"x": 1}}')
    assert _one(calls) == ("f", {"x": 1})
    content, calls = parse_tool_calls(
        '```json\n{"name": "g", "parameters": {"y": 2}}\n```')
    assert _one(calls) == ("g", {"y": 2})


def test_non_tool_text_passes_through():
    for text in ("plain prose answer", '{"not_a_call": 1}', "{broken json",
                 "[1, 2, 3]"):
        content, calls = parse_tool_calls(text)
        assert calls == []
        assert content == text


def test_explicit_format_and_unknown():
    _, calls = parse_tool_calls(
        '<tool_call>{"name": "h", "arguments": {}}</tool_call>',
        fmt="hermes")
    assert len(calls) == 1
    with pytest.raises(ValueError, match="unknown tool-call format"):
        parse_tool_calls("x", fmt="nope")
