"""Tool-call parser conformance (reference postprocessor/tool_calling)."""

import json

import pytest

from dynamo_tpu.llm.postprocessor import parse_tool_calls


def _one(calls):
    assert len(calls) == 1
    c = calls[0]
    assert c["type"] == "function" and c["id"].startswith("call_")
    return c["function"]["name"], json.loads(c["function"]["arguments"])


def test_hermes_format():
    text = ('thinking...\n<tool_call>\n{"name": "get_weather", '
            '"arguments": {"city": "Oslo"}}\n</tool_call>')
    content, calls = parse_tool_calls(text)
    assert _one(calls) == ("get_weather", {"city": "Oslo"})
    assert content == "thinking..."


def test_mistral_format():
    text = ('[TOOL_CALLS][{"name": "add", "arguments": {"a": 1, "b": 2}},'
            ' {"name": "sub", "arguments": {"a": 3, "b": 1}}]')
    content, calls = parse_tool_calls(text)
    assert len(calls) == 2
    assert calls[0]["function"]["name"] == "add"
    assert content == ""


def test_plain_json_and_fenced():
    content, calls = parse_tool_calls(
        '{"name": "f", "arguments": {"x": 1}}')
    assert _one(calls) == ("f", {"x": 1})
    content, calls = parse_tool_calls(
        '```json\n{"name": "g", "parameters": {"y": 2}}\n```')
    assert _one(calls) == ("g", {"y": 2})


def test_non_tool_text_passes_through():
    for text in ("plain prose answer", '{"not_a_call": 1}', "{broken json",
                 "[1, 2, 3]"):
        content, calls = parse_tool_calls(text)
        assert calls == []
        assert content == text


def test_explicit_format_and_unknown():
    _, calls = parse_tool_calls(
        '<tool_call>{"name": "h", "arguments": {}}</tool_call>',
        fmt="hermes")
    assert len(calls) == 1
    with pytest.raises(ValueError, match="unknown tool-call format"):
        parse_tool_calls("x", fmt="nope")


def test_streaming_parser_hermes_incremental():
    """Calls emit the moment </tool_call> closes, mid-stream, with the
    OpenAI delta shape: header (index/id/type/name) then arguments."""
    from dynamo_tpu.llm.postprocessor import StreamingToolCallParser

    p = StreamingToolCallParser("auto")
    seen = []
    content = ""
    for chunk in ['thinking...\n<tool', '_call>{"name": "f",',
                  ' "arguments": {"x": 1}}</tool_call>tail']:
        c, deltas = p.push(chunk)
        content += c
        seen.extend(deltas)
    assert seen, "deltas must emit before finish()"
    assert seen[0]["index"] == 0
    assert seen[0]["id"].startswith("call_")
    assert seen[0]["function"] == {"name": "f", "arguments": ""}
    assert json.loads(seen[1]["function"]["arguments"]) == {"x": 1}
    c, deltas, has_calls = p.finish()
    assert has_calls and not deltas
    assert (content + c).startswith("thinking...")


def test_streaming_parser_json_buffers_to_end():
    from dynamo_tpu.llm.postprocessor import StreamingToolCallParser

    p = StreamingToolCallParser("auto")
    c1, d1 = p.push('{"name": "g", "argum')
    c2, d2 = p.push('ents": {"y": 2}}')
    assert (c1, d1, c2, d2) == ("", [], "", [])  # undecidable: buffered
    content, deltas, has_calls = p.finish()
    assert has_calls and content == ""
    assert deltas[0]["function"]["name"] == "g"


def test_streaming_parser_prose_passthrough_and_jail():
    from dynamo_tpu.llm.postprocessor import StreamingToolCallParser

    p = StreamingToolCallParser("auto")
    assert p.push("hello ")[0] == "hello "
    # A possible marker prefix is jailed until it diverges...
    c1, _ = p.push("a <tool")
    c2, _ = p.push("box>")
    assert c1 + c2 == "a <toolbox>"
    content, deltas, has_calls = p.finish()
    assert not has_calls and not deltas and content == ""


def test_streaming_parser_malformed_hermes_kept_as_content():
    """Unary-parity on bad JSON: a closed <tool_call> block that fails to
    parse must stream through as content, not vanish."""
    from dynamo_tpu.llm.postprocessor import StreamingToolCallParser

    p = StreamingToolCallParser("auto")
    c1, d1 = p.push("before <tool_call>{bad json</tool_call> after")
    c2, d2, has_calls = p.finish()
    assert not d1 and not d2 and not has_calls
    assert c1 + c2 == "before <tool_call>{bad json</tool_call> after"


def test_streaming_parser_forced_tool_choice():
    from dynamo_tpu.llm.postprocessor import StreamingToolCallParser

    p = StreamingToolCallParser("auto", forced_name="get_weather")
    _, d1 = p.push("Os")
    _, d2 = p.push("lo")
    assert d1[0]["function"]["name"] == "get_weather"
    assert d1[1]["function"]["arguments"] == "Os"
    assert d2[0]["function"]["arguments"] == "lo"
    _, deltas, has_calls = p.finish()
    assert has_calls and not deltas


def test_forced_tool_name_rules():
    from dynamo_tpu.llm.postprocessor import forced_tool_name

    pinned = {"type": "function", "function": {"name": "f"}}
    assert forced_tool_name(pinned, None) == "f"
    assert forced_tool_name("required",
                            [{"function": {"name": "only"}}]) == "only"
    # Several tools + "required": the model still chooses.
    assert forced_tool_name("required", [{"function": {"name": "a"}},
                                         {"function": {"name": "b"}}]) is None
    assert forced_tool_name("auto", [{"function": {"name": "x"}}]) is None
    assert forced_tool_name(None, None) is None
