"""Fleet-wide prefix reuse (ISSUE 7): router donor hints, peer-to-peer
prefix pulls, SLO-aware tier eviction.

Two-worker e2e: a request routed to a NON-holder with a >=50%-shared
prefix pulls the prefix from the donor over the kv_blocks plane,
prefills only the residual tokens (asserted via scheduler admission
counters), and emits byte-identical greedy output.  Donor death
mid-pull falls back to local prefill with zero failed requests; a
mixed-kv-quant donor is refused loudly.  The heavy full-stack fleet
variant is slow-marked (tier-1 runs close to its timeout).
"""

import asyncio
import logging

import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.pool import BlockPool, slo_eviction_bias
from dynamo_tpu.llm.block_manager.prefix_share import (
    HINT_ANNOTATION,
    PrefixFetcher,
    PrefixShareClient,
    attach_hint,
    decode_hint,
)
from dynamo_tpu.llm.block_manager.transfer import (
    KV_BLOCKS_ENDPOINT,
    make_kv_blocks_handler,
)
from dynamo_tpu.llm.kv_router.protocols import (
    KvCacheEvent,
    KvCacheEventData,
    RouterEvent,
)
from dynamo_tpu.llm.kv_router.router import KvRouter, KvRouterConfig
from dynamo_tpu.llm.kv_router.scheduler import pick_donor
from dynamo_tpu.llm.preprocessor import PreprocessedRequest
from dynamo_tpu.llm.service import LocalEngineClient
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.runtime.rpc import RpcClient

TINY = mcfg.get_config("tiny-test")
BS = 8
LONG_PROMPT = list(range(1, 36))   # 4 sealed blocks + 3-token tail


def _core(kv_quant="none", kv_event_sink=None):
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64, kv_quant=kv_quant,
        scheduler=SchedulerConfig(
            max_seqs=4, block_size=BS, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4), prefill_buckets=(8, 16))),
        kv_event_sink=kv_event_sink)


class _Worker:
    """One in-process worker: engine + RPC server with kv_blocks, plus
    a captured KV-event stream (what the real worker pumps to the
    router)."""

    def __init__(self, kv_quant="none"):
        self.kv_quant = kv_quant
        self.events = []

    async def start(self):
        from dynamo_tpu.runtime.rpc import RpcServer

        self.engine = InferenceEngine(
            _core(self.kv_quant, kv_event_sink=self.events.append))
        await self.engine.start()
        self.client = LocalEngineClient(self.engine)
        self.rpc = RpcServer()
        self.rpc.register(KV_BLOCKS_ENDPOINT,
                          make_kv_blocks_handler(self.engine))
        self.address = await self.rpc.start()
        return self

    async def stop(self):
        await self.rpc.stop()
        await self.engine.stop()


async def _collect(client, rid, prompt, n=4, annotations=None):
    req = PreprocessedRequest(request_id=rid, model="m",
                              token_ids=list(prompt),
                              sampling=SamplingParams(max_tokens=n),
                              annotations=dict(annotations or {}))
    out = []
    async for d in client.generate(req):
        out.extend(d.token_ids)
        if d.finished:
            assert d.finish_reason is not None
            assert d.finish_reason.value != "error"
            break
    return out


def _run(coro):
    return asyncio.run(asyncio.wait_for(coro, 180))


def _route_spilled(donor_events, prompt, donor_id="A", other_id="B"):
    """Feed the donor's real KV events into a KvRouter, load the donor
    so the selector spills the repeat request onto the other worker,
    and return (chosen, overlap, last_donor)."""
    router = KvRouter(KvRouterConfig(block_size=BS))
    for ev in donor_events:
        router.apply_event(RouterEvent(worker_id=donor_id, event=ev))
    # The donor is busy: optimistic accounting carries a fat in-flight
    # request, so the spilled worker wins on load despite zero overlap.
    router.active.add_request("busy", donor_id, 512, 0,
                              expected_output_tokens=512)
    chosen, overlap = router.find_best_match(
        "r1", prompt, [donor_id, other_id])
    return router, chosen, overlap


def test_remote_prefix_pull_e2e():
    """A >=50%-shared-prefix request lands on the non-holder, pulls the
    donor's 4 sealed blocks peer-to-peer, prefills only the 3-token
    residual, and emits byte-identical greedy output."""

    async def main():
        wa = await _Worker().start()
        wb = await _Worker().start()
        rpc = RpcClient(wa.address)
        try:
            want = await _collect(wa.client, "seed", LONG_PROMPT)
            router, chosen, overlap = _route_spilled(
                [e for e in wa.events], LONG_PROMPT)
            assert chosen == "B" and overlap == 0
            donor = router.last_donor
            assert donor is not None and donor.worker_id == "A"
            assert donor.overlap_blocks == 4

            fetcher = PrefixFetcher(wb.engine, lambda a: rpc, BS)
            psc = PrefixShareClient(wb.client, fetcher)
            req = PreprocessedRequest(
                request_id="r1", model="m", token_ids=list(LONG_PROMPT),
                sampling=SamplingParams(max_tokens=4))
            attach_hint(req, wa.address, donor.overlap_blocks * BS,
                        donor.worker_id)
            got = []
            async for d in psc.generate(req):
                got.extend(d.token_ids)
                if d.finished:
                    break
            assert got == want                       # byte-identical
            sched = wb.engine.core.scheduler
            # Residual-only prefill: the 4 pulled blocks hit at
            # admission; only the 3-token tail missed.
            assert sched.prefix_hit_tokens == 4 * BS
            assert sched.prefix_miss_tokens == len(LONG_PROMPT) - 4 * BS
            assert fetcher.remote_hits == 1
            assert fetcher.pulled_blocks == 4
            assert fetcher.fallbacks == 0
            assert wb.engine.core.allocator.manager.onboarded_blocks == 4
        finally:
            await rpc.close()
            await wa.stop()
            await wb.stop()

    _run(main())


def test_donor_death_falls_back_to_local():
    """The hint points at a dead donor: the pull fails over to plain
    local prefill — zero failed requests, byte-identical output."""

    async def main():
        wa = await _Worker().start()
        wb = await _Worker().start()
        try:
            want = await _collect(wa.client, "seed", LONG_PROMPT)
            dead_address = wa.address
            await wa.rpc.stop()     # donor dies before the pull

            fetcher = PrefixFetcher(wb.engine, lambda a: RpcClient(a), BS,
                                    pull_timeout=10.0)
            psc = PrefixShareClient(wb.client, fetcher)
            got = await _collect(psc, "r1", LONG_PROMPT, annotations={
                HINT_ANNOTATION:
                    '{"address": "%s", "covered_tokens": %d}'
                    % (dead_address, 4 * BS)})
            assert got == want                       # request survived
            assert fetcher.fallbacks == 1
            assert fetcher.remote_hits == 0
            sched = wb.engine.core.scheduler
            assert sched.prefix_hit_tokens == 0      # full local prefill
        finally:
            await wa.engine.stop()   # rpc already stopped mid-test
            await wb.stop()

    _run(main())


def test_mixed_kv_quant_peer_refused_loudly(caplog):
    """An int8 donor's packed blocks must be REFUSED by a bf16 worker —
    pointed error log, fallback to local prefill, no junk in the cache."""

    async def main():
        wa = await _Worker(kv_quant="int8").start()
        wb = await _Worker().start()
        rpc = RpcClient(wa.address)
        try:
            await _collect(wa.client, "seed", LONG_PROMPT)
            fetcher = PrefixFetcher(wb.engine, lambda a: rpc, BS)
            psc = PrefixShareClient(wb.client, fetcher)
            with caplog.at_level(
                    logging.ERROR,
                    logger="dynamo_tpu.llm.block_manager.prefix_share"):
                got = await _collect(psc, "r1", LONG_PROMPT, annotations={
                    HINT_ANNOTATION:
                        '{"address": "%s", "covered_tokens": %d}'
                        % (wa.address, 4 * BS)})
            assert fetcher.fallbacks == 1 and fetcher.remote_hits == 0
            assert any("REFUSED" in r.message for r in caplog.records)
            mgr = wb.engine.core.allocator.manager
            assert mgr.onboarded_blocks == 0         # nothing injected
            # The fallback output is a plain deterministic local decode:
            # a repeat of the same prompt reproduces it exactly.
            again = await _collect(wb.client, "r2", LONG_PROMPT)
            assert got == again and len(got) == 4
        finally:
            await rpc.close()
            await wa.stop()
            await wb.stop()

    _run(main())


import numpy as np

from dynamo_tpu.llm.block_manager.transfer import encode_block, sealed_hashes

_PROMPT4 = list(range(1, 4 * BS + 1))            # 4 sealed blocks
_HASHES4 = sealed_hashes(_PROMPT4, BS)
_BLOCK = np.zeros((2, 1, BS, 4), np.float32)


class _ScriptWire:
    """kv_blocks stub: call N fails when N is in `fail_calls`; counts
    blocks actually served over the wire."""

    def __init__(self, fail_calls=(), die_after=None):
        self.fail_calls = set(fail_calls)
        self.die_after = die_after   # every call past this one fails
        self.calls = 0
        self.served = 0

    def call(self, endpoint, payload):
        self.calls += 1
        n = self.calls

        async def gen():
            if n in self.fail_calls or (self.die_after is not None
                                        and n > self.die_after):
                raise ConnectionError("donor died")
            for h in payload["hashes"]:
                self.served += 1
                yield encode_block(h, _BLOCK)

        return gen()


class _Sink:
    def __init__(self, accept=True):
        self.accept = accept
        self.imported = []

    async def import_blocks(self, blocks):
        if not self.accept:
            return 0
        self.imported.extend(blocks)
        return len(blocks)

    async def resident_prefix_blocks(self, hashes):
        n = 0
        for h in hashes:
            if n < len(self.imported) and self.imported[n] == h:
                n += 1
            else:
                break
        return n


def test_partial_pull_keeps_landed_prefix():
    """The donor dies mid-pull: the contiguous prefix that landed stays
    injected and is counted; the failure still registers a fallback."""

    async def main():
        wire = _ScriptWire(die_after=1)   # first batch lands, then death
        sink = _Sink()
        fetcher = PrefixFetcher(sink, lambda a: wire, BS,
                                max_inflight=1, batch_blocks=2)
        covered = await fetcher.pull(_PROMPT4, "dead", 4 * BS)
        # First 2-block batch landed before the death...
        assert covered == 2 * BS
        assert sink.imported == _HASHES4[:2]
        # ...and the accounting shows both the partial hit and the
        # fallback the residual failure triggered.
        assert fetcher.remote_hits == 1
        assert fetcher.pulled_blocks == 2
        assert fetcher.fallbacks == 1

    _run(main())


def test_gap_refetch_reuses_post_gap_blocks():
    """A transient failure on one batch refetches ONLY the gap: blocks
    that already crossed the wire are injected, not re-pulled."""

    async def main():
        wire = _ScriptWire(fail_calls={1})   # batch [0,2) fails once
        sink = _Sink()
        fetcher = PrefixFetcher(sink, lambda a: wire, BS,
                                max_inflight=2, batch_blocks=2)
        covered = await fetcher.pull(_PROMPT4, "flaky", 4 * BS)
        assert covered == 4 * BS
        assert sink.imported == _HASHES4
        # Wire traffic = the 4 prefix blocks exactly: the surviving
        # batch's 2 blocks were reused, only the gap was refetched.
        assert wire.served == 4
        assert fetcher.remote_hits == 1 and fetcher.pulled_blocks == 4
        assert fetcher.fallbacks == 0

    _run(main())


def test_concurrent_same_prefix_pulls_dedup():
    """A burst of requests carrying the same hint transfers the prefix
    ONCE: later pulls wait on the in-flight pull, find the blocks
    resident, and skip the wire."""

    async def main():
        wire = _ScriptWire()
        sink = _Sink()
        fetcher = PrefixFetcher(sink, lambda a: wire, BS,
                                max_inflight=2, batch_blocks=2)
        covered = await asyncio.gather(*(
            fetcher.pull(_PROMPT4, "donor", 4 * BS) for _ in range(3)))
        assert covered == [4 * BS] * 3
        assert wire.served == 4          # one transfer, not three
        assert sink.imported == _HASHES4
        assert fetcher.remote_hits == 1  # the burst is ONE remote hit
        assert fetcher.pulled_blocks == 4

    _run(main())


def test_capacity_stall_reports_no_phantom_hits():
    """A device pool that refuses injects must not report remote hits —
    and the fetcher stops burning wire on blocks it cannot land."""

    async def main():
        wire = _ScriptWire()
        sink = _Sink(accept=False)           # pool pinned full
        fetcher = PrefixFetcher(sink, lambda a: wire, BS,
                                max_inflight=1, batch_blocks=2)
        covered = await fetcher.pull(_PROMPT4, "full", 4 * BS)
        assert covered == 0
        assert fetcher.remote_hits == 0
        assert fetcher.pulled_blocks == 0
        # The stall short-circuits the remaining batches.
        assert wire.served <= 2

    _run(main())


# -- router policy units --------------------------------------------------


def test_pick_donor_policy_and_tiebreak():
    # Qualifying donor: covers >= 50% of 8 blocks and beats chosen by 2.
    d = pick_donor({"A": 6, "B": 0}, chosen="B", chosen_overlap=0,
                   request_blocks=8)
    assert d is not None and d.worker_id == "A" and d.overlap_blocks == 6
    # Below the coverage floor: no donor.
    assert pick_donor({"A": 3, "B": 0}, "B", 0, 8) is None
    # Insufficient gain over the chosen worker's own overlap.
    assert pick_donor({"A": 6, "B": 5}, "B", 5, 8) is None
    # The chosen worker never donates to itself.
    assert pick_donor({"B": 8}, "B", 8, 8) is None
    # Deterministic tie-break: equal overlap -> lowest worker id.
    for _ in range(5):
        d = pick_donor({"C": 6, "A": 6, "B": 6}, "Z", 0, 8)
        assert d.worker_id == "A"
    # Integer lease ids compare NUMERICALLY: worker 2 beats worker 10.
    d = pick_donor({10: 6, 2: 6}, 99, 0, 8)
    assert d.worker_id == 2


def test_router_donor_lifecycle_and_dead_purge():
    """last_donor comes from the live worker set and the indexer;
    remove_worker purges the index so hints never name dead donors."""
    from dynamo_tpu.llm.block_manager.transfer import sealed_hashes

    prompt = list(range(1, 36))
    hashes = sealed_hashes(prompt, BS)
    router = KvRouter(KvRouterConfig(block_size=BS))
    router.apply_event(RouterEvent(worker_id="A", event=KvCacheEvent(
        event_id=1, data=KvCacheEventData.stored(hashes))))
    router.active.add_request("busy", "A", 512, 0,
                              expected_output_tokens=512)
    chosen, _ = router.find_best_match("r1", prompt, ["A", "B"])
    assert chosen == "B"
    assert router.last_donor is not None
    assert router.last_donor.worker_id == "A"
    # A dead donor outside the live set is never offered...
    router.find_best_match("r2", prompt, ["B"], update_states=False)
    assert router.last_donor is None
    # ...and remove_worker purges its residency outright.
    router.remove_worker("A")
    assert router.indexer.find_matches(hashes).scores == {}
    router.find_best_match("r3", prompt, ["A", "B"], update_states=False)
    assert router.last_donor is None


def test_hint_codec_tolerates_garbage():
    req = PreprocessedRequest(request_id="r", model="m", token_ids=[1],
                              sampling=SamplingParams(max_tokens=1))
    attach_hint(req, "1.2.3.4:5", 64, "w7")
    h = decode_hint(req.annotations[HINT_ANNOTATION])
    assert h == {"address": "1.2.3.4:5", "covered_tokens": 64,
                 "worker": "w7"}
    assert decode_hint(None) is None
    assert decode_hint("") is None
    assert decode_hint("not json") is None
    assert decode_hint('{"covered_tokens": 8}') is None      # no address
    assert decode_hint('{"address": "x", "covered_tokens": 0}') is None


# -- SLO-aware eviction bias ----------------------------------------------


def _inactive_pool(hot_hash):
    """A full pool of inactive registered blocks 1..4 (LRU order 1
    oldest) with `hot_hash` carrying prefix-cache hit history.  Hits are
    stamped directly (an acquire/release would ALSO revive the block to
    MRU — the bias exists precisely for hot blocks that have aged back
    to the LRU head since their last hit)."""
    pool = BlockPool(4, name="t")
    for h in (1, 2, 3, 4):
        [s] = pool.allocate(1)
        pool.register(s, h)
        pool.release([s])
    pool.registry.lookup(hot_hash).hits = 2
    return pool


def test_acquire_matched_counts_slot_hits():
    pool = BlockPool(2, name="t")
    [s] = pool.allocate(1)
    pool.register(s, 7)
    pool.release([s])
    slots = pool.match_sequence_hashes([7])
    pool.release(pool.acquire_matched(slots))
    assert pool.registry.lookup(7).hits == 1


def test_slo_eviction_bias_protects_hot_blocks():
    burn = {"v": 0.0}
    # Budget healthy: pure LRU — the hot-but-oldest block 1 is evicted.
    pool = _inactive_pool(hot_hash=1)
    pool.set_eviction_bias(slo_eviction_bias(lambda: burn["v"]))
    pool.allocate(1)
    assert pool.registry.lookup(1) is None
    assert pool.bias_protected == 0
    # Budget burning: the hot LRU-oldest block survives; the oldest COLD
    # block goes instead.
    pool = _inactive_pool(hot_hash=1)
    pool.set_eviction_bias(slo_eviction_bias(lambda: burn["v"]))
    burn["v"] = 2.0
    pool.allocate(1)
    assert pool.registry.lookup(1) is not None   # hot prefix kept
    assert pool.registry.lookup(2) is None       # cold LRU evicted
    assert pool.bias_protected == 1
    # A broken burn signal degrades to LRU instead of wedging eviction.
    pool = _inactive_pool(hot_hash=1)

    def boom():
        raise RuntimeError("signal gone")

    pool.set_eviction_bias(slo_eviction_bias(boom))
    pool.allocate(1)
    assert pool.registry.lookup(1) is None


def test_manager_bias_applies_to_demoting_tiers():
    from dynamo_tpu.llm.block_manager.manager import (
        KvBlockManager, TieredConfig)

    mgr = KvBlockManager(TieredConfig(
        device_blocks=8, host_blocks=4, block_size=BS))
    bias = slo_eviction_bias(lambda: 2.0)
    mgr.set_eviction_bias(bias)
    assert mgr.device.eviction_bias is bias
    assert mgr.host.eviction_bias is bias
    mgr.close()


# -- metrics + dynamo top -------------------------------------------------


class _StubFetcher:
    remote_hits = 2
    pulled_blocks = 9
    fallbacks = 1


def test_prefix_share_metrics_deltas():
    from dynamo_tpu.runtime.metrics import KvCacheMetrics, MetricsRegistry

    reg = MetricsRegistry()
    kv = KvCacheMetrics(reg)
    kv.observe_prefix_share(_StubFetcher())
    kv.observe_prefix_share(_StubFetcher())   # same cumulatives: no double
    text = reg.expose()
    assert "dynamo_prefix_remote_hits_total 2" in text
    assert "dynamo_prefix_remote_pulled_blocks_total 9" in text
    assert "dynamo_prefix_remote_fallbacks_total 1" in text


def test_dynamo_top_remote_hit_column():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "dynamo_top", os.path.join(os.path.dirname(__file__), "..",
                                   "tools", "dynamo_top.py"))
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    samples = [("dynamo_prefix_remote_hits_total", {}, 3.0),
               ("dynamo_prefix_remote_fallbacks_total", {}, 1.0)]
    row = top.summarize("worker-both", "127.0.0.1:1", samples, None)
    assert row["remote_hits"] == 3.0
    assert row["remote_fallbacks"] == 1.0
    table = top.render_table({"control_plane": "cp", "processes": [row]})
    assert "RHIT" in table.splitlines()[1]
    assert any(h == "RHIT" for h, _, _ in top.COLUMNS)


# -- full-stack fleet variant (heavy: real engines behind the runtime) ----


@pytest.mark.slow
def test_fleet_prefix_share_full_stack():
    """The wiring worker/main.py + the frontend use, end to end: real
    engines served over the runtime with PrefixShareClient, KV events
    pumped to a KvRoutedEngineClient that attaches hints; concurrent
    shared-prefix requests spill off the holder and pull the prefix
    peer-to-peer."""
    from dynamo_tpu.llm.discovery import engine_wire_handler
    from dynamo_tpu.llm.kv_router.client import KvRoutedEngineClient
    from dynamo_tpu.runtime.control_plane import InProcessControlPlane
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        rts = [DistributedRuntime(cp), DistributedRuntime(cp)]
        workers, fetchers, insts = [], [], []
        for rt in rts:
            w = _Worker()
            w.engine = InferenceEngine(
                _core(kv_event_sink=w.events.append))
            await w.engine.start()
            w.client = LocalEngineClient(w.engine)
            rt.rpc.register(KV_BLOCKS_ENDPOINT,
                            make_kv_blocks_handler(w.engine))
            fetcher = PrefixFetcher(w.engine, rt.client_for, BS)
            serve = PrefixShareClient(w.client, fetcher)
            ep = (rt.namespace("dyn").component("backend")
                  .endpoint("generate"))
            inst = await ep.serve(engine_wire_handler(serve))
            workers.append(w)
            fetchers.append(fetcher)
            insts.append(inst)

        async def pump(w, iid):
            sent = 0
            while True:
                await asyncio.sleep(0.005)
                while sent < len(w.events):
                    ev = w.events[sent]
                    sent += 1
                    await cp.publish("kv_events", RouterEvent(
                        worker_id=iid, event=ev).to_dict())

        pumps = [asyncio.create_task(pump(w, inst.instance_id))
                 for w, inst in zip(workers, insts)]
        client = await (rts[0].namespace("dyn").component("backend")
                        .endpoint("generate").client())
        await client.wait_for_instances()
        kv = KvRoutedEngineClient(client, rts[0], block_size=BS)
        await kv.start()

        async def run_one(rid, n=4):
            out = []
            req = PreprocessedRequest(
                request_id=rid, model="m", token_ids=list(LONG_PROMPT),
                sampling=SamplingParams(max_tokens=n))
            async for d in kv.generate(req):
                out.extend(d.token_ids)
            return out

        try:
            want = await run_one("warm", n=4)
            await asyncio.sleep(0.1)          # let STORED events index
            # Concurrent repeats: optimistic load spills some off the
            # holder; spilled ones carry hints and pull peer-to-peer.
            outs = await asyncio.gather(*(run_one(f"r{i}", n=16)
                                          for i in range(4)))
            assert all(o[:4] == want for o in outs)
            assert kv.remote_hint_routes >= 1
            assert sum(f.remote_hits for f in fetchers) >= 1
            assert sum(f.fallbacks for f in fetchers) == 0
        finally:
            for t in pumps:
                t.cancel()
            await kv.stop()
            await client.stop()
            for w in workers:
                await w.engine.stop()
            for rt in rts:
                await rt.shutdown()
            await cp.close()

    _run(main())
