"""Generalized cross-mesh KV reshard (ISSUE 16 tentpole).

Property grid: a wire block exported from ANY source mesh layout lands
on ANY destination engine's `block_inject_sharding` and injects
byte-identically — bf16 and packed int8 — with the landing sharded like
the destination CACHE (zero device-0 pileup), not gathered onto one
chip.  Same tiny geometry as tests/test_compose_matrix.py so the grid
lowers to already-cached HLO.

E2E: a heterogeneous disagg cell — ring-SP int8 prefill slice feeding a
head-sharded tp int8 decode slice — serves byte-identical greedy output
vs the meshless oracle with the KV crossing on the DEVICE plane
(device_pulls and reshard_pulls counters pinned).
"""

import asyncio

import jax
import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore, InferenceEngine
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.llm.block_manager.transfer import sealed_hashes
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.parallel import MeshConfig, make_mesh

TINY = mcfg.get_config("tiny-test")
BS = 8

# SAME geometry as tests/test_compose_matrix.py / test_sharded_serving.py.
SCHED = dict(max_seqs=4, block_size=BS, max_pages_per_seq=8,
             max_prefill_chunk=16, decode_buckets=(2, 4),
             prefill_buckets=(8, 16))

# src×dst layouts the reshard must cross: replicated, head-sharded tp,
# replicated-cache dp, and the ring-SP mesh (sp×tp).
GRID_MESHES = {
    "meshless": (None, {}),
    "tp2": (MeshConfig(tp=2), {}),
    "dp2": (MeshConfig(dp=2), {}),
    "sp2": (MeshConfig(sp=2, tp=2), dict(sp_prefill_threshold=8)),
}

# One DISTINCT prompt per source mesh (3 sealed blocks + tail each), so
# every destination can inject every source's blocks without hash
# collisions against its own resident set.
GRID_PROMPTS = {name: list(range(1 + 40 * i, 28 + 40 * i))
                for i, name in enumerate(GRID_MESHES)}


def _core(mesh_name=None, kv_quant="none", **extra):
    kwargs = dict(extra)
    mesh = None
    if mesh_name is not None and GRID_MESHES[mesh_name][0] is not None:
        mesh_cfg, mesh_kwargs = GRID_MESHES[mesh_name]
        mesh = make_mesh(mesh_cfg, jax.devices()[:mesh_cfg.size])
        kwargs.update(mesh_kwargs)
    return EngineCore(EngineConfig(
        model=TINY, num_blocks=64, mesh=mesh, kv_quant=kv_quant,
        scheduler=SchedulerConfig(**SCHED), **kwargs))


def _populate(core, prompt):
    core.add_request("seed", list(prompt), SamplingParams(max_tokens=2))
    for _ in range(100):
        core.step()
        if not core._requests:
            return
    raise AssertionError("engine did not finish the seed request")


def _grid(kv_quant):
    engines = {}
    host_export = {}
    dev_export = {}
    hashes = {}
    for name in GRID_MESHES:
        core = _core(name, kv_quant)
        _populate(core, GRID_PROMPTS[name])
        h = sealed_hashes(GRID_PROMPTS[name], BS)
        assert len(h) == 3
        exp = core.export_blocks(h)
        assert set(exp) == set(h)
        engines[name] = core
        hashes[name] = h
        host_export[name] = {k: np.asarray(v) for k, v in exp.items()}
        # Source-layout device export: what the local-fabric transport
        # stages (no canonical gather onto device 0).
        dev_export[name] = core.export_blocks_device(h, canonical=False)

    for dst_name, dst in engines.items():
        dst.clear_prefix_cache()
        landing = dst.block_inject_sharding
        for src_name in engines:
            landed = {h: jax.device_put(a, landing)
                      for h, a in dev_export[src_name].items()}
            if dst.mesh is not None:
                # Zero device-0 pileup: the landing spans the dest mesh
                # (cache-sharded or replicated), never one chip.
                for a in landed.values():
                    assert len(a.sharding.device_set) > 1, \
                        f"{src_name}->{dst_name} piled onto one device"
            assert dst.import_blocks(landed) == 3, \
                f"{src_name}->{dst_name} inject rejected blocks"
            got = dst.export_blocks(hashes[src_name])
            for h in hashes[src_name]:
                a, b = host_export[src_name][h], np.asarray(got[h])
                assert a.dtype == b.dtype and a.shape == b.shape
                assert np.array_equal(a, b), \
                    f"{src_name}->{dst_name} block {h} corrupted bytes"


def test_reshard_grid_bf16():
    _grid("none")


def test_reshard_grid_int8_packed():
    # The packed int8 wire block ([2, L, bs, F + 4*Hkv] with in-band
    # f32 scales) must survive the same src×dst reshard byte-identically.
    _grid("int8")


def test_heterogeneous_disagg_serves_oracle_output():
    """Tentpole e2e: sp-prefill slice (sp2xtp2, int8) feeds a tp decode
    slice (tp2, int8) through the device transfer plane; greedy output
    is byte-identical to the meshless oracle and the reshard counters
    prove the path taken (ISSUE 16 acceptance: device counters > 0)."""
    from dynamo_tpu.llm.block_manager.device_transfer import (
        KV_OFFER_ENDPOINT, KV_PULLED_ENDPOINT, KvTransferPlane)
    from dynamo_tpu.llm.block_manager.transfer import (
        KV_BLOCKS_ENDPOINT, make_kv_blocks_handler)
    from dynamo_tpu.llm.disagg import (
        DisaggDecodeClient, disagg_config_key, prefill_worker_loop)
    from dynamo_tpu.llm.preprocessor import PreprocessedRequest
    from dynamo_tpu.llm.service import LocalEngineClient
    from dynamo_tpu.runtime.control_plane import InProcessControlPlane
    from dynamo_tpu.runtime.rpc import RpcServer

    NS = "test-topology"

    class _Worker:
        async def start(self, mesh_name=None, kv_quant="int8"):
            self.engine = InferenceEngine(_core(mesh_name, kv_quant))
            await self.engine.start()
            self.client = LocalEngineClient(self.engine)
            self.plane = KvTransferPlane(self.engine)
            self.plane.start()
            self.rpc = RpcServer()
            self.rpc.register(KV_BLOCKS_ENDPOINT,
                              make_kv_blocks_handler(self.engine))
            self.rpc.register(KV_OFFER_ENDPOINT,
                              self.plane.make_offer_handler())
            self.rpc.register(KV_PULLED_ENDPOINT,
                              self.plane.make_pulled_handler())
            self.address = await self.rpc.start()
            return self

        async def stop(self):
            await self.rpc.stop()
            self.plane.stop()
            await self.engine.stop()

    async def _collect(client, rid, prompt, n=4):
        req = PreprocessedRequest(request_id=rid, model="m",
                                  token_ids=list(prompt),
                                  sampling=SamplingParams(max_tokens=n))
        out = []
        async for d in client.generate(req):
            out.extend(d.token_ids)
            if d.finished:
                break
        return out

    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        await cp.put(disagg_config_key(NS), {"max_local_prefill_length": 12})

        prefill = await _Worker().start("sp2")   # ring-SP prefill slice
        decode = await _Worker().start("tp2")    # head-sharded decode slice
        ploop = asyncio.create_task(prefill_worker_loop(
            cp, NS, prefill.client, prefill.address))
        dec = DisaggDecodeClient(decode.client, decode.engine, cp, NS, BS,
                                 transfer_plane=decode.plane)
        await dec.start()
        try:
            # Meshless oracle, same kv mode (wire peers must share it).
            oracle = InferenceEngine(_core(None, "int8"))
            await oracle.start()
            long_prompt = list(range(1, 28))  # 3 sealed blocks + tail
            want = await _collect(LocalEngineClient(oracle), "ref",
                                  long_prompt)
            await oracle.stop()

            got = await _collect(dec, "r1", long_prompt)
            assert got == want                 # byte-identical greedy
            assert dec.remote_prefills == 1 and dec.local_fallbacks == 0
            assert dec.device_pulls >= 1       # KV crossed device plane
            assert dec.tokens_onboarded == 24
            assert prefill.plane.offers >= 1
            assert decode.plane.pulled_blocks == 3
            # Every pulled block landed SHARDED on the decode mesh (the
            # in-flight sp2-layout -> tp2-layout reshard), not piled on
            # one chip and re-laid at inject.
            assert decode.plane.reshard_pulls == 3
            mgr = decode.engine.core.allocator.manager
            assert mgr.onboarded_blocks == 3
        finally:
            ploop.cancel()
            await dec.stop()
            await prefill.stop()
            await decode.stop()
            await cp.close()

    asyncio.run(asyncio.wait_for(main(), 180))
