"""Ring attention (sp axis) vs the causal-attention oracle.

SURVEY §2.5 + VERDICT r2 item 8: ring/blockwise SP prefill attention
over the previously-dead sp axis, parity-tested on the 8-device CPU
mesh and wired into a sharded prefill step.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_forward_step
from dynamo_tpu.ops.attention import causal_attention
from dynamo_tpu.ops.ring_attention import ring_causal_attention
from dynamo_tpu.runtime.jax_compat import shard_map
from dynamo_tpu.parallel import (
    MeshConfig,
    cache_pspecs,
    make_mesh,
    make_sp_prefill_step,
    param_pspecs,
    shard_pytree,
)

CFG = mcfg.get_config("tiny-test")
BLOCK = 8


def _qkv(B, T, Hq, Hkv, D, key=0):
    ks = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(ks[0], (B, T, Hq, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    return q, k, v


def test_ring_single_shard_matches_causal():
    B, T, Hq, Hkv, D = 2, 16, 8, 4, 16
    q, k, v = _qkv(B, T, Hq, Hkv, D)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    want = causal_attention(q, k, v)
    got = ring_causal_attention(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


def test_ring_sharded_matches_causal():
    """shard_map over sp=8: every K/V block must make the full circuit."""
    B, T, Hq, Hkv, D = 2, 64, 8, 4, 16
    q, k, v = _qkv(B, T, Hq, Hkv, D, key=1)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    want = causal_attention(q, k, v)

    mesh = make_mesh(MeshConfig(sp=8), jax.devices())
    spec4 = P(None, "sp", None, None)
    fn = shard_map(
        lambda qs, ks, vs, ps: ring_causal_attention(qs, ks, vs, ps,
                                                     axis_name="sp"),
        mesh=mesh,
        in_specs=(spec4, spec4, spec4, P(None, "sp")),
        out_specs=spec4, check_vma=False)
    got = fn(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


def test_sp_prefill_step_matches_unsharded():
    """Full-prompt prefill over dp=2 x sp=2 x tp=2: logits AND the
    written KV cache must match the single-device step."""
    params = init_params(CFG, jax.random.key(0))
    batch, T = 4, 16
    tokens = jax.random.randint(jax.random.key(5), (batch, T), 0,
                                CFG.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (batch, T))
    bt = np.zeros((batch, 8), np.int32)
    for i in range(batch):
        bt[i, :4] = np.arange(1 + 4 * i, 5 + 4 * i)
    seq_lens = jnp.full((batch,), T, jnp.int32)
    sample_pos = jnp.full((batch,), T - 1, jnp.int32)
    inputs = (tokens, positions, seq_lens, jnp.asarray(bt), sample_pos)

    def fresh_cache():
        return kvc.init_cache(kvc.KvCacheConfig.for_model(
            CFG, num_blocks=64, block_size=BLOCK, dtype=jnp.float32))

    ref_step = make_forward_step(CFG, BLOCK)
    want, want_cache = ref_step(params, fresh_cache(), *inputs)

    mesh = make_mesh(MeshConfig(dp=2, sp=2, tp=2), jax.devices())
    sharded = shard_pytree(params, param_pspecs(CFG), mesh)
    cache = shard_pytree(fresh_cache(), cache_pspecs(CFG.num_layers), mesh)
    step = make_sp_prefill_step(CFG, BLOCK, mesh)
    got, got_cache = step(sharded, cache, *inputs)

    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=5e-4, atol=5e-4)
    # The sp-sharded chunk writes must land replica-consistent in the
    # paged cache (the decode continuation depends on it).
    np.testing.assert_allclose(
        np.asarray(want_cache["k"][0]), np.asarray(got_cache["k"][0]),
        rtol=5e-4, atol=5e-4)


def test_sp_prefill_then_decode_continues():
    """Prefill via the sp ring step, then decode one token with the
    regular step on the same cache — output equals a full unsharded run."""
    params = init_params(CFG, jax.random.key(0))
    batch, T = 2, 16
    tokens = jax.random.randint(jax.random.key(7), (batch, T), 0,
                                CFG.vocab_size)
    positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (batch, T))
    bt = np.zeros((batch, 8), np.int32)
    for i in range(batch):
        bt[i, :4] = np.arange(1 + 4 * i, 5 + 4 * i)
    bt = jnp.asarray(bt)

    def fresh_cache():
        return kvc.init_cache(kvc.KvCacheConfig.for_model(
            CFG, num_blocks=64, block_size=BLOCK, dtype=jnp.float32))

    ref_step = make_forward_step(CFG, BLOCK)
    logits, ref_cache = ref_step(
        params, fresh_cache(), tokens, positions,
        jnp.full((batch,), T, jnp.int32), bt,
        jnp.full((batch,), T - 1, jnp.int32))
    nxt = jnp.argmax(logits, -1).astype(jnp.int32)[:, None]
    want, _ = ref_step(
        params, ref_cache, nxt, jnp.full((batch, 1), T, jnp.int32),
        jnp.full((batch,), T + 1, jnp.int32), bt,
        jnp.zeros((batch,), jnp.int32))

    mesh = make_mesh(MeshConfig(dp=2, sp=4), jax.devices())
    sharded = shard_pytree(params, param_pspecs(CFG), mesh)
    cache = shard_pytree(fresh_cache(), cache_pspecs(CFG.num_layers), mesh)
    sp_step = make_sp_prefill_step(CFG, BLOCK, mesh)
    logits2, cache = sp_step(
        sharded, cache, tokens, positions,
        jnp.full((batch,), T, jnp.int32), bt,
        jnp.full((batch,), T - 1, jnp.int32))
    nxt2 = jnp.argmax(logits2, -1).astype(jnp.int32)[:, None]
    np.testing.assert_array_equal(np.asarray(nxt), np.asarray(nxt2))

    from dynamo_tpu.parallel import make_sharded_step

    dec_step = make_sharded_step(CFG, BLOCK, mesh)
    got, _ = dec_step(
        sharded, cache, nxt2, jnp.full((batch, 1), T, jnp.int32),
        jnp.full((batch,), T + 1, jnp.int32), bt,
        jnp.zeros((batch,), jnp.int32))
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=5e-4, atol=5e-4)
