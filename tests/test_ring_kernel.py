"""Pallas flash ring-attention kernel vs the XLA ring and causal oracle.

ISSUE 19: the kernel body runs in interpret mode on the CPU mesh (the
generalized remote-DMA discharge patch in ops/pallas/ring_attention.py
makes `make_async_remote_copy` interpretable on the repo's 5-axis
meshes), so tier-1 pins its numerics — bf16-path and int8
dequant-in-VMEM, soft_cap, fully-masked padding rows, degenerate sp=1 —
against `ring_causal_attention` (the XLA ppermute fallback, which stays
the oracle) and the meshless `causal_attention`.  Eligibility
(`ring_geometry_ok` / `ring_kernel_supported`) is tested as the ONE
predicate every dispatch site shares.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.ops.attention import causal_attention
from dynamo_tpu.ops.pallas.ring_attention import (
    ring_flash_attention,
    ring_geometry_ok,
    ring_kernel_supported,
)
from dynamo_tpu.ops.ring_attention import ring_causal_attention
from dynamo_tpu.parallel import MeshConfig, make_mesh
from dynamo_tpu.runtime.jax_compat import shard_map

B, T, Hq, Hkv, D = 2, 32, 4, 2, 32
SPEC4 = P("dp", "sp", "tp", None)
SPEC3 = P("dp", "sp", "tp")
SPEC2 = P("dp", "sp")


@pytest.fixture(scope="module")
def mesh():
    # sp=4 x tp=2 exercises multi-hop RDMA on a multi-axis mesh (the
    # LOGICAL-device-id flattening the kernel computes is nontrivial
    # exactly when another axis sits inside sp's stride).
    return make_mesh(MeshConfig(sp=4, tp=2))


def _qkv(key=0):
    ks = jax.random.split(jax.random.key(key), 3)
    q = jax.random.normal(ks[0], (B, T, Hq, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, T, Hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, T, Hkv, D), jnp.float32)
    return q, k, v


def _run(mesh, fn, *args, specs):
    f = shard_map(fn, mesh=mesh, in_specs=specs, out_specs=SPEC4,
                  check_vma=False)
    return np.asarray(jax.jit(f)(*args))


@pytest.mark.parametrize("soft_cap", [None, 30.0])
def test_kernel_matches_xla_ring_and_causal(mesh, soft_cap):
    q, k, v = _qkv()
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    specs = (SPEC4, SPEC4, SPEC4, SPEC2)
    got = _run(mesh, lambda qs, ks, vs, ps: ring_flash_attention(
        qs, ks, vs, ps, mesh=mesh, soft_cap=soft_cap, interpret=True),
        q, k, v, pos, specs=specs)
    want = _run(mesh, lambda qs, ks, vs, ps: ring_causal_attention(
        qs, ks, vs, ps, axis_name="sp", soft_cap=soft_cap),
        q, k, v, pos, specs=specs)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)
    # Meshless oracles: single-shard ring (soft_cap-aware) always, plain
    # causal_attention on the uncapped path.
    oracle = np.asarray(ring_causal_attention(q, k, v, pos,
                                              soft_cap=soft_cap))
    np.testing.assert_allclose(got, oracle, rtol=2e-5, atol=2e-5)
    if soft_cap is None:
        np.testing.assert_allclose(
            got, np.asarray(causal_attention(q, k, v)),
            rtol=2e-5, atol=2e-5)


def test_kernel_int8_matches_xla_ring(mesh):
    """int8 rows + per-token-per-head scales ride the ring; dequant in
    VMEM must reproduce the XLA ring's dequantize_rows numerics."""
    q, k, v = _qkv(key=1)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    kq, ks = kvc.quantize_kv_rows(k.reshape(B * T, Hkv * D), Hkv)
    vq, vs = kvc.quantize_kv_rows(v.reshape(B * T, Hkv * D), Hkv)
    kq = kq.reshape(B, T, Hkv, D)
    vq = vq.reshape(B, T, Hkv, D)
    ks = ks.reshape(B, T, Hkv)
    vs = vs.reshape(B, T, Hkv)
    specs = (SPEC4, SPEC4, SPEC4, SPEC3, SPEC3, SPEC2)
    got = _run(mesh, lambda qs, kk, vv, ksc, vsc, ps: ring_flash_attention(
        qs, kk, vv, ps, mesh=mesh, soft_cap=30.0, k_scale=ksc,
        v_scale=vsc, interpret=True),
        q, kq, vq, ks, vs, pos, specs=specs)
    want = _run(mesh, lambda qs, kk, vv, ksc, vsc, ps: ring_causal_attention(
        qs, kk, vv, ps, axis_name="sp", soft_cap=30.0, k_scale=ksc,
        v_scale=vsc),
        q, kq, vq, ks, vs, pos, specs=specs)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_kernel_padding_rows_match_xla_ring(mesh):
    """Fully-masked padding rows (position 0 tail after real tokens at
    higher positions) keep l == 0 on later shards; both implementations
    must produce the identical guarded junk-but-finite output."""
    q, k, v = _qkv(key=2)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    pos = pos.at[1, T - 5:].set(0)
    specs = (SPEC4, SPEC4, SPEC4, SPEC2)
    got = _run(mesh, lambda qs, ks, vs, ps: ring_flash_attention(
        qs, ks, vs, ps, mesh=mesh, interpret=True),
        q, k, v, pos, specs=specs)
    want = _run(mesh, lambda qs, ks, vs, ps: ring_causal_attention(
        qs, ks, vs, ps, axis_name="sp"),
        q, k, v, pos, specs=specs)
    assert np.all(np.isfinite(got))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_kernel_sp1_degenerate():
    """sp=1: zero hops, the kernel is a plain flash fold of the local
    block and must still match the meshless oracle."""
    q, k, v = _qkv(key=3)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    mesh1 = make_mesh(MeshConfig(tp=2), devices=jax.devices()[:2])
    specs = (P(None, None, "tp", None),) * 3 + (P(None, None),)
    f = shard_map(lambda qs, ks, vs, ps: ring_flash_attention(
        qs, ks, vs, ps, mesh=mesh1, interpret=True),
        mesh=mesh1, in_specs=specs,
        out_specs=P(None, None, "tp", None), check_vma=False)
    got = np.asarray(jax.jit(f)(q, k, v, pos))
    oracle = np.asarray(causal_attention(q, k, v))
    np.testing.assert_allclose(got, oracle, rtol=2e-5, atol=2e-5)


def test_geometry_gate_and_shared_predicate():
    # Mosaic-legal: 128-lane feature width, 8-sublane chunks.
    assert ring_geometry_ok(128, 8)
    assert ring_geometry_ok(256, 64)
    assert not ring_geometry_ok(64, 8)     # lane-misaligned feat
    assert not ring_geometry_ok(128, 12)   # sublane-misaligned chunk
    assert not ring_geometry_ok(128, 0)    # empty shard
    # Compiled mode defers to the geometry gate; interpret mode runs any
    # shape (tier-1's whole point) once the DMA patch installs.
    assert ring_kernel_supported(128, 8, interpret=False)
    assert not ring_kernel_supported(64, 8, interpret=False)
    assert ring_kernel_supported(64, 8, interpret=True)


def test_ineligible_geometry_raises_toward_xla_fallback(mesh):
    """Compiled-mode dispatch of a Mosaic-illegal shape must fail loudly
    at trace time and point at the XLA ring fallback — never lower a
    kernel that would die inside Mosaic."""
    q, k, v = _qkv(key=4)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    specs = (SPEC4, SPEC4, SPEC4, SPEC2)
    f = shard_map(lambda qs, ks, vs, ps: ring_flash_attention(
        qs, ks, vs, ps, mesh=mesh, interpret=False),
        mesh=mesh, in_specs=specs, out_specs=SPEC4, check_vma=False)
    with pytest.raises(ValueError, match="ring_attention.ring_causal"):
        jax.jit(f)(q, k, v, pos)
