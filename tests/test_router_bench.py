"""Trace synthesis + prefix analysis + the KV-vs-RR router benchmark
(VERDICT r3 next-6), and the worker-id-0 accounting regression the
benchmark caught."""

import asyncio
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.data_generator.synthesizer import (  # noqa: E402
    TraceRecord,
    TraceSynthesizer,
    analyze_prefixes,
    load_trace,
    save_trace,
    synthesize_prefix_heavy,
    tokens_for_record,
)


def test_trace_roundtrip(tmp_path):
    recs = synthesize_prefix_heavy(10, num_roots=2, context_blocks=3,
                                   block_size=16)
    path = tmp_path / "trace.jsonl"
    save_trace(recs, str(path))
    back = load_trace(str(path))
    assert [r.hash_ids for r in back] == [r.hash_ids for r in recs]
    assert [r.input_length for r in back] == [r.input_length for r in recs]


def test_tokens_replay_shared_prefixes_identically():
    recs = synthesize_prefix_heavy(4, num_roots=1, context_blocks=2,
                                   suffix_tokens=8, block_size=16)
    t0 = tokens_for_record(recs[0], 16, unique_seed=0)
    t1 = tokens_for_record(recs[1], 16, unique_seed=1)
    # Shared context blocks are byte-identical; suffixes differ.
    assert t0[:32] == t1[:32]
    assert t0[32:] != t1[32:]


def test_prefix_analyzer():
    recs = synthesize_prefix_heavy(10, num_roots=1, context_blocks=4,
                                   suffix_tokens=0, block_size=16)
    st = analyze_prefixes(recs, 16)
    assert st.num_requests == 10
    assert st.unique_blocks == 4
    # First request misses everything; the other 9 fully hit.
    assert st.total_reused_tokens == 9 * 4 * 16
    assert st.per_request_hit_rate[0] == 0.0
    assert st.per_request_hit_rate[-1] == 1.0


def test_synthesizer_learns_prefix_structure():
    src = synthesize_prefix_heavy(50, num_roots=3, context_blocks=4,
                                  suffix_tokens=32, block_size=16)
    syn = TraceSynthesizer(src, block_size=16)
    out = syn.synthesize(50, seed=1)
    assert len(out) == 50
    # Synthesized requests reuse the SOURCE trace's block ids (that is
    # the point: same prefix structure), at full context depth.
    src_ids = {h for r in src for h in r.hash_ids}
    for r in out:
        assert set(r.hash_ids) <= src_ids
        assert len(r.hash_ids) == 4
    # Reuse statistics land in the same regime as the source.
    s_src = analyze_prefixes(src, 16).token_reuse_rate
    s_out = analyze_prefixes(out, 16).token_reuse_rate
    assert abs(s_src - s_out) < 0.2


def test_worker_id_zero_accounting_regression():
    """Worker id 0 is falsy; free/mark/push must still clear its load
    (pre-fix, every request routed to worker 0 leaked phantom load and
    the selector starved it — found by the router benchmark)."""
    from dynamo_tpu.llm.kv_router.sequence import (
        ActiveSequencesMultiWorker)

    act = ActiveSequencesMultiWorker(block_size=16)
    act.add_request("r", 0, 32, 0, expected_output_tokens=16)
    assert act.decode_blocks()[0] > 0
    act.mark_prefill_complete("r")
    assert act.prefill_tokens()[0] == 0
    act.free("r")
    assert act.decode_blocks()[0] == 0


def test_router_bench_kv_beats_rr():
    """The artifact shape + the headline claim: KV routing improves both
    hit rate and TTFT on a prefix-heavy trace in the cache-thrash regime
    (reference claims 3x, architecture.md:91)."""
    from benchmarks.router_bench import run

    class Args:
        trace = None
        requests = 150
        workers = 4
        roots = 16
        context_blocks = 24
        suffix = 32
        osl = 8
        interval_ms = 400.0
        trace_block = 64
        speedup = 25.0
        engine_blocks = 224

    result = asyncio.run(asyncio.wait_for(run(Args()), 300))
    # Hit-rate gain is the regression guard for the cost function.  TTFT
    # is NOT asserted here: at CI time compression both modes run
    # sub-millisecond and asyncio timer noise swamps the signal; the
    # standalone bench (`python -m benchmarks.router_bench`, default
    # knobs) is where the TTFT delta is measured (1.3-3.3x observed).
    # Margin 0.1, not 0.2: the rr baseline's hit rate is NOT fully
    # order-driven — under a loaded box the 16 ms compressed arrival
    # intervals jitter enough to reorder evictions and rr has measured
    # as high as 0.54 (vs kv 0.64) mid-suite; 0.1 still fails a broken
    # cost function (kv ≈ rr) without flaking on contention.
    assert (result["kv"]["cache_hit_rate"]
            > result["rr"]["cache_hit_rate"] + 0.1)
    assert result["kv"]["ttft_ms_mean"] > 0  # artifact shape
    assert result["trace"]["num_requests"] == 150
