"""Standalone router service e2e (reference components/router).

Frontend (plain round-robin) → router service's routed endpoint →
kv-routed placement across mocker workers.
"""

import asyncio
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.e2e
def test_router_service_end_to_end():
    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.preprocessor import PreprocessedRequest
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.router_service import RouterService
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient,
        ControlPlaneServer,
    )
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    async def main():
        srv = ControlPlaneServer()
        port = await srv.start()
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        workers = []
        logs = []
        for i in range(2):
            log = open(f"/tmp/router_svc_worker_{os.getpid()}_{i}.log", "w")
            logs.append(log)
            workers.append(subprocess.Popen(
                [sys.executable, "-m", "dynamo_tpu.worker",
                 "--control-plane", f"127.0.0.1:{port}",
                 "--mocker", "--model-name", "m", "--block-size", "8"],
                env=env, cwd=REPO, stdout=log, stderr=subprocess.STDOUT))

        cp = ControlPlaneClient("127.0.0.1", port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        svc = RouterService(runtime, "m")
        consumer_cp = ControlPlaneClient("127.0.0.1", port)
        await consumer_cp.start()
        consumer_rt = DistributedRuntime(consumer_cp)
        models = ModelManager()
        watcher = ModelWatcher(consumer_rt, models)  # plain round-robin
        try:
            await svc.start(wait_for_model_s=30)
            await watcher.start()
            await watcher.wait_for_model("m-routed", timeout=15)
            handle = models.get("m-routed")
            out = []
            for i in range(4):
                req = PreprocessedRequest(
                    request_id=f"r{i}", model="m-routed",
                    token_ids=list(range(1, 20)),
                    sampling=SamplingParams(max_tokens=5))
                toks = []
                async for d in handle.client.generate(req):
                    toks.extend(d.token_ids)
                    if d.finished:
                        break
                out.append(toks)
            assert all(len(t) == 5 for t in out)
            # The router actually tracked these requests (kv routing ran).
            assert svc.models.get("m") is not None
        finally:
            await watcher.stop()
            await svc.stop()
            for pr in workers:
                pr.terminate()
            for pr in workers:
                try:
                    pr.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pr.kill()
            for log in logs:
                log.close()
            await consumer_rt.shutdown()
            await consumer_cp.close()
            await runtime.shutdown()
            await cp.close()
            await srv.stop()

    asyncio.run(asyncio.wait_for(main(), 120))
