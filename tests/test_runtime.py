"""Distributed runtime: control plane, RPC streams, component model.

Mirrors the reference's `lib/runtime/tests/{lifecycle,pipeline}.rs` +
bindings hello_world: echo handlers served cross-"process" (separate
runtimes in one test process, talking over real TCP sockets).
"""

import asyncio

import pytest

from dynamo_tpu.runtime.control_plane import (
    ControlPlaneState,
    InProcessControlPlane,
)
from dynamo_tpu.runtime.control_plane_tcp import (
    ControlPlaneClient,
    ControlPlaneServer,
)
from dynamo_tpu.runtime.distributed import DistributedRuntime, NoInstancesError
from dynamo_tpu.runtime.rpc import RpcClient, RpcError, RpcServer


def _run(coro, timeout=30):
    return asyncio.run(asyncio.wait_for(coro, timeout))


# -- control plane state -----------------------------------------------------


def test_kv_lease_expiry_removes_keys():
    async def main():
        st = ControlPlaneState()
        lease = st.lease_grant(ttl=0.05)
        st.put("instances/ns/c/e:1", {"x": 1}, lease=lease)
        st.put("persistent", {"y": 2})
        assert st.get("instances/ns/c/e:1") == {"x": 1}
        await asyncio.sleep(0.1)
        st.expire_leases()
        assert st.get("instances/ns/c/e:1") is None
        assert st.get("persistent") == {"y": 2}

    _run(main())


def test_watch_sees_existing_and_new():
    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        try:
            await cp.put("pre/a", {"v": 1})
            w = await cp.watch_prefix("pre/")
            ev = await w.next()
            assert (ev.kind, ev.key, ev.value) == ("put", "pre/a", {"v": 1})
            await cp.put("pre/b", {"v": 2})
            ev = await w.next()
            assert ev.key == "pre/b"
            await cp.delete("pre/a")
            ev = await w.next()
            assert (ev.kind, ev.key) == ("delete", "pre/a")
        finally:
            await cp.close()

    _run(main())


def test_pubsub_and_queue():
    async def main():
        cp = InProcessControlPlane()
        await cp.start()
        try:
            sub = await cp.subscribe("kv_events")
            await cp.publish("kv_events", {"n": 1})
            assert await sub.next() == {"n": 1}

            await cp.queue_push("prefill", {"req": "a"})
            assert await cp.queue_len("prefill") == 1
            mid, item = await cp.queue_pop("prefill")
            assert item == {"req": "a"}
            assert await cp.queue_ack("prefill", mid)
            # Un-acked items redeliver after the visibility timeout.
            await cp.queue_push("prefill", {"req": "b"})
            mid2, _ = await cp.queue_pop("prefill", visibility_timeout=0.05)
            await asyncio.sleep(0.1)
            assert cp.state.redeliver_expired() == 1
            mid3, item3 = await cp.queue_pop("prefill")
            assert item3 == {"req": "b"} and mid3 == mid2
            assert await cp.queue_ack("prefill", mid3)
        finally:
            await cp.close()

    _run(main())


# -- TCP control plane -------------------------------------------------------


def test_tcp_control_plane_roundtrip():
    async def main():
        srv = ControlPlaneServer()
        port = await srv.start()
        c1 = ControlPlaneClient("127.0.0.1", port)
        c2 = ControlPlaneClient("127.0.0.1", port)
        await c1.start()
        await c2.start()
        try:
            # KV + watch across clients.
            w = await c2.watch_prefix("m/")
            lease = await c1.lease_grant(ttl=5.0)
            await c1.put("m/x", {"addr": "h:1"}, lease=lease)
            ev = await w.next()
            assert (ev.kind, ev.key, ev.value) == ("put", "m/x", {"addr": "h:1"})
            assert await c2.get("m/x") == {"addr": "h:1"}
            assert await c2.get_prefix("m/") == {"m/x": {"addr": "h:1"}}

            # Lease revoke propagates as delete event.
            await c1.lease_revoke(lease)
            ev = await w.next()
            assert (ev.kind, ev.key) == ("delete", "m/x")

            # Pub/sub across clients.
            sub = await c2.subscribe("s")
            await c1.publish("s", {"k": 9})
            assert await sub.next() == {"k": 9}

            # Work queue: blocking pop completes when item arrives.
            pop = asyncio.create_task(c2.queue_pop("q"))
            await asyncio.sleep(0.05)
            await c1.queue_push("q", {"job": 1})
            mid, item = await pop
            assert item == {"job": 1}
            assert await c2.queue_ack("q", mid)
        finally:
            await c1.close()
            await c2.close()
            await srv.stop()

    _run(main())


def test_tcp_lease_ttl_expires_dead_client():
    async def main():
        srv = ControlPlaneServer()
        port = await srv.start()
        c1 = ControlPlaneClient("127.0.0.1", port)
        await c1.start()
        lease = await c1.lease_grant(ttl=0.2, auto_keepalive=False)
        await c1.put("inst/a:1", {"x": 1}, lease=lease)
        # Simulate worker death: close without revoke; TTL reaps the key.
        await c1.close()
        await asyncio.sleep(1.5)   # reaper interval 1s + ttl
        c2 = ControlPlaneClient("127.0.0.1", port)
        await c2.start()
        try:
            assert await c2.get("inst/a:1") is None
        finally:
            await c2.close()
            await srv.stop()

    _run(main())


# -- rpc ---------------------------------------------------------------------


def test_rpc_stream_and_error():
    async def main():
        srv = RpcServer()

        async def echo3(payload):
            for i in range(3):
                yield {"i": i, "msg": payload["msg"]}

        async def boom(payload):
            yield {"ok": 1}
            raise ValueError("kaboom")

        srv.register("ns/c/echo", echo3)
        srv.register("ns/c/boom", boom)
        addr = await srv.start()
        client = RpcClient(addr)
        try:
            got = [d async for d in client.call("ns/c/echo", {"msg": "hi"})]
            assert got == [{"i": 0, "msg": "hi"}, {"i": 1, "msg": "hi"},
                           {"i": 2, "msg": "hi"}]

            with pytest.raises(RpcError, match="kaboom"):
                async for d in client.call("ns/c/boom", {}):
                    assert d == {"ok": 1}

            with pytest.raises(RpcError, match="no such endpoint"):
                async for _ in client.call("ns/c/missing", {}):
                    pass
        finally:
            await client.close()
            await srv.stop()

    _run(main())


def test_rpc_cancellation_stops_handler():
    async def main():
        srv = RpcServer()
        cancelled = asyncio.Event()

        async def slow(payload):
            try:
                for i in range(1000):
                    yield {"i": i}
                    await asyncio.sleep(0.01)
            except asyncio.CancelledError:
                cancelled.set()
                raise

        srv.register("e", slow)
        addr = await srv.start()
        client = RpcClient(addr)
        try:
            agen = client.call("e", {})
            first = await agen.__anext__()
            assert first == {"i": 0}
            await agen.aclose()          # client walks away
            await asyncio.wait_for(cancelled.wait(), 5)
        finally:
            await client.close()
            await srv.stop()

    _run(main())


def test_rpc_connection_loss_surfaces():
    async def main():
        srv = RpcServer()

        async def forever(payload):
            yield {"first": True}
            await asyncio.sleep(3600)

        srv.register("e", forever)
        addr = await srv.start()
        client = RpcClient(addr)
        try:
            agen = client.call("e", {})
            assert await agen.__anext__() == {"first": True}
            await srv.stop()             # worker dies mid-stream
            with pytest.raises(ConnectionError):
                await agen.__anext__()
        finally:
            await client.close()

    _run(main())


# -- component model ---------------------------------------------------------


def test_component_serve_route_and_leave():
    async def main():
        cp_state = ControlPlaneState()
        cp = InProcessControlPlane(cp_state)
        await cp.start()

        # Two "workers" + one client runtime, sharing the control plane but
        # with their own RPC servers (real sockets).
        w1, w2 = DistributedRuntime(cp), DistributedRuntime(cp)
        frontend = DistributedRuntime(cp)

        async def make_handler(tag):
            async def handler(payload):
                yield {"from": tag, "echo": payload["x"]}
            return handler

        ep1 = w1.namespace("dyn").component("backend").endpoint("generate")
        ep2 = w2.namespace("dyn").component("backend").endpoint("generate")
        await ep1.serve(await make_handler("w1"))
        await ep2.serve(await make_handler("w2"))

        client = await (frontend.namespace("dyn").component("backend")
                        .endpoint("generate").client())
        await client.wait_for_instances()
        assert len(client.instance_ids()) == 2

        # Round-robin spreads.
        sources = set()
        for i in range(4):
            async for d in client.generate({"x": i}):
                sources.add(d["from"])
        assert sources == {"w1", "w2"}

        # Direct targets a specific instance.
        iid = client.instance_ids()[0]
        async for d in client.direct({"x": 9}, iid):
            assert d["echo"] == 9

        # Graceful leave removes from routing.
        await ep1.leave()
        await asyncio.sleep(0.05)
        assert len(client.instance_ids()) == 1
        async for d in client.generate({"x": 5}):
            assert d["from"] == "w2"

        await ep2.leave()
        await asyncio.sleep(0.05)
        with pytest.raises(NoInstancesError):
            async for _ in client.generate({"x": 0}):
                pass

        await client.stop()
        for rt in (w1, w2, frontend):
            await rt.shutdown()
        await cp.close()

    _run(main())


def test_tcp_client_reconnects_and_restores_streams():
    """Connection loss → client reconnects with backoff and re-establishes
    watches + subscriptions under their original sids; consumers see ONE
    ConnectionError per outage and then resume on the same objects."""

    async def main():
        server = ControlPlaneServer()
        port = await server.start()
        client = ControlPlaneClient("127.0.0.1", port)
        await client.start()
        sub = await client.subscribe("events")
        watch = await client.watch_prefix("models/")
        await client.put("models/a", {"v": 1})
        ev = await asyncio.wait_for(watch.next(), 5)
        assert ev.key == "models/a"
        await client.publish("events", {"n": 1})
        assert (await asyncio.wait_for(sub.next(), 5))["n"] == 1

        # Kill the server (state survives in-process); both streams poison.
        state = server.state
        await server.stop()
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(sub.next(), 5)
        with pytest.raises(ConnectionError):
            await asyncio.wait_for(watch.next(), 5)

        # Restart on the SAME port with the same state; the client's
        # reconnect loop re-dials and restores both streams.
        server2 = ControlPlaneServer(state)
        await server2.start(port=port)
        deadline = asyncio.get_running_loop().time() + 10
        # The watch replays existing state as synthetic puts on re-attach.
        ev = await asyncio.wait_for(watch.next(), 10)
        assert ev.key == "models/a" and ev.value == {"v": 1}
        # Pub/sub resumes (publish via a fresh client so delivery proves
        # the OLD subscription was restored server-side).
        pub = ControlPlaneClient("127.0.0.1", port)
        while True:
            try:
                await pub.start()
                break
            except OSError:
                assert asyncio.get_running_loop().time() < deadline
                await asyncio.sleep(0.1)
        await pub.publish("events", {"n": 2})
        assert (await asyncio.wait_for(sub.next(), 10))["n"] == 2
        await pub.close()
        await client.close()
        await server2.stop()

    _run(main())


def test_kv_file_backend_persists_unleased_only(tmp_path):
    """File backend (reference key_value_store pluggability): unleased
    config survives a control-plane restart; leased liveness records die
    with their leases."""
    from dynamo_tpu.runtime.kv_store import FileBackend, make_backend

    path = str(tmp_path / "kv.json")

    async def main():
        state = ControlPlaneState(backend=FileBackend(path))
        cp = InProcessControlPlane(state)
        await cp.start()
        lease = await cp.lease_grant(ttl=5.0, auto_keepalive=False)
        await cp.put("disagg/ns/config", {"max_local_prefill_length": 64})
        await cp.put("instances/ns/backend/gen:1", {"addr": "x"},
                     lease=lease)
        await cp.close()

        # Restart with the same snapshot.
        state2 = ControlPlaneState(backend=FileBackend(path))
        cp2 = InProcessControlPlane(state2)
        await cp2.start()
        assert await cp2.get("disagg/ns/config") == {
            "max_local_prefill_length": 64}
        assert await cp2.get("instances/ns/backend/gen:1") is None
        # Deletes propagate to the snapshot.
        await cp2.delete("disagg/ns/config")
        await cp2.close()
        state3 = ControlPlaneState(backend=FileBackend(path))
        assert state3.get("disagg/ns/config") is None

    _run(main())
    # Spec parsing.
    assert type(make_backend(None)).__name__ == "MemoryBackend"
    assert type(make_backend(f"file:{path}")).__name__ == "FileBackend"
    with pytest.raises(ValueError):
        make_backend("redis://nope")
