"""Sharded serving fast paths (VERDICT r3 next-2): decode windows,
speculative decoding, embeddings and the Pallas kernel all work under a
mesh, and a `--tp` worker serves over the distributed runtime.

Greedy output parity against the unsharded engine is the oracle: the
serving path must not depend on how the model is partitioned.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.parallel import MeshConfig, make_mesh

SCHED = dict(max_seqs=4, block_size=8, max_pages_per_seq=8,
             max_prefill_chunk=16, decode_buckets=(2, 4),
             prefill_buckets=(8, 16))


def _run_engine(mesh=None, decode_window=1, spec=0, dp_attention=False,
                use_pallas=None, n_tokens=12, kv_quant="none",
                dp_local=None):
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, dp_attention=dp_attention,
        dp_attention_local=dp_local,
        decode_window=decode_window, window_pipeline_depth=2,
        speculative_tokens=spec,
        use_pallas_decode=use_pallas,
        kv_quant=kv_quant,
        enable_prefix_cache=False,
        scheduler=SchedulerConfig(**SCHED)))
    core.add_request("a", [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                     SamplingParams(max_tokens=n_tokens))
    core.add_request("b", list(range(20, 34)),
                     SamplingParams(max_tokens=n_tokens))
    outputs = {}
    for _ in range(300):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    assert not core._requests, "engine did not finish"
    return outputs


@pytest.fixture(scope="module")
def oracle():
    """Unsharded single-step greedy output (the parity reference)."""
    return _run_engine()


def test_sharded_window_matches_unsharded(oracle):
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    got = _run_engine(mesh=mesh, decode_window=4)
    assert got == oracle


def test_sharded_single_step_matches_unsharded(oracle):
    mesh = make_mesh(MeshConfig(tp=4), jax.devices()[:4])
    got = _run_engine(mesh=mesh)
    assert got == oracle


def test_sharded_spec_decode_matches_unsharded(oracle):
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    got = _run_engine(mesh=mesh, spec=3)
    assert got == oracle


def test_dp_attention_window_matches_unsharded(oracle):
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    got = _run_engine(mesh=mesh, decode_window=4, dp_attention=True)
    assert got == oracle


def test_sharded_pallas_window_matches_unsharded(oracle):
    """The Pallas kernel under shard_map (interpret mode on CPU)."""
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    got = _run_engine(mesh=mesh, decode_window=4, use_pallas=True)
    assert got == oracle


def test_sp_ring_prefill_through_engine(oracle):
    """A SERVED request's prefill demonstrably runs the ring path
    (VERDICT r3 next-4: make_sp_prefill_step was test-only)."""
    mesh = make_mesh(MeshConfig(sp=2, tp=2), jax.devices()[:4])
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, sp_prefill_threshold=8,
        enable_prefix_cache=False,
        scheduler=SchedulerConfig(**SCHED)))
    core.add_request("a", [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                     SamplingParams(max_tokens=12))
    core.add_request("b", list(range(20, 34)),
                     SamplingParams(max_tokens=12))
    outputs = {}
    for _ in range(300):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    assert core.sp_prefill_count == 2, "prefill did not run the ring path"
    assert outputs == oracle


def test_pp_engine_serving(oracle):
    """A pp-mesh engine SERVES via the pipeline step (VERDICT r3 next-4:
    make_pp_step was test-only)."""
    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    got = _run_engine(mesh=mesh)
    assert got == oracle


def test_sharded_int8_matches_unsharded(oracle):
    """ISSUE 9 leg 1: the quantized KV plane composes with head-sharded
    tp — scales shard with their kv heads — and greedy output stays
    token-identical to the meshless bf16 oracle on BOTH sharded decode
    paths (fused window and the fused greedy single step, which also
    covers leg 3's make_sharded_greedy_step with an int8 cache)."""
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    assert _run_engine(mesh=mesh, decode_window=4,
                       kv_quant="int8") == oracle
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, kv_quant="int8", decode_window=1,
        enable_prefix_cache=False,
        scheduler=SchedulerConfig(**SCHED)))
    core.add_request("a", [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                     SamplingParams(max_tokens=12))
    core.add_request("b", list(range(20, 34)),
                     SamplingParams(max_tokens=12))
    outputs = {}
    for _ in range(300):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    assert outputs == oracle
    assert core._greedy_fused is not None, \
        "sharded int8 single-step decode did not take the fused path"


def test_dp_attention_plain_int8_matches_unsharded(oracle):
    """int8 × PLAIN dp_attention (no locality): the GSPMD slot-sharded
    gather path with P('tp', None) scale buffers — the README matrix
    advertises this combination, so it needs its own parity pin
    (enable_prefix_cache=False would auto-resolve locality; force it
    off to keep the test on the non-local path)."""
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    got = _run_engine(mesh=mesh, decode_window=4, dp_attention=True,
                      dp_local=False, kv_quant="int8")
    assert got == oracle


def test_dp_local_pallas_int8_matches_unsharded(oracle):
    """ISSUE 9 leg 2: the Pallas kernel runs SHARD-LOCALLY under
    dp_attention locality (block tables rebase to the shard's local page
    range inside the shard_map body) — with the int8 cache threading its
    scale shards into the kernel's k_scale/v_scale variant."""
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    got = _run_engine(mesh=mesh, decode_window=4, dp_attention=True,
                      use_pallas=True, kv_quant="int8")
    assert got == oracle


def test_sharded_fused_step_counters():
    """The sharded fused greedy step's loop discipline (ISSUE 9 leg 3):
    in steady single-step decode each engine iteration is ONE fused
    dispatch with ONE host sync and zero new compiled shapes — the same
    pin the meshless path carries in test_decode_window."""
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, decode_window=1, enable_prefix_cache=False,
        scheduler=SchedulerConfig(**SCHED)))
    core.add_request("a", [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                     SamplingParams(max_tokens=30))
    core.add_request("b", list(range(20, 34)),
                     SamplingParams(max_tokens=30))
    for _ in range(6):   # prefill + warm the fused program
        core.step()
    assert core._greedy_fused is not None
    base = core.counters.snapshot()
    n = 8
    for _ in range(n):
        core.step()
    d = core.counters.delta(base)
    assert d["single_step_dispatches"] == n
    assert d["host_syncs"] == n, "fused sharded step must cost 1 sync"
    assert d["xla_cache_misses"] == 0, "steady shape recompiled"


def test_sharded_per_chip_modeled_bytes():
    """Modeled-bytes honesty under meshes (ISSUE 9 satellite): a tp2
    engine sweeps HALF the KV bytes per chip, so
    `effective_bytes_per_token` (and the per-chip mbu derived from it)
    must halve vs meshless; `dynamo_kv_bytes_per_block` reports per-chip
    block bytes on sharded pools."""
    from dynamo_tpu.runtime.metrics import KvCacheMetrics, MetricsRegistry

    def run(mesh):
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=64,
            mesh=mesh, enable_prefix_cache=False,
            scheduler=SchedulerConfig(**SCHED)))
        core.add_request("a", [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                         SamplingParams(max_tokens=12))
        for _ in range(300):
            core.step()
            if not core._requests:
                break
        return core

    meshless = run(None)
    tp2 = run(make_mesh(MeshConfig(tp=2), jax.devices()[:2]))
    assert meshless.kv_shard_count == 1
    assert tp2.kv_shard_count == 2
    b0 = meshless.counters.effective_bytes_per_token
    b2 = tp2.counters.effective_bytes_per_token
    assert b2 > 0
    assert abs(b2 / b0 - 0.5) < 1e-6
    reg = MetricsRegistry()
    kvm = KvCacheMetrics(reg)
    kvm.observe_engine(tp2)
    got = kvm.kv_bytes_per_block.value(labels={"kv_quant": "none"})
    assert got == tp2.cache_cfg.bytes_per_block / 2


def test_sharded_int8_wire_block_mismatch_refused():
    """Disagg / prefix-share between sharded int8 peers keeps refusing
    mixed-mode blocks loudly: the packed wire format is
    sharding-independent, so a bf16 peer's block into a tp2 int8 cache
    must be rejected BEFORE any bytes touch the cache."""
    import numpy as np

    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, kv_quant="int8", enable_prefix_cache=False,
        scheduler=SchedulerConfig(**SCHED)))
    cfg = core.cache_cfg
    bf16_shape = (2, cfg.num_layers, cfg.block_size, cfg.feature_dim)
    with pytest.raises(ValueError, match="kv_quant"):
        core._validate_block(np.zeros(bf16_shape, np.float32))
    # The exact packed block passes the format check.
    core._validate_block(np.zeros(cfg.block_wire_shape, np.int8))


def test_sharded_embeddings():
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    cfg = mcfg.get_config("tiny-test")

    def embed(mesh_):
        core = EngineCore(EngineConfig(
            model=cfg, num_blocks=64, mesh=mesh_,
            enable_prefix_cache=False,
            scheduler=SchedulerConfig(**SCHED)))
        return core.embed_tokens([[5, 6, 7, 8], list(range(20, 31))])

    want = embed(None)
    got = embed(mesh)
    assert got.shape == (2, cfg.hidden_size)
    np.testing.assert_allclose(want, got, rtol=2e-2, atol=2e-2)


@pytest.mark.e2e
def test_tp_worker_serves_http():
    """A real-engine worker launched with --tp 2 --dp 2 serves a chat
    completion end-to-end over the distributed runtime (the 'one flag'
    contract, reference `sglang/launch/disagg.sh:25`)."""
    import asyncio
    import os
    import subprocess
    import sys
    import time

    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        watcher = ModelWatcher(runtime, models, migration_limit=0)
        await watcher.start()
        svc = HttpService(models)
        http_port = await svc.start()

        log = open(f"/tmp/dynamo_tpu_tp_worker_{os.getpid()}.log", "w+")
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--control-plane", f"127.0.0.1:{cp_port}",
             "--model", "tiny-test", "--model-name", "tiny-tp",
             "--block-size", "8", "--tp", "2", "--dp", "2",
             "--decode-window", "4"],
            env=env, cwd=repo, stdout=log, stderr=subprocess.STDOUT,
            text=True)
        try:
            await watcher.wait_for_model("tiny-tp", timeout=120)
            base = f"http://127.0.0.1:{http_port}"
            async with ClientSession() as s:
                async with s.post(f"{base}/v1/chat/completions", json={
                        "model": "tiny-tp",
                        "messages": [{"role": "user", "content": "hello"}],
                        "max_tokens": 8}) as r:
                    body = await r.json()
                    assert r.status == 200, body
                    assert body["choices"][0]["message"]["content"]
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
            log.flush(); log.seek(0)
            print(log.read()[-2000:])
            log.close()
            await svc.stop()
            await watcher.stop()
            await runtime.shutdown()
            await cp.close()
            await cp_server.stop()

    asyncio.run(main())


def test_pp_prefix_cache_hits(oracle):
    """PP v2 (VERDICT r4 next-10): the tiered prefix cache runs under the
    stacked pp layout — a repeated prompt prefix must HIT (prefill
    skipped) and greedy output must stay identical to the unsharded
    oracle."""
    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, enable_prefix_cache=True,
        scheduler=SchedulerConfig(**SCHED)))
    assert core._managed_cache, "pp engine must run the tiered source"

    def run(rid):
        core.add_request(rid, [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                         SamplingParams(max_tokens=12))
        out = []
        for _ in range(300):
            for d in core.step():
                out.extend(d.token_ids)
            if not core._requests:
                break
        assert not core._requests
        return out

    first = run("p1")
    assert first == oracle["a"], "pp+prefix first run diverged"
    # Second identical prompt: the sealed prefix blocks must match.
    second = run("p2")
    assert second == first, "prefix hit changed greedy output"
    # The hit is observable as skipped prefill work: the second request
    # admitted with prefilled > 0 (allocator.match returned cached
    # tokens).  Verify via the manager's match bookkeeping.
    mgr = core.allocator
    cached, pages = mgr.match([5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                              mgr.prompt_hashes([5, 6, 7, 8, 9, 10,
                                                 5, 6, 7, 8]))
    assert cached > 0, "sealed prefix blocks not matchable under pp"
    if pages:
        mgr.release(pages)


def test_pp_block_extract_inject_roundtrip():
    """The stacked-layout block ops must move the exact bytes the
    flat-layout ops define (the canonical [2, L, bs, F] block)."""
    from dynamo_tpu.engine import kv_cache as kvc
    from dynamo_tpu.parallel.pipeline import (
        init_pp_cache, make_pp_block_ops, pp_cache_pspecs)
    from dynamo_tpu.parallel.sharding import shard_pytree

    cfg = mcfg.get_config("tiny-test")
    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    cache_cfg = kvc.KvCacheConfig.for_model(cfg, num_blocks=8,
                                            block_size=8,
                                            dtype=np.float32)
    cache = shard_pytree(init_pp_cache(cache_cfg), pp_cache_pspecs(), mesh)
    ex, inj = make_pp_block_ops(8, mesh)
    rng = np.random.default_rng(0)
    blk = rng.standard_normal(
        (2, cfg.num_layers, 8, cache_cfg.feature_dim)).astype(np.float32)
    cache = inj(cache, np.int32(3), blk)
    out = np.asarray(ex(cache, np.int32(3)))
    np.testing.assert_array_equal(out, blk)
    # Other pages stay zero.
    other = np.asarray(ex(cache, np.int32(2)))
    assert (other == 0).all()
