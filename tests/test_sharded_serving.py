"""Sharded serving fast paths (VERDICT r3 next-2): decode windows,
speculative decoding, embeddings and the Pallas kernel all work under a
mesh, and a `--tp` worker serves over the distributed runtime.

Greedy output parity against the unsharded engine is the oracle: the
serving path must not depend on how the model is partitioned.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.parallel import MeshConfig, make_mesh

SCHED = dict(max_seqs=4, block_size=8, max_pages_per_seq=8,
             max_prefill_chunk=16, decode_buckets=(2, 4),
             prefill_buckets=(8, 16))


def _run_engine(mesh=None, decode_window=1, spec=0, dp_attention=False,
                use_pallas=None, n_tokens=12):
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, dp_attention=dp_attention,
        decode_window=decode_window, window_pipeline_depth=2,
        speculative_tokens=spec,
        use_pallas_decode=use_pallas,
        enable_prefix_cache=False,
        scheduler=SchedulerConfig(**SCHED)))
    core.add_request("a", [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                     SamplingParams(max_tokens=n_tokens))
    core.add_request("b", list(range(20, 34)),
                     SamplingParams(max_tokens=n_tokens))
    outputs = {}
    for _ in range(300):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    assert not core._requests, "engine did not finish"
    return outputs


@pytest.fixture(scope="module")
def oracle():
    """Unsharded single-step greedy output (the parity reference)."""
    return _run_engine()


def test_sharded_window_matches_unsharded(oracle):
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    got = _run_engine(mesh=mesh, decode_window=4)
    assert got == oracle


def test_sharded_single_step_matches_unsharded(oracle):
    mesh = make_mesh(MeshConfig(tp=4), jax.devices()[:4])
    got = _run_engine(mesh=mesh)
    assert got == oracle


def test_sharded_spec_decode_matches_unsharded(oracle):
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    got = _run_engine(mesh=mesh, spec=3)
    assert got == oracle


def test_dp_attention_window_matches_unsharded(oracle):
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    got = _run_engine(mesh=mesh, decode_window=4, dp_attention=True)
    assert got == oracle


def test_sharded_pallas_window_matches_unsharded(oracle):
    """The Pallas kernel under shard_map (interpret mode on CPU)."""
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    got = _run_engine(mesh=mesh, decode_window=4, use_pallas=True)
    assert got == oracle


def test_sp_ring_prefill_through_engine(oracle):
    """A SERVED request's prefill demonstrably runs the ring path
    (VERDICT r3 next-4: make_sp_prefill_step was test-only)."""
    mesh = make_mesh(MeshConfig(sp=2, tp=2), jax.devices()[:4])
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, sp_prefill_threshold=8,
        enable_prefix_cache=False,
        scheduler=SchedulerConfig(**SCHED)))
    core.add_request("a", [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                     SamplingParams(max_tokens=12))
    core.add_request("b", list(range(20, 34)),
                     SamplingParams(max_tokens=12))
    outputs = {}
    for _ in range(300):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        if not core._requests:
            break
    assert core.sp_prefill_count == 2, "prefill did not run the ring path"
    assert outputs == oracle


def test_pp_engine_serving(oracle):
    """A pp-mesh engine SERVES via the pipeline step (VERDICT r3 next-4:
    make_pp_step was test-only)."""
    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    got = _run_engine(mesh=mesh)
    assert got == oracle


def test_sharded_embeddings():
    mesh = make_mesh(MeshConfig(tp=2, dp=2), jax.devices()[:4])
    cfg = mcfg.get_config("tiny-test")

    def embed(mesh_):
        core = EngineCore(EngineConfig(
            model=cfg, num_blocks=64, mesh=mesh_,
            enable_prefix_cache=False,
            scheduler=SchedulerConfig(**SCHED)))
        return core.embed_tokens([[5, 6, 7, 8], list(range(20, 31))])

    want = embed(None)
    got = embed(mesh)
    assert got.shape == (2, cfg.hidden_size)
    np.testing.assert_allclose(want, got, rtol=2e-2, atol=2e-2)


@pytest.mark.e2e
def test_tp_worker_serves_http():
    """A real-engine worker launched with --tp 2 --dp 2 serves a chat
    completion end-to-end over the distributed runtime (the 'one flag'
    contract, reference `sglang/launch/disagg.sh:25`)."""
    import asyncio
    import os
    import subprocess
    import sys
    import time

    from aiohttp import ClientSession

    from dynamo_tpu.llm.discovery import ModelWatcher
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.service import ModelManager
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()
        cp = ControlPlaneClient("127.0.0.1", cp_port)
        await cp.start()
        runtime = DistributedRuntime(cp)
        models = ModelManager()
        watcher = ModelWatcher(runtime, models, migration_limit=0)
        await watcher.start()
        svc = HttpService(models)
        http_port = await svc.start()

        log = open(f"/tmp/dynamo_tpu_tp_worker_{os.getpid()}.log", "w+")
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
        proc = subprocess.Popen(
            [sys.executable, "-m", "dynamo_tpu.worker",
             "--control-plane", f"127.0.0.1:{cp_port}",
             "--model", "tiny-test", "--model-name", "tiny-tp",
             "--block-size", "8", "--tp", "2", "--dp", "2",
             "--decode-window", "4"],
            env=env, cwd=repo, stdout=log, stderr=subprocess.STDOUT,
            text=True)
        try:
            await watcher.wait_for_model("tiny-tp", timeout=120)
            base = f"http://127.0.0.1:{http_port}"
            async with ClientSession() as s:
                async with s.post(f"{base}/v1/chat/completions", json={
                        "model": "tiny-tp",
                        "messages": [{"role": "user", "content": "hello"}],
                        "max_tokens": 8}) as r:
                    body = await r.json()
                    assert r.status == 200, body
                    assert body["choices"][0]["message"]["content"]
        finally:
            proc.terminate()
            try:
                proc.wait(timeout=15)
            except subprocess.TimeoutExpired:
                proc.kill()
            log.flush(); log.seek(0)
            print(log.read()[-2000:])
            log.close()
            await svc.stop()
            await watcher.stop()
            await runtime.shutdown()
            await cp.close()
            await cp_server.stop()

    asyncio.run(main())


def test_pp_prefix_cache_hits(oracle):
    """PP v2 (VERDICT r4 next-10): the tiered prefix cache runs under the
    stacked pp layout — a repeated prompt prefix must HIT (prefill
    skipped) and greedy output must stay identical to the unsharded
    oracle."""
    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    core = EngineCore(EngineConfig(
        model=mcfg.get_config("tiny-test"), num_blocks=64,
        mesh=mesh, enable_prefix_cache=True,
        scheduler=SchedulerConfig(**SCHED)))
    assert core._managed_cache, "pp engine must run the tiered source"

    def run(rid):
        core.add_request(rid, [5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                         SamplingParams(max_tokens=12))
        out = []
        for _ in range(300):
            for d in core.step():
                out.extend(d.token_ids)
            if not core._requests:
                break
        assert not core._requests
        return out

    first = run("p1")
    assert first == oracle["a"], "pp+prefix first run diverged"
    # Second identical prompt: the sealed prefix blocks must match.
    second = run("p2")
    assert second == first, "prefix hit changed greedy output"
    # The hit is observable as skipped prefill work: the second request
    # admitted with prefilled > 0 (allocator.match returned cached
    # tokens).  Verify via the manager's match bookkeeping.
    mgr = core.allocator
    cached, pages = mgr.match([5, 6, 7, 8, 9, 10, 5, 6, 7, 8],
                              mgr.prompt_hashes([5, 6, 7, 8, 9, 10,
                                                 5, 6, 7, 8]))
    assert cached > 0, "sealed prefix blocks not matchable under pp"
    if pages:
        mgr.release(pages)


def test_pp_block_extract_inject_roundtrip():
    """The stacked-layout block ops must move the exact bytes the
    flat-layout ops define (the canonical [2, L, bs, F] block)."""
    from dynamo_tpu.engine import kv_cache as kvc
    from dynamo_tpu.parallel.pipeline import (
        init_pp_cache, make_pp_block_ops, pp_cache_pspecs)
    from dynamo_tpu.parallel.sharding import shard_pytree

    cfg = mcfg.get_config("tiny-test")
    mesh = make_mesh(MeshConfig(pp=2), jax.devices()[:2])
    cache_cfg = kvc.KvCacheConfig.for_model(cfg, num_blocks=8,
                                            block_size=8,
                                            dtype=np.float32)
    cache = shard_pytree(init_pp_cache(cache_cfg), pp_cache_pspecs(), mesh)
    ex, inj = make_pp_block_ops(8, mesh)
    rng = np.random.default_rng(0)
    blk = rng.standard_normal(
        (2, cfg.num_layers, 8, cache_cfg.feature_dim)).astype(np.float32)
    cache = inj(cache, np.int32(3), blk)
    out = np.asarray(ex(cache, np.int32(3)))
    np.testing.assert_array_equal(out, blk)
    # Other pages stay zero.
    other = np.asarray(ex(cache, np.int32(2)))
    assert (other == 0).all()
