"""SLA planner: interpolators, decision math, profiler sweep, and the
e2e where synthetic load with an SLA target scales the fleet to the
interpolated replica count (VERDICT r3 next-5)."""

import asyncio

import pytest

from dynamo_tpu.planner import (
    SlaObservation,
    SlaPlanner,
    SlaPlannerConfig,
    TrendPredictor,
)
from dynamo_tpu.planner.interpolation import (
    DecodeInterpolator,
    PrefillInterpolator,
)

# A hand-built profile with easy arithmetic:
# - prefill: 1000 tok/s/chip flat, TTFT grows with ISL;
# - decode: ITL degrades with kv load; 0.02s ITL is met up to kv=0.5
#   where throughput is 500 tok/s/chip (columns beyond exceed the SLA).
PROFILE = {
    "prefill": {
        "isl": [128, 512, 2048],
        "ttft_s": [0.1, 0.4, 1.6],
        "tok_s_per_chip": [1000.0, 1000.0, 1000.0],
    },
    "decode": {
        "kv_usage": [0.2, 0.5, 0.8],
        "context": [256, 1024],
        "itl_s": [[0.01, 0.02, 0.05], [0.01, 0.02, 0.05]],
        "tok_s_per_chip": [[200.0, 500.0, 800.0], [200.0, 500.0, 800.0]],
    },
}


def test_interpolators():
    pre = PrefillInterpolator(PROFILE)
    assert pre.interpolate_ttft(128) == pytest.approx(0.1)
    assert pre.interpolate_ttft(320) == pytest.approx(0.25)  # midpoint
    assert pre.interpolate_thpt_per_chip(9999) == 1000.0     # clamped

    dec = DecodeInterpolator(PROFILE)
    assert dec.interpolate_itl(0.35, 256) == pytest.approx(0.015)
    # Best throughput meeting ITL<=0.02 is the kv=0.5 column.
    assert dec.find_best_throughput_per_chip(0.02, 256) == 500.0
    # A looser SLA admits the most loaded column.
    assert dec.find_best_throughput_per_chip(0.05, 1024) == 800.0
    # An unmeetable SLA falls back to the least-loaded column.
    assert dec.find_best_throughput_per_chip(0.001, 256) == 200.0


def test_trend_predictor_leads_ramps():
    p = TrendPredictor(window=4)
    for v in (10, 20, 30, 40):
        p.add_data_point(v)
    assert p.predict_next() > 40  # extrapolates the ramp


class FakeConnector:
    def __init__(self, n=1):
        self.n = n

    def replicas(self):
        return self.n

    async def add_worker(self):
        self.n += 1

    async def remove_worker(self):
        self.n -= 1


def test_sla_decision_math():
    planner = SlaPlanner(
        PROFILE, observe=lambda: SlaObservation(),
        decode_connector=FakeConnector(),
        prefill_connector=FakeConnector(),
        config=SlaPlannerConfig(
            ttft_s=0.5, itl_s=0.02, adjustment_interval_s=10.0,
            predictor="constant", max_replicas=16, max_chip_budget=32))
    # 100 req / 10s at isl=512, osl=100:
    # prefill load = 100*512/10 = 5120 tok/s → /1000 → 6 prefill chips;
    # decode: best thpt at ITL<=0.02 is 500 → 100*100/10/500 = 2 chips.
    d = planner.decide(SlaObservation(
        num_requests=100, avg_isl=512, avg_osl=100))
    assert d.num_prefill == 6
    assert d.num_decode == 2

    # Measured ITL 2x the profile expectation tightens the corrected SLA
    # to 0.01 → only the kv=0.2 column (200 tok/s) qualifies → 5 chips.
    d = planner.decide(SlaObservation(
        num_requests=100, avg_isl=512, avg_osl=100,
        itl_s=2 * 0.02))
    assert d.d_correction == pytest.approx(2.0)
    assert d.num_decode == 5

    # Zero load floors at min_replicas.
    d = planner.decide(SlaObservation())
    assert d.num_prefill == 1 and d.num_decode == 1


def test_sla_budget_clamp():
    planner = SlaPlanner(
        PROFILE, observe=lambda: SlaObservation(),
        decode_connector=FakeConnector(),
        config=SlaPlannerConfig(
            ttft_s=0.5, itl_s=0.02, adjustment_interval_s=10.0,
            predictor="constant", max_replicas=100, max_chip_budget=8))
    d = planner.decide(SlaObservation(
        num_requests=1000, avg_isl=2048, avg_osl=500))
    total = d.num_prefill + d.num_decode
    assert total <= 8


def test_sla_e2e_converges_fleet():
    """Synthetic load ramp drives connectors to the interpolated counts;
    load drop scales back down."""

    async def main():
        obs_feed = []

        def observe():
            return obs_feed.pop(0) if obs_feed else SlaObservation()

        pc, dc = FakeConnector(1), FakeConnector(1)
        planner = SlaPlanner(
            PROFILE, observe=observe,
            decode_connector=dc, prefill_connector=pc,
            config=SlaPlannerConfig(
                ttft_s=0.5, itl_s=0.02, adjustment_interval_s=10.0,
                predictor="constant", max_replicas=16, max_chip_budget=32))
        obs_feed.append(SlaObservation(num_requests=100, avg_isl=512,
                                       avg_osl=100))
        await planner.step()
        # Convergence is rate-limited (max 4 moves per tick — a crashing
        # worker must not become an unbounded spawn loop): 1 → 5 first.
        assert (pc.n, dc.n) == (5, 2)
        obs_feed.append(SlaObservation(num_requests=100, avg_isl=512,
                                       avg_osl=100))
        await planner.step()
        assert (pc.n, dc.n) == (6, 2)

        obs_feed.append(SlaObservation(num_requests=10, avg_isl=128,
                                       avg_osl=50))
        await planner.step()
        assert (pc.n, dc.n) == (2, 1)  # drain rate-limited: 6 → 2
        obs_feed.append(SlaObservation(num_requests=10, avg_isl=128,
                                       avg_osl=50))
        await planner.step()
        assert pc.n == 1  # 10*128/10=128 tok/s → 1 chip
        assert dc.n == 1

    asyncio.run(asyncio.wait_for(main(), 30))


def test_profiler_sweep_feeds_interpolators():
    """The mini-profiler sweeps a real (tiny, CPU) EngineCore and its
    output drives the interpolators end to end."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.planner.profiler import profile_engine

    def make():
        return EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=64,
            enable_prefix_cache=False, decode_window=1,
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16, decode_buckets=(1, 2, 4),
                prefill_buckets=(8, 16))))

    profile = profile_engine(make, isl_grid=(8, 16),
                             context_grid=(16,), kv_grid=(0.2, 0.6),
                             decode_tokens=4)
    assert len(profile["prefill"]["isl"]) == 2
    assert all(t > 0 for t in profile["prefill"]["ttft_s"])
    pre = PrefillInterpolator(profile)
    assert pre.interpolate_thpt_per_chip(12) > 0
    dec = DecodeInterpolator(profile)
    assert dec.interpolate_itl(0.4, 16) > 0
    assert dec.find_best_throughput_per_chip(10.0, 16) > 0


def test_prometheus_scraper_against_live_frontend():
    """The scraper diffs the real frontend exposition into interval
    observations (isl/osl/ttft/itl averages)."""
    import aiohttp  # noqa: F401 — skip when missing

    from dynamo_tpu.planner import PrometheusScraper

    async def main():
        import aiohttp

        from tests.test_http_service import _serve_tiny

        svc, engine, port = await _serve_tiny()
        try:
            scraper = PrometheusScraper(
                f"http://127.0.0.1:{port}/metrics")
            base = await asyncio.to_thread(scraper.observe)  # baseline
            assert base.num_requests >= 0
            async with aiohttp.ClientSession() as s:
                for _ in range(2):
                    async with s.post(
                            f"http://127.0.0.1:{port}/v1/completions",
                            json={"model": "tiny", "prompt": "hello",
                                  "max_tokens": 4}) as r:
                        assert r.status == 200
            obs = await asyncio.to_thread(scraper.observe)
            assert obs.num_requests == 2
            assert obs.avg_isl > 0
            assert obs.avg_osl == pytest.approx(4.0)
            assert obs.itl_s >= 0
        finally:
            await svc.stop()
            await engine.stop()

    asyncio.run(asyncio.wait_for(main(), 120))


def test_sla_planner_cli_mode_parses():
    """--mode sla flag wiring (no run; just argument validation path)."""
    from dynamo_tpu.planner.__main__ import main

    with pytest.raises(SystemExit):
        # missing --profile/--metrics-url must error, not crash later
        main(["--control-plane", "127.0.0.1:1", "--mode", "sla"])
