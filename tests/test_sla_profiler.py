"""SLA profiler + capacity frontier (`benchmarks/sla_profiler.py`):
knee detection on synthetic curves, the mocker-parity simulator's
feature axes, profile schema round-trip through
`load_profile`/`save_profile`, `SlaPlanner` consuming a
profiler-produced profile end to end, the PINNED cheapest-fleet fixture
the deterministic sweep guarantees, and (slow-marked) the 100-worker
mocker fleet cross-checked against the model via the real
`tools/dynamo_top.py --once --json` CLI with `--profile` headroom.
"""

import asyncio
import json
import os
import subprocess
import sys

import pytest

from benchmarks.sla_profiler import (
    AGREEMENT_ATOL_S,
    AGREEMENT_FACTOR,
    MOE_DENSE_WEIGHT_FACTOR,
    MOE_GROUPED_SPEEDUP,
    CellConfig,
    SMOKE_SLO,
    SloTarget,
    agreement,
    cell_timing,
    find_knee,
    make_traffic,
    plan_capacity,
    profile_cell,
    run_fleet,
    run_smoke,
    scale_to_rate,
    simulate_cell,
    sustainable_rps,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def smoke():
    """One shared deterministic sweep for every consumer below (~1 s)."""
    return run_smoke(None)


# -- knee detection ----------------------------------------------------------


def test_knee_on_hockey_stick():
    # Flat then exploding: kneedle flags the max-deviation point — the
    # middle of the bend.
    idx = find_knee([1, 2, 4, 8, 16, 32],
                    [10.0, 10.5, 11.0, 12.0, 80.0, 400.0])
    assert idx == 4


def test_knee_absent_on_flat_and_linear_curves():
    # A curve that never saturates has no knee — inventing one would
    # cap capacity at an arbitrary load.
    assert find_knee([1, 2, 4, 8], [10.0, 10.1, 10.2, 10.3]) is None
    assert find_knee([1, 2, 3], [1.0, 1.0, 1.0]) is None
    # Too few points to call a bend.
    assert find_knee([1, 2], [1.0, 100.0]) is None
    # A 0.0 point must not defeat the no-saturation guard (the relative
    # 1.3x threshold divides by ~zero): a microsecond-scale linear
    # curve starting at 0 has no knee either.
    assert find_knee([1, 2, 4, 8, 16],
                     [0.0, 1e-6, 2e-6, 3e-6, 4e-6]) is None
    # ...but a real climb from 0.0 still gets one.
    assert find_knee([1, 2, 4, 8, 16],
                     [0.0, 0.001, 0.002, 0.05, 0.4]) is not None


def test_knee_input_validation():
    with pytest.raises(ValueError):
        find_knee([1, 2, 3], [1.0, 2.0])
    with pytest.raises(ValueError):
        find_knee([1, 1, 2], [1.0, 2.0, 3.0])


def test_closed_loop_knee_survives_saturation_plateau():
    # A closed-loop frontier's offered_rps = conc/wall plateaus once
    # the engine saturates — find_knee would raise on the repeated
    # loads; closed_loop_knee must keep working (the --tpu sweep path).
    from benchmarks.sla_profiler import FrontierPoint, closed_loop_knee

    def pt(rps, ttft):
        return FrontierPoint(offered_rps=rps, ttft_p50_s=ttft,
                             ttft_p99_s=ttft, tpot_p50_s=0.0,
                             tpot_p99_s=0.0, throughput_tok_s=0.0,
                             mean_inflight=0.0)

    # Bend inside the increasing prefix → kneedle's pick (index 3, the
    # max-deviation-below-the-chord point of the 5-point prefix).
    bent = [pt(r, t) for r, t in
            [(10, 0.01), (20, 0.011), (40, 0.012), (60, 0.05),
             (70, 0.4), (70, 1.6)]]
    assert closed_loop_knee(bent) == 3
    # Flat latency until the throughput plateau → the last point still
    # on the rise is the saturation onset.
    flat = [pt(r, 0.01) for r in [10, 20, 40, 60]] + [pt(60, 0.011)]
    assert closed_loop_knee(flat) == 3
    # Strictly increasing, never saturating → no knee, as find_knee.
    assert closed_loop_knee(
        [pt(r, 0.01) for r in [10, 20, 40, 80]]) is None


def test_refusal_reason_quotes_min_load_point():
    # When every point misses the SLO, the rejection must quote the
    # MIN-load latency (how far off the config is at its best), not the
    # saturated tail.
    f = profile_cell(CellConfig("base"), "agentic", [4.0, 32.0, 128.0],
                     num_requests=48)
    rps, reason = sustainable_rps(
        f, SloTarget(ttft_p99_s=1e-6, tpot_p99_s=1e-9))
    assert rps == 0.0
    lo = f.points[0]
    assert f"ttft_p99={lo.ttft_p99_s:.4f}s" in reason


# -- the mocker-parity simulator ---------------------------------------------


def test_feature_axes_change_timing():
    base = cell_timing(CellConfig("base"))
    int8 = cell_timing(CellConfig("i", kv_quant="int8"))
    spec = cell_timing(CellConfig("s", spec_decode=4))
    packed = cell_timing(CellConfig("p", packed_prefill=True))
    tp2 = cell_timing(CellConfig("t", tp=2))
    # int8 shrinks the KV-bandwidth (per-seq) term only.
    assert int8.decode_ms_per_seq < base.decode_ms_per_seq
    assert int8.decode_base_ms == base.decode_base_ms
    assert int8.prefill_ms_per_token == base.prefill_ms_per_token
    # spec decode speeds both decode terms, not prefill.
    assert spec.decode_base_ms < base.decode_base_ms
    assert spec.prefill_ms_per_token == base.prefill_ms_per_token
    # packed prefill speeds prefill only.
    assert packed.prefill_ms_per_token < base.prefill_ms_per_token
    assert packed.decode_base_ms == base.decode_base_ms
    # tp2 speeds everything, sublinearly per chip (0.91 efficiency).
    assert tp2.prefill_ms_per_token > base.prefill_ms_per_token / 2
    assert tp2.prefill_ms_per_token < base.prefill_ms_per_token


def test_moe_axis_timing_and_validation():
    base = cell_timing(CellConfig("base"))
    dense = cell_timing(CellConfig("md", moe="dense"))
    grouped = cell_timing(CellConfig("mg", moe="grouped"))
    ep2 = cell_timing(CellConfig("me", moe="grouped", ep=2))
    # MoE multiplies the weight-read terms (prefill per-token + decode
    # base) by the expert-traffic factor; the KV per-seq term carries
    # no expert weights and must be untouched.
    assert dense.decode_base_ms == pytest.approx(
        base.decode_base_ms * MOE_DENSE_WEIGHT_FACTOR)
    assert grouped.decode_base_ms == pytest.approx(
        base.decode_base_ms * MOE_DENSE_WEIGHT_FACTOR
        / MOE_GROUPED_SPEEDUP)
    assert dense.prefill_ms_per_token == pytest.approx(
        base.prefill_ms_per_token * MOE_DENSE_WEIGHT_FACTOR)
    assert dense.decode_ms_per_seq == base.decode_ms_per_seq
    # ep2 shards the expert stream (same per-chip efficiency curve as
    # tp) but never beats the equivalent dense-model cell.
    assert base.decode_base_ms < ep2.decode_base_ms < grouped.decode_base_ms
    # Axis validation is a construction-time error, not a silent sweep.
    with pytest.raises(ValueError, match="moe="):
        CellConfig("bad", moe="fused")
    with pytest.raises(ValueError, match="ep="):
        CellConfig("bad", ep=2)
    # ep doubles the chip bill the capacity plan prices.
    assert CellConfig("me2", moe="grouped", ep=2, tp=2).chips == 4


def test_moe_plan_answered_beside_dense_plan(smoke):
    # The MoE grid sweeps under its own mix and yields its OWN plan —
    # the dense pinned fixture cannot drift from this PR.  At the
    # shared smoke SLO the dense-MoE oracle can't hold TPOT at any
    # load (the E/k weight wall); the only feasible fleet composes
    # grouped + ep2 + every serving plane.
    assert smoke["plan"].cell["name"] == "int8+spec+packed"
    mp = smoke["moe_plan"]
    assert mp.feasible
    assert mp.cell["name"] == "moe-grouped-ep2+int8+spec+packed"
    assert mp.mix == "moe_agentic"
    assert any(r["cell"] == "moe-dense" for r in mp.rejected)


def test_duty_axis_binds():
    # duty < 1 gates prefill to every round(1/duty)-th step while the
    # fleet decodes (the engine's mixed_prefill_duty semantics) — it
    # must actually show up in the frontier, not profile identically to
    # base (budget-scaling never bound at swept traffic).
    loads = [8.0, 32.0]
    base = profile_cell(CellConfig("base"), "agentic", loads,
                        num_requests=48)
    half = profile_cell(CellConfig("duty-half", duty=0.5), "agentic",
                        loads, num_requests=48)
    assert half.points[0].ttft_p99_s > base.points[0].ttft_p99_s


def test_knee_concurrency_tracks_planned_cell(smoke):
    # dynamo_top HEADRM measures live workers against the knee of the
    # cell the plan DEPLOYS, not whatever cell happened to be swept
    # first.
    plan = smoke["plan"]
    meta = smoke["profile"]["meta"]["capacity"]
    chosen = next(f for f in smoke["frontiers"]
                  if f.cell.name == plan.cell["name"])
    assert meta["knee_concurrency_per_worker"] == pytest.approx(
        chosen.knee.mean_inflight / chosen.cell.workers)


def test_prefix_cache_hits_skip_prefill_work():
    recs = make_traffic("agentic", 32)
    s = simulate_cell(CellConfig("base"), recs)
    assert len(s.ttft_busy_s) == 32
    # The first request of a root pays the full context prefill; later
    # sharers skip the cached blocks — busy TTFT must reflect that.
    assert min(s.ttft_busy_s) < max(s.ttft_busy_s) / 2


def test_simulator_is_deterministic(smoke):
    again = run_smoke(None)
    assert (json.dumps(again["profile"], sort_keys=True)
            == json.dumps(smoke["profile"], sort_keys=True))
    assert again["plan"].to_dict() == smoke["plan"].to_dict()


def test_frontier_latency_rises_with_load(smoke):
    for f in smoke["frontiers"]:
        lats = [p.ttft_p99_s for p in f.points]
        # Saturated end must be far above the unloaded end (that's what
        # makes a knee findable), and the knee must exist in-range.
        assert lats[-1] > 2 * max(lats[0], 1e-6)
        assert f.knee_idx is not None
        assert 0 <= f.knee_idx < len(f.points)


# -- profile schema ----------------------------------------------------------


def test_profile_round_trips_and_planner_consumes_it(smoke, tmp_path):
    from dynamo_tpu.planner.interpolation import (
        DecodeInterpolator,
        PrefillInterpolator,
        load_profile,
        save_profile,
    )
    from dynamo_tpu.planner.sla import SlaObservation, SlaPlanner

    path = str(tmp_path / "sla_profile.json")
    save_profile(smoke["profile"], path)
    loaded = load_profile(path)
    assert loaded == json.loads(json.dumps(smoke["profile"]))
    assert loaded["meta"]["schema_version"] == 2
    assert loaded["meta"]["capacity"]["plan"]["feasible"] is True

    # The interpolators read the v1 grids and ignore meta entirely.
    pre = PrefillInterpolator(loaded)
    dec = DecodeInterpolator(loaded)
    assert pre.interpolate_ttft(256) > 0
    assert dec.interpolate_itl(0.5, 256) > 0

    class Conn:
        def __init__(self):
            self.n = 1

        def replicas(self):
            return self.n

        async def add_worker(self):
            self.n += 1

        async def remove_worker(self):
            self.n -= 1

    planner = SlaPlanner(loaded, observe=lambda: SlaObservation(),
                         decode_connector=Conn(),
                         prefill_connector=Conn())
    d = None
    for _ in range(3):
        d = planner.decide(SlaObservation(
            num_requests=200, avg_isl=216, avg_osl=16,
            ttft_s=0.05, itl_s=0.008))
    assert d.num_prefill >= 1 and d.num_decode >= 1


# -- capacity model ----------------------------------------------------------


def test_pinned_cheapest_fleet(smoke):
    """The acceptance fixture: SMOKE_SLO at 40 rps on the agentic mix.
    The sweep is a pure virtual clock, so this is byte-stable; drift
    means the timing model changed and the pin must be re-derived
    consciously."""
    plan = smoke["plan"]
    assert plan.feasible
    assert plan.cell["name"] == "int8+spec+packed"
    assert plan.replicas == 3
    assert plan.total_chips == 3
    assert plan.per_replica_rps == 16.0
    # The composed cell must beat the plain ones: base sustains less.
    by_name = {f.cell.name: f for f in smoke["frontiers"]}
    base_rps, _ = sustainable_rps(by_name["base"], SMOKE_SLO)
    assert base_rps < plan.per_replica_rps


def test_capacity_refuses_over_slo(smoke):
    plan = plan_capacity(smoke["frontiers"],
                         SloTarget(ttft_p99_s=0.001, tpot_p99_s=1e-4),
                         40.0)
    assert not plan.feasible
    assert plan.cell is None
    assert len(plan.rejected) == len(smoke["frontiers"])
    assert all("over SLO" in r["reason"] for r in plan.rejected)


def test_capacity_respects_replica_cap(smoke):
    plan = plan_capacity(smoke["frontiers"], SMOKE_SLO, 10_000.0,
                         max_replicas=3)
    assert not plan.feasible
    assert any("replicas" in r["reason"] for r in plan.rejected)


def test_agreement_tolerance_semantics():
    assert agreement(0.1, 0.15)                       # within factor
    assert agreement(0.0, 0.005)                      # within atol
    assert not agreement(0.1, 0.1 * (AGREEMENT_FACTOR + 1))
    assert not agreement(0.0, AGREEMENT_ATOL_S * 20)  # zero + far: no
    assert not agreement(0.1, 0.0)                    # no scrape data


# -- traffic mixes -----------------------------------------------------------


def test_traffic_mixes_shapes():
    ag = make_traffic("agentic", 48)
    lc = make_traffic("long_context", 48)
    di = make_traffic("diurnal", 48)
    assert len(ag) == len(lc) == len(di) == 48
    # Agentic shares prefixes; long-context never does.
    assert len({tuple(r.hash_ids) for r in ag}) < 48
    assert len({tuple(r.hash_ids) for r in lc}) == 48
    assert lc[0].input_length > ag[0].input_length
    # Diurnal: bursty — inter-arrival gaps vary ~4x trough-to-peak.
    gaps = [b.timestamp - a.timestamp for a, b in zip(di, di[1:])]
    assert max(gaps) > 2.5 * min(gaps)
    with pytest.raises(ValueError):
        make_traffic("nope", 8)


def test_scale_to_rate_preserves_shape():
    di = make_traffic("diurnal", 48)
    scaled = scale_to_rate(di, 100.0)
    span_s = (scaled[-1].timestamp - scaled[0].timestamp) / 1e3
    assert (len(scaled) - 1) / span_s == pytest.approx(100.0, rel=1e-6)
    gaps0 = [b.timestamp - a.timestamp for a, b in zip(di, di[1:])]
    gaps1 = [b.timestamp - a.timestamp
             for a, b in zip(scaled, scaled[1:])]
    ratios = [g1 / g0 for g0, g1 in zip(gaps0, gaps1)]
    assert max(ratios) == pytest.approx(min(ratios), rel=1e-9)


# -- CLI ---------------------------------------------------------------------


def test_cli_smoke_emits_planner_loadable_profile(tmp_path):
    """The acceptance command: `python -m benchmarks.sla_profiler
    --smoke` emits a profile SlaPlanner loads unchanged and prints the
    pinned capacity answer."""
    from benchmarks.sla_profiler import main

    out = str(tmp_path / "prof.json")
    assert main(["--smoke", "--out", out]) == 0
    from dynamo_tpu.planner.interpolation import load_profile
    from dynamo_tpu.planner.sla import SlaObservation, SlaPlanner

    prof = load_profile(out)

    class Conn:
        n = 1

        def replicas(self):
            return self.n

    SlaPlanner(prof, observe=lambda: SlaObservation(),
               decode_connector=Conn())
    plan = prof["meta"]["capacity"]["plan"]
    assert plan["feasible"] and plan["cell"]["name"] == "int8+spec+packed"


# -- fleet validation (the observability-plane cross-check) ------------------


def _drive_fleet_and_scrape(num_workers, num_requests, rps,
                            profile_path, speedup=0.1):
    """Run the mocker fleet, scrape it with the REAL dynamo_top CLI
    (--once --json --profile), return (modeled stats, snapshot).

    `speedup < 1` STRETCHES the mocker's simulated time: per-step
    event-loop overhead (which a 100-engine loop pays in milliseconds)
    shrinks relative to simulated latency, so the scrape measures the
    queueing model instead of asyncio scheduling.  0.1 keeps the
    overhead term under the documented 10 ms absolute tolerance even
    with the rest of the suite contending for the CPU (0.25 was
    observed marginal there: ~46 ms wall overhead → 11.6 ms sim)."""
    cell = CellConfig("fleet", workers=num_workers)
    records = scale_to_rate(make_traffic("agentic", num_requests), rps)
    modeled = simulate_cell(cell, records)

    async def drive():
        cp_port, summary, teardown = await run_fleet(
            cell, records, num_workers=num_workers, slo=SMOKE_SLO,
            speedup_ratio=speedup)
        try:
            proc = await asyncio.create_subprocess_exec(
                sys.executable,
                os.path.join(REPO, "tools", "dynamo_top.py"),
                "--control-plane", f"127.0.0.1:{cp_port}",
                "--once", "--json", "--profile", profile_path,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE, cwd=REPO)
            out, err = await asyncio.wait_for(proc.communicate(), 120)
            assert proc.returncode == 0, err.decode()[-2000:]
            return summary, json.loads(out.decode())
        finally:
            await teardown()

    summary, snapshot = asyncio.run(asyncio.wait_for(drive(), 300))
    return modeled, summary, snapshot


@pytest.mark.slow
def test_fleet_100_workers_matches_model(tmp_path):
    """The fleet-scale acceptance check: 100 real MockEngine workers,
    each with its own status server, driven under generated agentic
    load; TTFT/TPOT scraped via the real `dynamo_top --once --json`
    must agree with the modeled values within the documented tolerance,
    every worker row must carry an SLO verdict, and `--profile` must
    fill the capacity-headroom column."""
    from benchmarks.sla_profiler import (
        fleet_quantiles_from_snapshot,
        percentile,
    )

    profile_path = str(tmp_path / "prof.json")
    run_smoke(profile_path)
    modeled, summary, snapshot = _drive_fleet_and_scrape(
        num_workers=100, num_requests=300, rps=1200.0,
        profile_path=profile_path)

    rows = [p for p in snapshot["processes"]
            if not p.get("unreachable")]
    assert len(rows) == 100
    scraped = fleet_quantiles_from_snapshot(snapshot)
    assert scraped["workers"] == 100
    # Every worker carries an SLO verdict from its own monitor.
    assert all(r.get("slo_state") in ("OK", "WARN", "PAGE")
               for r in rows)
    # --profile fills headroom: drained fleet, inflight 0 → 100%.
    assert all(r.get("capacity_headroom") == pytest.approx(1.0)
               for r in rows)

    mod_ttft = percentile(modeled.ttft_s, 50)
    mod_tpot = percentile(modeled.tpot_s, 50)
    assert agreement(mod_ttft, scraped["ttft_p50_s"]), (
        f"modeled ttft_p50 {mod_ttft} vs scraped "
        f"{scraped['ttft_p50_s']}")
    assert agreement(mod_tpot, scraped["tpot_p50_s"]), (
        f"modeled tpot_p50 {mod_tpot} vs scraped "
        f"{scraped['tpot_p50_s']}")
    # The driver's own wall measurements corroborate the scrape (same
    # histograms, so quantiles can only differ by bucket rounding).
    assert agreement(summary["ttft_p50_s"], scraped["ttft_p50_s"],
                     factor=1.5)


def test_fleet_smoke_cell_agrees_inprocess():
    """Tier-1-sized version: 4 workers through the in-process collector
    (the bench_gate smoke runs the same path; this keeps the contract
    pinned even when the gate is skipped)."""
    from benchmarks.sla_profiler import validate_fleet_model

    res = validate_fleet_model(
        CellConfig("base"), "agentic", 30.0, num_workers=4,
        num_requests=24, slo=SMOKE_SLO)
    assert res["ttft_p50_agree"], res
    assert res["tpot_p50_agree"], res
    assert res["scraped"]["workers"] == 4
    assert res["scraped"]["slo_states"]
