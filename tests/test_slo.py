"""SLO burn-rate monitor (runtime/slo.py): window math, state
transitions, NaN propagation, sources, and the planner scale-up bias."""

import json

from dynamo_tpu.runtime.metrics import (
    Histogram, MetricsRegistry, RequestMetrics)
from dynamo_tpu.runtime.slo import (
    OK, PAGE, WARN, SloMonitor, SloObjective, disabled_payload,
    error_source, latency_source, max_burn, monitor_from_args)


class _Source:
    """Controllable cumulative (total, bad) source."""

    def __init__(self):
        self.total = 0.0
        self.bad = 0.0

    def __call__(self):
        return self.total, self.bad


def _monitor(src, objective=0.99, **kw):
    kw.setdefault("fast_window", 300.0)
    kw.setdefault("slow_window", 3600.0)
    return SloMonitor([(SloObjective("ttft_p99", objective=objective,
                                     threshold_s=0.5), src)], **kw)


def _obj(payload, name="ttft_p99"):
    return next(o for o in payload["objectives"] if o["name"] == name)


# -- burn-rate math ----------------------------------------------------------


def test_burn_rate_is_bad_fraction_over_budget():
    src = _Source()
    mon = _monitor(src)
    mon.tick(now=0.0)
    src.total, src.bad = 1000.0, 0.0
    o = _obj(mon.tick(now=100.0))
    assert o["burn_fast"] == 0.0 and o["compliant"]
    # 50 bad of 2000 window events = 2.5% bad over a 1% budget → burn
    # 2.5 on both windows (they cover the same samples here).
    src.total, src.bad = 2000.0, 50.0
    o = _obj(mon.tick(now=200.0))
    assert abs(o["bad_frac_fast"] - 0.025) < 1e-9
    assert abs(o["burn_fast"] - 2.5) < 1e-6
    assert abs(o["burn_slow"] - 2.5) < 1e-6
    assert not o["compliant"]


def test_window_edges_old_samples_excluded_from_fast_window():
    src = _Source()
    mon = _monitor(src)
    mon.tick(now=0.0)                      # (0, 0)
    src.total, src.bad = 1000.0, 500.0
    mon.tick(now=1000.0)                   # old badness
    src.total, src.bad = 1100.0, 500.0     # 100 clean events since
    o = _obj(mon.tick(now=1400.0))
    # Fast window [1100, 1400]: baseline = the t=1000 sample → clean.
    assert o["burn_fast"] == 0.0
    # Slow window still sees the incident.
    assert o["burn_slow"] > 10.0


def test_no_traffic_burns_no_budget():
    src = _Source()
    mon = _monitor(src)
    mon.tick(now=0.0)
    o = _obj(mon.tick(now=100.0))
    assert o["burn_fast"] == 0.0 and o["burn_slow"] == 0.0
    assert o["bad_frac_fast"] is None       # no events ≠ 0% bad
    assert o["compliant"]
    assert mon.state == OK


def test_counter_reset_treated_as_no_data():
    src = _Source()
    mon = _monitor(src)
    src.total, src.bad = 1000.0, 100.0
    mon.tick(now=0.0)
    src.total, src.bad = 10.0, 0.0          # process restarted
    o = _obj(mon.tick(now=100.0))
    assert o["burn_fast"] == 0.0 and o["compliant"]


def test_series_pruned_but_slow_window_baseline_kept():
    src = _Source()
    mon = _monitor(src, slow_window=100.0, fast_window=10.0)
    for i in range(50):
        src.total += 10
        mon.tick(now=float(i * 10))
    dq = mon._series["ttft_p99"]
    # Bounded: only ~slow_window worth of samples plus one baseline.
    assert len(dq) <= 13
    assert dq[0][0] <= 490.0 - 100.0  # a baseline at/just past the edge


# -- state transitions -------------------------------------------------------


def test_warn_then_page_transitions():
    src = _Source()
    mon = _monitor(src, warn_burn=3.0, page_burn=14.4)
    mon.tick(now=0.0)
    # 4% bad (burn 4): WARN but not PAGE.
    src.total, src.bad = 1000.0, 40.0
    mon.tick(now=100.0)
    assert mon.state == WARN
    # Incident escalates: 20% bad in the new traffic → burn >= 14.4 on
    # both windows.
    src.total, src.bad = 2000.0, 340.0
    mon.tick(now=200.0)
    assert mon.state == PAGE
    # Recovery: fast window clears first (PAGE needs BOTH windows).
    src.total, src.bad = 4000.0, 340.0
    mon.tick(now=500.0)
    assert mon.state in (OK, WARN)
    assert mon.state != PAGE


def test_state_gauges_exported():
    registry = MetricsRegistry()
    src = _Source()
    mon = _monitor(src, registry=registry)
    mon.tick(now=0.0)
    src.total, src.bad = 1000.0, 500.0
    mon.tick(now=10.0)
    text = registry.expose()
    assert 'dynamo_slo_burn_rate{objective="ttft_p99",window="fast"}' in text
    assert 'dynamo_slo_compliant{objective="ttft_p99"} 0.0' in text
    assert "dynamo_slo_state 2.0" in text


# -- NaN propagation / JSON safety -------------------------------------------


def test_empty_histogram_nan_propagates_as_none_and_json_safe():
    hist = Histogram("t", "t")
    # The underlying NaN contract (Histogram.mean on no data) ...
    import math

    assert math.isnan(hist.mean())
    assert math.isnan(hist.total_mean())
    # ... must surface as JSON null, never a bare NaN token.
    mon = SloMonitor([(SloObjective("ttft_p99", threshold_s=0.5),
                       latency_source(hist, 0.5))])
    payload = mon.tick(now=0.0)
    payload = mon.tick(now=10.0)
    o = _obj(payload)
    assert o["bad_frac_fast"] is None
    parsed = json.loads(json.dumps(payload, allow_nan=False))
    assert parsed["state"] == OK


# -- sources -----------------------------------------------------------------


def test_latency_source_counts_above_threshold_as_bad():
    hist = Histogram("t", "t")
    for _ in range(9):
        hist.observe(0.01, {"model": "a"})
    hist.observe(2.0, {"model": "b"})       # across label sets
    total, bad = latency_source(hist, 0.5)()
    assert total == 10 and bad == 1


def test_error_source_reads_outcome_counter():
    registry = MetricsRegistry()
    rm = RequestMetrics(registry)
    for _ in range(7):
        rm.observe_outcome(ok=True)
    rm.observe_outcome(ok=False)
    total, bad = error_source(rm.outcomes)()
    assert total == 8 and bad == 1


def test_monitor_from_args_flag_surface():
    import argparse

    from dynamo_tpu.runtime.slo import add_slo_args

    p = argparse.ArgumentParser()
    add_slo_args(p)
    registry = MetricsRegistry()
    rm = RequestMetrics(registry)
    assert monitor_from_args(p.parse_args([]), rm) is None
    args = p.parse_args(["--slo-ttft-p99", "0.5", "--slo-error-rate",
                         "0.01", "--slo-fast-window", "60"])
    mon = monitor_from_args(args, rm, registry=registry)
    names = {obj.name for obj, _ in mon.objectives}
    assert names == {"ttft_p99", "error_rate"}
    assert mon.fast_window == 60.0
    rm.ttft.observe(0.1, {"model": "m"})
    payload = mon.tick(now=0.0)
    assert payload["enabled"] and len(payload["objectives"]) == 2


def test_max_burn_helper():
    assert max_burn(None) == 0.0
    assert max_burn(disabled_payload()) == 0.0
    assert max_burn({"enabled": True, "objectives": [
        {"burn_fast": 1.5}, {"burn_fast": None}, {"burn_fast": 7.0},
    ]}) == 7.0


# -- planner bias ------------------------------------------------------------


class _Conn:
    def __init__(self, n):
        self.n = n

    def replicas(self):
        return self.n


def test_planner_scales_up_on_slo_burn_and_vetoes_scale_down():
    import time

    from dynamo_tpu.planner.core import LoadPlanner, PlannerConfig
    from dynamo_tpu.runtime.control_plane import InProcessControlPlane

    def inject(planner, burn):
        planner._slo = {"enabled": True,
                        "objectives": [{"burn_fast": burn}]}
        planner._slo_ts = time.monotonic()

    cp = InProcessControlPlane()
    planner = LoadPlanner(cp, _Conn(2), PlannerConfig(
        min_replicas=1, max_replicas=4, slo_burn_scale_up=2.0))
    # No SLO payload, no metrics: no decision.
    assert planner.plan_step() is None
    # Burning budget fast → scale up without any load observation.
    inject(planner, 5.0)
    assert planner.plan_step() == "up"
    assert "slo_burn~5.0" in planner._reason()
    # A stale payload (dead SLO source) stops exerting pressure.
    planner._slo_ts = time.monotonic() - 120.0
    assert planner.slo_pressure() == 0.0
    assert planner.plan_step() is None
    # At max replicas the bias cannot exceed the ceiling.
    inject(planner, 5.0)
    planner.connector = _Conn(4)
    assert planner.plan_step() is None
    # Sub-threshold but >= 1.0 burn vetoes scale-down even at low usage.
    planner2 = LoadPlanner(cp, _Conn(2), PlannerConfig(
        min_replicas=1, max_replicas=4, kv_low=0.5))
    inject(planner2, 1.2)

    def observe():
        return (2, 0.05, 0)

    planner2._observe = observe
    assert planner2.plan_step() is None     # would be "down" without SLO
    inject(planner2, 0.2)
    assert planner2.plan_step() == "down"
