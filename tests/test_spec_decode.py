"""Self-speculative decoding (ISSUE 6b): batched verify + rejection
sampling + pluggable drafters.

The load-bearing property is LOSSLESSNESS: greedy spec output is
byte-identical to plain greedy (argmax chain), and stochastic spec
preserves the exact sampling distribution (Leviathan-style rejection
sampling with a point-mass proposal).  Draft quality may only change
speed, never bytes.
"""

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine.drafter import DraftModelDrafter, NgramDrafter
from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams, speculative_verify
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.models import config as mcfg

TINY = mcfg.get_config("tiny-test")


def small_engine(**kw) -> EngineCore:
    defaults = dict(
        model=TINY,
        num_blocks=64,
        scheduler=SchedulerConfig(
            max_seqs=8, block_size=8, max_pages_per_seq=8,
            max_prefill_chunk=16,
            decode_buckets=(1, 2, 4, 8), prefill_buckets=(8, 16)),
    )
    defaults.update(kw)
    return EngineCore(EngineConfig(**defaults))


def run_to_completion(core, max_steps=800):
    outputs = {}
    for _ in range(max_steps):
        for d in core.step():
            outputs.setdefault(d.request_id, []).extend(d.token_ids)
        if core.scheduler.num_active == 0 and not core._requests:
            break
    return outputs


# -- speculative_verify ------------------------------------------------------


def _verify(logits, drafts, temp, keys, top_k=None, top_p=None):
    B = logits.shape[0]
    return speculative_verify(
        jnp.asarray(logits, jnp.float32), jnp.asarray(drafts, jnp.int32),
        jnp.asarray(temp, jnp.float32),
        jnp.asarray(top_k if top_k is not None else np.zeros(B), jnp.int32),
        jnp.asarray(top_p if top_p is not None else np.ones(B),
                    jnp.float32),
        keys)


def test_greedy_verify_is_argmax_chain():
    """Greedy rows: accept while draft == argmax; emitted tokens are
    exactly the argmax chain (positions 0..n_emit-1)."""
    V, K = 8, 3
    logits = np.full((2, K + 1, V), -5.0, np.float32)
    # Row 0: argmax sequence [2, 4, 6, 1]; draft [2, 4, 0] → accept 2,
    # emit [2, 4, 6] (6 = argmax at the first rejection).
    for j, t in enumerate([2, 4, 6, 1]):
        logits[0, j, t] = 5.0
    # Row 1: argmax [3, 3, 3, 3]; draft [3, 3, 3] → full accept + bonus.
    for j in range(K + 1):
        logits[1, j, 3] = 5.0
    drafts = np.array([[2, 4, 0], [3, 3, 3]], np.int32)
    keys = jax.random.split(jax.random.key(0), 2)
    emitted, n_emit = _verify(logits, drafts, [0.0, 0.0], keys)
    emitted, n_emit = np.asarray(emitted), np.asarray(n_emit)
    assert n_emit.tolist() == [3, 4]
    assert emitted[0, :3].tolist() == [2, 4, 6]
    assert emitted[1, :4].tolist() == [3, 3, 3, 3]

    # The static greedy_only fast path (argmax-only, no sort/softmax/
    # categorical — what all-greedy serving batches compile) must agree
    # exactly with the traced temperature==0 path.
    em2, ne2 = speculative_verify(
        jnp.asarray(logits), jnp.asarray(drafts),
        jnp.zeros(2), jnp.zeros(2, jnp.int32), jnp.ones(2), keys,
        greedy_only=True)
    assert np.asarray(ne2).tolist() == n_emit.tolist()
    for b in range(2):
        assert (np.asarray(em2)[b, :n_emit[b]].tolist()
                == emitted[b, :n_emit[b]].tolist())


def test_rejection_sampling_preserves_distribution():
    """The lossless-acceptance core: with a point-mass draft, the
    marginal of the FIRST emitted token must equal the target softmax —
    draft accepted (emit d) with prob p(d), else residual resample."""
    V, N = 6, 6000
    row_logits = np.array([2.0, 1.0, 0.5, 0.0, -1.0, -2.0], np.float32)
    target = np.exp(row_logits) / np.exp(row_logits).sum()
    logits = np.broadcast_to(row_logits, (N, 2, V)).copy()
    drafts = np.full((N, 1), 1, np.int32)  # draft token 1 (p ≈ 0.26)
    keys = jax.random.split(jax.random.key(7), N)
    emitted, n_emit = _verify(logits, drafts, np.ones(N, np.float32), keys)
    first = np.asarray(emitted)[:, 0]
    emp = np.bincount(first, minlength=V) / N
    np.testing.assert_allclose(emp, target, atol=0.03)
    # And acceptance happened at roughly p(draft).
    acc_rate = (np.asarray(n_emit) > 1).mean()
    assert abs(acc_rate - target[1]) < 0.03


def test_verify_respects_top_k_filter():
    """A draft outside the top-k set must never be accepted, and the
    resample must come from the filtered set."""
    V, N = 8, 500
    row_logits = np.array([3.0, 2.5, 2.0, -1, -1, -1, -1, -1], np.float32)
    logits = np.broadcast_to(row_logits, (N, 2, V)).copy()
    drafts = np.full((N, 1), 7, np.int32)       # far outside top-3
    keys = jax.random.split(jax.random.key(9), N)
    emitted, n_emit = _verify(logits, drafts, np.ones(N, np.float32),
                              keys, top_k=np.full(N, 3))
    assert np.all(np.asarray(n_emit) == 1)       # never accepted
    assert set(np.asarray(emitted)[:, 0].tolist()) <= {0, 1, 2}


# -- drafters ----------------------------------------------------------------


def test_ngram_drafter_self_extends():
    """The truncated-continuation fix: a period-1 cycle must draft k
    tokens, not 1 (the match near the tail yields a 1-token continuation
    that re-lookup extends)."""
    d = NgramDrafter(ngram=3)
    hist = [7, 8, 9] + [5] * 6
    assert d.propose(hist, 4) == [5, 5, 5, 5]
    # Period-2 cycle extends too.
    hist2 = [1, 2] * 6
    assert d.propose(hist2, 4) == [1, 2, 1, 2]
    # No repetition → no draft.
    assert d.propose([1, 2, 3, 4, 5, 6], 4) == []
    assert d.propose([1, 2], 4) == []


def test_draft_model_drafter_adapter():
    calls = []

    def fn(hist, k):
        calls.append((len(hist), k))
        return [42] * (k + 5)  # over-long: adapter truncates

    d = DraftModelDrafter(fn)
    assert d.propose([1, 2, 3], 3) == [42, 42, 42]
    assert calls == [(3, 3)]


def test_pluggable_drafter_wrong_drafts_stay_lossless():
    """A deliberately WRONG drafter: outputs must still equal plain
    greedy (verify rejects everything), acceptance telemetry reads 0."""
    prompt = [5, 6, 7, 8] * 4

    plain = small_engine(decode_window=1)
    plain.add_request("a", prompt, SamplingParams(max_tokens=10))
    want = run_to_completion(plain)

    class WrongDrafter:
        def propose(self, history, k):
            return [0] * k  # token 0 is (practically) never the argmax

    spec = small_engine(speculative_tokens=3, drafter=WrongDrafter())
    spec.add_request("a", prompt, SamplingParams(max_tokens=10))
    got = run_to_completion(spec)
    assert got == want
    stats = spec.metrics.spec_decode_stats
    assert stats.num_drafts > 0 and stats.num_accepted_tokens == 0


# -- engine integration ------------------------------------------------------


def test_seeded_stochastic_keeps_plain_path_contract():
    """Seeded stochastic rows are routed AROUND the spec path (a jointly
    drawn burst can't honor the (seed, token-index) stream contract), so
    --spec-decode must not change a seeded request's bytes at all."""
    sp = SamplingParams(temperature=0.8, seed=42, max_tokens=10)
    prompt = [5, 6, 7, 8] * 3 + [5, 6]

    plain = small_engine()
    plain.add_request("r", prompt, sp)
    want = run_to_completion(plain)["r"]

    spec = small_engine(speculative_tokens=3)
    spec.add_request("r", prompt, sp)
    got = run_to_completion(spec)["r"]
    assert got == want and len(got) == 10
    # And the spec path really was bypassed for the seeded request.
    assert spec.counters.spec_dispatches == 0


def test_unseeded_stochastic_spec_runs():
    """Unseeded stochastic rows stay spec-eligible (rejection sampling
    preserves their distribution); the stream completes at length.  A
    constant-draft drafter forces the verify step to dispatch — sampled
    output rarely repeats, so the n-gram drafter alone would sit out."""
    class ConstantDrafter:
        def propose(self, history, k):
            return [history[-1]] * k

    core = small_engine(speculative_tokens=3, drafter=ConstantDrafter())
    core.add_request("r", [5, 6, 7, 8] * 3 + [5, 6],
                     SamplingParams(temperature=0.8, max_tokens=10))
    out = run_to_completion(core)["r"]
    assert len(out) == 10
    assert core.counters.spec_dispatches > 0


def test_mixed_greedy_and_stochastic_spec_batch():
    """Greedy and stochastic rows share one verify step; the greedy
    row's output must still be byte-identical to its solo plain run."""
    prompt_g = [5, 6, 7, 8] * 4
    plain = small_engine(decode_window=1)
    plain.add_request("g", prompt_g, SamplingParams(max_tokens=10))
    want_g = run_to_completion(plain)["g"]

    core = small_engine(speculative_tokens=3)
    core.add_request("g", prompt_g, SamplingParams(max_tokens=10))
    core.add_request("s", [9, 9, 8, 9, 9, 8],
                     SamplingParams(temperature=0.9, max_tokens=10))
    got = run_to_completion(core)
    assert got["g"] == want_g
    assert len(got["s"]) == 10


def test_spec_metrics_exported():
    """Acceptance-rate + effective-bytes series reach /metrics via
    KvCacheMetrics.observe_engine."""
    from dynamo_tpu.runtime.metrics import KvCacheMetrics, MetricsRegistry

    core = small_engine(speculative_tokens=3)
    core.add_request("a", [5, 6, 7, 8] * 4, SamplingParams(max_tokens=24))
    run_to_completion(core)
    stats = core.metrics.spec_decode_stats
    assert stats.num_drafts > 0 and stats.num_accepted_tokens > 0
    assert core.counters.spec_dispatches > 0
    assert core.counters.effective_bytes_per_token > 0

    reg = MetricsRegistry()
    kvm = KvCacheMetrics(reg)
    kvm.observe_engine(core)
    text = reg.expose()
    assert kvm.spec_drafted.value() == stats.num_drafts
    assert kvm.spec_accepted.value() == stats.num_accepted_tokens
    assert kvm.spec_acceptance_rate.value() == (
        stats.num_accepted_tokens / stats.num_drafts)
    assert "dynamo_spec_decode_acceptance_rate" in text
    assert "dynamo_kv_effective_bytes_per_token" in text


def test_acceptance_floor_on_repetitive_workload():
    """The bench_gate floor, run tier-1: acceptance >= 0.6 and modeled
    sweep speedup >= 1.3 on the acceptance-friendly workload, with spec
    output byte-identical to the non-spec baseline."""
    from dynamo_tpu.bench.decode_wall import measure_spec_acceptance

    res = measure_spec_acceptance(TINY, n_requests=1, n_out=32)
    assert res["acceptance_rate"] >= 0.6
    assert res["modeled_decode_speedup"] >= 1.3
    assert res["output_identical_to_baseline"]
    assert res["accepted_per_pos"][0] >= res["accepted_per_pos"][-1]
