import pytest

from dynamo_tpu.tokens import (
    ROOT_PARENT_HASH,
    SaltedBlockHasher,
    TokenBlockSequence,
    compute_block_hashes,
    hash_block,
)


def test_hash_determinism():
    assert hash_block(ROOT_PARENT_HASH, [1, 2, 3]) == hash_block(ROOT_PARENT_HASH, [1, 2, 3])
    assert hash_block(ROOT_PARENT_HASH, [1, 2, 3]) != hash_block(ROOT_PARENT_HASH, [1, 2, 4])
    assert hash_block(1, [1, 2, 3]) != hash_block(2, [1, 2, 3])


def test_chained_hashes_commit_to_prefix():
    a = compute_block_hashes(list(range(128)), 64)
    b = compute_block_hashes(list(range(64)) + list(range(100, 164)), 64)
    assert len(a) == len(b) == 2
    assert a[0] == b[0]  # shared first block
    assert a[1] != b[1]  # diverged second block
    # same second-block *content* with different prefix hashes differently
    c = compute_block_hashes(list(range(1, 65)) + list(range(64, 128)), 64)
    assert c[1] != a[1]


def test_partial_block_not_hashed():
    assert compute_block_hashes(list(range(63)), 64) == []
    assert len(compute_block_hashes(list(range(65)), 64)) == 1


def test_token_block_sequence_incremental_matches_bulk():
    toks = list(range(200))
    seq = TokenBlockSequence(block_size=16)
    completed = []
    for t in toks:
        blk = seq.append(t)
        if blk:
            completed.append(blk.block_hash)
    assert completed == compute_block_hashes(toks, 16)
    assert len(seq) == 200
    assert seq.tokens == toks
    assert len(seq.partial_tokens) == 200 % 16


def test_token_block_sequence_truncate():
    seq = TokenBlockSequence(range(100), block_size=16)
    seq.truncate(40)
    assert len(seq) == 40
    assert seq.block_hashes == compute_block_hashes(list(range(40)), 16)


def test_salted_hasher_domain_separation():
    toks = list(range(64))
    plain = compute_block_hashes(toks, 64)
    salted = SaltedBlockHasher(salt=b"lora-x").block_hashes(toks, 64)
    assert plain != salted
    assert SaltedBlockHasher().block_hashes(toks, 64) == plain


def test_bad_block_size():
    with pytest.raises(ValueError):
        compute_block_hashes([1], 0)


def test_numpy_array_input_no_silent_wrap():
    import numpy as np

    with pytest.raises(ValueError):
        compute_block_hashes(np.array([-1, 5, 6, 7], dtype=np.int64), 4)
    with pytest.raises(ValueError):
        compute_block_hashes(np.array([2**33, 5, 6, 7], dtype=np.int64), 4)
    # valid numpy input matches list input
    assert compute_block_hashes(np.array([1, 2, 3, 4], dtype=np.int64), 4) == compute_block_hashes(
        [1, 2, 3, 4], 4
    )


def test_append_bad_token_does_not_wedge_sealing():
    seq = TokenBlockSequence(block_size=2)
    seq.append(1)
    with pytest.raises(ValueError):
        seq.append(2**33)
    assert seq.append(2) is not None  # sealing still works
    assert seq.block_hashes == compute_block_hashes([1, 2], 2)


def test_bulk_extend_matches_per_token():
    toks = list(range(1000))
    a = TokenBlockSequence(block_size=16)
    a.extend(toks)
    b = TokenBlockSequence(block_size=16)
    for t in toks:
        b.append(t)
    assert a.block_hashes == b.block_hashes
    assert a.tokens == b.tokens


def test_truncate_preserves_prefix_blocks_identity():
    seq = TokenBlockSequence(range(100), block_size=16)
    before = seq.blocks[:4]
    seq.truncate(70)  # 4 full blocks + 6 tail
    assert seq.blocks == before
    assert seq.tokens == list(range(70))
    seq.truncate(64)
    assert seq.tokens == list(range(64))
    # truncate into partial tail only
    s2 = TokenBlockSequence(range(10), block_size=16)
    s2.truncate(3)
    assert s2.tokens == [0, 1, 2]


def test_float_tokens_rejected():
    import numpy as np

    with pytest.raises(ValueError):
        compute_block_hashes(np.array([1.5, 2.7, 3.0, 4.9]), 4)
    # exact-integer floats are accepted and match int input
    assert compute_block_hashes(np.array([1.0, 2.0, 3.0, 4.0]), 4) == compute_block_hashes(
        [1, 2, 3, 4], 4
    )
