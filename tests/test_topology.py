"""Slice topology plane unit tests (ISSUE 16): the declarative SliceSpec
codec, --slice parsing, reachability, and the fleet-brain reads
(placement validation, role placement, donor preference ordering)."""

import pytest

from dynamo_tpu.fleet.topology import (
    SliceSpec,
    donor_preference_key,
    free_hbm_bytes,
    parse_slice,
    place_role,
    stable_id_key,
    validate_placement,
)


class TestSliceSpec:
    def test_parse_full_spec(self):
        s = parse_slice("sp2xtp2,int8,packed,role=prefill")
        assert s.mesh == (1, 1, 2, 1, 2)
        assert s.role == "prefill"
        assert s.kv_quant == "int8"
        assert "packed_prefill" in s.features
        assert s.describe() == "sp2xtp2"
        assert s.chips == 4

    def test_parse_single_and_defaults(self):
        s = parse_slice("single")
        assert s.mesh == (1, 1, 1, 1, 1)
        assert s.role == "both" and s.kv_quant == "none"
        assert s.describe() == "single"

    def test_parse_rejects_unknown_token(self):
        with pytest.raises(ValueError):
            parse_slice("tp2,warp9")
        with pytest.raises(ValueError):
            parse_slice("role=sidecar")

    def test_wire_roundtrip(self):
        s = parse_slice("tp2,int8,role=decode,window4")
        back = SliceSpec.from_dict(s.to_dict())
        assert back == s

    def test_from_dict_tolerates_garbage(self):
        # Older workers publish nothing; version skew publishes junk —
        # the fleet brain must degrade to None/defaults, never raise.
        assert SliceSpec.from_dict(None) is None
        assert SliceSpec.from_dict("tp2") is None
        assert SliceSpec.from_dict({"mesh": [2]}) is None
        assert SliceSpec.from_dict({"mesh": ["x"] * 5}) is None
        s = SliceSpec.from_dict({"role": "sidecar"})
        assert s is not None and s.role == "both"

    def test_mesh_config_matches_describe(self):
        s = parse_slice("sp2xtp2")
        mc = s.mesh_config()
        assert mc.describe() == s.describe() == "sp2xtp2"

    def test_reachability(self):
        pj = SliceSpec(fabric="pjrt")
        loc_a = SliceSpec(fabric="local:1")
        loc_b = SliceSpec(fabric="local:2")
        none = SliceSpec(fabric="")
        assert pj.reachable(SliceSpec(fabric="pjrt"))
        assert loc_a.reachable(SliceSpec(fabric="local:1"))
        assert not loc_a.reachable(loc_b)
        assert not pj.reachable(loc_a)
        assert not none.reachable(none)  # host-wire-only builds


class TestPlacement:
    def test_decode_on_prefill_slice_refused(self):
        prefill = parse_slice("sp2xtp2,role=prefill")
        ok, reason = validate_placement("decode", prefill)
        assert not ok and "prefill" in reason

    def test_matching_and_unconstrained_placements(self):
        assert validate_placement("prefill",
                                  parse_slice("sp2,role=prefill"))[0]
        assert validate_placement("decode", parse_slice("tp2"))[0]
        assert validate_placement("decode", None)[0]  # mixed fleet
        assert not validate_placement("both",
                                      parse_slice("tp2,role=decode"))[0]
        assert not validate_placement("sidecar", None)[0]

    def test_place_role_picks_valid_slice_with_headroom(self):
        slices = {
            "p": parse_slice("sp2xtp2,role=prefill"),
            "d_small": SliceSpec(role="decode", hbm_per_chip_bytes=100),
            "d_big": SliceSpec(role="decode", hbm_per_chip_bytes=1000),
        }
        assert place_role("decode", slices) == "d_big"
        assert place_role("prefill", slices) == "p"
        # No slice serves "both" in a dedicated cell: spawn cue.
        assert place_role("both", slices) is None


class TestDonorPreference:
    def test_stable_id_total_order(self):
        # ints numeric (2 beats 10 — the old string compare bug class),
        # ints before strings, strings lexical.
        assert stable_id_key(2) < stable_id_key(10)
        assert stable_id_key(10) < stable_id_key("w0")
        assert stable_id_key("w0") < stable_id_key("w1")

    def test_reachability_dominates_overlap(self):
        far = donor_preference_key("far", 8, reachable=False)
        near = donor_preference_key("near", 6, reachable=True)
        assert near > far

    def test_free_hbm_breaks_equal_overlap(self):
        poor = donor_preference_key("a", 6, reachable=True, free_hbm=10)
        rich = donor_preference_key("b", 6, reachable=True, free_hbm=99)
        assert rich > poor

    def test_ascending_id_breaks_exact_ties(self):
        # max() over keys must prefer the LOWER id when all else ties.
        assert donor_preference_key(2, 6) > donor_preference_key(10, 6)
        assert donor_preference_key("w0", 6) > donor_preference_key("w1", 6)


class TestFreeHbm:
    def test_scaled_by_published_occupancy(self):
        class KvStats:
            gpu_cache_usage_perc = 0.75

        class Metrics:
            kv_stats = KvStats()

        spec = SliceSpec(mesh=(1, 1, 1, 1, 2), hbm_per_chip_bytes=1000)
        assert spec.total_hbm_bytes == 2000
        assert free_hbm_bytes(spec, Metrics()) == 500
        assert free_hbm_bytes(spec, None) == 2000

    def test_unknown_capacity_reports_zero(self):
        assert free_hbm_bytes(None, None) == 0
        assert free_hbm_bytes(SliceSpec(), None) == 0
