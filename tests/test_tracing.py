"""Distributed request tracing (runtime/tracing.py): context propagation
across an RPC hop, sampling, ring-buffer bounds, slow-request force
sampling, Chrome trace-event export, and the frontend+worker e2e merged
trace the ISSUE acceptance names.
"""

import asyncio
import json
import math

import pytest

from dynamo_tpu.runtime import tracing
from dynamo_tpu.runtime.tracing import TraceContext, Tracer, chrome_trace


def _run(coro, timeout=120):
    return asyncio.run(asyncio.wait_for(coro, timeout))


@pytest.fixture(autouse=True)
def _clean_global_tracer():
    tr = tracing.get_tracer()
    tr.enabled = False
    tr.sampling = 1.0
    tr.slow_ms = None
    tr.slow_log_path = None
    tr.reset()
    yield
    tr.enabled = False
    tr.sampling = 1.0
    tr.slow_ms = None
    tr.slow_log_path = None
    tr.reset()


# ---------------------------------------------------------------------------
# TraceContext wire format


def test_context_wire_roundtrip():
    ctx = TraceContext("tid1", "sid1", sampled=True)
    child = ctx.child()
    assert child.trace_id == "tid1"
    assert child.parent_id == "sid1"
    assert child.span_id != "sid1"
    back = TraceContext.from_wire(ctx.to_wire())
    assert back.trace_id == "tid1" and back.span_id == "sid1"
    assert back.sampled is True


def test_context_from_wire_malformed():
    for bad in (None, 42, "x", {}, {"trace_id": "t"}, {"span_id": "s"},
                {"trace_id": "", "span_id": "s"}):
        assert TraceContext.from_wire(bad) is None


# ---------------------------------------------------------------------------
# Tracer core: parenting, sampling, bounds, slow force-sampling


def test_span_parenting_and_finalize():
    tr = Tracer("svc", enabled=True)
    root = tr.start_span("root", trace_id="rid")
    child = tr.start_span("child", root)
    grand = tr.start_span("grand", child)
    grand.end()
    child.end()
    assert tr.completed() == []          # root still open: not finalized
    root.end()
    traces = tr.completed()
    assert len(traces) == 1
    spans = {s["name"]: s for s in traces[0]["spans"]}
    assert spans["child"]["parent_id"] == spans["root"]["span_id"]
    assert spans["grand"]["parent_id"] == spans["child"]["span_id"]
    assert all(s["trace_id"] == "rid" for s in traces[0]["spans"])


def test_disabled_tracer_is_noop():
    tr = Tracer("svc", enabled=False)
    span = tr.start_span("root")
    assert span is tracing.NULL_SPAN
    span.set_attr(x=1)
    span.end()
    assert tr.completed() == []
    assert tr.spans_recorded == 0


def test_sampling_honors_rate():
    tr = Tracer("svc", enabled=True, sampling=0.3, ring_size=4096)
    n = 600
    for _ in range(n):
        tr.start_span("root").end()
    kept = len(tr.completed())
    # Deterministic per-trace-id hash sampling over uuid ids: binomial
    # around 0.3 (sd ~11 at n=600); ±0.1 absolute is > 5 sd.
    assert 0.2 * n < kept < 0.4 * n, kept
    assert tr.traces_dropped_unsampled == n - kept


def test_sampling_is_deterministic_per_trace_id():
    tr = Tracer("svc", enabled=True, sampling=0.5)
    decisions = {tid: tr.start_span("r", trace_id=tid).ctx.sampled
                 for tid in ("a1", "b2", "c3", "d4")}
    for tid, want in decisions.items():
        again = tr.start_span("r", trace_id=tid)
        assert again.ctx.sampled is want


def test_ring_buffer_bounds_memory():
    tr = Tracer("svc", enabled=True, ring_size=8)
    for i in range(50):
        tr.start_span("root", trace_id=f"t{i}").end()
    traces = tr.completed()
    assert len(traces) == 8
    # Newest first, oldest evicted.
    assert traces[0]["trace_id"] == "t49"
    assert not tr._pending


def test_per_trace_span_cap():
    tr = Tracer("svc", enabled=True, max_spans_per_trace=16)
    root = tr.start_span("root", trace_id="big")
    for i in range(100):
        tr.start_span(f"s{i}", root).end()
    root.end()
    (trace,) = tr.completed()
    assert len(trace["spans"]) == 16
    assert trace["spans_dropped"] == 85  # 100 subs + root − 16 kept


def test_slow_request_force_sampling_fires(tmp_path):
    log = tmp_path / "slow.jsonl"
    tr = Tracer("svc", enabled=True, sampling=0.0, slow_ms=5.0,
                slow_log_path=str(log))
    # Fast + unsampled: dropped entirely.
    tr.start_span("root", trace_id="fast").end()
    assert tr.completed() == []
    # Slow + unsampled: force-kept and logged as structured JSONL.
    span = tr.start_span("root", trace_id="slow-one",
                         attrs={"rid": "slow-one", "model": "m"})
    import time

    time.sleep(0.02)
    span.end()
    (trace,) = tr.completed()
    assert trace["trace_id"] == "slow-one"
    assert trace["forced_slow_sample"] is True
    assert tr.traces_forced_slow == 1
    lines = [json.loads(line) for line in log.read_text().splitlines()]
    assert len(lines) == 1
    assert lines[0]["event"] == "slow_request"
    assert lines[0]["trace_id"] == "slow-one"
    assert lines[0]["duration_ms"] > 5.0
    assert lines[0]["attrs"]["model"] == "m"


def test_record_span_binding():
    """The engine-thread path: bind rid → ctx, record measured spans."""
    import time

    tr = Tracer("svc", enabled=True)
    root = tr.start_span("root", trace_id="rid")
    tr.bind("req-1", root.ctx)
    t0 = time.monotonic() - 0.25
    tr.record_span("engine.ttft", tr.ctx_for("req-1"), t0,
                   attrs={"request_id": "req-1"})
    tr.unbind("req-1")
    assert tr.ctx_for("req-1") is None
    root.end()
    (trace,) = tr.completed()
    spans = {s["name"]: s for s in trace["spans"]}
    assert spans["engine.ttft"]["parent_id"] == spans["root"]["span_id"]
    assert 0.2 < spans["engine.ttft"]["dur"] < 2.0


# ---------------------------------------------------------------------------
# Chrome trace-event export


def test_chrome_trace_export_is_valid():
    tr = Tracer("frontend", enabled=True)
    root = tr.start_span("http.chat", trace_id="rid")
    tr.start_span("router.select", root).end()
    root.end()
    out = chrome_trace(tr.completed())
    text = json.dumps(out)              # serializable
    parsed = json.loads(text)
    events = parsed["traceEvents"]
    xs = [e for e in events if e["ph"] == "X"]
    ms = [e for e in events if e["ph"] == "M"]
    assert len(xs) == 2 and len(ms) == 1
    for e in xs:
        assert isinstance(e["ts"], (int, float)) and e["ts"] > 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        assert e["args"]["trace_id"] == "rid"
    assert ms[0]["name"] == "process_name"
    assert ms[0]["args"]["name"] == "frontend"


def test_chrome_trace_dedupes_spans_across_payloads():
    tr = Tracer("svc", enabled=True)
    tr.start_span("root", trace_id="rid").end()
    traces = tr.completed()
    out = chrome_trace(traces + traces)   # same payload twice
    assert sum(1 for e in out["traceEvents"] if e["ph"] == "X") == 1


def test_trace_merge_payloads():
    from tools.trace_merge import merge_payloads

    f = Tracer("frontend", enabled=True)
    w = Tracer("worker", enabled=True)
    root = f.start_span("http.chat", trace_id="rid")
    client = f.start_span("rpc.client:generate", root)
    # Worker side parents off the wire context.
    ctx = TraceContext.from_wire(client.ctx.to_wire())
    server = w.start_span("rpc.server:generate", ctx)
    server.end()
    client.end()
    root.end()
    merged = merge_payloads([
        {"service": "frontend", "traces": f.completed()},
        {"service": "worker", "traces": w.completed()},
    ])
    xs = {e["name"]: e for e in merged["traceEvents"] if e["ph"] == "X"}
    assert set(xs) == {"http.chat", "rpc.client:generate",
                       "rpc.server:generate"}
    # One trace, two processes, parent chain intact across the hop.
    assert xs["rpc.server:generate"]["args"]["parent_id"] == \
        xs["rpc.client:generate"]["args"]["span_id"]
    assert xs["rpc.server:generate"]["pid"] != xs["http.chat"]["pid"]
    assert len({e["args"]["trace_id"] for e in
                merged["traceEvents"] if e["ph"] == "X"}) == 1


# ---------------------------------------------------------------------------
# RPC hop propagation (real RpcServer/RpcClient)


def test_rpc_hop_client_span_parents_server_span():
    from dynamo_tpu.runtime.rpc import RpcClient, RpcServer

    tr = tracing.get_tracer()
    tr.configure(enabled=True, sampling=1.0)

    async def main():
        server = RpcServer()

        async def handler(payload):
            # Worker-side sub-span under the server span (the engine
            # analog); the current span must be the rpc.server span.
            span = tracing.current_span()
            assert span is not None and span.name == "rpc.server:gen"
            with tr.start_span("work"):
                yield {"ok": 1}

        server.register("gen", handler)
        addr = await server.start()
        client = RpcClient(addr)
        root = tr.start_span("root", trace_id="rid-hop")
        tok = tracing.use_span(root)
        try:
            deltas = [d async for d in client.call("gen", {})]
        finally:
            tracing.restore(tok)
        assert deltas == [{"ok": 1}]
        # Server-side span end races the client's stream end; wait for
        # the server task to settle before closing the trace.
        for _ in range(100):
            if not server.active_streams:
                break
            await asyncio.sleep(0.01)
        await asyncio.sleep(0.02)
        root.end()
        await client.close()
        await server.stop()
        for _ in range(100):
            if tr.completed():
                break
            await asyncio.sleep(0.01)
        return tr.completed()

    traces = _run(main())
    assert len(traces) == 1
    spans = {s["name"]: s for s in traces[0]["spans"]}
    assert set(spans) == {"root", "rpc.client:gen", "rpc.server:gen",
                          "work"}
    assert all(s["trace_id"] == "rid-hop" for s in spans.values())
    assert spans["rpc.client:gen"]["parent_id"] == spans["root"]["span_id"]
    assert spans["rpc.server:gen"]["parent_id"] == \
        spans["rpc.client:gen"]["span_id"]
    assert spans["work"]["parent_id"] == spans["rpc.server:gen"]["span_id"]


def test_rpc_without_trace_field_still_works():
    from dynamo_tpu.runtime.rpc import RpcClient, RpcServer

    async def main():
        server = RpcServer()

        async def handler(payload):
            yield {"v": payload["x"] + 1}

        server.register("inc", handler)
        addr = await server.start()
        client = RpcClient(addr)
        out = [d async for d in client.call("inc", {"x": 1})]
        await client.close()
        await server.stop()
        return out

    assert _run(main()) == [{"v": 2}]


# ---------------------------------------------------------------------------
# Histogram edge behavior (satellite)


def test_histogram_nan_safe_edges():
    from dynamo_tpu.runtime.metrics import LATENCY_BUCKETS, Histogram

    h = Histogram("x", "")
    assert math.isnan(h.quantile(0.5))
    assert math.isnan(h.mean())
    assert math.isnan(h.quantile(0.0, labels={"model": "nope"}))
    h.observe(0.003)
    # Single observation answers every quantile with its own bucket.
    assert h.quantile(0.0) == h.quantile(0.5) == h.quantile(1.0) == 0.005
    assert h.quantile(-3.0) == h.quantile(7.5) == 0.005  # clamped
    h.observe(1e9)  # beyond the last bucket
    assert h.quantile(1.0) == float("inf")
    # Sub-ms resolution exists and the top covers a minute.
    assert LATENCY_BUCKETS[0] <= 0.0001 and LATENCY_BUCKETS[-1] >= 60.0


# ---------------------------------------------------------------------------
# End-to-end: frontend + worker over RPC → merged Perfetto trace


def test_e2e_frontend_worker_merged_trace():
    """The ISSUE acceptance scenario: a streamed chat request through
    HttpService → KV router → RPC → worker engine produces ONE trace with
    parented spans for routing, queue wait, prefill, and ≥3 decode token
    intervals; /metrics reports nonzero dynamo_request_ttft_seconds; the
    merged Chrome JSON from frontend + worker /debug/traces buffers loads
    as one timeline."""
    import aiohttp

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore, \
        InferenceEngine
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.llm.discovery import (
        ModelWatcher, engine_wire_handler, register_llm)
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.llm.service import LocalEngineClient, ModelManager
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.runtime.control_plane_tcp import (
        ControlPlaneClient, ControlPlaneServer)
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.status import StatusServer
    from tools.trace_merge import merge_payloads

    tr = tracing.get_tracer()
    tr.configure(enabled=True, sampling=1.0)

    async def main():
        cp_server = ControlPlaneServer()
        cp_port = await cp_server.start()

        # -- worker side (engine behind an RPC endpoint) ------------------
        wcp = ControlPlaneClient("127.0.0.1", cp_port)
        await wcp.start()
        wruntime = DistributedRuntime(wcp)
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=128,
            decode_window=1,   # one delta per token → real TPOT intervals
            scheduler=SchedulerConfig(
                max_seqs=4, block_size=8, max_pages_per_seq=32,
                max_prefill_chunk=128, decode_buckets=(1, 2, 4),
                prefill_buckets=(16, 128))))
        engine = InferenceEngine(core)
        await engine.start()
        endpoint = (wruntime.namespace("dynamo").component("backend")
                    .endpoint("generate"))
        instance = await endpoint.serve(
            engine_wire_handler(LocalEngineClient(engine)))
        await register_llm(endpoint, instance, ModelDeploymentCard(
            name="traced-model", kv_block_size=8))
        worker_status = StatusServer()
        worker_port = await worker_status.start()

        # -- frontend side (discovery + KV routing + HTTP) ----------------
        fcp = ControlPlaneClient("127.0.0.1", cp_port)
        await fcp.start()
        fruntime = DistributedRuntime(fcp)
        models = ModelManager()
        watcher = ModelWatcher(fruntime, models, router_mode="kv")
        await watcher.start()
        await watcher.wait_for_model("traced-model")
        svc = HttpService(models)
        http_port = await svc.start()

        try:
            async with aiohttp.ClientSession() as session:
                async with session.post(
                        f"http://127.0.0.1:{http_port}/v1/chat/completions",
                        json={"model": "traced-model",
                              "messages": [{"role": "user",
                                            "content": "hello trace"}],
                              "max_tokens": 10, "stream": True}) as resp:
                    assert resp.status == 200
                    body = await resp.text()
                    assert "data: [DONE]" in body

                async with session.get(
                        f"http://127.0.0.1:{http_port}/metrics") as resp:
                    metrics_text = await resp.text()

                # Both processes' trace buffers (shared tracer here; the
                # merge dedupes by span id exactly as it must for
                # co-located processes).
                async with session.get(
                        f"http://127.0.0.1:{http_port}/debug/traces?n=8"
                        ) as resp:
                    frontend_payload = await resp.json()
                async with session.get(
                        f"http://127.0.0.1:{worker_port}/debug/traces?n=8"
                        ) as resp:
                    worker_payload = await resp.json()
        finally:
            await svc.stop()
            await worker_status.stop()
            await watcher.stop()
            await endpoint.leave()
            await engine.stop()
            await fruntime.shutdown()
            await fcp.close()
            await wruntime.shutdown()
            await wcp.close()
            await cp_server.stop()
        return metrics_text, frontend_payload, worker_payload

    metrics_text, frontend_payload, worker_payload = _run(main(), 300)

    # Lifecycle histograms on /metrics: nonzero TTFT counts.
    assert "dynamo_request_ttft_seconds" in metrics_text
    count_lines = [ln for ln in metrics_text.splitlines()
                   if ln.startswith("dynamo_request_ttft_seconds_count")]
    assert count_lines and float(count_lines[0].rsplit(" ", 1)[1]) >= 1
    assert "dynamo_request_tpot_seconds" in metrics_text
    assert "dynamo_request_queue_wait_seconds" in metrics_text

    # One merged trace with every hop.
    assert frontend_payload["traces"], frontend_payload
    merged = merge_payloads([frontend_payload, worker_payload])
    xs = [e for e in merged["traceEvents"] if e["ph"] == "X"]
    by_name: dict = {}
    for e in xs:
        by_name.setdefault(e["name"], []).append(e)
    # RPC spans carry the full endpoint path (dynamo/backend/generate).
    rpc_client = "rpc.client:dynamo/backend/generate"
    rpc_server = "rpc.server:dynamo/backend/generate"
    for needed in ("http.chat", "router.select", rpc_client, rpc_server,
                   "frontend.queue_wait", "engine.queue_wait",
                   "engine.prefill", "engine.ttft", "frontend.ttft",
                   "decode.tpot"):
        assert needed in by_name, (needed, sorted(by_name))
    assert len(by_name["decode.tpot"]) >= 3

    # Parent chain: everything rolls up to the single request trace.
    trace_ids = {e["args"]["trace_id"] for e in xs}
    assert len(trace_ids) == 1
    spans = {e["args"]["span_id"]: e for e in xs}
    root = by_name["http.chat"][0]
    assert root["args"]["parent_id"] is None
    assert by_name["router.select"][0]["args"]["parent_id"] == \
        root["args"]["span_id"]
    assert by_name[rpc_server][0]["args"]["parent_id"] == \
        by_name[rpc_client][0]["args"]["span_id"]
    assert by_name["engine.prefill"][0]["args"]["parent_id"] == \
        by_name[rpc_server][0]["args"]["span_id"]
    for e in xs:   # every non-root parent resolves within the trace
        pid = e["args"]["parent_id"]
        assert pid is None or pid in spans
    # And the whole thing is valid, loadable JSON.
    json.loads(json.dumps(merged))
