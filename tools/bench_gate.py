#!/usr/bin/env python
"""Regression-gate entry point: BENCH JSON vs baseline, exit nonzero on
regression.

    # gate a fresh bench run against the previous round
    python tools/bench_gate.py BENCH_new.json --baseline BENCH_r05.json

    # default baseline: newest BENCH_r*.json in the repo root
    python tools/bench_gate.py BENCH_new.json

    # CPU-only smoke (tier-1): synthesize → analyze → mocker replay →
    # gate, asserting the whole loop end to end
    python tools/bench_gate.py --smoke

Exit codes: 0 gate passed, 1 regression or invalid run, 2 usage error.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.bench import gate  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_baseline(exclude: str = "") -> str:
    """Newest BENCH_r*.json in the repo root (the previous round)."""
    rounds = []
    for p in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        if os.path.abspath(p) == os.path.abspath(exclude):
            continue
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            rounds.append((int(m.group(1)), p))
    if not rounds:
        raise FileNotFoundError(
            "no BENCH_r*.json baseline found; pass --baseline")
    return max(rounds)[1]


def run_gate(args) -> int:
    baseline = args.baseline or default_baseline(exclude=args.new)
    result = gate.gate_files(args.new, baseline, threshold=args.threshold)
    out = result.to_dict()
    out["baseline_path"] = baseline
    print(json.dumps(out, indent=2))
    return 0 if result.ok else 1


def tracing_overhead_checks() -> dict:
    """Tracing must be free where it matters: steady-state decode with
    sampling=1.0 adds ZERO host syncs and ZERO per-window span records
    (lifecycle spans land once per request at first token, never per
    window), and the per-span record cost bounds any request's total
    tracing work under 1% of its decode wall time.

    The wall-clock ratio between a traced and untraced run is reported
    for the record but NOT gated on — CPU timer jitter at tiny-model
    window times dwarfs a 1% budget; the counting assertions are exact
    and deterministic (the same EngineStepCounters delta discipline as
    tests/test_decode_window.py)."""
    import time

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.runtime import tracing

    tracer = tracing.get_tracer()

    def steady_run():
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=128,
            enable_prefix_cache=False, decode_window=2,
            window_pipeline_depth=2,
            scheduler=SchedulerConfig(
                max_seqs=8, block_size=8, max_pages_per_seq=32,
                max_prefill_chunk=128, decode_buckets=(1, 2, 4, 8),
                prefill_buckets=(16, 128))))
        # Bind a trace context so first-token lifecycle spans actually
        # record when tracing is on (the serving layer's bind step).
        tracer.bind("a", tracing.TraceContext("t-bench", "s-bench"))
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):   # prefill + window warmup
            core.step()
        base = core.counters.snapshot()
        spans0 = tracer.spans_recorded
        t0 = time.perf_counter()
        for _ in range(20):
            core.step()
        wall = time.perf_counter() - t0
        tracer.unbind("a")
        return (core.counters.delta(base), wall,
                tracer.spans_recorded - spans0)

    try:
        tracer.enabled = False
        tracer.reset()
        d_off, t_off, _ = steady_run()
        tracer.reset()
        tracer.configure(enabled=True, sampling=1.0)
        d_on, t_on, steady_spans = steady_run()
    finally:
        # Never leak enabled tracing into the rest of the smoke run —
        # the other checks' determinism depends on the default-off state.
        tracer.enabled = False
        tracer.reset()

    # Per-span record cost → the 1% budget.  A request's tracing work is
    # a handful of spans (queue-wait, prefill, TTFT, ~K TPOT intervals),
    # amortised over its max_tokens/window decode windows; with
    # SPANS_PER_REQUEST spans across the 32 windows this geometry runs,
    # the per-window tracing cost must stay under 1% of window time.
    bench = tracing.Tracer("bench", enabled=True, sampling=1.0,
                           max_spans_per_trace=8192)
    root = bench.start_span("r")
    n = 4000
    t1 = time.perf_counter()
    now = time.monotonic()
    for _ in range(n):
        bench.record_span("s", root, now, now)
    span_cost = (time.perf_counter() - t1) / n
    root.end()
    # Engine-process spans per request: queue-wait + prefill + TTFT,
    # recorded once at first token.  (The frontend's capped TPOT spans
    # ride the frontend event loop, not the decode window — its own
    # budget is the reported span cost × 32 per request, trivially off
    # the engine's critical path.)
    SPANS_PER_REQUEST = 3
    windows_per_request = 64 // 2       # max_tokens / decode_window
    per_window = t_off / 20
    overhead_frac = (SPANS_PER_REQUEST * span_cost
                     / max(windows_per_request * per_window, 1e-9))
    return {
        "tracing_extra_host_syncs": d_on["host_syncs"] - d_off["host_syncs"],
        "tracing_zero_extra_syncs":
            d_on["host_syncs"] == d_off["host_syncs"]
            and d_on["xla_cache_misses"] == d_off["xla_cache_misses"],
        "tracing_steady_window_spans": steady_spans,
        "tracing_zero_steady_spans": steady_spans == 0,
        "tracing_span_cost_us": round(span_cost * 1e6, 2),
        "tracing_wall_ratio": round(t_on / t_off, 3) if t_off else None,
        "tracing_overhead_frac": round(overhead_frac, 6),
        "tracing_overhead_within_1pct": overhead_frac <= 0.01,
    }


def telemetry_overhead_checks() -> dict:
    """KV/HBM telemetry must be free where it matters: a steady decode
    window with the memory-plane collectors sampling EVERY step (far
    hotter than the real scrape cadence) pays 0 extra host syncs and 0
    extra dispatches vs telemetry disabled — the same
    EngineStepCounters.delta pinning discipline as the tracing check."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.runtime.metrics import (
        HbmPoller, KvCacheMetrics, MetricsRegistry)

    def steady_run(observe: bool):
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=128,
            enable_prefix_cache=True, decode_window=2,
            window_pipeline_depth=2,
            scheduler=SchedulerConfig(
                max_seqs=8, block_size=8, max_pages_per_seq=32,
                max_prefill_chunk=128, decode_buckets=(1, 2, 4, 8),
                prefill_buckets=(16, 128))))
        kvm = KvCacheMetrics(MetricsRegistry())
        poller = HbmPoller(kvm)
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):   # prefill + window warmup
            core.step()
        base = core.counters.snapshot()
        for _ in range(20):
            core.step()
            if observe:
                kvm.observe_engine(core)
        if observe:
            poller.poll_once()
        return core.counters.delta(base)

    d_off = steady_run(False)
    d_on = steady_run(True)
    dispatch_keys = ("window_dispatches", "single_step_dispatches",
                     "prefill_dispatches", "h2d_uploads")
    return {
        "kv_telemetry_extra_host_syncs":
            d_on["host_syncs"] - d_off["host_syncs"],
        "kv_telemetry_zero_extra_syncs":
            d_on["host_syncs"] == d_off["host_syncs"],
        "kv_telemetry_extra_dispatches":
            sum(d_on[k] - d_off[k] for k in dispatch_keys),
        "kv_telemetry_zero_extra_dispatches":
            all(d_on[k] == d_off[k] for k in dispatch_keys),
    }


def flight_recorder_overhead_checks() -> dict:
    """ISSUE 14: the flight recorder must be free where it matters — a
    steady decode window with the ring ENABLED produces EngineStepCounters
    deltas byte-identical to recorder-off (0 extra host syncs, 0 extra
    dispatches, 0 recompiles) and stays inside the per-window ring-write
    budget: at most one ring write per window dispatch plus one periodic
    counters breadcrumb.  A fabricated chatty recorder (several writes
    per step — the regression this gate exists to catch) must FAIL the
    budget check."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.runtime import flight_recorder

    rec = flight_recorder.get_recorder()

    def steady_run(chatty: int = 0):
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=128,
            enable_prefix_cache=False, decode_window=2,
            window_pipeline_depth=2,
            scheduler=SchedulerConfig(
                max_seqs=8, block_size=8, max_pages_per_seq=32,
                max_prefill_chunk=128, decode_buckets=(1, 2, 4, 8),
                prefill_buckets=(16, 128))))
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):   # prefill + window warmup
            core.step()
        base = core.counters.snapshot()
        writes0 = rec.events_written
        for _ in range(20):
            core.step()
            for _ in range(chatty):   # fabricated chatty recorder
                rec.record("chatty", x=1)
        return (core.counters.delta(base),
                rec.events_written - writes0)

    def budget_ok(ring_writes: int, delta: dict) -> bool:
        # One write per window dispatch + one periodic counters
        # breadcrumb (cadence 64 ⇒ ≤ 1 over a 20-step window).
        return ring_writes <= delta["window_dispatches"] + 1

    try:
        rec.reset()
        rec.enabled = False
        d_off, _ = steady_run()
        rec.configure(enabled=True, ring_size=4096)
        d_on, writes_on = steady_run()
        _, writes_chatty = steady_run(chatty=3)
    finally:
        # Never leak an enabled recorder into the other smoke checks.
        rec.enabled = False
        rec.reset()

    return {
        "flight_extra_host_syncs":
            d_on["host_syncs"] - d_off["host_syncs"],
        "flight_zero_extra_syncs":
            d_on["host_syncs"] == d_off["host_syncs"]
            and d_on["xla_cache_misses"] == d_off["xla_cache_misses"],
        "flight_counters_byte_identical": d_on == d_off,
        "flight_ring_writes": writes_on,
        "flight_window_budget_ok": budget_ok(writes_on, d_on),
        # The budget check must actually have teeth: a recorder writing
        # several events per steady step blows it.
        "flight_chatty_run_fails": not budget_ok(writes_chatty, d_on),
    }


def device_truth_checks() -> dict:
    """ISSUE 20: the device-truth plane must be FREE and HONEST.

    Free — a steady decode window with the profiler ENABLED produces
    EngineStepCounters deltas byte-identical to profiler-off: the
    cost-analysis harvest rides first-seen shapes only (the compile
    event), never the steady window.  Honest — the harvest lands real
    programs in the cost registry, the drift audit's modeled-vs-measured
    ratios sit INSIDE the one-sided band on the CPU tiny model (modeled
    KV bytes are a component of XLA's totals, so the honest ratio is
    well under 1), and a FABRICATED 2x modeled over-claim must drive the
    auditor to PAGE after its strike budget — the gate this plane exists
    to provide."""
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.runtime import device_profiler

    prof = device_profiler.get_profiler()

    def steady_run():
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=128,
            enable_prefix_cache=False, decode_window=2,
            window_pipeline_depth=2,
            scheduler=SchedulerConfig(
                max_seqs=8, block_size=8, max_pages_per_seq=32,
                max_prefill_chunk=128, decode_buckets=(1, 2, 4, 8),
                prefill_buckets=(16, 128))))
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):   # prefill + window warmup (harvests land)
            core.step()
        base = core.counters.snapshot()
        for _ in range(20):
            core.step()
        return core, core.counters.delta(base)

    try:
        prof.reset()
        prof.enabled = False
        _, d_off = steady_run()
        prof.configure(enabled=True)
        core_on, d_on = steady_run()
        registry_size = prof.registry.size()
        ratios = prof.audit_engine(core_on)
        states = prof.auditor.states()
        in_band = bool(ratios) and all(
            st["state"] == "ok" for st in states.values())
        # The drift band must have teeth: an accounting bug that
        # over-claims modeled bytes 2x (the PR-16 int8 scale-pack
        # double-count class) must strike out and PAGE.
        fab = device_profiler.DriftAuditor()
        for _ in range(device_profiler.PAGE_STRIKES):
            fab.observe("kv_decode", modeled=2.0, measured=1.0)
    finally:
        # Never leak an enabled profiler into the other smoke checks.
        prof.enabled = False
        prof.reset()

    return {
        "device_truth_counters_byte_identical": d_on == d_off,
        "device_truth_registry_programs": registry_size,
        "device_truth_registry_nonempty": registry_size > 0,
        "device_truth_ratios": {k: round(v, 4)
                                for k, v in sorted(ratios.items())},
        "device_truth_ratios_in_band": in_band,
        "device_truth_overclaim_pages": fab.paged(),
    }


def ledger_checks() -> dict:
    """ISSUE 18: the request ledger must be HONEST and FREE.

    Honest — a mocker fleet's assembled ledgers must explain >= 90% of
    each request's measured TTFT (no dark time), and a FABRICATED
    ledger claiming more phase time than the wall-clock envelope must
    FAIL `coverage_ok` (a ledger that can over-claim can hide anything).
    Free — steady-decode `EngineStepCounters` deltas are byte-identical
    ledger-on vs ledger-off (the same pinning discipline as the
    tracing/flight-recorder checks: zero added host syncs, dispatches or
    recompiles)."""
    import asyncio
    import time

    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.llm.mocker.engine import MockEngine, MockEngineArgs
    from dynamo_tpu.llm.preprocessor import PreprocessedRequest
    from dynamo_tpu.models import config as mcfg
    from dynamo_tpu.runtime import ledger as ledger_mod

    async def fleet_coverage():
        """3 concurrent requests against a mocker whose prefill budget
        forces multi-step (really-sleeping) prefills, so TTFT is real
        wall time the queue/prefill stamps must account for."""
        eng = MockEngine(MockEngineArgs(
            block_size=32, num_blocks=4096, max_batched_tokens=64,
            speedup_ratio=1.0))
        try:
            async def one(i: int) -> float:
                req = PreprocessedRequest(
                    request_id=f"led{i}", model="smoke",
                    token_ids=list(range(1, 257)),
                    sampling=SamplingParams(max_tokens=2))
                led = ledger_mod.begin(req)
                t0 = time.monotonic()
                ttft = 0.0
                async for d in eng.generate(req):
                    if d.token_ids:
                        ttft = time.monotonic() - t0
                        break
                return ledger_mod.ttft_coverage(led, ttft)
            return await asyncio.gather(*(one(i) for i in range(3)))
        finally:
            await eng.stop()

    ratios = asyncio.run(asyncio.wait_for(fleet_coverage(), 120))

    # Fabricated over-claim: a ledger whose phases sum past the
    # wall-clock envelope must FAIL the coverage check.
    fab = ledger_mod.RequestLedger("fabricated")
    fab.stamp("prefill", dur=2.0)
    fabricated_fails = not ledger_mod.coverage_ok(fab, 1.0)

    def steady_run(on: bool):
        ledger_mod.set_enabled(on)
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=128,
            enable_prefix_cache=False, decode_window=2,
            window_pipeline_depth=2,
            scheduler=SchedulerConfig(
                max_seqs=8, block_size=8, max_pages_per_seq=32,
                max_prefill_chunk=128, decode_buckets=(1, 2, 4, 8),
                prefill_buckets=(16, 128))))
        core.add_request("a", list(range(1, 71)),
                         SamplingParams(max_tokens=64))
        for _ in range(8):   # prefill + window warmup
            core.step()
        base = core.counters.snapshot()
        for _ in range(20):
            core.step()
        return core.counters.delta(base)

    try:
        d_off = steady_run(False)
        d_on = steady_run(True)
    finally:
        ledger_mod.set_enabled(True)  # the process default

    return {
        "ledger_fleet_ttft_coverage": round(min(ratios), 4),
        "ledger_coverage_ok": all(
            ledger_mod.COVERAGE_FLOOR <= r <= ledger_mod.COVERAGE_CEIL
            for r in ratios),
        "ledger_fabricated_overclaim_fails": fabricated_fails,
        "ledger_extra_host_syncs":
            d_on["host_syncs"] - d_off["host_syncs"],
        "ledger_counters_byte_identical": d_on == d_off,
    }


def decode_wall_checks() -> dict:
    """ISSUE 6 smoke: the decode-bandwidth-wall features measured on CPU
    with the tiny model —

    - int8-KV traffic model at SERVING geometry (llama-3-1b, head_dim
      64): ratio <= 0.55 (the floor TPU rounds gate on; the formula is
      the same bytes_per_block accounting the block manager reports);
    - greedy quality pin: tiny-model greedy decode token-exact between
      bf16 and int8 KV caches;
    - speculative decoding on the repetitive workload: acceptance >=
      0.6, modeled sweep speedup >= 1.3, and output byte-identical to
      the non-spec baseline (lossless by construction, measured here)."""
    from dynamo_tpu.bench.decode_wall import (
        kv_quant_traffic, measure_spec_acceptance)
    from dynamo_tpu.engine.engine import EngineConfig, EngineCore
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.engine.scheduler import SchedulerConfig
    from dynamo_tpu.models import config as mcfg

    serving = kv_quant_traffic(mcfg.get_config("llama-3-1b"))

    def greedy_tokens(kv_quant: str):
        core = EngineCore(EngineConfig(
            model=mcfg.get_config("tiny-test"), num_blocks=64,
            kv_quant=kv_quant, enable_prefix_cache=False,
            scheduler=SchedulerConfig(
                max_seqs=8, block_size=8, max_pages_per_seq=8,
                max_prefill_chunk=16, decode_buckets=(1, 2, 4, 8),
                prefill_buckets=(8, 16))))
        core.add_request("q", list(range(1, 30)),
                         SamplingParams(max_tokens=24))
        out = []
        for _ in range(500):
            for d in core.step():
                out.extend(d.token_ids)
            if not core._requests:
                break
        return out

    pin_bf16 = greedy_tokens("none")
    pin_int8 = greedy_tokens("int8")

    spec = measure_spec_acceptance(mcfg.get_config("tiny-test"))

    return {
        "kv_quant_traffic_ratio": serving["traffic_ratio"],
        "kv_quant_ratio_ok": serving["traffic_ratio"] <= 0.55,
        "kv_quant_greedy_pin": pin_bf16 == pin_int8 and len(pin_bf16) == 24,
        "spec_acceptance_rate": spec["acceptance_rate"],
        "spec_acceptance_ok": spec["acceptance_rate"] >= 0.6,
        "spec_modeled_speedup": spec["modeled_decode_speedup"],
        "spec_speedup_ok": spec["modeled_decode_speedup"] >= 1.3,
        "spec_output_identical": spec["output_identical_to_baseline"],
    }


def sharded_decode_checks() -> dict:
    """ISSUE 9 + 12 smoke: the sharded fast-decode plane measured on the
    CPU mesh rig — tp2 fused window/greedy step, the pp2 all-in-one
    stage program vs its unfused loop, the sp2 mode, int8 on all three,
    and the compose_matrix summary (no cell may read "rejected"; the
    declared-impossible cells must quote the capability table).

    The CPU ratios are NOT gated: host-process sharding overhead at tiny
    geometry swamps them; only presence + plumbing are asserted here,
    the 0.8 / 1.2 floors bind on TPU rounds."""
    import jax

    from dynamo_tpu.bench.sharded_decode import run_sharded_decode
    from dynamo_tpu.models import config as mcfg

    out = run_sharded_decode(
        mcfg.get_config("tiny-test"), batch=4, ctx=16, block=8, width=4,
        window=2, modes=("tp2", "sp2", "pp2"), with_int8=True)
    tp2 = out.get("tp2", {})
    pp2 = out.get("pp2", {})
    sp2 = out.get("sp2", {})
    matrix = out.get("compose_matrix", {})
    statuses = [c.get("status", "") for c in matrix.values()]
    ran = "tok_s_per_chip" in tp2
    return {
        "sharded_decode_devices": out["devices"],
        "sharded_decode_ran_tp2": ran,
        "sharded_decode_ratio": out.get("tok_s_per_chip_ratio"),
        "sharded_decode_pp_fused_vs_single": out.get(
            "pp_fused_vs_single"),
        "sharded_decode_section_ok": (
            ran and isinstance(out.get("tok_s_per_chip_ratio"), float)
            and out["tok_s_per_chip_ratio"] > 0
            and tp2.get("single_step_ms", 0) > 0
            and tp2.get("window_step_ms_int8", 0) > 0
            and len(jax.devices()) >= 2),
        # ISSUE 12: pp2/sp2 measured through the real stage programs,
        # fused-vs-unfused reported, int8 composing on every mode.
        # Presence checks only — tiny-geometry CPU slopes can clamp to 0
        # under machine load, so >0 would flake; the gated ratios bind
        # on TPU where slope timing is real.
        "sharded_decode_pp_ok": all(
            isinstance(pp2.get(k), (int, float))
            for k in ("single_step_ms", "single_unfused_ms",
                      "window_step_ms", "window_step_ms_int8",
                      "fused_vs_unfused")),
        "sharded_decode_sp_ok": all(
            isinstance(sp2.get(k), (int, float))
            for k in ("single_step_ms", "fused_vs_unfused",
                      "window_step_ms_int8")),
        "sharded_decode_matrix_no_rejects": (
            len(matrix) > 0
            and not any(s.startswith("rejected") for s in statuses)),
        "sharded_decode_matrix_declares_impossible": any(
            s.startswith("declared") for s in statuses),
    }


def ring_plane_checks() -> dict:
    """ISSUE 19 smoke: the ring-attention plane measured on the CPU mesh
    rig — the flash ring kernel (interpret mode) must agree with the XLA
    ppermute ring numerically, the section must carry the gated ratio
    and both modeled per-hop payload figures, and the tiny sp2+pallas
    engine must serve token-identical output with EVERY sp prefill
    attributed to the kernel path (ring_kernel_prefills — an XLA-ring
    fallback can't pass silently).

    The CPU ratio itself is NOT gated (interpret-mode kernel cost swamps
    it); the 1.15 floor binds on TPU rounds and is fabricated-failure-
    checked in run_smoke."""
    from dynamo_tpu.bench.ring_plane import run_tiny_ring_plane

    out = run_tiny_ring_plane()
    eng = out.get("engine", {})
    return {
        "ring_plane_ratio": out.get("kernel_vs_xla"),
        "ring_plane_numeric_parity": out.get("numeric_parity"),
        "ring_plane_section_ok": all(
            isinstance(out.get(k), (int, float))
            for k in ("kernel_ms", "xla_ring_ms", "meshless_ms",
                      "kernel_vs_xla", "per_hop_bytes",
                      "per_hop_bytes_int8_modeled")),
        # int8 exchange modeled payload must be smaller than bf16's —
        # the scales-ride-with-rows accounting, not a forked formula.
        "ring_plane_int8_payload_smaller": (
            out.get("per_hop_bytes_int8_modeled", 0)
            < out.get("per_hop_bytes", 0)),
        "ring_plane_engine_token_parity": eng.get("tokens_match"),
        "ring_plane_kernel_path_counted": (
            eng.get("ring_kernel_prefills", 0) > 0
            and eng.get("ring_kernel_prefills")
            == eng.get("sp_prefill_count")),
    }


def moe_decode_checks() -> dict:
    """ISSUE 17 smoke: the MoE fast-decode plane measured on CPU with
    tiny-moe — the grouped kernel (interpret mode) must be BITWISE equal
    to the moe_dense oracle in both plain and int8-weight form, the
    [E+1] stats must account every assignment with zero drops, and the
    section must carry the gated ratio.

    The CPU ratio itself is NOT gated (interpret-mode kernel cost swamps
    it); the 1.5 floor binds on TPU rounds and is fabricated-failure-
    checked in run_smoke."""
    from dynamo_tpu.bench.moe_decode import run_moe_decode
    from dynamo_tpu.models import config as mcfg

    cfg = mcfg.get_config("tiny-moe")
    out = run_moe_decode(cfg, batch=4)
    k = cfg.num_experts_per_token
    return {
        "moe_decode_ratio": out.get("grouped_vs_dense"),
        "moe_decode_token_parity": out.get("token_parity"),
        "moe_decode_int8_parity": out.get("int8_parity"),
        "moe_decode_load_accounted": (
            sum(out.get("expert_load", [])) == 4 * k
            and out.get("dropped_tokens") == 0),
        "moe_decode_section_ok": all(
            isinstance(out.get(key), (int, float))
            for key in ("dense_step_ms", "grouped_step_ms",
                        "grouped_int8_step_ms", "grouped_vs_dense",
                        "grouped_expert_weight_bytes")),
    }


def prefill_plane_checks() -> dict:
    """ISSUE 10 smoke: the packed ragged prefill plane measured on CPU
    with the tiny model — both planes serve the same ragged prompt set
    through real EngineCores (packed runs the Pallas flash-prefill
    kernel in interpret mode), the section must carry the gated ratio,
    and the first tokens must be byte-identical plane-to-plane.

    The CPU ratio itself is NOT gated: interpret-mode kernel cost
    swamps it; only presence + parity + packed-dispatch plumbing are
    asserted here, the 1.2 floor binds on TPU rounds."""
    from dynamo_tpu.bench.prefill_plane import run_tiny_prefill_plane

    out = run_tiny_prefill_plane()
    ratio = out.get("packed_vs_padded_tok_s_ratio")
    return {
        "prefill_plane_ratio": ratio,
        "prefill_plane_section_ok": (
            isinstance(ratio, float) and ratio > 0
            and out["packed"]["packed_dispatches"] > 0
            and out["padded"]["packed_dispatches"] == 0),
        "prefill_plane_token_parity": out["token_parity"],
    }


def transfer_plane_checks() -> dict:
    """ISSUE 13 smoke: the KV transfer planes measured on CPU between
    two real tiny engines — host-staged, device-direct, and streamed
    all land the full prefix with BYTE parity, the device plane really
    pulled blocks (the local device fabric on this jax build), and the
    plane-choice counters recorded the device pulls.  The CPU GB/s
    values are NOT gated (localhost wire); the 2x floor binds on TPU
    rounds and is fabricated-failure-checked in run_smoke."""
    import asyncio

    from dynamo_tpu.bench.transfer_plane import run_tiny_transfer_plane
    from dynamo_tpu.llm.block_manager.device_transfer import plane_counts

    before = sum(n for (plane, _), n in plane_counts().items()
                 if plane == "device")
    out = asyncio.run(asyncio.wait_for(run_tiny_transfer_plane(), 180))
    device_delta = sum(n for (plane, _), n in plane_counts().items()
                       if plane == "device") - before
    return {
        "transfer_transport": out["transport"],
        "transfer_host_gbs": out["host_staged_gbs"],
        "transfer_device_gbs": out["device_direct_gbs"],
        "transfer_streamed_gbs": out["streamed_gbs"],
        "transfer_section_ok": all(
            isinstance(out[k], (int, float)) and out[k] > 0
            for k in ("host_staged_gbs", "device_direct_gbs",
                      "streamed_gbs", "device_vs_host_ratio")),
        "transfer_device_plane_used": (out["device_blocks_pulled"] > 0
                                       and out["streamed_device_blocks"]
                                       > 0),
        "transfer_plane_counters_recorded": device_delta > 0,
        "transfer_byte_parity": out["byte_parity"],
    }


def prefix_fleet_checks() -> dict:
    """ISSUE 7 smoke: fleet-wide prefix reuse measured on CPU — the real
    router must hand out remote-prefix hints on the shared-prefix
    workload (remote_hit_rate >= 0.2, the TPU gate floor), remote reuse
    must beat local-only modeled TTFT, and the real PrefixFetcher must
    pull + inject the full context over the mocked wire with zero
    fallbacks."""
    import asyncio

    from dynamo_tpu.bench.prefix_fleet import run_prefix_fleet

    out = asyncio.run(asyncio.wait_for(run_prefix_fleet(), 120))
    measured = out["measured"]
    return {
        "prefix_fleet_remote_hit_rate": out["remote_hit_rate"],
        "prefix_fleet_hit_rate_ok": out["remote_hit_rate"] >= 0.2,
        "prefix_fleet_ttft_speedup": out["modeled_ttft_speedup"],
        "prefix_fleet_reuse_beats_local": out["modeled_ttft_speedup"] > 1.0,
        "prefix_fleet_pull_wall_ms": round(
            measured["pull_wall_s"] * 1e3, 1),
        "prefix_fleet_pull_complete": (measured["all_blocks_injected"]
                                       and measured["fallbacks"] == 0),
    }


def drain_migration_checks() -> dict:
    """ISSUE 15 smoke: the KV-carrying drain-migration resume (real
    PrefixFetcher over the modeled wire) must beat cold re-prefill —
    blip_ratio < 1.0 with blocks actually carried and zero re-prefill
    fallbacks — and the FABRICATED drop-the-KV donor (serves nothing)
    must FAIL that same claim: a gate that can't catch the KV silently
    not moving isn't a gate."""
    import asyncio

    from dynamo_tpu.bench.drain import run_drain_migration_model

    out = asyncio.run(asyncio.wait_for(run_drain_migration_model(), 120))
    dropped = asyncio.run(asyncio.wait_for(
        run_drain_migration_model(drop_kv=True), 120))
    return {
        "drain_migration_blip_ratio": out["blip_ratio"],
        "drain_migration_kv_carried_blocks": out["kv_carried_blocks"],
        "drain_migration_beats_reprefill": out["migration_beats_reprefill"],
        # The happy path took zero re-prefill fallbacks (acceptance pin).
        "drain_migration_no_fallbacks": out["reprefill_fallbacks"] == 0,
        # Fabricated drop-the-KV run: carried nothing, so the
        # beats-reprefill claim must come out False.
        "drain_fabricated_drop_kv_fails": (
            not dropped["migration_beats_reprefill"]
            and dropped["kv_carried_blocks"] == 0),
    }


def sla_profiler_checks() -> dict:
    """ISSUE 11 smoke: the SLA profiler + capacity frontier on CPU —
    the deterministic mocker-cell sweep must emit a profile SlaPlanner
    loads unchanged, the capacity model must name the PINNED cheapest
    fleet for the smoke (SLO, traffic-mix) fixture, a fabricated
    over-SLO requirement must make it REFUSE (naming every rejected
    config), and a mocker fleet cell driven through real MockEngines +
    status servers must agree with the modeled TTFT/TPOT when scraped
    through dynamo_top's collector (the documented factor-2/10ms
    tolerance)."""
    from benchmarks.sla_profiler import (
        CellConfig,
        SloTarget,
        find_knee,
        plan_capacity,
        run_smoke as profiler_smoke,
        validate_fleet_model,
    )
    from dynamo_tpu.planner.interpolation import (
        DecodeInterpolator,
        PrefillInterpolator,
    )
    from dynamo_tpu.planner.sla import SlaObservation, SlaPlanner

    res = profiler_smoke(None)
    plan = res["plan"]
    moe_plan = res["moe_plan"]
    profile = res["profile"]

    # The planner consumes the profiler's profile UNCHANGED (meta and
    # all), and a loaded interval produces a real scaling decision.
    planner_ok = True
    try:
        PrefillInterpolator(profile)
        DecodeInterpolator(profile)

        class _Conn:
            n = 1

            def replicas(self):
                return self.n

        planner = SlaPlanner(profile, observe=lambda: SlaObservation(),
                             decode_connector=_Conn())
        d = planner.decide(SlaObservation(
            num_requests=100, avg_isl=216, avg_osl=16,
            ttft_s=0.05, itl_s=0.008))
        planner_ok = d.num_decode >= 1
    except Exception:
        planner_ok = False

    # Fabricated over-SLO requirement: no profiled config can hold a
    # 1ms TTFT / 0.1ms TPOT SLO — the model must refuse, not deploy.
    refused = plan_capacity(res["frontiers"],
                            SloTarget(ttft_p99_s=0.001,
                                      tpot_p99_s=0.0001), 40.0)

    # Mocker fleet cell: real MockEngines + per-worker /metrics +
    # /debug/slo scraped via dynamo_top's collector, vs the model.
    fleet = validate_fleet_model(
        CellConfig("base"), "agentic", 30.0, num_workers=4,
        num_requests=32, slo=SloTarget(ttft_p99_s=0.25,
                                       tpot_p99_s=0.012))

    # Kneedle flags the max-deviation-below-the-chord point — the middle
    # of the bend (idx 4 = load 16 here), not its onset.
    knee = find_knee([1, 2, 4, 8, 16, 32],
                     [10.0, 10.5, 11.0, 12.0, 80.0, 400.0])
    return {
        "sla_profile_loads_in_planner": planner_ok,
        "sla_plan_feasible": plan.feasible,
        # Pinned fixture (SMOKE_SLO at SMOKE_RPS on the agentic mix):
        # the sweep is a pure virtual clock, so the cheapest fleet is
        # byte-stable — any drift is a model change and must be looked
        # at, not averaged away.
        "sla_plan_cell": (plan.cell or {}).get("name"),
        "sla_plan_pinned": ((plan.cell or {}).get("name")
                            == "int8+spec+packed"
                            and plan.replicas == 3
                            and plan.total_chips == 3),
        # Pinned MoE fixture (ISSUE 17): the MoE grid is swept under
        # its own mix and answered as its own plan, so the dense pin
        # above cannot drift.  At the shared smoke SLO the dense-MoE
        # oracle can't hold TPOT at ANY load (the E/k weight-traffic
        # wall the grouped kernel exists for) — the only feasible
        # fleet composes grouped + ep2 + every serving plane.
        "sla_moe_plan_cell": (moe_plan.cell or {}).get("name"),
        "sla_moe_plan_pinned": (
            (moe_plan.cell or {}).get("name")
            == "moe-grouped-ep2+int8+spec+packed"
            and moe_plan.replicas == 10
            and moe_plan.total_chips == 20),
        "sla_moe_dense_rejected": any(
            r["cell"] == "moe-dense" for r in moe_plan.rejected),
        "sla_over_slo_refused": (not refused.feasible
                                 and len(refused.rejected) > 0),
        "sla_fleet_ttft_agree": fleet["ttft_p50_agree"],
        "sla_fleet_tpot_agree": fleet["tpot_p50_agree"],
        # Boolean, not the raw count: the gate only fails on literal
        # False, so a partial scrape (3/4, or None) must not slip by.
        "sla_fleet_all_workers_scraped": (
            fleet["scraped"].get("workers") == 4),
        "sla_knee_detected_at_bend": knee == 4,
    }


def disagg_topology_checks() -> dict:
    """ISSUE 16 smoke: the slice topology plane measured end to end — a
    heterogeneous disagg cell (ring-SP int8 prefill slice → head-sharded
    tp int8 decode slice) serves byte-identical greedy output vs the
    meshless oracle with the KV crossing the DEVICE plane and landing
    resharded on the decode mesh (reshard_pulls pinned), and the
    fabricated mesh-blind planner decision — decode role deployed onto
    the prefill-only slice — must be REFUSED by `validate_placement`."""
    import asyncio

    from dynamo_tpu.bench.disagg_topology import run_disagg_topology

    out = asyncio.run(asyncio.wait_for(run_disagg_topology(), 300))
    return {
        "disagg_topology_prefill_slice": out["prefill_slice"],
        "disagg_topology_decode_slice": out["decode_slice"],
        "disagg_topology_token_parity": out["token_parity"],
        "disagg_topology_remote_prefills": out["remote_prefills"],
        "disagg_topology_no_fallbacks": out["local_fallbacks"] == 0,
        "disagg_topology_device_plane_used": (
            out["device_pulls"] > 0 and out["pulled_blocks"] > 0),
        "disagg_topology_reshard_pulls": out["reshard_pulls"],
        "disagg_topology_kv_resharded": out["reshard_pulls"] > 0,
        "disagg_topology_onboarded_blocks": out["onboarded_blocks"],
        "disagg_topology_mesh_blind_placement_refused":
            out["placement_guard_refuses_mesh_blind"],
    }


def run_smoke(args) -> int:
    """Mocker-backed smoke of the whole measurement loop — CPU-only, no
    JAX device work, fast enough for tier-1.

    1. synthesize a prefix-heavy trace;
    2. analyze it (predicted hit rate);
    3. replay against one MockEngine, compare measured vs predicted;
    4. gate a fabricated regressed run and a fabricated invalid run —
       both must FAIL the gate; an honest run must pass;
    5. bound tracing overhead: steady decode with sampling=1.0 adds no
       host syncs, no per-window spans, and ≤1% modeled wall time;
    6. measure the modeled disagg-TTFT benchmark (real EagerPuller over
       a mocked seal timeline + wire): eager streaming must hide >= half
       the transfer behind prefill (transfer_overlap_ratio >= 0.5) and
       land TTFT near max(prefill, transfer) + tail, not their sum;
    7. bound KV/HBM telemetry overhead: per-step memory-plane sampling
       adds 0 host syncs and 0 dispatches to the steady decode window;
    7b. bound flight-recorder overhead (ISSUE 14): recorder-on steady
       decode keeps EngineStepCounters deltas byte-identical to
       recorder-off (0 extra host syncs) and within the one-ring-write-
       per-window budget; a fabricated chatty recorder must fail it;
    7c. request-ledger honesty + overhead (ISSUE 18): a mocker fleet's
       assembled ledgers explain >= 90% of each measured TTFT, a
       fabricated ledger claiming more time than the wall-clock
       envelope FAILS coverage_ok, and ledger-on steady decode keeps
       EngineStepCounters deltas byte-identical to ledger-off;
    7d. device-truth plane (ISSUE 20): profiler-on steady decode keeps
       EngineStepCounters deltas byte-identical to profiler-off, the
       compile-time harvest lands a non-empty XLA cost registry, the
       drift audit's modeled-vs-measured ratios sit inside the band on
       CPU, a fabricated 2x modeled over-claim drives the auditor to
       PAGE, and the new TPU floor fails a fabricated over-claiming run;
    8. decode-bandwidth-wall features (ISSUE 6): int8-KV traffic ratio
       <= 0.55 at serving geometry, tiny-model greedy pin bf16 == int8,
       spec-decode acceptance >= 0.6 + modeled sweep speedup >= 1.3 on
       the repetitive workload with byte-identical output, and the new
       gate floors verified to fail fabricated bad runs;
    9. sharded fast-decode plane (ISSUE 9 + 12): tp2/sp2/pp2 fused
       windows + fused greedy steps + int8 measured on the CPU mesh rig
       through the real stage programs, the compose_matrix carrying no
       rejected cells, and the tok_s_per_chip_ratio /
       pp_fused_vs_single floors plus the rejected-cell check verified
       to fail fabricated bad runs;
    9b. MoE fast-decode plane (ISSUE 17): the grouped expert kernel
        bitwise equal to the moe_dense oracle (plain and int8-weight,
        interpret mode) with every assignment accounted and zero drops,
        and the grouped_vs_dense floor verified to fail a fabricated
        slower-than-dense run;
    9c. ring-attention plane (ISSUE 19): the Pallas flash ring kernel
        (interpret mode) numerically equal to the XLA ppermute ring at
        sp2, the tiny sp2+pallas engine token-identical with every sp
        prefill attributed to the kernel path, and the kernel_vs_xla
        floor verified to fail a fabricated slower-than-XLA kernel run;
    10. prefill plane (ISSUE 10): packed ragged vs padded prefill on the
        tiny model with byte-identical first tokens, and the
        packed_vs_padded_tok_s_ratio floor verified to fail a
        fabricated slow-packed run;
    11a. transfer plane (ISSUE 13): host-staged vs device-direct vs
        streamed KV pulls between two real tiny engines with byte
        parity, the device plane demonstrably used (plane counters),
        and the device_vs_host_ratio floor verified to fail a
        fabricated slower-than-host device run;
    11. SLA profiler + capacity frontier (ISSUE 11): the deterministic
        mocker-cell sweep emits a profile SlaPlanner loads unchanged,
        the capacity model names the pinned cheapest fleet and REFUSES
        a fabricated over-SLO requirement, and a mocker fleet cell
        scraped through dynamo_top agrees with the model within the
        documented tolerance;
    12. drain migration (ISSUE 15): the KV-carrying drain resume (real
        PrefixFetcher over the modeled wire) beats cold re-prefill
        (blip_ratio < 1, blocks carried, zero fallbacks), and the
        fabricated drop-the-KV donor must FAIL the same claim;
    13. slice topology (ISSUE 16): a heterogeneous disagg cell
        (sp-prefill slice → tp+int8 decode slice) serves byte-identical
        greedy output vs the meshless oracle with the KV resharded on
        the device plane (reshard_pulls > 0), and the fabricated
        mesh-blind placement (decode role on the prefill-only slice)
        must be refused by the topology guard.
    """
    # The sharded checks need a multi-device rig: force the 8-way
    # virtual-CPU platform BEFORE anything imports jax (this smoke is
    # CPU-only by contract — the module docstring and the tier-1 test
    # both pin JAX_PLATFORMS=cpu).
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if ("xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

    import asyncio

    from benchmarks.data_generator.prefix_analyzer import analyze_trace
    from benchmarks.data_generator.synthesizer import (
        synthesize_prefix_heavy,
        tokens_for_record,
    )
    from dynamo_tpu.engine.sampling import SamplingParams
    from dynamo_tpu.llm.mocker.engine import MockEngine, MockEngineArgs
    from dynamo_tpu.llm.preprocessor import PreprocessedRequest

    block = 32
    records = synthesize_prefix_heavy(
        40, num_roots=4, context_blocks=6, suffix_tokens=16,
        output_tokens=4, interval_ms=1.0, block_size=block)
    report = analyze_trace(records, block)
    predicted = report.theoretical_hit_rate

    async def replay() -> float:
        eng = MockEngine(MockEngineArgs(
            block_size=block, num_blocks=4096, speedup_ratio=1000.0))
        hit_tokens = input_tokens = 0
        try:
            for i, rec in enumerate(records):
                toks = tokens_for_record(rec, block, unique_seed=i)
                input_tokens += len(toks)
                async for d in eng.generate(PreprocessedRequest(
                        request_id=f"s{i}", model="smoke", token_ids=toks,
                        sampling=SamplingParams(
                            max_tokens=rec.output_length))):
                    if d.finished:
                        break
            hit_tokens = eng.kv.hit_blocks * block
        finally:
            await eng.stop()
        return hit_tokens / input_tokens if input_tokens else 0.0

    measured = asyncio.run(asyncio.wait_for(replay(), 120))
    hit_delta = abs(measured - predicted)

    good = {"value": 100.0, "serving_tok_s": 50.0, "prefill_tok_s": 200.0,
            "itl_ms": 6.0, "calibration_ok": True, "tenancy_health": "ok"}
    regressed = dict(good, serving_tok_s=50.0 * 0.7)       # 30% drop
    invalid = dict(good, calibration_ok=False,
                   tenancy_health="invalid", vs_baseline=None)
    # Absolute TPU floors: a run below the MBU / interference floor fails
    # even against a baseline that already regressed there.
    tpu_good = dict(good, device="TPU v5 lite0", mbu=0.82,
                    mixed_prefill_decode={"interference_ratio": 0.88},
                    kv_quant={"traffic_ratio": 0.531},
                    spec_decode={"acceptance_rate": 0.9,
                                 "modeled_decode_speedup": 1.9},
                    prefix_fleet={"remote_hit_rate": 0.34},
                    sharded_decode={
                        "tok_s_per_chip_ratio": 0.91,
                        "pp_fused_vs_single": 1.6,
                        "compose_matrix": {
                            "fused_decode × pp2": {"status": "ok"},
                            "spec × multihost": {
                                "status": "declared: lockstep"}}},
                    prefill_plane={
                        "packed_vs_padded_tok_s_ratio": 1.45},
                    moe_decode={"grouped_vs_dense": 2.7,
                                "token_parity": True},
                    ring_plane={"kernel_vs_xla": 1.6,
                                "numeric_parity": True},
                    transfer={"device_vs_host_ratio": 3.4},
                    device_truth={"modeled_vs_measured_kv": 0.95})
    tpu_low_mbu = dict(tpu_good, mbu=0.60)
    tpu_interfered = dict(
        tpu_good, mixed_prefill_decode={"interference_ratio": 0.70})
    # New ISSUE-6 floors: a fat quantized cache (scales forgotten or
    # stored wide) and a collapsed acceptance rate must each fail.
    tpu_fat_quant = dict(tpu_good, kv_quant={"traffic_ratio": 0.80})
    tpu_low_accept = dict(
        tpu_good, spec_decode={"acceptance_rate": 0.3,
                               "modeled_decode_speedup": 1.9})
    # ISSUE-7 floor: a fleet that stopped handing out remote-prefix
    # hints (remote_hit_rate collapsed) must fail.
    tpu_no_remote = dict(tpu_good,
                         prefix_fleet={"remote_hit_rate": 0.05})
    # ISSUE-9 floor: a sharded engine that fell back to the slow gather
    # path (per-chip throughput collapsed vs meshless) must fail.
    tpu_sharded_slow = dict(
        tpu_good, sharded_decode=dict(
            tpu_good["sharded_decode"], tok_s_per_chip_ratio=0.5))
    # ISSUE-12 floor: a fused pp stage program that stopped beating the
    # unfused 3-dispatch loop (the r5 cliff back) must fail.
    tpu_pp_cliff = dict(
        tpu_good, sharded_decode=dict(
            tpu_good["sharded_decode"], pp_fused_vs_single=1.0))
    # ISSUE-12 matrix: a fabricated STILL-REJECTING cell — a combo the
    # capability table says composes but whose builder raised — must
    # fail the gate even with every headline number healthy.
    tpu_rejected_cell = dict(
        tpu_good, sharded_decode=dict(
            tpu_good["sharded_decode"],
            compose_matrix={"int8 × sp2": {
                "status": "rejected: ValueError: kv_quant=int8 is not "
                          "wired for ring-SP"}}))
    # ISSUE-10 floor: a packed prefill plane that stopped beating the
    # padded one (regressed to the gather path) must fail.
    tpu_slow_prefill = dict(
        tpu_good, prefill_plane={"packed_vs_padded_tok_s_ratio": 0.9})
    # ISSUE-17 floor: a grouped MoE kernel SLOWER than the dense
    # all-experts path (regressed to dense-ish weight streaming) must
    # fail — as must a parity failure, which zeroes the ratio at the
    # bench.
    tpu_moe_slow = dict(
        tpu_good, moe_decode={"grouped_vs_dense": 0.9,
                              "token_parity": True})
    # ISSUE-19 floor: a flash ring kernel that stopped beating the XLA
    # ppermute ring (RDMA no longer overlapping the fold, or a silent
    # fallback) must fail — as must a parity failure, which zeroes the
    # ratio at the bench.
    tpu_ring_slow = dict(
        tpu_good, ring_plane={"kernel_vs_xla": 1.05,
                              "numeric_parity": True})
    # ISSUE-13 floor: a device plane slower than the host-staged wire
    # (regressed to host staging under the covers, or double-copying on
    # inject) must fail — as must a parity failure, which zeroes the
    # ratio at the bench.
    tpu_slow_transfer = dict(
        tpu_good, transfer={"device_vs_host_ratio": 0.8})
    # ISSUE-20 floor: a modeled series claiming 2x the bytes XLA says
    # the decode programs actually touch (the accounting-over-claim bug
    # class the drift auditor pages on) must fail.
    tpu_drift_overclaim = dict(
        tpu_good, device_truth={"modeled_vs_measured_kv": 2.0})

    from dynamo_tpu.bench.disagg import run_disagg_ttft_model

    disagg = asyncio.run(asyncio.wait_for(run_disagg_ttft_model(), 120))

    checks = {
        "predicted_hit_rate": round(predicted, 4),
        "measured_hit_rate": round(measured, 4),
        "hit_rate_delta": round(hit_delta, 4),
        "hit_rate_within_5pts": hit_delta <= 0.05,
        "honest_run_passes": gate.compare(good, good).ok,
        "regression_fails": not gate.compare(regressed, good).ok,
        "invalid_run_fails": not gate.compare(invalid, good).ok,
        "tpu_floors_pass": gate.compare(tpu_good, tpu_good).ok,
        "low_mbu_fails": not gate.compare(tpu_low_mbu, tpu_low_mbu).ok,
        "interference_fails": not gate.compare(tpu_interfered,
                                               tpu_interfered).ok,
        "fat_quant_fails": not gate.compare(tpu_fat_quant,
                                            tpu_fat_quant).ok,
        "low_acceptance_fails": not gate.compare(tpu_low_accept,
                                                 tpu_low_accept).ok,
        "no_remote_hits_fails": not gate.compare(tpu_no_remote,
                                                 tpu_no_remote).ok,
        "sharded_floor_fails": not gate.compare(tpu_sharded_slow,
                                                tpu_sharded_slow).ok,
        "pp_cliff_fails": not gate.compare(tpu_pp_cliff,
                                           tpu_pp_cliff).ok,
        "rejected_cell_fails": not gate.compare(tpu_rejected_cell,
                                                tpu_rejected_cell).ok,
        "slow_prefill_plane_fails": not gate.compare(tpu_slow_prefill,
                                                     tpu_slow_prefill).ok,
        "slow_moe_grouped_fails": not gate.compare(tpu_moe_slow,
                                                   tpu_moe_slow).ok,
        "slow_ring_kernel_fails": not gate.compare(tpu_ring_slow,
                                                   tpu_ring_slow).ok,
        "slow_device_transfer_fails": not gate.compare(
            tpu_slow_transfer, tpu_slow_transfer).ok,
        "drift_overclaim_fails": not gate.compare(
            tpu_drift_overclaim, tpu_drift_overclaim).ok,
        "disagg_ttft_serial_ms": round(disagg["ttft_serial_s"] * 1e3, 1),
        "disagg_ttft_streamed_ms": round(
            disagg["ttft_streamed_s"] * 1e3, 1),
        "transfer_overlap_ratio": disagg["overlap_ratio"],
        "transfer_overlap_ok": disagg["overlap_ratio"] >= 0.5,
        "disagg_streamed_beats_serial": disagg["streamed_beats_serial"],
        "disagg_ttft_near_max_bound": disagg["ttft_near_max_bound"],
        **tracing_overhead_checks(),
        **telemetry_overhead_checks(),
        **flight_recorder_overhead_checks(),
        **device_truth_checks(),
        **ledger_checks(),
        **decode_wall_checks(),
        **moe_decode_checks(),
        **ring_plane_checks(),
        **prefill_plane_checks(),
        **transfer_plane_checks(),
        **prefix_fleet_checks(),
        **sharded_decode_checks(),
        **sla_profiler_checks(),
        **drain_migration_checks(),
        **disagg_topology_checks(),
    }
    ok = all(v is not False for v in checks.values())
    print(json.dumps({"smoke": "pass" if ok else "fail", **checks},
                     indent=2))
    return 0 if ok else 1


def main(argv=None) -> int:
    p = argparse.ArgumentParser("tools/bench_gate.py",
                                description=__doc__.splitlines()[0])
    p.add_argument("new", nargs="?", default=None,
                   help="fresh bench JSON (bare output or BENCH_rNN form)")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON (default: newest BENCH_r*.json)")
    p.add_argument("--threshold", type=float,
                   default=gate.DEFAULT_THRESHOLD,
                   help="fractional regression that fails (default 0.2)")
    p.add_argument("--smoke", action="store_true",
                   help="CPU-only synthesize→analyze→mocker→gate smoke")
    args = p.parse_args(argv)
    if args.smoke:
        return run_smoke(args)
    if not args.new:
        p.error("pass a bench JSON or --smoke")
    return run_gate(args)


if __name__ == "__main__":
    sys.exit(main())
