#!/usr/bin/env python
"""dynamo-lint: machine-checked concurrency + hot-path contracts.

The serving stack's correctness discipline ("engine thread only",
"never the engine thread", "zero host syncs in the steady window",
"metrics mutate under self._lock") lived in ~25 comments enforced by
convention.  This analyzer checks them as rules over the stdlib `ast`
(no dependencies), reading the `runtime/contracts.py` decorators as its
source of truth so the static layer and the `DYNAMO_CONTRACTS=1`
runtime layer enforce the SAME contract.

Rules:

  DL001  host-sync call (`.item()`, `jax.device_get`,
         `.block_until_ready()`, `np.asarray`, blocking `.result()`)
         inside a function decorated `@hot_path`
  DL002  blocking call (`time.sleep`, `subprocess.*`, sync sockets,
         `urllib.request.urlopen`, `requests.*`, `os.system`) inside
         `async def` — stalls the whole event loop
  DL003  silent exception swallow: `except Exception: pass` (body is
         ONLY `pass`) — serving-path failures must log or be
         explicitly suppressed with a reason
  DL004  metrics discipline: registry metric names must be bare
         (`dynamo_` is added by the registry prefix) and lowercase;
         direct Counter/Gauge/Histogram constructions must carry the
         `dynamo_` prefix themselves; classes owning a
         `self._lock = threading.Lock()` must mutate their dict state
         inside `with self._lock:`
  DL005  thread-contract consistency: an `@engine_thread_only`
         function may not call a `@never_engine_thread` one (or vice
         versa) — resolved per-class when possible, by globally-unique
         method name otherwise
  DL006  flight-recorder discipline: `FlightRecorder.record(...)`
         calls inside `@hot_path` bodies must pass pre-computed
         scalars only — plain names, constants, shallow attribute
         reads.  f-strings, %-formatting, container displays,
         comprehensions, call expressions and deep attribute chains
         allocate/format on the hot path and are rejected; do the
         formatting at dump time, not per step.  The same contract
         covers the request ledger's `.stamp(...)` and the
         device-truth plane's `.record(...)`/`.observe(...)` on
         profiler/auditor receivers (runtime/device_profiler.py)

Suppression: append `# dynamo-lint: disable=DL003 <reason>` to the
flagged line (or put it on its own line immediately above).  Multiple
codes comma-separate: `disable=DL001,DL004`.

Usage:
    python tools/dynamo_lint.py dynamo_tpu tools benchmarks
    python tools/dynamo_lint.py --json dynamo_tpu

Exit status: 0 when clean, 1 when any unsuppressed finding, 2 on usage
error.  Tier-1 runs this over the tree
(`tests/test_lint.py::test_tree_is_clean`), so a new violation fails
the suite — the repo has no external CI; tier-1 IS the gate.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

CONTRACT_DECORATORS = ("engine_thread_only", "never_engine_thread",
                       "hot_path")

@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    col: int
    code: str
    message: str
    end_line: int = 0  # suppression span (multi-line nodes)

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.code} " \
               f"{self.message}"

    def to_dict(self) -> dict:
        return {"path": self.path, "line": self.line, "col": self.col,
                "code": self.code, "message": self.message}


@dataclass
class FileCtx:
    """One parsed source file plus its suppression map."""

    path: str
    tree: ast.AST
    # line -> set of suppressed codes (from `# dynamo-lint: disable=`)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)

    def suppressed(self, f: Finding) -> bool:
        """A finding is suppressed by a disable comment on the line
        immediately above it, or anywhere within the flagged node's own
        span (so `except Exception:` suppressions can live in the
        handler body they justify)."""
        for ln in range(f.line - 1, max(f.line, f.end_line) + 1):
            if f.code in self.suppressions.get(ln, ()):
                return True
        return False


@dataclass(frozen=True)
class ContractEntry:
    path: str
    cls: Optional[str]       # enclosing class name (None = module level)
    name: str
    contract: str            # one of CONTRACT_DECORATORS
    line: int


class Project:
    """Cross-file state: the decorator-derived contract table DL005
    resolves against."""

    def __init__(self, files: List[FileCtx]) -> None:
        self.files = files
        self.contracts: List[ContractEntry] = []
        for ctx in files:
            self.contracts.extend(_collect_contracts(ctx))
        # name -> set of THREAD contracts anywhere in the tree (hot_path
        # is orthogonal and must not make a name "ambiguous"; remaining
        # ambiguity makes DL005 skip rather than guess).  by_class keys
        # include the file path: two same-named classes in different
        # files must not clobber each other's contracts.
        self.by_name: Dict[str, Set[str]] = {}
        self.by_class: Dict[Tuple[str, str, str], str] = {}
        for e in self.contracts:
            if e.contract not in THREAD_CONTRACTS:
                continue
            self.by_name.setdefault(e.name, set()).add(e.contract)
            if e.cls is not None:
                self.by_class[(e.path, e.cls, e.name)] = e.contract


def _decorator_name(node: ast.expr) -> Optional[str]:
    """`@hot_path`, `@contracts.hot_path`, `@hot_path()` all resolve."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _fn_contracts(node) -> Set[str]:
    """ALL contract decorators on a function — decorators stack
    (`@engine_thread_only` + `@hot_path` on EngineCore.step), so a
    first-match scan would leave the hottest functions unchecked."""
    return {name for name in (_decorator_name(d)
                              for d in node.decorator_list)
            if name in CONTRACT_DECORATORS}


THREAD_CONTRACTS = frozenset({"engine_thread_only", "never_engine_thread"})


def _thread_contract(node) -> Optional[str]:
    """The function's thread-affinity contract, if exactly one."""
    found = _fn_contracts(node) & THREAD_CONTRACTS
    return next(iter(found)) if len(found) == 1 else None


def _collect_contracts(ctx: FileCtx) -> List[ContractEntry]:
    out: List[ContractEntry] = []

    def visit(node, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for c in sorted(_fn_contracts(child)):
                    out.append(ContractEntry(ctx.path, cls, child.name, c,
                                             child.lineno))
                visit(child, cls)
            else:
                visit(child, cls)

    visit(ctx.tree, None)
    return out


def _own_statements(fn) -> Iterable[ast.AST]:
    """Walk a function body EXCLUDING nested function/lambda bodies —
    closures may legally execute on another thread (e.g. work submitted
    to an executor), so lexical nesting does not inherit the contract."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _dotted(node: ast.expr) -> Optional[str]:
    """`a.b.c` -> "a.b.c" for simple attribute chains; None otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# -- rule framework --------------------------------------------------------


class Rule:
    code = "DL000"
    name = "base"

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        raise NotImplementedError

    def finding(self, ctx: FileCtx, node, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(ctx.path, line, getattr(node, "col_offset", 0),
                       self.code, message,
                       end_line=getattr(node, "end_lineno", line) or line)


class HostSyncInHotPath(Rule):
    """DL001: host-sync calls inside `@hot_path` functions."""

    code = "DL001"
    name = "host-sync-in-hot-path"

    ZERO_ARG_ATTRS = ("item", "block_until_ready", "result")
    SYNC_DOTTED = ("jax.device_get", "np.asarray", "numpy.asarray",
                   "onp.asarray")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if "hot_path" not in _fn_contracts(fn):
                continue
            for node in _own_statements(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if (isinstance(f, ast.Attribute)
                        and f.attr in self.ZERO_ARG_ATTRS
                        and not node.args and not node.keywords):
                    out.append(self.finding(
                        ctx, node,
                        f"host sync `.{f.attr}()` inside @hot_path "
                        f"function {fn.name!r} — the steady window must "
                        "not stall the engine thread"))
                    continue
                dotted = _dotted(f)
                if dotted in self.SYNC_DOTTED:
                    # np.asarray over a HOST literal (list/tuple/
                    # comprehension/constant) builds an array, it does
                    # not settle a device value — only flag opaque args.
                    if node.args and isinstance(
                            node.args[0],
                            (ast.List, ast.Tuple, ast.ListComp,
                             ast.GeneratorExp, ast.Dict, ast.Constant)):
                        continue
                    out.append(self.finding(
                        ctx, node,
                        f"host sync `{dotted}` inside @hot_path function "
                        f"{fn.name!r} — device values must settle off "
                        "the steady window"))
        return out


class BlockingInAsync(Rule):
    """DL002: blocking calls lexically inside `async def`.

    Known blind spot: only MODULE-dotted names are matched
    (`time.sleep`, `subprocess.run`) — receiver-method calls like
    `proc.wait()` or `sock.recv()` are invisible because the receiver's
    type is unknowable from the AST.  Those stay code-review
    territory; keep them off the loop with `asyncio.to_thread`."""

    code = "DL002"
    name = "blocking-call-in-async"

    BLOCKING_DOTTED = {
        "time.sleep": "use `await asyncio.sleep(...)`",
        "subprocess.run": "use `asyncio.create_subprocess_exec` or hop "
                          "to a thread",
        "subprocess.call": "use `asyncio.create_subprocess_exec`",
        "subprocess.check_call": "use `asyncio.create_subprocess_exec`",
        "subprocess.check_output": "use `asyncio.create_subprocess_exec`",
        "subprocess.Popen": "use `asyncio.create_subprocess_exec`",
        "socket.create_connection": "use `asyncio.open_connection`",
        "urllib.request.urlopen": "use an async client or "
                                  "`asyncio.to_thread`",
        "request.urlopen": "use an async client or `asyncio.to_thread`",
        "os.system": "use `asyncio.create_subprocess_shell`",
        "requests.get": "use an async client or `asyncio.to_thread`",
        "requests.post": "use an async client or `asyncio.to_thread`",
        "requests.request": "use an async client or `asyncio.to_thread`",
    }

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, ast.AsyncFunctionDef):
                continue
            for node in _own_statements(fn):
                if not isinstance(node, ast.Call):
                    continue
                dotted = _dotted(node.func)
                hint = self.BLOCKING_DOTTED.get(dotted or "")
                if hint is not None:
                    out.append(self.finding(
                        ctx, node,
                        f"blocking `{dotted}` inside `async def "
                        f"{fn.name}` stalls the event loop — {hint}"))
        return out


class SilentSwallow(Rule):
    """DL003: `except Exception: pass` with nothing else in the body."""

    code = "DL003"
    name = "silent-exception-swallow"

    BROAD = ("Exception", "BaseException")

    def _is_broad(self, handler: ast.ExceptHandler) -> bool:
        t = handler.type
        if t is None:
            return True  # bare `except:`
        if isinstance(t, ast.Name):
            return t.id in self.BROAD
        if isinstance(t, ast.Tuple):
            return any(isinstance(e, ast.Name) and e.id in self.BROAD
                       for e in t.elts)
        return False

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not self._is_broad(node):
                continue
            if len(node.body) == 1 and isinstance(node.body[0], ast.Pass):
                out.append(self.finding(
                    ctx, node,
                    "broad `except` swallows the exception silently — "
                    "log (rate-limited) or suppress with a reason"))
        return out


class MetricsDiscipline(Rule):
    """DL004: metric naming + lock discipline in `_lock`-owning classes."""

    code = "DL004"
    name = "metrics-discipline"

    REGISTRY_METHODS = ("counter", "gauge", "histogram")
    METRIC_CLASSES = ("Counter", "Gauge", "Histogram")
    NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
    MUTATORS = ("pop", "update", "clear", "setdefault", "popitem",
                "append", "extend", "add", "discard", "remove",
                "popleft", "appendleft")

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out: List[Finding] = []
        out.extend(self._check_names(ctx))
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_locks(ctx, node))
        return out

    # -- naming ------------------------------------------------------------

    def _check_names(self, ctx: FileCtx) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            first = node.args[0]
            if not (isinstance(first, ast.Constant)
                    and isinstance(first.value, str)):
                continue
            name = first.value
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    f.attr in self.REGISTRY_METHODS:
                if name.startswith("dynamo_"):
                    out.append(self.finding(
                        ctx, node,
                        f"registry metric {name!r} double-prefixes: the "
                        "MetricsRegistry prefix already adds `dynamo_`"))
                elif not self.NAME_RE.match(name):
                    out.append(self.finding(
                        ctx, node,
                        f"registry metric {name!r} is not a valid "
                        "lowercase Prometheus name fragment"))
            elif isinstance(f, ast.Name) and f.id in self.METRIC_CLASSES:
                if not name.startswith("dynamo_"):
                    out.append(self.finding(
                        ctx, node,
                        f"directly-constructed metric {name!r} must carry "
                        "the `dynamo_` prefix (no registry adds it here)"))
        return out

    # -- lock discipline ---------------------------------------------------

    def _init_of(self, cls: ast.ClassDef):
        for item in cls.body:
            if isinstance(item, ast.FunctionDef) and item.name == "__init__":
                return item
        return None

    def _guarded_attrs(self, init) -> Optional[Set[str]]:
        """None when the class owns no `self._lock`; else the private
        container attrs (`self._x = {}` / dict() / OrderedDict() /
        defaultdict() / deque()) whose mutation the lock must cover."""
        has_lock = False
        attrs: Set[str] = set()
        for node in ast.walk(init):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            t = node.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            v = node.value
            if t.attr == "_lock":
                d = _dotted(v.func) if isinstance(v, ast.Call) else None
                if d in ("threading.Lock", "Lock", "threading.RLock",
                         "RLock"):
                    has_lock = True
                continue
            if not t.attr.startswith("_"):
                continue
            if isinstance(v, ast.Dict) and not v.keys:
                attrs.add(t.attr)
            elif isinstance(v, ast.Call):
                d = _dotted(v.func)
                if d in ("dict", "OrderedDict", "collections.OrderedDict",
                         "defaultdict", "collections.defaultdict",
                         "deque", "collections.deque", "set"):
                    attrs.add(t.attr)
        return attrs if has_lock else None

    def _is_lock_with(self, node: ast.With) -> bool:
        for item in node.items:
            e = item.context_expr
            if (isinstance(e, ast.Attribute) and e.attr == "_lock"
                    and isinstance(e.value, ast.Name)
                    and e.value.id == "self"):
                return True
        return False

    def _check_locks(self, ctx: FileCtx, cls: ast.ClassDef) -> List[Finding]:
        init = self._init_of(cls)
        if init is None:
            return []
        guarded = self._guarded_attrs(init)
        if not guarded:
            return []
        out: List[Finding] = []

        def is_guarded_self_attr(node) -> Optional[str]:
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self" and node.attr in guarded):
                return node.attr
            return None

        def walk(node, locked: bool, fn_name: str):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    continue  # closures: may run anywhere; out of scope
                child_locked = locked
                if isinstance(child, ast.With) and self._is_lock_with(child):
                    child_locked = True
                if not locked:
                    attr = self._mutation_attr(child, is_guarded_self_attr)
                    if attr is not None:
                        out.append(self.finding(
                            ctx, child,
                            f"`self.{attr}` (lock-guarded state of "
                            f"{cls.name}) mutated in {fn_name!r} outside "
                            "`with self._lock:` — scrapes may tear"))
                walk(child, child_locked, fn_name)

        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and item.name != "__init__":
                walk(item, False, item.name)
        return out

    def _mutation_attr(self, node, is_guarded) -> Optional[str]:
        # self._x[...] = v   /  self._x[...] += v  /  del self._x[...]
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AugAssign):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = node.targets
        for t in targets:
            if isinstance(t, ast.Subscript):
                attr = is_guarded(t.value)
                if attr:
                    return attr
        # self._x.pop(...) etc.
        if isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
            f = node.value.func
            if isinstance(f, ast.Attribute) and f.attr in self.MUTATORS:
                attr = is_guarded(f.value)
                if attr:
                    return attr
        return None


class ContractConsistency(Rule):
    """DL005: engine-thread-only functions may not call
    never-engine-thread ones, and vice versa."""

    code = "DL005"
    name = "thread-contract-consistency"

    CONFLICTS = {("engine_thread_only", "never_engine_thread"),
                 ("never_engine_thread", "engine_thread_only")}

    # Method names that collide with ubiquitous stdlib APIs (Task.cancel,
    # Lock.release, socket.close, ...): resolving these BY NAME on an
    # arbitrary receiver would be guessing.  Same-class `self.m()` calls
    # still resolve precisely above this filter.
    GENERIC_NAMES = frozenset({
        "cancel", "close", "start", "stop", "clear", "get", "put", "set",
        "pop", "join", "result", "done", "release", "acquire", "add",
        "remove", "update", "send", "recv", "wait", "run", "next",
    })

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out: List[Finding] = []

        def visit(node, cls: Optional[str]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    visit(child, child.name)
                    continue
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    c = _thread_contract(child)
                    if c is not None:
                        out.extend(self._check_fn(ctx, project, child,
                                                  c, cls))
                    visit(child, cls)
                    continue
                visit(child, cls)

        visit(ctx.tree, None)
        return out

    def _resolve(self, project: Project, ctx: FileCtx, call: ast.Call,
                 cls: Optional[str]) -> Optional[Tuple[str, str]]:
        """(callee_name, contract) or None when unknown/ambiguous."""
        f = call.func
        name = None
        if isinstance(f, ast.Attribute):
            name = f.attr
            # self.m() resolves against the enclosing class first —
            # path-qualified, so a same-named class elsewhere in the
            # tree cannot misattribute the contract
            if (isinstance(f.value, ast.Name) and f.value.id == "self"
                    and cls is not None
                    and (ctx.path, cls, name) in project.by_class):
                return name, project.by_class[(ctx.path, cls, name)]
        elif isinstance(f, ast.Name):
            name = f.id
        if name is None or name in self.GENERIC_NAMES:
            return None
        contracts = project.by_name.get(name)
        if contracts is None or len(contracts) != 1:
            return None  # unknown or ambiguous: do not guess
        return name, next(iter(contracts))

    def _check_fn(self, ctx: FileCtx, project: Project, fn, contract: str,
                  cls: Optional[str]) -> List[Finding]:
        out: List[Finding] = []
        for node in _own_statements(fn):
            if not isinstance(node, ast.Call):
                continue
            resolved = self._resolve(project, ctx, node, cls)
            if resolved is None:
                continue
            callee, callee_contract = resolved
            if (contract, callee_contract) in self.CONFLICTS:
                out.append(self.finding(
                    ctx, node,
                    f"@{contract} function {fn.name!r} calls "
                    f"@{callee_contract} function {callee!r} — the two "
                    "contracts are mutually exclusive on one thread"))
        return out


class FlightRecorderDiscipline(Rule):
    """DL006: `FlightRecorder.record(...)` — and request-ledger
    `.stamp(...)` — in `@hot_path` bodies must pass pre-computed
    scalars only.

    The recorder's hot-path contract (runtime/flight_recorder.py) is
    that `record` itself does no formatting — which only holds if call
    sites don't smuggle the formatting into the ARGUMENTS.  The request
    ledger (runtime/ledger.py) makes the same promise for `stamp`, so
    the same rule covers both.  Allowed argument expressions:
    constants, bare names, attribute chains up to `a.b.c` (a plain slot
    read), and unary +/- of those.  Rejected: f-strings / %-formatting
    / `.format()` and any call expression, container displays and
    comprehensions (they allocate per event), and deeper attribute
    chains (`a.b.c.d` — in this tree, a chain that deep is reaching
    through an object graph and usually hides a property).  Receivers
    recognized as flight recorders: any `*.record(...)` whose receiver
    chain ends in `flight`, `recorder`, `flight_recorder`, or the
    conventional local alias `fl`; as ledgers: `.stamp(...)` on
    `ledger`, `led`, `hop`, or `request_ledger`.

    The device-truth plane (runtime/device_profiler.py) makes the same
    no-formatting promise for its hot-path-adjacent entry points, so
    the rule also covers `.record(...)` on profiler/registry receivers
    (`profiler`, `prof`, `device_profiler`, `registry`) and
    `.observe(...)` on drift-auditor receivers (`auditor`, `drift`,
    `drift_auditor`) — an f-string program label built per step inside
    `@hot_path` would defeat the zero-steady-state-cost design."""

    code = "DL006"
    name = "flight-recorder-hot-path-args"

    RECEIVERS = frozenset({"flight", "recorder", "flight_recorder", "fl",
                           # device-truth plane: ProgramCostRegistry
                           # .record on the profiler / registry objects
                           "profiler", "prof", "device_profiler",
                           "registry"})
    LEDGER_RECEIVERS = frozenset({"ledger", "led", "hop",
                                  "request_ledger"})
    AUDITOR_RECEIVERS = frozenset({"auditor", "drift", "drift_auditor"})
    MAX_ATTR_PARTS = 3        # self.x.y is a slot read; deeper is a smell

    def _is_recorder_call(self, call: ast.Call) -> bool:
        f = call.func
        if not isinstance(f, ast.Attribute):
            return False
        if f.attr in ("record", "record_always"):
            receivers = self.RECEIVERS
        elif f.attr == "stamp":
            receivers = self.LEDGER_RECEIVERS
        elif f.attr == "observe":
            # DriftAuditor.observe — receiver-gated so plain metric
            # Histogram.observe on other receivers stays out of scope.
            receivers = self.AUDITOR_RECEIVERS
        else:
            return False
        recv = f.value
        if isinstance(recv, ast.Name):
            return recv.id in receivers
        if isinstance(recv, ast.Attribute):
            return recv.attr in receivers
        if isinstance(recv, ast.Call):
            # flight_recorder.get_recorder().record(...) — the inline
            # singleton spelling must not evade the rule.
            name = _decorator_name(recv.func)
            return name == "get_recorder"
        return False

    def _arg_problem(self, node: ast.expr) -> Optional[str]:
        """Why this argument expression is too expensive for a hot
        record site, or None when it is scalar-cheap."""
        if isinstance(node, ast.Constant):
            return None
        if isinstance(node, ast.Name):
            return None
        if isinstance(node, ast.Attribute):
            parts = 1
            cur = node
            while isinstance(cur, ast.Attribute):
                parts += 1
                cur = cur.value
            if not isinstance(cur, ast.Name):
                return "attribute chain on a computed receiver"
            if parts > self.MAX_ATTR_PARTS:
                return (f"attribute chain deeper than "
                        f"{self.MAX_ATTR_PARTS} parts")
            return None
        if isinstance(node, ast.UnaryOp) and isinstance(
                node.operand, (ast.Constant, ast.Name)):
            return None
        if isinstance(node, ast.JoinedStr):
            return "f-string (formats per event)"
        if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.Tuple)):
            return "container display (allocates per event)"
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            return "comprehension (allocates per event)"
        if isinstance(node, ast.Call):
            return "call expression (compute before the hot path)"
        if isinstance(node, (ast.BinOp, ast.BoolOp, ast.Compare,
                             ast.IfExp)):
            return "computed expression (pre-compute the scalar)"
        return "non-scalar expression"

    def check(self, ctx: FileCtx, project: Project) -> List[Finding]:
        out: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if "hot_path" not in _fn_contracts(fn):
                continue
            for node in _own_statements(fn):
                if not isinstance(node, ast.Call) \
                        or not self._is_recorder_call(node):
                    continue
                exprs = list(node.args) + [kw.value for kw in node.keywords]
                what = {"stamp": "ledger stamp",
                        "observe": "DriftAuditor.observe"}.get(
                            node.func.attr, "FlightRecorder.record")
                for expr in exprs:
                    why = self._arg_problem(expr)
                    if why is not None:
                        out.append(self.finding(
                            ctx, expr,
                            f"{what} arg in @hot_path "
                            f"function {fn.name!r} is not a pre-computed "
                            f"scalar: {why}"))
        return out


RULES: Sequence[Rule] = (HostSyncInHotPath(), BlockingInAsync(),
                         SilentSwallow(), MetricsDiscipline(),
                         ContractConsistency(),
                         FlightRecorderDiscipline())

RULE_TABLE = {r.code: r.name for r in RULES}


# -- driver ---------------------------------------------------------------


def _parse_suppressions(source: str) -> Dict[int, Set[str]]:
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        if "dynamo-lint" not in line:
            continue
        m = re.search(r"#\s*dynamo-lint:\s*disable=([A-Z0-9,]+)", line)
        if m:
            out[i] = {c.strip() for c in m.group(1).split(",") if c.strip()}
    return out


def load_file(path: str) -> Optional[FileCtx]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as e:
        print(f"dynamo-lint: cannot parse {path}: {e}", file=sys.stderr)
        return None
    return FileCtx(path=path, tree=tree,
                   suppressions=_parse_suppressions(source))


def discover(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                files.append(p)
            continue
        for root, dirs, names in os.walk(p):
            dirs[:] = [d for d in dirs
                       if d != "__pycache__" and not d.startswith(".")]
            files.extend(os.path.join(root, n) for n in sorted(names)
                         if n.endswith(".py"))
    return sorted(set(files))


def run_lint(paths: Sequence[str],
             rules: Sequence[Rule] = RULES) -> List[Finding]:
    """Lint `paths` (files or directories); returns UNSUPPRESSED
    findings sorted by location.  Importable — the tier-1 gate test and
    the CLI share this."""
    ctxs = [c for c in (load_file(f) for f in discover(paths))
            if c is not None]
    project = Project(ctxs)
    findings: List[Finding] = []
    for ctx in ctxs:
        for rule in rules:
            for f in rule.check(ctx, project):
                if not ctx.suppressed(f):
                    findings.append(f)
    return sorted(findings, key=lambda f: (f.path, f.line, f.col, f.code))


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        "dynamo_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings on stdout")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule table and exit")
    args = ap.parse_args(argv)
    if args.list_rules:
        for code, name in sorted(RULE_TABLE.items()):
            print(f"{code}  {name}")
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        return 2
    findings = run_lint(args.paths)
    if args.json:
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "count": len(findings),
            "rules": RULE_TABLE,
        }, indent=2))
    else:
        for f in findings:
            print(f.format())
        n = len(findings)
        print(f"dynamo-lint: {n} finding{'s' if n != 1 else ''}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
