#!/usr/bin/env python
"""`dynamo top` — one table for the whole fleet's capacity + SLO state.

Discovers every process advertised under the control plane's
`status_endpoints/` registry (workers, frontend, router_service,
planner — anything with a status server), scrapes each one's `/metrics`
and `/debug/slo`, and renders one row per process: role, inflight
requests, KV usage, prefix-cache hit rate, HBM, TTFT/TPOT p50/p99, and
the SLO burn-rate state (OK|WARN|PAGE).

    # live view, refreshed every 2 s
    python tools/dynamo_top.py --control-plane 127.0.0.1:4222

    # one machine-readable snapshot (scripting / tests / cron)
    python tools/dynamo_top.py --control-plane 127.0.0.1:4222 --once --json

Latency quantiles are computed client-side from the scraped
`dynamo_request_{ttft,tpot}_seconds` histogram buckets (bucket upper
bounds, same resolution as the server's own `Histogram.quantile`).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import math
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dynamo_tpu.runtime.control_plane_tcp import ControlPlaneClient  # noqa: E402
from dynamo_tpu.runtime.slo import max_burn  # noqa: E402
from dynamo_tpu.runtime.status import STATUS_ENDPOINTS_PREFIX  # noqa: E402

_LABEL_RE = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')

Sample = Tuple[str, Dict[str, str], float]


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def parse_prom(text: str) -> List[Sample]:
    """Prometheus text exposition → [(name, labels, value)].  Tolerant:
    unparseable lines are skipped (one bad series must not blank a whole
    process's row)."""
    out: List[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name_labels, _, raw = line.rpartition(" ")
        if not name_labels:
            continue
        try:
            value = float(raw)
        except ValueError:
            continue
        labels: Dict[str, str] = {}
        name = name_labels
        brace = name_labels.find("{")
        if brace >= 0:
            name = name_labels[:brace]
            labels = {k: _unescape(v) for k, v in
                      _LABEL_RE.findall(name_labels[brace:])}
        out.append((name, labels, value))
    return out


def total(samples: List[Sample], name: str,
          **match: str) -> Optional[float]:
    """Sum of `name` across label sets matching the given subset; None
    when the series is absent (distinct from a real 0)."""
    vals = [v for n, labels, v in samples
            if n == name and all(labels.get(k) == mv
                                 for k, mv in match.items())]
    return sum(vals) if vals else None


def hist_quantile(samples: List[Sample], name: str,
                  q: float) -> Optional[float]:
    """Approximate quantile from `<name>_bucket` cumulative counts,
    aggregated across label sets (shared bucket bounds).  A quantile
    landing in the +Inf overflow bucket clamps to the largest finite
    bound (read as "at least this") — the worst latencies must render
    as a number, not as the no-data dash, exactly when the operator
    needs them.  None only with no data at all."""
    by_le: Dict[float, float] = {}
    for n, labels, v in samples:
        if n != name + "_bucket" or "le" not in labels:
            continue
        le = labels["le"]
        bound = math.inf if le == "+Inf" else float(le)
        by_le[bound] = by_le.get(bound, 0.0) + v
    if not by_le:
        return None
    bounds = sorted(by_le)
    total_n = by_le[bounds[-1]]
    if total_n <= 0:
        return None
    finite = [b for b in bounds if not math.isinf(b)]
    target = max(1, math.ceil(min(max(q, 0.0), 1.0) * total_n))
    for b in bounds:
        if by_le[b] >= target:
            if math.isinf(b):
                break
            return b
    return finite[-1] if finite else None


# -- per-process summarization ------------------------------------------


def knee_concurrency_from_profile(profile: dict) -> Optional[float]:
    """Per-worker knee concurrency out of an SLA-profiler profile
    (`benchmarks/sla_profiler.py` meta schema v2); None for v1 profiles
    or profiles whose sweep never found a knee."""
    try:
        v = profile["meta"]["capacity"]["knee_concurrency_per_worker"]
    except (KeyError, TypeError):
        return None
    return float(v) if v else None


def summarize(component: str, address: str, samples: List[Sample],
              slo: Optional[dict],
              knee_concurrency: Optional[float] = None) -> dict:
    """One `dynamo top` row from a process's scraped series.

    `knee_concurrency`: the profiled per-worker saturation knee
    (`--profile sla_profile.json`) — fills the HEADRM column with how
    far this worker's observed inflight load sits from the knee
    (1.0 = idle, 0 = at the knee, negative = past it).  Worker rows
    only: a frontend's inflight gauge is the FLEET total, which a
    per-worker knee would misread as catastrophic overload."""
    frontend_inflight = total(samples,
                              "dynamo_frontend_inflight_requests")
    worker_inflight = total(samples,
                            "dynamo_worker_request_active_slots")
    inflight = (frontend_inflight if frontend_inflight is not None
                else worker_inflight)
    kv_active = total(samples, "dynamo_kv_pool_active_blocks",
                      tier="device")
    kv_capacity = total(samples, "dynamo_kv_pool_capacity_blocks",
                        tier="device")
    kv_usage = None
    if kv_active is not None and kv_capacity:
        kv_usage = kv_active / kv_capacity
    if kv_usage is None:
        kv_usage = total(samples, "dynamo_worker_kv_usage")
    hits = total(samples, "dynamo_kv_prefix_cache_hits_tokens")
    misses = total(samples, "dynamo_kv_prefix_cache_misses_tokens")
    hit_rate = None
    if hits is not None or misses is not None:
        h, m = hits or 0.0, misses or 0.0
        hit_rate = h / (h + m) if (h + m) > 0 else 0.0
    if hit_rate is None:
        hit_rate = total(samples, "dynamo_worker_kv_prefix_cache_hit_rate")
    hbm_used = total(samples, "dynamo_hbm_used_bytes")
    hbm_limit = total(samples, "dynamo_hbm_limit_bytes")
    slo_state = None
    if slo is not None:
        slo_state = slo.get("state") if slo.get("enabled") else "—"
    headroom = None
    if (knee_concurrency and knee_concurrency > 0
            and worker_inflight is not None
            and frontend_inflight is None):
        headroom = 1.0 - worker_inflight / knee_concurrency
    row = {
        "component": component,
        "address": address,
        "inflight": inflight,
        "kv_active_blocks": kv_active,
        "kv_capacity_blocks": kv_capacity,
        "kv_usage": kv_usage,
        "prefix_hit_rate": hit_rate,
        "remote_hits": total(samples, "dynamo_prefix_remote_hits_total"),
        "remote_fallbacks": total(
            samples, "dynamo_prefix_remote_fallbacks_total"),
        # Bulk KV transfer plane split (ISSUE 13): device-direct pulls
        # vs host-staged fallbacks — a worker whose device plane
        # silently degraded shows d0 with a growing h count.
        "device_pulls": total(samples, "dynamo_kv_transfer_plane_total",
                              plane="device"),
        "host_pulls": total(samples, "dynamo_kv_transfer_plane_total",
                            plane="host"),
        "evictions": total(samples, "dynamo_kv_evictions_total"),
        "hbm_used_bytes": hbm_used,
        "hbm_limit_bytes": hbm_limit,
        "ttft_p50_s": hist_quantile(samples,
                                    "dynamo_request_ttft_seconds", 0.5),
        "ttft_p99_s": hist_quantile(samples,
                                    "dynamo_request_ttft_seconds", 0.99),
        "tpot_p50_s": hist_quantile(samples,
                                    "dynamo_request_tpot_seconds", 0.5),
        "tpot_p99_s": hist_quantile(samples,
                                    "dynamo_request_tpot_seconds", 0.99),
        "slo_state": slo_state,
        "slo_max_burn": (max_burn(slo)
                         if slo and slo.get("enabled") else None),
        "capacity_headroom": headroom,
        # Flight-recorder / stall-watchdog series (ISSUE 14): heartbeat
        # age of the engine step loop, cumulative stall count, and the
        # watchdog's currently-stalled flag — the AGE/STL column.
        "engine_step_age_s": total(
            samples, "dynamo_engine_last_step_age_seconds"),
        "engine_stalls": total(samples, "dynamo_engine_stalls_total"),
        "engine_stalled": total(samples, "dynamo_engine_stalled"),
        # Elasticity / QoS plane (ISSUE 15): QoS preemption count,
        # streams migrated out (drain handoffs), and whether the worker
        # is currently draining — the QOS/DRN column.
        "qos_preemptions": total(samples, "dynamo_qos_preemptions_total"),
        "migrated_out": total(samples, "dynamo_requests_migrated_total"),
        "migrated_in": total(samples,
                             "dynamo_requests_migrated_in_total"),
        "draining": total(samples, "dynamo_worker_draining"),
    }
    # MoE fast-decode plane (ISSUE 17): the per-expert assignment
    # histogram (`dynamo_moe_expert_load{expert="e"}`) folded into the
    # EXP column's three numbers — active experts, load imbalance
    # (max/mean), capacity drops.  Dense workers publish no series and
    # keep the no-data dash.
    loads = [v for n, labels, v in samples
             if n == "dynamo_moe_expert_load" and "expert" in labels]
    if loads:
        mean = sum(loads) / len(loads)
        row["moe_experts_active"] = sum(1 for v in loads if v > 0)
        row["moe_experts_total"] = len(loads)
        row["moe_load_imbalance"] = (max(loads) / mean if mean > 0
                                     else 0.0)
    else:
        row["moe_experts_active"] = None
        row["moe_experts_total"] = None
        row["moe_load_imbalance"] = None
    row["moe_dropped_tokens"] = total(
        samples, "dynamo_moe_dropped_tokens_total")
    # Request-ledger attribution (ISSUE 18): goodput = SLO-good tokens /
    # total tokens, and the dominant phase = the phase with the biggest
    # summed seconds across completed ledgers (decode excluded — it
    # scales with output length and would drown every upstream stall).
    # The WHY column names the hop eating the latency budget.
    good = total(samples, "dynamo_goodput_good_tokens_total")
    tot = total(samples, "dynamo_goodput_tokens_total")
    row["goodput"] = (good / tot if good is not None and tot else None)
    phase_sums = {
        labels["phase"]: v
        for n, labels, v in samples
        if n == "dynamo_request_phase_seconds_sum" and "phase" in labels
        and labels["phase"] != "decode"}
    row["dominant_phase"] = (
        max(phase_sums, key=phase_sums.get)
        if phase_sums and max(phase_sums.values()) > 0 else None)
    # Device-truth plane (ISSUE 20): modeled-vs-measured drift ratios
    # per series plus the XLA cost-registry size — the DRIFT column.
    # A ratio creeping toward the band ceiling means the analytical
    # model (roofline math, KV-byte accounting) is drifting from what
    # XLA says the compiled programs actually do.
    row["drift_ratios"] = {
        labels["series"]: v
        for n, labels, v in samples
        if n == "dynamo_modeled_vs_measured_ratio" and "series" in labels}
    row["program_registry_size"] = total(
        samples, "dynamo_program_registry_size")
    return row


# -- collection ----------------------------------------------------------


async def _scrape(addr: str, timeout: float) -> Tuple[Optional[str],
                                                      Optional[dict]]:
    """(metrics_text, slo_payload) for one process; None parts on
    failure (a dead process still gets a row — marked unreachable)."""
    import aiohttp

    t = aiohttp.ClientTimeout(total=timeout)
    metrics_text = slo = None
    try:
        async with aiohttp.ClientSession(timeout=t) as s:
            try:
                async with s.get(f"http://{addr}/metrics") as r:
                    if r.status == 200:
                        metrics_text = await r.text()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
                pass
            try:
                async with s.get(f"http://{addr}/debug/slo") as r:
                    if r.status == 200:
                        slo = await r.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                    ValueError):
                pass
    except Exception:
        # dynamo-lint: disable=DL003 dead target renders as unreachable
        pass  # the row itself is the error report
    return metrics_text, slo


async def collect(cp_addr: str, timeout: float = 3.0,
                  knee_concurrency: Optional[float] = None) -> dict:
    """One fleet snapshot: discover via `status_endpoints/`, scrape
    every process concurrently, summarize.  Importable (the mini-fleet
    e2e test calls this in-process; the CLI wraps it).
    `knee_concurrency` (from `--profile`) fills per-row capacity
    headroom.

    Stale-registration reaping (ISSUE 14): a kill -9'd worker leaves
    its `status_endpoints/` key behind.  An unreachable target whose
    registration pid is provably dead (loopback address + signal-0
    probe — `runtime/status.registration_pid_dead`) gets its key
    DELETED and renders once as a `reaped` row instead of an
    UNREACHABLE row forever."""
    from dynamo_tpu.runtime.status import registration_pid_dead

    host, _, port = cp_addr.rpartition(":")
    cp = ControlPlaneClient(host or "127.0.0.1", int(port))
    await cp.start()
    reaped = 0
    try:
        entries = await cp.get_prefix(f"{STATUS_ENDPOINTS_PREFIX}/")
        targets = []
        seen = set()
        for key, entry in sorted(entries.items()):
            if not isinstance(entry, dict) or not entry.get("address"):
                continue
            addr = entry["address"]
            if addr in seen:
                continue  # one process may be re-registered across restarts
            seen.add(addr)
            targets.append((entry.get("component")
                            or key.split("/")[1], addr, key, entry))
        scrapes = await asyncio.gather(
            *(_scrape(addr, timeout) for _, addr, _, _ in targets))
        processes = []
        for (component, addr, key, entry), (text, slo) in zip(targets,
                                                              scrapes):
            if text is None and slo is None:
                if registration_pid_dead(entry):
                    try:
                        await cp.delete(key)
                        reaped += 1
                        processes.append({
                            "component": component, "address": addr,
                            "pid": entry.get("pid"), "reaped": True})
                        continue
                    except Exception:
                        # dynamo-lint: disable=DL003 reap is best-effort
                        pass  # fall through to the unreachable row
                processes.append({"component": component, "address": addr,
                                  "unreachable": True})
                continue
            row = summarize(component, addr, parse_prom(text or ""), slo,
                            knee_concurrency=knee_concurrency)
            # Slice topology (ISSUE 16): the worker publishes its
            # declarative SliceSpec in the status registration — the
            # MESH column renders the mesh shape + role straight from
            # it (no scrape needed; pre-topology workers show a dash).
            row["mesh"] = entry.get("mesh")
            sl = entry.get("slice")
            row["slice_role"] = (sl.get("role")
                                 if isinstance(sl, dict) else None)
            processes.append(row)
    finally:
        await cp.close()
    return {"generated_at": time.time(), "control_plane": cp_addr,
            "reaped": reaped, "processes": processes}


# -- rendering -----------------------------------------------------------


def _fmt(v, kind: str = "num") -> str:
    if v is None:
        return "—"
    if kind == "pct":
        return f"{100.0 * v:.1f}%"
    if kind == "ms":
        return f"{1e3 * v:.1f}"
    if kind == "bytes":
        for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
            if abs(v) < 1024 or unit == "TiB":
                return (f"{v:.0f}{unit}" if unit == "B"
                        else f"{v:.1f}{unit}")
            v /= 1024
    if kind == "int":
        return str(int(v))
    return f"{v:g}"


def _fmt_age_stall(r: dict) -> str:
    """AGE/STL cell: engine heartbeat age / cumulative stall count,
    suffixed `!` while the watchdog holds the worker stalled.  A row
    with neither series (mocker/frontend) renders the no-data dash."""
    age = r.get("engine_step_age_s")
    stalls = r.get("engine_stalls")
    if age is None and stalls is None:
        return "—"
    a = ("—" if age is None
         else f"{age:.1f}s" if age < 100 else f"{age:.0f}s")
    s = "—" if stalls is None else str(int(stalls))
    mark = "!" if (r.get("engine_stalled") or 0) > 0 else ""
    return f"{a}/{s}{mark}"


def _fmt_qos_drain(r: dict) -> str:
    """QOS/DRN cell: QoS preemption count / streams migrated out,
    suffixed `D` while the worker is draining.  Rows without the series
    (frontend, old workers) render the no-data dash."""
    qos = r.get("qos_preemptions")
    mig = r.get("migrated_out")
    if qos is None and mig is None:
        return "—"
    q = "—" if qos is None else str(int(qos))
    m = "—" if mig is None else str(int(mig))
    mark = "D" if (r.get("draining") or 0) > 0 else ""
    return f"{q}/{m}{mark}"


def _fmt_exp(r: dict) -> str:
    """EXP cell: active/total experts seeing load, `x`-suffixed
    imbalance (max/mean), and `!N` when the capacity-honesty drop
    counter is nonzero — a skewed router or a lossy capacity cap must
    be visible at a glance.  Dense workers render the no-data dash."""
    active = r.get("moe_experts_active")
    if active is None:
        return "—"
    cell = (f"{int(active)}/{int(r.get('moe_experts_total') or 0)}e"
            f" {r.get('moe_load_imbalance') or 0:.1f}x")
    drops = r.get("moe_dropped_tokens")
    if drops:
        cell += f"!{int(drops)}"
    return cell


def _fmt_why(r: dict) -> str:
    """WHY cell: the dominant request phase (where completed requests
    spent the most summed time, decode excluded) plus goodput — the
    fraction of emitted tokens from SLO-good requests.  Only frontends
    fold ledgers, so worker rows render the no-data dash."""
    phase = r.get("dominant_phase")
    goodput = r.get("goodput")
    if phase is None and goodput is None:
        return "—"
    g = "—" if goodput is None else f"{100.0 * goodput:.0f}%"
    return f"{phase or '—'} {g}"


def _fmt_drift(r: dict) -> str:
    """DRIFT cell: worst modeled-vs-measured ratio across audited
    series + the program-count of the XLA cost registry.  The ratio is
    modeled/measured, so >1 means the analytical model OVER-claims
    versus device truth (the drift auditor pages past its band).
    Processes without the device-truth plane render the no-data dash."""
    ratios = r.get("drift_ratios") or {}
    size = r.get("program_registry_size")
    if not ratios and size is None:
        return "—"
    n = "—" if size is None else str(int(size))
    if not ratios:
        return f"—/{n}p"
    worst = max(ratios.values())
    return f"{worst:.2f}/{n}p"


def _fmt_mesh(r: dict) -> str:
    """MESH cell from the worker's published SliceSpec: the mesh shape
    (`describe()` string), suffixed :P / :D for a dedicated
    prefill/decode slice.  Pre-topology registrations render the
    no-data dash."""
    mesh = r.get("mesh")
    if not mesh:
        return "—"
    role = r.get("slice_role")
    mark = {"prefill": ":P", "decode": ":D"}.get(role, "")
    return f"{mesh}{mark}"


COLUMNS = (
    ("ROLE", 16, lambda r: r["component"]),
    ("ADDRESS", 21, lambda r: r["address"]),
    # Slice topology plane: mesh shape + role from the published
    # SliceSpec (status registration, not a scrape).
    ("MESH", 11, _fmt_mesh),
    ("INFL", 5, lambda r: _fmt(r.get("inflight"), "int")),
    ("KV%", 6, lambda r: _fmt(r.get("kv_usage"), "pct")),
    ("HIT%", 6, lambda r: _fmt(r.get("prefix_hit_rate"), "pct")),
    ("RHIT", 5, lambda r: _fmt(r.get("remote_hits"), "int")),
    # Bulk-transfer plane split: device-direct vs host-staged pulls.
    ("PLANE", 9, lambda r: (
        f'd{_fmt(r.get("device_pulls"), "int")}'
        f'/h{_fmt(r.get("host_pulls"), "int")}'
        if r.get("device_pulls") is not None
        or r.get("host_pulls") is not None else "—")),
    ("HBM", 16, lambda r: (f'{_fmt(r.get("hbm_used_bytes"), "bytes")}'
                           f'/{_fmt(r.get("hbm_limit_bytes"), "bytes")}'
                           if r.get("hbm_used_bytes") is not None
                           else "—")),
    ("TTFTp50", 8, lambda r: _fmt(r.get("ttft_p50_s"), "ms")),
    ("TTFTp99", 8, lambda r: _fmt(r.get("ttft_p99_s"), "ms")),
    ("TPOTp50", 8, lambda r: _fmt(r.get("tpot_p50_s"), "ms")),
    ("TPOTp99", 8, lambda r: _fmt(r.get("tpot_p99_s"), "ms")),
    ("SLO", 5, lambda r: r.get("slo_state") or "—"),
    # Request-ledger attribution: dominant phase + goodput fraction.
    ("WHY", 14, _fmt_why),
    # Engine heartbeat age / stall count (flight recorder + watchdog):
    # a wedged step loop reads as a growing AGE with a `!` marker.
    ("AGE/STL", 9, _fmt_age_stall),
    # QoS preemptions / drain-migrated streams, `D` while draining.
    ("QOS/DRN", 8, _fmt_qos_drain),
    # MoE expert-load plane: active/total experts, imbalance, drops.
    ("EXP", 11, _fmt_exp),
    # Device-truth drift: worst modeled/measured ratio + XLA cost
    # registry size.  >1 = the model over-claims vs compiled reality.
    ("DRIFT", 9, _fmt_drift),
    # How far from the profiled saturation knee (--profile): 100% idle,
    # 0% at the knee, negative past it.
    ("HEADRM", 7, lambda r: _fmt(r.get("capacity_headroom"), "pct")),
)


def render_table(snapshot: dict) -> str:
    lines = [f"dynamo top — {len(snapshot['processes'])} process(es) via "
             f"{snapshot['control_plane']}  (latencies in ms)"]
    lines.append("  ".join(h.ljust(w) for h, w, _ in COLUMNS))
    for row in snapshot["processes"]:
        if row.get("reaped"):
            lines.append("  ".join([
                row["component"].ljust(16), row["address"].ljust(21),
                f"REAPED (pid {row.get('pid')} dead; "
                "registration removed)"]))
            continue
        if row.get("unreachable"):
            lines.append("  ".join([
                row["component"].ljust(16), row["address"].ljust(21),
                "UNREACHABLE"]))
            continue
        lines.append("  ".join(
            str(fn(row))[:w].ljust(w) for _, w, fn in COLUMNS))
    return "\n".join(lines)


async def _run(args) -> int:
    knee = None
    if args.profile:
        from dynamo_tpu.planner.interpolation import load_profile

        knee = knee_concurrency_from_profile(load_profile(args.profile))
        if knee is None:
            print(f"# profile {args.profile} carries no knee "
                  "concurrency (v1 schema or kneeless sweep); HEADRM "
                  "stays empty", file=sys.stderr)
    while True:
        snapshot = await collect(args.control_plane, timeout=args.timeout,
                                 knee_concurrency=knee)
        if args.json:
            print(json.dumps(snapshot, indent=None if args.once else 2))
        else:
            if not args.once:
                print("\x1b[2J\x1b[H", end="")  # clear screen, home
            print(render_table(snapshot), flush=True)
        if args.once:
            return 0
        await asyncio.sleep(args.interval)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "tools/dynamo_top.py", description=__doc__.splitlines()[0])
    p.add_argument("--control-plane", required=True, help="HOST:PORT")
    p.add_argument("--once", action="store_true",
                   help="print one snapshot and exit")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh interval (seconds)")
    p.add_argument("--timeout", type=float, default=3.0,
                   help="per-process scrape timeout (seconds)")
    p.add_argument("--profile", default=None,
                   help="SLA-profiler profile JSON "
                        "(benchmarks/sla_profiler.py); enables the "
                        "HEADRM capacity-headroom column")
    args = p.parse_args(argv)
    try:
        return asyncio.run(_run(args))
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
