"""Load-generator subprocess for benchmarks/frontend_bench.py: issues
streamed chat completions against a frontend and prints ONE JSON line
{"requests": N, "tokens": T, "wall_s": W}.  Run N of these in parallel
so client-side SSE parsing never shares a core with the frontend loop.
"""

import argparse
import asyncio
import json
import time


async def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--base", required=True)
    p.add_argument("--model", default="bench-model")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=16)
    p.add_argument("--max-tokens", type=int, default=64)
    p.add_argument("--prompt-tokens", type=int, default=64)
    p.add_argument("--unary", action="store_true")
    args = p.parse_args()

    from aiohttp import ClientSession

    payload = {
        "model": args.model,
        "messages": [{"role": "user",
                      "content": "word " * args.prompt_tokens}],
        "max_tokens": args.max_tokens,
        "stream": not args.unary,
    }
    tokens = 0
    sem = asyncio.Semaphore(args.concurrency)

    async with ClientSession() as s:

        async def one() -> int:
            async with sem:
                async with s.post(f"{args.base}/v1/chat/completions",
                                  json=payload) as r:
                    assert r.status == 200, await r.text()
                    if args.unary:
                        body = await r.json()
                        return body["usage"]["completion_tokens"]
                    ok = False
                    async for raw in r.content:
                        if b'"finish_reason": "length"' in raw or \
                                b'"finish_reason":"length"' in raw:
                            ok = True
                    assert ok, "no length finish"
                    return args.max_tokens

        # warmup
        await asyncio.gather(*[one() for _ in range(4)])
        t0 = time.perf_counter()
        results = await asyncio.gather(*[one()
                                         for _ in range(args.requests)])
        wall = time.perf_counter() - t0
        tokens = sum(results)
    print(json.dumps({"requests": args.requests, "tokens": tokens,
                      "wall_s": wall}), flush=True)


if __name__ == "__main__":
    asyncio.run(main())
