"""Decode-step component profiler (round-4 perf work, VERDICT item 1).

Isolates where the window step's time goes, all slope-timed with forced
completion (the axon backend returns from block_until_ready early):

  - hbm_bw: achievable HBM read bandwidth (big-array reduction)
  - peak_flops: dependent-chain bf16 matmul ceiling
  - weights_only: model forward with ctx=1 (attention reads ~nothing;
    cost = weight streaming + elementwise + lm_head)
  - attn_kernel: the Pallas paged-decode kernel alone x num_layers
  - attn_xla: the gather-path attention alone x num_layers
  - window_pallas / window_xla: full fused window per-token
  - sampling: argmax over [B, V] logits alone
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_decode_window
from dynamo_tpu.ops.pallas import paged_decode_attention

BATCH = 64
CTX = 512
BLOCK = 64
WIDTH = 16


def _sync(x):
    jax.device_get(jax.tree.leaves(x)[0].ravel()[0])


def slope(fn, n1=3, n2=9):
    """fn(n) runs n dependent iterations and syncs; returns per-iter secs."""
    fn(1)  # warm
    t1 = fn(n1)
    t2 = fn(n2)
    return max((t2 - t1) / (n2 - n1), 1e-9)


# Peak/bandwidth probes live in bench.py (ONE methodology — VERDICT r3
# weak #2); import rather than fork them.
from bench import calibrate_peak_flops, measure_hbm_bw  # noqa: E402


def _window_time(cfg, params, use_pallas, window=8, ctx=CTX):
    num_blocks = 1 + BATCH * WIDTH
    win = jax.jit(
        make_decode_window(cfg, BLOCK, window, use_pallas_decode=use_pallas,
                           greedy_only=True),
        donate_argnums=(1,))
    bt = np.zeros((BATCH, WIDTH), np.int32)
    for i in range(BATCH):
        bt[i] = np.arange(1 + i * WIDTH, 1 + (i + 1) * WIDTH)
    bt = jnp.asarray(bt)
    z = jnp.zeros((BATCH,), jnp.float32)
    zi = jnp.zeros((BATCH,), jnp.int32)
    ones = jnp.ones((BATCH,), jnp.float32)
    keys = jax.random.split(jax.random.key(0), BATCH)

    def fresh():
        return (kvc.init_cache(kvc.KvCacheConfig.for_model(
                    cfg, num_blocks=num_blocks, block_size=BLOCK)),
                jnp.ones((BATCH,), jnp.int32))

    def run(n):
        cache, last = fresh()
        t0 = time.perf_counter()
        for _ in range(n):
            cache, out, _, _, _ = win(params, cache, last,
                                      jnp.full((BATCH,), ctx, jnp.int32),
                                      jnp.full((BATCH,), ctx + 1, jnp.int32),
                                      bt, z, zi, ones, keys, zi)
            last = out[window - 1]
        _sync(last)
        return time.perf_counter() - t0

    per = slope(run, 2, 6)
    return per / window


def bench_attn_kernel(cfg, ctx=CTX, layers=None):
    """Pallas paged-decode kernel alone, chained x num_layers per 'step'."""
    L = layers or cfg.num_layers
    S = (1 + BATCH * WIDTH) * BLOCK
    k_cache = jnp.ones((S, cfg.num_kv_heads * cfg.head_dim), jnp.bfloat16)
    v_cache = jnp.ones((S, cfg.num_kv_heads * cfg.head_dim), jnp.bfloat16)
    bt = np.zeros((BATCH, WIDTH), np.int32)
    for i in range(BATCH):
        bt[i] = np.arange(1 + i * WIDTH, 1 + (i + 1) * WIDTH)
    bt = jnp.asarray(bt)
    sl = jnp.full((BATCH,), ctx, jnp.int32)

    @jax.jit
    def step(q):
        for _ in range(L):
            q = paged_decode_attention(q, k_cache, v_cache, bt, sl,
                                       block_size=BLOCK)
        return q

    q0 = jnp.ones((BATCH, cfg.num_heads, cfg.head_dim), jnp.bfloat16)

    def run(n):
        q = q0
        t0 = time.perf_counter()
        for _ in range(n):
            q = step(q)
        _sync(q)
        return time.perf_counter() - t0

    return slope(run)


def main():
    jax.config.update("jax_compilation_cache_dir", "/tmp/dynamo_tpu_xla_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    cfg = mcfg.get_config("llama-3-1b")
    params = init_params(cfg, jax.random.key(0))
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    w_bytes = n_params * 2
    kv_bytes = (BATCH * CTX * cfg.num_layers * cfg.num_kv_heads
                * cfg.head_dim * 2 * 2)

    bw = measure_hbm_bw().measured
    print(f"hbm_bw             {bw/1e9:8.1f} GB/s")
    pk = calibrate_peak_flops().measured
    print(f"peak_bf16          {pk/1e12:8.1f} TFLOP/s")
    print(f"weights            {w_bytes/1e9:8.2f} GB  -> floor "
          f"{w_bytes/bw*1e3:6.2f} ms")
    print(f"kv traffic         {kv_bytes/1e9:8.2f} GB  -> floor "
          f"{kv_bytes/bw*1e3:6.2f} ms")

    t = bench_attn_kernel(cfg)
    print(f"attn_kernel x{cfg.num_layers}    {t*1e3:8.2f} ms/step "
          f"(floor {kv_bytes/bw*1e3:.2f})")

    t = _window_time(cfg, params, use_pallas=True, ctx=1)
    print(f"window ctx=1 pallas{t*1e3:8.2f} ms/tok (weights floor "
          f"{w_bytes/bw*1e3:.2f})")

    t = _window_time(cfg, params, use_pallas=True)
    print(f"window ctx=512 pal {t*1e3:8.2f} ms/tok")

    t = _window_time(cfg, params, use_pallas=False)
    print(f"window ctx=512 xla {t*1e3:8.2f} ms/tok")


if __name__ == "__main__":
    main()
