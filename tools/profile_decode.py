"""Decode-step component profiler with a per-phase breakdown.

Round-4 built the first version (isolated window/kernel slopes); round 6
extends it into the serving-path diagnosis tool the r5 regression lacked:
one JSON artifact that splits a decode step into

  - kernel        — the Pallas paged-decode kernel alone x num_layers
  - weights       — window at ctx=1 (attention reads ~nothing; cost =
                    weight streaming + elementwise + lm_head)
  - non_attention — window minus kernel (RoPE/norm/MLP/lm_head/sampling
                    inside the fused program, plus loop fixed costs)
  - sampling      — argmax over [B, V] logits alone
  - host_sync     — blocking device→host fetch of one window's [K, B]
                    token block (what _sync_one_window pays per window)
  - scheduler     — host-side Scheduler.plan() cost per step at this
                    batch (pure CPU; the engine pays it every iteration)

All device timings are slope-timed with forced completion (the axon
backend returns from block_until_ready early).  Runs on CPU with a tiny
model for tests (`--model tiny-test --no-probes --json`); on TPU the
default geometry matches bench.py's serving shape (b64/ctx512).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _prescan_mesh() -> None:
    """`--tp/--sp/--pp N` on a CPU host needs N visible devices, and the
    XLA flag must land before jax initialises (same discipline as
    worker/__main__.py's prescan).  Harmless under a real TPU backend:
    the flag only multiplies the HOST platform's device count."""
    argv = sys.argv[1:]
    need = 1
    for flag in ("--tp", "--sp", "--pp"):
        deg = 0
        for i, a in enumerate(argv):
            if a == flag and i + 1 < len(argv):
                deg = int(argv[i + 1])
            elif a.startswith(flag + "="):
                deg = int(a.split("=", 1)[1])
        need *= max(deg, 1)
    if need > 1 and ("xla_force_host_platform_device_count"
                     not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={max(need, 8)}"
        ).strip()


_prescan_mesh()

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import (
    BlockAllocator,
    Request,
    RequestState,
    Scheduler,
    SchedulerConfig,
)
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_decode_window
from dynamo_tpu.ops.pallas import paged_decode_attention

BATCH = 64
CTX = 512
BLOCK = 64
WIDTH = 16
WINDOW = 8


def _sync(x):
    jax.device_get(jax.tree.leaves(x)[0].ravel()[0])


def slope(fn, n1=3, n2=9):
    """fn(n) runs n dependent iterations and syncs; returns per-iter secs."""
    fn(1)  # warm
    t1 = fn(n1)
    t2 = fn(n2)
    return max((t2 - t1) / (n2 - n1), 1e-9)


def _block_tables(batch, width):
    from dynamo_tpu.bench.harness import sequential_block_tables

    return jnp.asarray(sequential_block_tables(batch, width))


def window_time(cfg, params, use_pallas, *, batch=BATCH, ctx=CTX,
                block=BLOCK, width=WIDTH, window=WINDOW,
                kv_quant="none", mesh=None):
    """Per-token device time inside the fused K-step decode window.
    With `mesh`, the SHARDED window (parallel.sharding.make_sharded_window
    — exactly the program a `--tp N` worker dispatches) with params and
    cache laid out over it."""
    num_blocks = 1 + batch * width
    quant = kv_quant != "none"
    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        # Fused pp stage programs (ISSUE 12): the schedule-looping
        # decode window over the STACKED layer/cache layout — exactly
        # what a `--pp N` worker dispatches per steady window.
        from dynamo_tpu.parallel.pipeline import (
            init_pp_cache, make_pp_decode_window, pp_cache_pspecs,
            pp_param_pspecs, stack_layer_params)
        from dynamo_tpu.parallel.sharding import shard_pytree

        win = make_pp_decode_window(cfg, block, mesh, 2, window,
                                    greedy_only=True, kv_quant=quant)
        params = shard_pytree(stack_layer_params(params),
                              pp_param_pspecs(cfg), mesh)
        pp_specs = pp_cache_pspecs(quant)

        def make_cache(c):
            del c
            return shard_pytree(
                init_pp_cache(kvc.KvCacheConfig.for_model(
                    cfg, num_blocks=num_blocks, block_size=block,
                    kv_quant=kv_quant)), pp_specs, mesh)
    elif mesh is not None:
        from dynamo_tpu.parallel.sharding import (
            cache_pspecs, make_sharded_window, param_pspecs, shard_pytree)

        win = make_sharded_window(cfg, block, mesh, window,
                                  greedy_only=True,
                                  use_pallas_decode=use_pallas,
                                  kv_quant=quant)
        params = shard_pytree(params, param_pspecs(cfg), mesh)
        cache_specs = cache_pspecs(cfg.num_layers, kv_quant=quant)

        def make_cache(c):
            return shard_pytree(c, cache_specs, mesh)
    else:
        win = jax.jit(
            make_decode_window(cfg, block, window,
                               use_pallas_decode=use_pallas,
                               greedy_only=True),
            donate_argnums=(1,))

        def make_cache(c):
            return c
    bt = _block_tables(batch, width)
    z = jnp.zeros((batch,), jnp.float32)
    zi = jnp.zeros((batch,), jnp.int32)
    ones = jnp.ones((batch,), jnp.float32)
    keys = jnp.zeros((batch, 2), jnp.uint32)

    def fresh():
        return (make_cache(kvc.init_cache(kvc.KvCacheConfig.for_model(
                    cfg, num_blocks=num_blocks, block_size=block,
                    kv_quant=kv_quant))),
                jnp.ones((batch,), jnp.int32))

    def run(n):
        cache, last = fresh()
        t0 = time.perf_counter()
        for _ in range(n):
            cache, out, _, _, _ = win(
                params, cache, last,
                jnp.full((batch,), ctx, jnp.int32),
                jnp.full((batch,), ctx + 1, jnp.int32),
                bt, z, zi, ones, keys, zi)
            last = out[window - 1]
        _sync(last)
        return time.perf_counter() - t0

    per = slope(run, 2, 6)
    return per / window


def kernel_time(cfg, *, batch=BATCH, ctx=CTX, block=BLOCK, width=WIDTH,
                layers=None, interpret=None, tp=1):
    """Pallas paged-decode kernel alone, chained x num_layers per 'step'.
    `tp` > 1 profiles the PER-SHARD geometry a head-sharded engine hands
    the kernel inside shard_map (Hq/tp query heads over an [S, F/tp]
    cache slice) — the honest per-chip kernel cost under tensor
    parallelism."""
    L = layers or cfg.num_layers
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    S = (1 + batch * width) * block
    F = cfg.num_kv_heads * cfg.head_dim // tp
    k_cache = jnp.ones((S, F), jnp.bfloat16)
    v_cache = jnp.ones((S, F), jnp.bfloat16)
    bt = _block_tables(batch, width)
    sl = jnp.full((batch,), ctx, jnp.int32)

    @jax.jit
    def step(q):
        for _ in range(L):
            q = paged_decode_attention(q, k_cache, v_cache, bt, sl,
                                       block_size=block,
                                       interpret=interpret)
        return q

    q0 = jnp.ones((batch, cfg.num_heads // tp, cfg.head_dim),
                  jnp.bfloat16)

    def run(n):
        q = q0
        t0 = time.perf_counter()
        for _ in range(n):
            q = step(q)
        _sync(q)
        return time.perf_counter() - t0

    return slope(run)


def sampling_time(cfg, *, batch=BATCH):
    """Greedy sampling alone: argmax over [B, V] f32 logits."""
    logits = jnp.ones((batch, cfg.vocab_size), jnp.float32)

    @jax.jit
    def step(x, i):
        return jnp.argmax(x + i[None, :].astype(jnp.float32), -1)

    def run(n):
        i = jnp.zeros((cfg.vocab_size,), jnp.int32)
        out = None
        t0 = time.perf_counter()
        for _ in range(n):
            out = step(logits, i)
            i = i.at[0].set(out[0].astype(jnp.int32))  # dependency chain
        _sync(out)
        return time.perf_counter() - t0

    return slope(run)


def host_sync_time(*, batch=BATCH, window=WINDOW, reps=5):
    """Blocking device→host fetch of one window's [K, B] token block —
    the cost _sync_one_window pays when the pipeline can't hide it.
    Fixed cost (median of reps), NOT slope-timed: the round-trip itself
    is the number."""
    x = jnp.ones((window, batch), jnp.int32)
    _sync(x)
    samples = []
    for _ in range(reps):
        t0 = time.perf_counter()
        np.asarray(jax.device_get(x))
        samples.append(time.perf_counter() - t0)
    return sorted(samples)[len(samples) // 2]


def scheduler_time(*, batch=BATCH, ctx=CTX, block=BLOCK, iters=200):
    """Host-side Scheduler.plan() per step with `batch` sequences in
    steady decode — pure CPU, the engine pays it every iteration."""
    pages_per = (ctx + block - 1) // block + 1
    alloc = BlockAllocator(1 + batch * pages_per)
    sched = Scheduler(SchedulerConfig(
        max_seqs=max(batch, 64), block_size=block,
        max_pages_per_seq=pages_per + 1), alloc)
    for i in range(batch):
        req = Request(request_id=f"r{i}", prompt_tokens=list(range(ctx)),
                      sampling=SamplingParams(max_tokens=64))
        sched.add_request(req)
    sched.plan()  # admit
    for req in sched.running:
        req.prefilled = len(req.prompt_tokens)
        req.state = RequestState.DECODE
    t0 = time.perf_counter()
    for _ in range(iters):
        sched.plan()
    return (time.perf_counter() - t0) / iters


def phase_breakdown(cfg, params, *, batch=BATCH, ctx=CTX, block=BLOCK,
                    width=WIDTH, window=WINDOW, use_pallas=None,
                    with_kernel=True, mesh=None):
    """The per-phase decode-step split, all values in ms.

    `non_attention` is derived (window - kernel) and only meaningful
    when both run on the real device; on CPU the kernel runs in
    interpret mode and the subtraction is reported as None.

    `mesh` (ISSUE 9 satellite): the window/weights phases run the
    SHARDED programs, so a `--tp N` gap vs meshless is attributable to a
    phase instead of being one opaque number; the kernel phase profiles
    the per-shard geometry."""
    from dynamo_tpu.ops.pallas import mosaic_geometry_ok

    on_tpu = jax.default_backend() == "tpu"
    tp = mesh.shape["tp"] if mesh is not None else 1
    if use_pallas is None:
        feat = cfg.num_kv_heads * cfg.head_dim // max(tp, 1)
        use_pallas = on_tpu and mosaic_geometry_ok(feat, block)
    win_ms = window_time(cfg, params, use_pallas, batch=batch, ctx=ctx,
                         block=block, width=width, window=window,
                         mesh=mesh) * 1e3
    weights_ms = window_time(cfg, params, use_pallas, batch=batch, ctx=1,
                             block=block, width=width,
                             window=window, mesh=mesh) * 1e3
    # 6 decimals: tiny-model CPU smokes can slope-clamp to 1e-6 ms under
    # machine load, and 4-decimal rounding flattened that to a 0.0 that
    # reads as "not measured".
    phases = {
        "window_ms_per_tok": round(win_ms, 6),
        "weights_ms": round(weights_ms, 6),
        "sampling_ms": round(sampling_time(cfg, batch=batch) * 1e3, 6),
        "host_sync_ms": round(
            host_sync_time(batch=batch, window=window) * 1e3, 6),
        "scheduler_ms": round(
            scheduler_time(batch=batch, ctx=ctx, block=block) * 1e3, 6),
        "kernel_ms": None,
        "non_attention_ms": None,
    }
    if with_kernel and cfg.num_heads % max(tp, 1) == 0 \
            and cfg.num_kv_heads % max(tp, 1) == 0:
        k_ms = kernel_time(cfg, batch=batch, ctx=ctx, block=block,
                           width=width, tp=tp) * 1e3
        phases["kernel_ms"] = round(k_ms, 6)
        # Interpret-mode kernel times are not comparable to compiled
        # window times — the subtraction only means something on TPU.
        if on_tpu:
            phases["non_attention_ms"] = round(win_ms - k_ms, 4)
    return phases


def transfer_phase(cfg, block, batch_sizes=(1, 4, 8, 16),
                   n_blocks=32, kv_quant="none"):
    """Pure-transport GB/s of the device plane per pull batch size:
    stage `bsz` wire blocks on a KvTransferPlane, pull them, wall-clock
    the round.  Measures the fabric + staging cost the batched pull
    pipelines amortise — no engines, no RPC, so the number isolates the
    transport itself (pjrt service where the build has it, the local
    device_put fabric otherwise)."""
    import asyncio

    from dynamo_tpu.engine.kv_cache import KvCacheConfig
    from dynamo_tpu.llm.block_manager.device_transfer import (
        KvTransferPlane)

    cache_cfg = KvCacheConfig.for_model(cfg, num_blocks=n_blocks + 1,
                                        block_size=block,
                                        kv_quant=kv_quant)
    shape = cache_cfg.block_wire_shape
    dtype = cache_cfg.block_wire_dtype
    blocks = {h: jnp.zeros(shape, dtype) for h in range(1, n_blocks + 1)}
    jax.block_until_ready(list(blocks.values()))
    block_bytes = cache_cfg.bytes_per_block
    plane = KvTransferPlane(offer_ttl_s=30.0)
    plane.start()

    async def pull_all(bsz: int) -> float:
        order = sorted(blocks)
        t0 = time.perf_counter()
        for lo in range(0, n_blocks, bsz):
            meta = plane.stage(blocks, order[lo:lo + bsz],
                               peer_fabric=plane.fabric)
            assert meta is not None, plane.last_refusal
            pulled = await plane.pull(meta)
            plane.mark_pulled(meta["uuid"])
            assert len(pulled) == len(order[lo:lo + bsz])
        return time.perf_counter() - t0

    per_batch = {}
    for bsz in batch_sizes:
        asyncio.run(pull_all(min(bsz, n_blocks)))    # warm
        wall = asyncio.run(pull_all(min(bsz, n_blocks)))
        per_batch[str(bsz)] = round(
            n_blocks * block_bytes / wall / 1e9, 4) if wall else 0.0
    transport = plane.transport_kind
    plane.stop()
    return {
        "transport": transport,
        "kv_quant": kv_quant,
        "block_bytes": block_bytes,
        "n_blocks": n_blocks,
        "gbs_per_batch_size": per_batch,
    }


def main(argv=None):
    p = argparse.ArgumentParser("tools/profile_decode.py")
    p.add_argument("--model", default="llama-3-1b")
    p.add_argument("--batch", type=int, default=BATCH)
    p.add_argument("--ctx", type=int, default=CTX)
    p.add_argument("--block", type=int, default=BLOCK)
    p.add_argument("--width", type=int, default=WIDTH)
    p.add_argument("--window", type=int, default=WINDOW)
    p.add_argument("--tp", type=int, default=1,
                   help="profile a SHARDED engine's decode phases: the "
                        "window/weights phases run under a tp-degree "
                        "mesh (CPU hosts get virtual devices forced "
                        "before jax init), the kernel phase profiles "
                        "the per-shard geometry — so the sharded gap "
                        "is attributable per phase")
    p.add_argument("--pp", type=int, default=1,
                   help="profile the fused pp stage programs (ISSUE 12):"
                        " window/weights phases run the schedule-looping"
                        " pp decode window over the stacked layout; "
                        "modeled bytes divide by pp (each stage streams "
                        "its layer slice), matching the engine's "
                        "kv_traffic_shards.  Exclusive of --tp/--sp "
                        "(pipeline v1 composes with no other in-mesh "
                        "axis)")
    p.add_argument("--sp", type=int, default=1,
                   help="build the mesh with an sp axis (ring-SP "
                        "engines): decode phases run the sharded "
                        "programs under it.  Modeled decode bytes do "
                        "NOT divide by sp — the sp axis replicates "
                        "decode (its win is ring prefill), and "
                        "dividing would flatter the per-chip numbers "
                        "(the engine's kv_traffic_shards makes the "
                        "same call).  Also grows a ring-kernel phase: "
                        "flash-ring vs XLA-ring vs meshless slopes at "
                        "this geometry + modeled per-hop ICI bytes "
                        "(skip with --no-kernel)")
    p.add_argument("--json", action="store_true",
                   help="emit one JSON object instead of the text report")
    p.add_argument("--no-probes", action="store_true",
                   help="skip the HBM-bandwidth / peak-FLOPs probes "
                        "(slow; pointless off-TPU)")
    p.add_argument("--no-kernel", action="store_true",
                   help="skip the Pallas kernel phase (interpret mode "
                        "is slow on CPU at real geometries)")
    p.add_argument("--kv-quant", choices=("none", "int8"), default="none",
                   help="also measure the fused window with the "
                        "quantized KV cache (modeled int8 rooflines are "
                        "always reported)")
    p.add_argument("--transfer", action="store_true",
                   help="also profile the device-transfer plane: pure "
                        "transport GB/s of staged wire-block pulls per "
                        "batch size (ISSUE 13; CPU-runnable — the local "
                        "device fabric on builds without "
                        "jax.experimental.transfer), at this model's "
                        "wire-block geometry in both cache modes")
    p.add_argument("--moe", action="store_true",
                   help="also profile the MoE fast-decode plane (ISSUE "
                        "17): dense-oracle vs grouped-kernel slope "
                        "timing at decode shape plus modeled expert-"
                        "weight bytes (and their HBM floors when probes "
                        "run).  A dense --model profiles an 8-expert "
                        "top-2 variant at its dims; interpret mode "
                        "off-TPU — times then show plumbing, not "
                        "silicon")
    p.add_argument("--prefill-attn", action="store_true",
                   help="also slope-time prefill attention: the Pallas "
                        "paged flash-prefill kernel vs the gather_kv "
                        "path at this geometry (ISSUE 10; interpret "
                        "mode off-TPU — times then show plumbing, not "
                        "silicon)")
    args = p.parse_args(argv)

    # Same env override as bench.py: lets the tier-1 subprocess tests
    # point at the suite's persistent cache so repeated runs in one
    # container stay warm.
    jax.config.update("jax_compilation_cache_dir",
                      os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                     "/tmp/dynamo_tpu_xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    cfg = mcfg.get_config(args.model)
    params = init_params(cfg, jax.random.key(0))
    mesh = None
    if args.pp > 1 and (args.tp > 1 or args.sp > 1):
        p.error("--pp is exclusive of --tp/--sp (pipeline v1 composes "
                "with no other in-mesh axis)")
    mesh_need = max(args.tp, 1) * max(args.sp, 1) * max(args.pp, 1)
    if mesh_need > 1:
        from dynamo_tpu.parallel import MeshConfig, make_mesh

        devices = jax.devices()
        if len(devices) < mesh_need:
            p.error(f"--tp {args.tp} --sp {args.sp} --pp {args.pp} "
                    f"needs {mesh_need} devices; have {len(devices)}")
        mesh = make_mesh(MeshConfig(tp=args.tp, sp=args.sp, pp=args.pp),
                         devices[:mesh_need])
    n_params = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    # PER-CHIP modeled bytes (same honesty rule as the engine's
    # kv_traffic_shards and the bench's mbu_per_chip): the measured
    # window/kernel times below are per-chip sharded times, so a
    # whole-model byte count would inflate any mbu/roofline derived
    # from this JSON.  Weights and KV both split tp-ways under
    # head-sharded tensor parallelism and pp-ways under the stacked
    # stage layout (each stage streams its layer slice for all rows);
    # sp REPLICATES decode, so it is deliberately NOT a divisor —
    # exactly the engine's kv_traffic_shards discipline.
    shards = max(args.pp, 1) if args.pp > 1 else max(args.tp, 1)
    w_bytes = n_params * 2 // shards
    # True per-context-token KV bytes (incl. int8 scales) from the ONE
    # accounting everything else gates on (bench.py BENCH JSON, the
    # bench_gate traffic-ratio floor) — no forked formula here.
    from dynamo_tpu.bench.decode_wall import kv_quant_traffic

    traffic = kv_quant_traffic(cfg, block_size=args.block,
                               batch=args.batch, ctx=args.ctx)
    kv_bytes = traffic["kv_bytes_per_step_bf16"] // shards
    kv_bytes_int8 = traffic["kv_bytes_per_step_int8"] // shards

    out = {
        "model": args.model,
        "batch": args.batch,
        "ctx": args.ctx,
        "window": args.window,
        "tp": args.tp,
        "pp": args.pp,
        "sp": args.sp,
        "modeled_byte_shards": shards,
        "device": str(jax.devices()[0]),
        "weight_bytes": w_bytes,
        "kv_bytes_per_step": kv_bytes,
        # The decode-bandwidth-wall phase (ISSUE 6): modeled KV bytes
        # each emitted token costs in HBM sweeps, both cache modes — the
        # "move half the bytes" claim as arithmetic a CPU can check
        # (per chip under --tp, like every other figure here).
        "effective_bytes_per_token": {
            "bf16": args.ctx * traffic["bytes_per_context_token_bf16"]
            // shards,
            "int8": args.ctx * traffic["bytes_per_context_token_int8"]
            // shards,
            "traffic_ratio": traffic["traffic_ratio"],
        },
    }
    if not args.no_probes:
        # Peak/bandwidth probes live in bench.py (ONE methodology —
        # VERDICT r3 weak #2); import rather than fork them.
        from bench import calibrate_peak_flops, measure_hbm_bw

        bw = measure_hbm_bw().measured
        pk = calibrate_peak_flops().measured
        out["hbm_bw_gbs"] = round(bw / 1e9, 1)
        out["peak_bf16_tflops"] = round(pk / 1e12, 1)
        out["weights_floor_ms"] = round(w_bytes / bw * 1e3, 4)
        out["kv_floor_ms"] = round(kv_bytes / bw * 1e3, 4)
        out["roofline_ms"] = round((w_bytes + kv_bytes) / bw * 1e3, 4)
        # Quantized-cache roofline: same weights, ~0.53x the KV bytes.
        out["kv_floor_ms_int8"] = round(kv_bytes_int8 / bw * 1e3, 4)
        out["roofline_ms_int8"] = round(
            (w_bytes + kv_bytes_int8) / bw * 1e3, 4)
    out["phases"] = phase_breakdown(
        cfg, params, batch=args.batch, ctx=args.ctx, block=args.block,
        width=args.width, window=args.window,
        with_kernel=not args.no_kernel, mesh=mesh)
    if args.kv_quant != "none":
        # Measured: the fused window's wall time with the quantized cache
        # (gather path dequant on CPU; kernel dequant on TPU) — lets a
        # TPU round report measured-vs-modeled for the int8 plane.
        # Composes with --tp: scales shard with their kv heads.
        from dynamo_tpu.ops.pallas import mosaic_geometry_ok

        feat = cfg.num_kv_heads * cfg.head_dim // max(args.tp, 1)
        use_pallas = (jax.default_backend() == "tpu"
                      and mosaic_geometry_ok(feat, args.block))
        out["phases"]["window_ms_per_tok_int8"] = round(window_time(
            cfg, params, use_pallas,
            batch=args.batch, ctx=args.ctx, block=args.block,
            width=args.width, window=args.window,
            kv_quant=args.kv_quant, mesh=mesh) * 1e3, 6)

    if args.sp > 1:
        # Ring-kernel phase (ISSUE 19): one measurement methodology with
        # the gated `ring_plane` bench section — import, don't fork.
        # Reports the flash-ring-kernel vs XLA-ppermute-ring vs meshless
        # slopes at this geometry plus the modeled per-hop ICI payload
        # in both cache modes (interpret mode off-TPU unless --no-kernel
        # — times then show plumbing, not silicon).
        if args.no_kernel:
            out["ring"] = {"skipped": "--no-kernel"}
        else:
            from dynamo_tpu.bench.ring_plane import run_ring_plane

            out["ring"] = run_ring_plane(
                cfg, batch=min(args.batch, 4), seq=args.ctx, sp=args.sp,
                with_engine=False)

    if args.transfer:
        # Device-transfer transport phase (ISSUE 13): per-batch-size
        # GB/s in both cache modes at this model's wire-block geometry.
        out["transfer"] = {
            "bf16": transfer_phase(cfg, args.block),
            "int8": transfer_phase(cfg, args.block, kv_quant="int8"),
        }

    if args.moe:
        # MoE fast-decode phase (ISSUE 17): one measurement methodology
        # with the gated `moe_decode` bench section — import, don't
        # fork.  Reports dense/grouped/int8 step slopes, bitwise parity,
        # the [E+1] expert-load histogram, and modeled per-step expert-
        # weight bytes (dense streams all E experts; grouped streams
        # only the active ones).
        from dynamo_tpu.bench.moe_decode import run_moe_decode

        moe_cfg = cfg if cfg.is_moe else cfg.replace(
            name=cfg.name + "-moe8", num_experts=8,
            num_experts_per_token=2)
        moe = run_moe_decode(moe_cfg, batch=args.batch)
        # Expert-weight HBM floors against the SAME measured bandwidth
        # the dense rooflines above use — the grouped kernel's claim
        # ("decode is weight-bytes-bound; stop streaming inactive
        # experts") as arithmetic next to the measured slopes.
        if "hbm_bw_gbs" in out and "dense_expert_weight_bytes" in moe:
            bw = out["hbm_bw_gbs"] * 1e9
            moe["dense_expert_weights_floor_ms"] = round(
                moe["dense_expert_weight_bytes"] / bw * 1e3, 4)
            moe["grouped_expert_weights_floor_ms"] = round(
                moe["grouped_expert_weight_bytes"] / bw * 1e3, 4)
        out["moe"] = moe

    if args.prefill_attn:
        # Prefill-plane attention phase (ISSUE 10): one measurement
        # methodology with the gated bench — import, don't fork.
        from dynamo_tpu.bench.prefill_plane import measure_prefill_attention

        out["prefill_attention"] = measure_prefill_attention(
            cfg, block_size=args.block,
            ctx=min(args.ctx, args.width * args.block),
            chunk=min(args.ctx, args.width * args.block),
            segments=4,
            interpret=jax.default_backend() != "tpu")

    if args.json:
        print(json.dumps(out))
        return out
    for k, v in out.items():
        if k == "phases":
            print("phases (ms):")
            for pk_, pv in v.items():
                print(f"  {pk_:22s} {pv}")
        else:
            print(f"{k:24s} {v}")
    return out


if __name__ == "__main__":
    main()
