"""Does a per-window D2H token fetch stall the pipelined window stream?

Dispatches 16 windows back-to-back and compares wall-clock with
(a) no intermediate fetches, (b) np.asarray of each window's [K, B]
tokens from a fetch thread (the engine's pattern), (c) fetch every 4th
window (grouped).
"""

import time
from concurrent.futures import ThreadPoolExecutor

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_decode_window

BATCH, CTX, BLOCK, WIDTH, K = 64, 512, 64, 16, 8
N_WIN = 16


def main():
    jax.config.update("jax_compilation_cache_dir", "/tmp/dynamo_tpu_xla_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    cfg = mcfg.get_config("llama-3-1b")
    params = init_params(cfg, jax.random.key(0))
    num_blocks = 1 + BATCH * WIDTH
    win = jax.jit(
        make_decode_window(cfg, BLOCK, K, use_pallas_decode=True,
                           greedy_only=True),
        donate_argnums=(1,))
    bt = np.zeros((BATCH, WIDTH), np.int32)
    for i in range(BATCH):
        bt[i] = np.arange(1 + i * WIDTH, 1 + (i + 1) * WIDTH)
    bt = jnp.asarray(bt)
    z = jnp.zeros((BATCH,), jnp.float32)
    zi = jnp.zeros((BATCH,), jnp.int32)
    ones = jnp.ones((BATCH,), jnp.float32)
    keys = jax.random.split(jax.random.key(0), BATCH)
    pool = ThreadPoolExecutor(max_workers=1)

    def run(mode):
        cache = kvc.init_cache(kvc.KvCacheConfig.for_model(
            cfg, num_blocks=num_blocks, block_size=BLOCK))
        last = jnp.ones((BATCH,), jnp.int32)
        pos = jnp.full((BATCH,), CTX, jnp.int32)
        seq = jnp.full((BATCH,), CTX + 1, jnp.int32)
        off = zi
        futs = []
        pend = []
        t0 = time.perf_counter()
        for w in range(N_WIN):
            cache, out, pos, seq, off = win(params, cache, last, pos, seq,
                                            bt, z, zi, ones, keys, off)
            last = out[K - 1]
            if mode == "each":
                futs.append(pool.submit(np.asarray, out))
            elif mode == "async_each":
                out.copy_to_host_async()
                futs.append(pool.submit(np.asarray, out))
            elif mode == "group4":
                pend.append(out)
                if len(pend) == 4:
                    grp = jnp.concatenate(pend)
                    pend = []
                    futs.append(pool.submit(np.asarray, grp))
        for f in futs:
            f.result()
        jax.device_get(last)
        return time.perf_counter() - t0

    for mode in ("none", "each", "async_each", "group4", "group4",
                 "async_each", "none"):
        t = run(mode)
        print(f"{mode:7s} {t/N_WIN*1e3:7.1f} ms/window "
              f"({t/N_WIN/K*1e3:.2f} ms/tok)")


if __name__ == "__main__":
    main()
