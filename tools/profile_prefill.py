"""Where does the prefill step's time go on the real chip?

Times the full forward step at serving prefill geometry, then ablations:
matmuls only (attention stubbed), attention only, and the paged-context
gather alone.  Slope-timed (N1 vs N2 runs) to cancel the tunnel RTT,
matching bench.py methodology.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_forward_step

ROWS = 16          # prefill batch rows (8192-token budget / 512 chunk)
CHUNK = 512
BLOCK = 64


def slope(fn, n1=2, n2=6):
    def run(n):
        t0 = time.perf_counter()
        x = None
        for _ in range(n):
            x = fn()
        jax.device_get(jax.tree.leaves(x)[0].ravel()[0])
        return time.perf_counter() - t0

    run(1)  # compile
    t1, t2 = run(n1), run(n2)
    return (t2 - t1) / (n2 - n1)


def main():
    cfg = mcfg.get_config("llama-3-1b")
    params = init_params(cfg, jax.random.key(0))
    pages = CHUNK // BLOCK
    num_blocks = 1 + ROWS * pages
    cache_cfg = kvc.KvCacheConfig.for_model(cfg, num_blocks=num_blocks,
                                            block_size=BLOCK)
    cache = kvc.init_cache(cache_cfg)
    step = jax.jit(make_forward_step(cfg, BLOCK), donate_argnums=(1,))

    tokens = jnp.ones((ROWS, CHUNK), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(CHUNK, dtype=jnp.int32),
                                 (ROWS, CHUNK))
    seq_lens = jnp.full((ROWS,), CHUNK, jnp.int32)
    bt = np.zeros((ROWS, pages), np.int32)
    for i in range(ROWS):
        bt[i] = np.arange(1 + i * pages, 1 + (i + 1) * pages)
    bt = jnp.asarray(bt)
    sample_pos = jnp.full((ROWS,), CHUNK - 1, jnp.int32)

    state = {"cache": cache}

    def full():
        logits, state["cache"] = step(params, state["cache"], tokens,
                                      positions, seq_lens, bt, sample_pos)
        return logits

    s_full = slope(full)
    toks = ROWS * CHUNK
    flops_tok = 2 * sum(int(np.prod(p.shape))
                        for p in jax.tree.leaves(params))
    print(f"full step: {s_full*1e3:.1f} ms, {toks/s_full:.0f} tok/s, "
          f"MFU~{toks/s_full*flops_tok/197e12:.3f}")

    # Ablation: params-matmul-only proxy — dense transformer without
    # attention context (q@k of the chunk only, no cache gather).
    h = jnp.ones((ROWS, CHUNK, cfg.hidden_size), jnp.bfloat16)

    def mm_only():
        x = h
        for _ in range(cfg.num_layers):
            q = x @ params["layers"][0]["wq"].astype(jnp.bfloat16) \
                if isinstance(params["layers"][0], dict) else x
            x = x + 0.0 * q[..., :cfg.hidden_size]
        return x

    # Attention-only: the paged_attention op at this geometry.
    from dynamo_tpu.ops.attention import paged_attention

    Hq, Hkv, D = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    q = jnp.ones((ROWS, CHUNK, Hq, D), jnp.bfloat16)
    kctx = jnp.ones((ROWS, CHUNK, Hkv, D), jnp.bfloat16)
    kv_pos = jnp.broadcast_to(jnp.arange(CHUNK, dtype=jnp.int32),
                              (ROWS, CHUNK))
    attn = jax.jit(lambda q, k, v: paged_attention(
        q, k, v, kv_pos, kv_pos, seq_lens))

    def attn_only():
        return attn(q, kctx, kctx)

    s_attn = slope(attn_only)
    print(f"attention only (1 layer): {s_attn*1e3:.2f} ms; "
          f"x{cfg.num_layers} = {s_attn*cfg.num_layers*1e3:.1f} ms")

    # Gather-only: context materialisation from the paged cache.
    slots = kvc.slots_for_positions(bt, kv_pos, BLOCK) \
        if hasattr(kvc, "slots_for_positions") else None
    if slots is not None:
        layer_k = state["cache"]["k"][0]

        gather = jax.jit(lambda lk, s: jnp.take(lk, s.reshape(-1), axis=0))

        def gather_only():
            return gather(layer_k, slots)

        s_g = slope(gather_only)
        print(f"context gather (1 layer, k only): {s_g*1e3:.2f} ms; "
              f"x{cfg.num_layers}x2 = {s_g*cfg.num_layers*2*1e3:.1f} ms")


if __name__ == "__main__":
    main()
