"""Instrument the EngineCore serving loop: where does wall-clock go
relative to the raw window device time?"""

import time

import jax
import numpy as np

from dynamo_tpu.engine.engine import EngineConfig, EngineCore
from dynamo_tpu.engine.sampling import SamplingParams
from dynamo_tpu.engine.scheduler import SchedulerConfig
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params

BATCH, CTX, BLOCK, MAX_PAGES = 64, 512, 64, 128


def main():
    jax.config.update("jax_compilation_cache_dir", "/tmp/dynamo_tpu_xla_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    cfg = mcfg.get_config("llama-3-1b")
    params = init_params(cfg, jax.random.key(0))
    core = EngineCore(EngineConfig(
        model=cfg, num_blocks=1 + BATCH * (MAX_PAGES // 8),
        enable_prefix_cache=False, decode_window=8,
        scheduler=SchedulerConfig(
            max_seqs=BATCH, block_size=BLOCK, max_pages_per_seq=MAX_PAGES,
            max_prefill_chunk=512, max_batched_tokens=8192,
            decode_buckets=(16, 64), prefill_buckets=(512,))), params=params)
    rng = np.random.default_rng(0)
    for i in range(BATCH):
        core.add_request(f"r{i}", rng.integers(1, cfg.vocab_size,
                                               size=CTX).tolist(),
                         SamplingParams(max_tokens=256))
    t0 = time.perf_counter()
    while any(r.state.value in ("waiting", "prefill")
              for r in core._requests.values()):
        core.step()
    print(f"prefill wall {time.perf_counter()-t0:.2f}s")

    # instrument the window internals
    orig_dispatch = core._dispatch_window
    orig_sync = core._sync_one_window
    orig_fn = core._window_fn
    stats = {"dispatch": [], "sync": [], "fncall": []}

    def timed_fn(greedy):
        inner = orig_fn(greedy)

        def wrapped(*a):
            t = time.perf_counter()
            r = inner(*a)
            stats["fncall"].append(time.perf_counter() - t)
            return r
        return wrapped

    def timed_dispatch(work):
        t = time.perf_counter()
        r = orig_dispatch(work)
        stats["dispatch"].append(time.perf_counter() - t)
        return r

    def timed_sync():
        t = time.perf_counter()
        r = orig_sync()
        stats["sync"].append(time.perf_counter() - t)
        return r

    core._window_fn = timed_fn

    core._dispatch_window = timed_dispatch
    core._sync_one_window = timed_sync

    produced = 0
    t0 = time.perf_counter()
    first = None
    while core.has_work:
        d = core.step()
        produced += sum(len(x.token_ids) for x in d)
        if first is None and produced:
            first = time.perf_counter() - t0
    wall = time.perf_counter() - t0
    print(f"decode wall {wall:.2f}s produced {produced} "
          f"tok/s {produced/wall:.0f}")
    print(f"first sync at {first:.2f}s (includes window compile)")
    for k in ("dispatch", "sync", "fncall"):
        v = stats[k]
        ms = [f"{x*1e3:.0f}" for x in v]
        print(f"{k:9s} n={len(v)} ms each: {' '.join(ms)}")


if __name__ == "__main__":
    main()
