"""Capture an XLA op-level trace of the decode window and print the top ops."""

import glob
import time

import jax
import jax.numpy as jnp
import numpy as np

from dynamo_tpu.engine import kv_cache as kvc
from dynamo_tpu.models import config as mcfg
from dynamo_tpu.models.llama import init_params, make_decode_window

BATCH, CTX, BLOCK, WIDTH = 64, 512, 64, 16


def main():
    cfg = mcfg.get_config("llama-3-1b")
    params = init_params(cfg, jax.random.key(0))
    num_blocks = 1 + BATCH * WIDTH
    win = jax.jit(
        make_decode_window(cfg, BLOCK, 8, use_pallas_decode=True,
                           greedy_only=True),
        donate_argnums=(1,))
    bt = np.zeros((BATCH, WIDTH), np.int32)
    for i in range(BATCH):
        bt[i] = np.arange(1 + i * WIDTH, 1 + (i + 1) * WIDTH)
    bt = jnp.asarray(bt)
    z = jnp.zeros((BATCH,), jnp.float32)
    zi = jnp.zeros((BATCH,), jnp.int32)
    ones = jnp.ones((BATCH,), jnp.float32)
    keys = jax.random.split(jax.random.key(0), BATCH)

    def fresh():
        return (kvc.init_cache(kvc.KvCacheConfig.for_model(
                    cfg, num_blocks=num_blocks, block_size=BLOCK)),
                jnp.ones((BATCH,), jnp.int32))

    cache, last = fresh()
    for _ in range(2):  # warm
        cache, out, _, _, _ = win(params, cache, last,
                                  jnp.full((BATCH,), CTX, jnp.int32),
                                  jnp.full((BATCH,), CTX + 1, jnp.int32),
                                  bt, z, zi, ones, keys, zi)
        last = out[-1]
    jax.device_get(last)

    logdir = "/tmp/jaxtrace"
    with jax.profiler.trace(logdir):
        for _ in range(3):
            cache, out, _, _, _ = win(params, cache, last,
                                      jnp.full((BATCH,), CTX, jnp.int32),
                                      jnp.full((BATCH,), CTX + 1, jnp.int32),
                                      bt, z, zi, ones, keys, zi)
            last = out[-1]
        jax.device_get(last)
        time.sleep(0.5)

    files = glob.glob(logdir + "/**/*.xplane.pb", recursive=True)
    print("xplane files:", files)


if __name__ == "__main__":
    main()
